"""Phase-attribution profiler: the ledger that proves where the 100ms goes.

ROADMAP items 2-3 (solve batching, device-resident state) exist because
host-side orchestration dominates the ~2-3ms device kernel by 30-50x —
but spans alone (PR 1) don't PROVE where a reconcile's wall time went;
they decompose one trace at a time. The `PhaseLedger` here consumes
every finished trace (a tracer sink) and attributes each span's SELF
time (duration minus its children's) into an exhaustive taxonomy of
named phase buckets, aggregated per tenant and per solve signature
class. The result is the "where does the 100ms go" table the batching/
residency work will be judged against: `make profile-report`, the
`/debug/profile` route, per-run `profile_bench.json`, and the
`karpenter_tpu_profile_*` metric families.

Coverage invariant
------------------
Attribution is exhaustive BY CONSTRUCTION below the root: a span whose
name has no bucket inherits its nearest mapped ancestor's, so the only
wall time that can escape is the ENCLOSING root's own self-time — the
un-spanned seams at the top of the hot path. That gap is metered as
`unattributed_ms`; when a trace's buckets cover <99% of the enclosing
wall, a `profile.unattributed` marker trace is flight-recorded so the
regression arrives with the offending trace id attached.

Zero overhead when tracing is off: sinks only fire from
`Tracer._finish`, which never runs disabled.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..metrics.tenant import current_tenant
from .tracer import TRACER, Span, Trace

# --- the ledger taxonomy ---------------------------------------------------
# Every bucket a solve or reconcile decomposes into. docs/observability.md
# documents the table; `make obs-audit` asserts every name here is
# exercised by at least one test (tests/test_observatory.py).
PHASES: Tuple[str, ...] = (
    "queue_wait",       # fleet service submit/dispatch bookkeeping
    "batch_pack",       # batched dispatch: request packing + batch upload
    "pipeline_wait",    # batched dispatch: blocked on an in-flight batch
    "resident_patch",   # device-resident state: sparse row patch (digest
    #                     diff + changed-row upload + donated scatter)
    "hooks",            # engine per-tick hooks (cloud tick, arrivals)
    "batch",            # pending-group collection (store index)
    "encode_cold",      # pod->tensor lowering, rows not in the encode cache
    "encode_cached",    # cached re-encode (gather path)
    "affinity",         # zone-affinity pre-pass
    "spread",           # topology-spread split
    "prep",             # node budget, padding, input packing
    "catalog_put",      # catalog tensors -> device (epoch miss only)
    "device_put",       # per-solve uploads (bytes metered)
    "compile",          # XLA compile (first shape-bucket dispatch)
    "dispatch",         # warm kernel dispatch
    "readback",         # the ONE blocking device->host read
    "decode",           # host-side SolveResult reconstruction
    "solve_host",       # host/native backend runs (no device stages)
    "solver_overhead",  # solve-path glue between instrumented stages
    "launch",           # CreateFleet-equivalent batch
    "bind",             # claim/nomination bookkeeping
    "commit",           # warm-path headroom-ledger rebuild
    "warm_admit",       # warm-path admission
    "journal_fsync",    # intent-journal append + fsync
    "cloud_api",        # batcher wire calls
    "optimizer_search",  # disruption optimizer: subset generation +
    #                     batched tournament + relaxation dispatch
    "optimizer_verify",  # disruption optimizer: exact Solver.solve()
    #                     verification of ranked subsets
    "integrity",        # solution-integrity plane: feasibility oracle,
    #                     canary dual-path re-solves, resident audits
    "wire",             # federation plane: serialized RPC latency between
    #                     a fleet process and the solver server (encode +
    #                     transport + server turnaround; the bench's
    #                     c17_wire_overhead_frac numerator)
    "reconcile_other",  # controller pass glue outside the seams above
)

# buckets on the DEVICE side of the host/device split profile-report
# prints (batch_pack is the batched upload — tunnel traffic like
# device_put; pipeline_wait is device execution the host could not hide)
DEVICE_PHASES = frozenset(
    {"catalog_put", "device_put", "compile", "dispatch", "readback",
     "batch_pack", "pipeline_wait", "resident_patch"})

# static span-name -> bucket map; names absent here inherit their nearest
# mapped ancestor's bucket (and the root's own self-time is the gap)
_SPAN_PHASE: Dict[str, str] = {
    "engine.hooks": "hooks",
    "provision.batch": "batch",
    "provision.pool": "reconcile_other",
    "provision.launch": "launch",
    "provision.bind": "bind",
    "warmpath.admit": "warm_admit",
    "warmpath.commit": "commit",
    "journal.fsync": "journal_fsync",
    "encode.cache_hit": "encode_cached",
    "encode.affinity": "affinity",
    "solve.spread": "spread",
    "solve.prep": "prep",
    "solve.catalog_put": "catalog_put",
    "solve.device_put": "device_put",
    "solve.compile": "compile",
    "solve.dispatch": "dispatch",
    "solve.readback": "readback",
    "solve.decode": "decode",
    "solve.device": "solver_overhead",
    "solve.batch_pack": "batch_pack",
    "solve.resident_patch": "resident_patch",
    "fleet.pipeline_wait": "pipeline_wait",
    "fleet.submit": "queue_wait",
    "fleet.dispatch": "queue_wait",
    "fleet.batch_stage": "queue_wait",
    "fleet.pump": "queue_wait",
    "cloud.create_fleet": "cloud_api",
    "cloud.terminate": "cloud_api",
    "cloud.describe": "cloud_api",
    "restart.adopt": "reconcile_other",
    "optimizer.search": "optimizer_search",
    "optimizer.verify": "optimizer_verify",
    "integrity.verify": "integrity",
    "federation.wire": "wire",
}

COVERAGE_TARGET = 0.99


def _encode_bucket(span: Span) -> str:
    """encode.lower classifies by its own cache attrs: a pure gather
    (no misses, some hits) is the cached path; anything that lowered a
    row is cold."""
    hits = span.attrs.get("cache_hits") or 0
    misses = span.attrs.get("cache_misses")
    return "encode_cached" if (misses == 0 and hits > 0) else "encode_cold"


def span_bucket(span: Span, trace: Trace) -> Optional[str]:
    """Static span -> phase bucket classification (no ancestor
    inheritance — callers walk parent chains themselves). Shared by the
    PhaseLedger and the RecomputeLedger (obs/recompute.py) so the two
    planes can never disagree about which bucket a span's self-time
    lands in."""
    if span.name == "encode.lower":
        return _encode_bucket(span)
    if span.name == "solve.encode":
        # inherit the classification of its lowering child
        for c in trace.spans:
            if (c.parent_id == span.span_id
                    and c.name == "encode.lower"):
                return _encode_bucket(c)
        return "encode_cold"
    if span.name == "solve.run":
        backend = span.attrs.get("backend", "")
        return ("solve_host" if backend in ("host", "native")
                else "solver_overhead")
    if span.name.startswith("reconcile:"):
        return "reconcile_other"
    if span.name.startswith("disruption."):
        return "reconcile_other"
    if span.name.startswith("fault."):
        return "reconcile_other"
    return _SPAN_PHASE.get(span.name)


class PhaseLedger:
    """Aggregates finished traces into per-(tenant, kind, phase) wall
    time. `kind` is "solve" for bare solve-rooted traces and "reconcile"
    for everything else (engine ticks, controller passes, bench roots
    that wrap a whole reconcile's worth of work)."""

    def __init__(self, coverage_target: float = COVERAGE_TARGET):
        self.coverage_target = coverage_target
        self._lock = threading.Lock()
        # (tenant, kind, phase) -> [ms, count]
        self._phases: Dict[Tuple[str, str, str], List[float]] = {}
        # (tenant, phase) -> bytes (h2d for puts, d2h for readback)
        self._bytes: Dict[Tuple[str, str], int] = {}
        # (tenant, kind) -> [wall_ms, unattributed_ms, traces]
        self._walls: Dict[Tuple[str, str], List[float]] = {}
        # (tenant, sig) -> [solve_ms, count] per padded signature class
        self._sigs: Dict[Tuple[str, str], List[float]] = {}
        # tenant -> virtual queueing delay ms (fleet cost model, NOT wall
        # time — reported separately, never part of coverage)
        self._virtual_wait: Dict[str, float] = {}
        self.traces = 0
        self.errors = 0

    # --- ingestion --------------------------------------------------------
    def ingest(self, trace: Trace) -> None:
        """Tracer sink: attribute one finished trace. Defensive — the
        profiler must never take a traced reconcile down (errors are
        counted and visible in the snapshot)."""
        try:
            self._ingest(trace)
        except Exception:  # noqa: BLE001 — observability must not crash the path it observes
            with self._lock:
                self.errors += 1

    @staticmethod
    def _kind_of(root_name: str) -> Optional[str]:
        """Only instrumented hot-path roots are ledger material — an
        ad-hoc user/test trace must neither skew the table nor trip the
        coverage invariant."""
        if root_name.startswith("solve."):
            return "solve"
        if (root_name == "engine.tick"
                or root_name.startswith("reconcile:")
                or root_name.startswith("reconcile.")
                or root_name.startswith("fleet.")
                or root_name.startswith("warmpath.")
                or root_name.startswith("bench.")):
            return "reconcile"
        return None

    def _ingest(self, trace: Trace) -> None:
        root = trace.root
        kind = self._kind_of(root.name)
        if kind is None:
            return
        tenant = current_tenant()
        by_id = {s.span_id: s for s in trace.spans}
        child_dur: Dict[int, float] = {}
        for s in trace.spans:
            if s.parent_id is not None:
                child_dur[s.parent_id] = (child_dur.get(s.parent_id, 0.0)
                                          + s.duration)

        def bucket_of(span: Span) -> Optional[str]:
            return span_bucket(span, trace)

        def tenant_of(span: Span) -> str:
            """Per-span tenant: the span's own `tenant` attr, else the
            nearest ancestor's, else the trace-level scope tenant. A
            BATCHED fleet pump serves many tenants inside ONE trace
            (the per-ticket stage/dispatch spans carry tenant attrs),
            and their phases must land on their own series — a single
            trace-level read would lump every co-batched tenant's work
            onto whoever happened to trigger the pump."""
            node = span
            while node is not None:
                t = node.attrs.get("tenant")
                if t:
                    return str(t)
                node = (by_id.get(node.parent_id)
                        if node.parent_id is not None else None)
            return tenant

        attributed = 0.0
        sig_by: Dict[str, Optional[str]] = {}
        solve_by: Dict[str, float] = {}
        phase_acc: Dict[Tuple[str, str], List[float]] = {}
        bytes_acc: Dict[Tuple[str, str], int] = {}
        vwait: Dict[str, float] = {}
        for s in trace.spans:
            self_ms = max(0.0, s.duration - child_dur.get(s.span_id, 0.0)) \
                * 1e3
            b = bucket_of(s)
            node = s
            while b is None and node.parent_id is not None:
                node = by_id.get(node.parent_id)
                if node is None:
                    break
                b = bucket_of(node)
            if b is None:
                # reaches here only for the root's own self-time (or an
                # orphaned parent chain): the unattributed gap
                continue
            st = tenant_of(s)
            row = phase_acc.setdefault((st, b), [0.0, 0.0])
            row[0] += self_ms
            row[1] += 1.0
            attributed += self_ms
            # solve.resident_patch is deliberately ABSENT here: its
            # transfers happen inside the enclosing device_put/
            # catalog_put span, whose transfer-ledger delta already
            # covers them — counting both would double the H2D bytes
            if s.name in ("solve.device_put", "solve.catalog_put",
                          "solve.batch_pack"):
                bytes_acc[(st, b)] = bytes_acc.get((st, b), 0) \
                    + int(s.attrs.get("h2d_bytes", 0) or 0)
            elif s.name == "solve.readback":
                bytes_acc[(st, b)] = bytes_acc.get((st, b), 0) \
                    + int(s.attrs.get("d2h_bytes", 0) or 0)
            if s.name == "fleet.dispatch":
                vwait[st] = vwait.get(st, 0.0) \
                    + float(s.attrs.get("wait_ms", 0.0) or 0.0)
            if s.name == "solve.prep" and sig_by.get(st) is None:
                g = s.attrs.get("groups_padded")
                n = s.attrs.get("n_max")
                if g is not None and n is not None:
                    sig_by[st] = f"g{g}/n{n}"
            if s.name in ("solve.device", "solve.run"):
                solve_by[st] = max(solve_by.get(st, 0.0),
                                   s.duration * 1e3)
                if sig_by.get(st) is None and s.name == "solve.run" \
                        and s.attrs.get("backend") in ("host", "native"):
                    sig_by[st] = f"host/g{s.attrs.get('groups', '?')}"

        wall_ms = root.duration * 1e3
        unattr_ms = max(0.0, wall_ms - attributed)
        coverage = 1.0 - (unattr_ms / wall_ms if wall_ms > 0 else 0.0)
        with self._lock:
            self.traces += 1
            for (st, b), (ms, n) in phase_acc.items():
                row = self._phases.setdefault((st, kind, b), [0.0, 0.0])
                row[0] += ms
                row[1] += n
            for (st, b), by in bytes_acc.items():
                self._bytes[(st, b)] = self._bytes.get((st, b), 0) + by
            wrow = self._walls.setdefault((tenant, kind), [0.0, 0.0, 0.0])
            wrow[0] += wall_ms
            wrow[1] += unattr_ms
            wrow[2] += 1.0
            for st, ms in solve_by.items():
                if ms > 0.0:
                    srow = self._sigs.setdefault(
                        (st, sig_by.get(st) or "-"), [0.0, 0.0])
                    srow[0] += ms
                    srow[1] += 1.0
            for st, v in vwait.items():
                if v:
                    self._virtual_wait[st] = (
                        self._virtual_wait.get(st, 0.0) + v)

        from ..metrics import (PROFILE_COVERAGE, PROFILE_PHASE_MS,
                               PROFILE_UNATTRIBUTED_MS)
        for (st, b), (ms, _n) in phase_acc.items():
            PROFILE_PHASE_MS.inc(ms, phase=b, kind=kind, tenant=st)
        if unattr_ms:
            PROFILE_UNATTRIBUTED_MS.inc(unattr_ms, kind=kind, tenant=tenant)
        PROFILE_COVERAGE.set(self.coverage(tenant=tenant, kind=kind),
                             kind=kind, tenant=tenant)
        if coverage < self.coverage_target and wall_ms > 0:
            self._flight_record_gap(trace, tenant, kind, unattr_ms,
                                    coverage)

    def _flight_record_gap(self, trace: Trace, tenant: str, kind: str,
                           gap_ms: float, coverage: float) -> None:
        """The coverage invariant tripped: land a marker trace in the
        flight-recorder ring pointing at the under-attributed trace, so
        the gap is diagnosable from /debug/traces without re-running."""
        marker = Span(
            name="profile.unattributed",
            trace_id=f"profgap-{trace.trace_id}", span_id=0,
            parent_id=None, t0=0.0, t1=gap_ms / 1e3,
            ts=trace.root.ts,
            attrs={"source_trace": trace.trace_id, "tenant": tenant,
                   "kind": kind, "gap_ms": round(gap_ms, 3),
                   "coverage": round(coverage, 4),
                   "root": trace.root.name})
        TRACER.recorder.offer(Trace(trace_id=marker.trace_id,
                                    spans=[marker]), meter=False)

    # --- read side --------------------------------------------------------
    def coverage(self, tenant: Optional[str] = None,
                 kind: Optional[str] = None) -> float:
        """Aggregate attribution coverage (attributed/enclosing wall)
        over everything ingested, optionally filtered."""
        with self._lock:
            wall = unattr = 0.0
            for (t, k), (w, u, _n) in self._walls.items():
                if tenant is not None and t != tenant:
                    continue
                if kind is not None and k != kind:
                    continue
                wall += w
                unattr += u
        return 1.0 if wall <= 0 else 1.0 - unattr / wall

    def unattributed_ms(self) -> float:
        with self._lock:
            return sum(u for (_w, u, _n) in self._walls.values())

    def unattributed_by_tenant(self) -> Dict[str, float]:
        """tenant -> total unattributed ms — the per-tenant split the
        watchdog's profile_unattributed monitor baselines, so a fleet
        finding names WHOSE hot path grew an un-spanned seam."""
        with self._lock:
            out: Dict[str, float] = {}
            for (t, _k), (_w, u, _n) in self._walls.items():
                out[t] = out.get(t, 0.0) + u
            return out

    def snapshot(self) -> dict:
        """JSON-ready aggregate view — /debug/profile and the
        profile_bench.json body."""
        with self._lock:
            phases: Dict[str, dict] = {}
            for (tenant, kind, phase), (ms, n) in sorted(
                    self._phases.items()):
                d = phases.setdefault(tenant, {}).setdefault(kind, {})
                d[phase] = {"ms": round(ms, 3), "count": int(n),
                            "side": ("device" if phase in DEVICE_PHASES
                                     else "host")}
            walls = {
                t: {k: {"wall_ms": round(w, 3),
                        "unattributed_ms": round(u, 3),
                        "traces": int(n),
                        "coverage": round(1.0 - (u / w if w > 0 else 0.0),
                                          4)}
                    for (tt, k), (w, u, n) in self._walls.items()
                    if tt == t}
                for t in {tt for tt, _ in self._walls}}
            return {
                "phases": phases,
                "coverage": walls,
                "bytes": {f"{t}/{b}": by
                          for (t, b), by in sorted(self._bytes.items())},
                "signatures": {
                    t: {s: {"solve_ms": round(ms, 3), "count": int(n)}
                        for (tt, s), (ms, n) in sorted(self._sigs.items())
                        if tt == t}
                    for t in {tt for tt, _ in self._sigs}},
                "virtual_queue_wait_ms": {
                    t: round(v, 3)
                    for t, v in sorted(self._virtual_wait.items())},
                "taxonomy": list(PHASES),
                "traces": self.traces,
                "errors": self.errors,
            }

    def payload(self, query: str = "") -> dict:
        return self.snapshot()

    def report(self) -> str:
        return format_report(self.snapshot())

    def reset(self) -> None:
        with self._lock:
            self._phases.clear()
            self._bytes.clear()
            self._walls.clear()
            self._sigs.clear()
            self._virtual_wait.clear()
            self.traces = 0
            self.errors = 0


def format_report(snapshot: dict) -> str:
    """The `make profile-report` table: per tenant, every phase with its
    host/device side, share of the enclosing wall, and byte volume —
    then the host-vs-device rollup the ROADMAP optimizations target."""
    out: List[str] = []
    phases = snapshot.get("phases", {})
    cov = snapshot.get("coverage", {})
    raw_bytes = snapshot.get("bytes", {})
    if not phases:
        return "profile report: no traces ingested (is tracing enabled?)"
    out.append("phase attribution — where does the reconcile go")
    for tenant in sorted(phases):
        kinds = phases[tenant]
        wall = sum(v.get("wall_ms", 0.0)
                   for v in cov.get(tenant, {}).values())
        out.append(f"\ntenant={tenant}  wall={wall:.1f}ms")
        out.append(f"  {'phase':<18} {'side':<7} {'ms':>10} {'%':>6} "
                   f"{'count':>7} {'bytes':>12}")
        out.append("  " + "-" * 64)
        merged: Dict[str, dict] = {}
        for kind, d in kinds.items():
            for phase, row in d.items():
                m = merged.setdefault(phase, {"ms": 0.0, "count": 0,
                                              "side": row["side"]})
                m["ms"] += row["ms"]
                m["count"] += row["count"]
        host_ms = dev_ms = 0.0
        for phase, row in sorted(merged.items(), key=lambda kv:
                                 -kv[1]["ms"]):
            pct = 100.0 * row["ms"] / wall if wall else 0.0
            nbytes = raw_bytes.get(f"{tenant}/{phase}", 0)
            bcol = f"{nbytes:>12,d}" if nbytes else f"{'-':>12}"
            out.append(f"  {phase:<18} {row['side']:<7} {row['ms']:>10.3f} "
                       f"{pct:>5.1f}% {row['count']:>7} {bcol}")
            if row["side"] == "device":
                dev_ms += row["ms"]
            else:
                host_ms += row["ms"]
        unattr = sum(v.get("unattributed_ms", 0.0)
                     for v in cov.get(tenant, {}).values())
        covs = [v.get("coverage", 1.0) for v in cov.get(tenant, {}).values()]
        out.append("  " + "-" * 64)
        out.append(f"  host total {host_ms:.3f}ms | device total "
                   f"{dev_ms:.3f}ms | unattributed {unattr:.3f}ms "
                   f"| coverage {min(covs) if covs else 1.0:.4f}")
        vq = snapshot.get("virtual_queue_wait_ms", {}).get(tenant)
        if vq:
            out.append(f"  virtual queue wait (fleet cost model): {vq:.3f}ms")
        sigs = snapshot.get("signatures", {}).get(tenant, {})
        for sig, row in sorted(sigs.items(),
                               key=lambda kv: -kv[1]["solve_ms"])[:6]:
            out.append(f"  signature {sig:<14} solves={row['count']:<4} "
                       f"total={row['solve_ms']:.3f}ms")
    if snapshot.get("errors"):
        out.append(f"\nWARNING: {snapshot['errors']} trace(s) failed to "
                   "ingest")
    return "\n".join(out)


# THE process-wide ledger, installed as a tracer sink at import (the
# sink only fires while tracing is enabled, so this is free otherwise).
LEDGER = PhaseLedger()
TRACER.add_sink(LEDGER.ingest)

from .exposition import register_debug_route  # noqa: E402 (after LEDGER)

register_debug_route("/debug/profile",
                     lambda ledger, query: ledger.payload(query),
                     owner=LEDGER)
