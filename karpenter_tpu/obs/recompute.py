"""Recompute observatory: the work-provenance ledger that measures WHO
redoes identical work (ROADMAP item 3's missing instrument).

The PhaseLedger (obs/profile.py) answers "where does the reconcile
wall go"; this plane answers the follow-up the zero-recompute roadmap
item needs: of the work each stage did, how much was a recomputation of
inputs it had already seen? Every unit of stage work registers an input
fingerprint (the same uint64 row-digest machinery the upload-redundancy
meter uses — obs/devicemem.UploadMeter._row_digests, never Python
`hash()`: PYTHONHASHSEED must not leak into a repeat-determinism
contract) and is classified into one of three outcomes:

- **fresh**        — a fingerprint this stage has not seen (real work);
- **redundant**    — the same fingerprint recomputed from scratch (the
                     measured headroom a memo/cache/residency layer can
                     spend — CvxCluster's "cost scales with the delta"
                     target, PAPERS.md);
- **delta_served** — the work was answered by an existing cache,
                     memo, or residency layer (encode-cache hit,
                     conflict memo, screen memo, optimizer no-op memo,
                     warm admission) instead of being recomputed.

Stage taxonomy (STAGES): `encode`, `conflict`, `affinity`, `spread`,
`solve`, `optimizer`, `disrupt` — every stage ROADMAP item 3 targets.
Outcome unit counters always move (classification is a dict update);
**ms and bytes attribution rides the PhaseLedger span buckets**: when
tracing is enabled, a tracer sink maps each finished span's SELF time
to a stage (profile.span_bucket + STAGE_OF, so the two ledgers cannot
disagree) and splits it across the outcomes the same trace classified,
proportionally by units. Stage wall with NO classification in its trace
is the coverage gap — metered as
`karpenter_tpu_recompute_unattributed_ms_total` and flight-recorded as
a `recompute.unattributed` marker (offer(meter=False), like every
observability plane's self-markers) when a trace's classified share of
its taxonomy wall drops below COVERAGE_TARGET.

Decision-output glue buckets (launch/bind/commit/journal/cloud_api/
hooks/batch/integrity/reconcile_other) are NOT taxonomy stages: they
are excluded from the coverage denominator by design — "traced solve
wall" here means wall spent in recompute-taxonomy stages.

Zero overhead when tracing is off beyond the unit-counter updates;
the sink only fires from Tracer._finish, which never runs disabled.
Seed-deterministic: same call sequence => same snapshot; the ledger is
read-only over everything it observes, so chaos `--repeat` hashes and
fault fingerprints are byte-identical with the plane armed
(tests/test_recompute.py + the chaos suites assert so).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..metrics.tenant import current_tenant
from .profile import PhaseLedger, span_bucket
from .tracer import TRACER, Span, Trace

# --- the work taxonomy ------------------------------------------------------
# Every stage of the reconcile whose work can be provenance-classified.
# docs/observability.md documents the table; `make obs-audit` asserts
# every stage AND outcome is exercised by tests/test_recompute.py.
STAGES: Tuple[str, ...] = (
    "encode",      # pod->tensor lowering (per signature group)
    "conflict",    # anti-affinity conflict-matrix build
    "affinity",    # zone-affinity pre-pass
    "spread",      # topology-spread split
    "solve",       # gbuf dispatch: prep/upload/kernel/readback/decode,
    #                or a warm admission serving the batch from the ledger
    "optimizer",   # disruption consolidation screen + subset search
    "disrupt",     # drift/expiration/disruption classification pass
)

OUTCOMES: Tuple[str, ...] = ("fresh", "redundant", "delta_served")

# PhaseLedger bucket -> taxonomy stage. Buckets absent here are
# decision-output glue: excluded from the coverage denominator.
STAGE_OF: Dict[str, str] = {
    "encode_cold": "encode",
    "encode_cached": "encode",
    "affinity": "affinity",
    "spread": "spread",
    "queue_wait": "solve",
    "batch_pack": "solve",
    "pipeline_wait": "solve",
    "resident_patch": "solve",
    "prep": "solve",
    "catalog_put": "solve",
    "device_put": "solve",
    "compile": "solve",
    "dispatch": "solve",
    "readback": "solve",
    "decode": "solve",
    "solve_host": "solve",
    "solver_overhead": "solve",
    "warm_admit": "solve",
    "optimizer_search": "optimizer",
    "optimizer_verify": "optimizer",
}

COVERAGE_TARGET = 0.99

# bounded per-(tenant, stage) fingerprint memory: enough to recognize a
# steady cluster's whole working set, small enough to never matter
SEEN_CAP = 4096
# bounded in-flight trace classifications (a trace that never finishes
# — tracing disabled mid-flight — must not leak its pending entry)
PENDING_CAP = 64

# the excluded-glue sentinel _stage_of returns for spans whose bucket is
# known but deliberately outside the taxonomy (no ancestor inheritance)
_GLUE = "_glue"


# --- fingerprint helpers ----------------------------------------------------
def fingerprint_bytes(data: bytes) -> int:
    """Deterministic uint64 content fingerprint — the devicemem row
    digest applied to one byte string (weighted sum + fmix64 finalize).
    Never Python hash(): PYTHONHASHSEED would break repeat contracts."""
    import numpy as np

    from .devicemem import UploadMeter
    if not data:
        return 0x9E3779B97F4A7C15
    arr = np.frombuffer(data, dtype=np.uint8).reshape(1, -1)
    return int(UploadMeter._row_digests(arr)[0])


def fingerprint(*parts) -> int:
    """Deterministic uint64 over a tuple of repr-stable values. Callers
    must pass ordered collections (sort sets first) — repr of an
    unordered container is only stable within one process."""
    return fingerprint_bytes(
        "\x1f".join(repr(p) for p in parts).encode())


def fingerprint_rows(*matrices) -> "object":
    """Vectorized per-row uint64 fingerprints over one or more aligned
    matrices (same row count): each matrix digests per row, then the
    stacked digest columns digest again — one combined fingerprint per
    logical row. Returns a uint64 numpy vector."""
    import numpy as np

    from .devicemem import UploadMeter
    cols = []
    for m in matrices:
        m = np.ascontiguousarray(m)
        if m.ndim == 1:
            m = m.reshape(-1, 1)
        cols.append(UploadMeter._row_digests(m))
    if len(cols) == 1:
        return cols[0]
    return UploadMeter._row_digests(
        np.ascontiguousarray(np.stack(cols, axis=1)))


def fingerprint_fold(values) -> int:
    """Order-sensitive fold of an iterable/vector of uint64
    fingerprints into one."""
    import numpy as np
    arr = np.asarray(list(values) if not hasattr(values, "dtype")
                     else values, dtype=np.uint64)
    if arr.size == 0:
        return 0x9E3779B97F4A7C15
    return fingerprint_bytes(np.ascontiguousarray(arr).tobytes())


def encoded_fingerprint(enc) -> int:
    """One uint64 over an EncodedPods' solve-relevant content: per-group
    combined row digests (requests/compat/zone/cap masks) folded with
    the group counts. The gbuf identity the solve stage classifies on —
    an unchanged fingerprint re-solved from scratch is redundant work a
    warm admission or resident state should have served."""
    import numpy as np
    if getattr(enc, "G", 0) == 0:
        return 0x9E3779B97F4A7C15
    rows = fingerprint_rows(enc.requests, enc.compat, enc.allow_zone,
                            enc.allow_cap)
    return fingerprint_fold(np.concatenate(
        [rows, np.ascontiguousarray(enc.counts).astype(np.uint64)]))


def _stage_of(span: Span, trace: Trace) -> Optional[str]:
    """Span -> taxonomy stage, or _GLUE (bucket known, deliberately
    excluded) or None (unmapped name: inherit the nearest classified
    ancestor's stage)."""
    name = span.name
    if name == "encode.conflicts":
        return "conflict"
    if name.startswith("disruption."):
        # the batched consolidation screen is optimizer work; the rest
        # of a disruption pass (drift/expiry/candidate classification)
        # is the disrupt stage
        return "optimizer" if name == "disruption.screen" else "disrupt"
    b = span_bucket(span, trace)
    if b is None:
        return None
    return STAGE_OF.get(b, _GLUE)


class RecomputeLedger:
    """Process-wide work-provenance ledger (module singleton RECOMPUTE,
    weakref /debug/recompute route, tenant-scoped, seed-deterministic).

    Call sites call `classify(stage, fp)` per unit of work — a bounded
    per-(tenant, stage) LRU of seen fingerprints decides fresh vs
    redundant; `served=True` marks work a cache/memo/residency layer
    answered (delta_served, no fingerprint needed). The tracer sink
    (`ingest`) attributes traced stage wall/bytes across the outcomes
    each trace classified."""

    def __init__(self, coverage_target: float = COVERAGE_TARGET,
                 seen_cap: int = SEEN_CAP):
        self.coverage_target = coverage_target
        self.seen_cap = seen_cap
        self._lock = threading.Lock()
        # (tenant, stage, outcome) -> units of work
        self._units: Dict[Tuple[str, str, str], int] = {}
        # (tenant, stage) -> LRU of seen fingerprints
        self._seen: Dict[Tuple[str, str], "OrderedDict[int, None]"] = {}
        # trace_id -> stage -> outcome -> units classified while that
        # trace was current (consumed by ingest; bounded)
        self._pending: Dict[str, Dict[str, Dict[str, int]]] = {}
        # (stage, outcome) -> attributed ms / bytes
        self._ms: Dict[Tuple[str, str], float] = {}
        self._bytes: Dict[Tuple[str, str], int] = {}
        # stage -> [taxonomy wall ms, unattributed ms]
        self._stage_wall: Dict[str, List[float]] = {}
        self.traces = 0
        self.errors = 0

    # --- classification (call sites) --------------------------------------
    def classify(self, stage: str, fp: Optional[int] = None, *,
                 served: bool = False, units: int = 1,
                 tenant: Optional[str] = None) -> str:
        """Register `units` of `stage` work with input fingerprint `fp`
        and return the outcome. served=True short-circuits to
        delta_served (fp unused). Cheap: two dict updates and a metric
        inc — safe on the hot path with tracing off."""
        if units <= 0:
            return "delta_served" if served else "fresh"
        t = tenant if tenant is not None else current_tenant()
        if served:
            outcome = "delta_served"
        else:
            with self._lock:
                seen = self._seen.get((t, stage))
                if seen is None:
                    seen = self._seen[(t, stage)] = OrderedDict()
                key = int(fp) if fp is not None else 0
                if key in seen:
                    seen.move_to_end(key)
                    outcome = "redundant"
                else:
                    seen[key] = None
                    if len(seen) > self.seen_cap:
                        seen.popitem(last=False)
                    outcome = "fresh"
        self._record(t, stage, outcome, units)
        return outcome

    def classify_rows(self, stage: str, fps, *,
                      tenant: Optional[str] = None) -> Tuple[int, int]:
        """Batch classification of a fingerprint vector (one unit each)
        under a single lock pass — the cold-encode path classifies a
        whole group matrix this way. Returns (fresh, redundant)."""
        t = tenant if tenant is not None else current_tenant()
        fresh = redundant = 0
        with self._lock:
            seen = self._seen.get((t, stage))
            if seen is None:
                seen = self._seen[(t, stage)] = OrderedDict()
            for fp in fps:
                key = int(fp)
                if key in seen:
                    seen.move_to_end(key)
                    redundant += 1
                else:
                    seen[key] = None
                    if len(seen) > self.seen_cap:
                        seen.popitem(last=False)
                    fresh += 1
        if fresh:
            self._record(t, stage, "fresh", fresh)
        if redundant:
            self._record(t, stage, "redundant", redundant)
        return fresh, redundant

    def _record(self, tenant: str, stage: str, outcome: str,
                units: int) -> None:
        with self._lock:
            key = (tenant, stage, outcome)
            self._units[key] = self._units.get(key, 0) + units
            tid = TRACER.current_trace_id()
            if tid is not None:
                pend = self._pending.get(tid)
                if pend is None:
                    if len(self._pending) >= PENDING_CAP:
                        self._pending.pop(next(iter(self._pending)))
                    pend = self._pending[tid] = {}
                row = pend.setdefault(stage, {})
                row[outcome] = row.get(outcome, 0) + units
        from ..metrics import RECOMPUTE_WORK, REDUNDANT_WORK_FRAC
        RECOMPUTE_WORK.inc(units, stage=stage, outcome=outcome,
                           tenant=tenant)
        REDUNDANT_WORK_FRAC.set(self.redundant_frac(stage), stage=stage)

    # --- ingestion (tracer sink) -------------------------------------------
    def ingest(self, trace: Trace) -> None:
        """Tracer sink: attribute one finished trace's taxonomy wall.
        Defensive — observability must never take down the path it
        observes."""
        try:
            self._ingest(trace)
        except Exception:  # noqa: BLE001 — observability must not crash the path it observes
            with self._lock:
                self.errors += 1

    def _ingest(self, trace: Trace) -> None:
        with self._lock:
            pending = self._pending.pop(trace.trace_id, None)
        kind = PhaseLedger._kind_of(trace.root.name)
        if kind is None:
            return
        by_id = {s.span_id: s for s in trace.spans}
        child_dur: Dict[int, float] = {}
        for s in trace.spans:
            if s.parent_id is not None:
                child_dur[s.parent_id] = (child_dur.get(s.parent_id, 0.0)
                                          + s.duration)

        def resolve(span: Span) -> Optional[str]:
            st = _stage_of(span, trace)
            node = span
            while st is None and node.parent_id is not None:
                node = by_id.get(node.parent_id)
                if node is None:
                    break
                st = _stage_of(node, trace)
            return None if st in (None, _GLUE) else st

        stage_ms: Dict[str, float] = {}
        stage_bytes: Dict[str, int] = {}
        for s in trace.spans:
            st = resolve(s)
            if st is None:
                continue
            self_ms = max(0.0, s.duration
                          - child_dur.get(s.span_id, 0.0)) * 1e3
            stage_ms[st] = stage_ms.get(st, 0.0) + self_ms
            if s.name in ("solve.device_put", "solve.catalog_put",
                          "solve.batch_pack"):
                stage_bytes[st] = stage_bytes.get(st, 0) \
                    + int(s.attrs.get("h2d_bytes", 0) or 0)
            elif s.name == "solve.readback":
                stage_bytes[st] = stage_bytes.get(st, 0) \
                    + int(s.attrs.get("d2h_bytes", 0) or 0)

        pending = pending or {}
        total_ms = sum(stage_ms.values())
        attributed = 0.0
        red_ms: Dict[str, float] = {}
        unattr_by_stage: Dict[str, float] = {}
        with self._lock:
            self.traces += 1
            for st, ms in stage_ms.items():
                wall = self._stage_wall.setdefault(st, [0.0, 0.0])
                wall[0] += ms
                mix = pending.get(st)
                mix_units = sum(mix.values()) if mix else 0
                if not mix_units:
                    wall[1] += ms
                    unattr_by_stage[st] = ms
                    continue
                attributed += ms
                for outcome, n in mix.items():
                    share = ms * (n / mix_units)
                    key = (st, outcome)
                    self._ms[key] = self._ms.get(key, 0.0) + share
                    if outcome == "redundant":
                        red_ms[st] = red_ms.get(st, 0.0) + share
                    b = stage_bytes.get(st, 0)
                    if b:
                        self._bytes[key] = self._bytes.get(key, 0) \
                            + int(b * (n / mix_units))
        from ..metrics import (RECOMPUTE_UNATTRIBUTED_MS,
                               REDUNDANT_WORK_MS)
        for st, ms in red_ms.items():
            REDUNDANT_WORK_MS.inc(ms, stage=st)
        for st, ms in unattr_by_stage.items():
            if ms:
                RECOMPUTE_UNATTRIBUTED_MS.inc(ms, stage=st)
        coverage = (attributed / total_ms) if total_ms > 0 else 1.0
        if coverage < self.coverage_target and total_ms > 0:
            self._flight_record_gap(trace, unattr_by_stage, coverage)

    def _flight_record_gap(self, trace: Trace,
                           unattr: Dict[str, float],
                           coverage: float) -> None:
        """The coverage invariant tripped for one trace: land a marker
        in the flight-recorder ring naming the unclassified stages, so
        the gap is diagnosable from /debug/traces without re-running.
        meter=False: a plane's self-marker must not move the overflow
        meters it coexists with (the chaos determinism contract)."""
        gap_ms = sum(unattr.values())
        marker = Span(
            name="recompute.unattributed",
            trace_id=f"recompgap-{trace.trace_id}", span_id=0,
            parent_id=None, t0=0.0, t1=gap_ms / 1e3,
            ts=trace.root.ts,
            attrs={"source_trace": trace.trace_id,
                   "gap_ms": round(gap_ms, 3),
                   "coverage": round(coverage, 4),
                   "stages": {s: round(ms, 3)
                              for s, ms in sorted(unattr.items())},
                   "root": trace.root.name})
        TRACER.recorder.offer(Trace(trace_id=marker.trace_id,
                                    spans=[marker]), meter=False)

    # --- read side ---------------------------------------------------------
    def stage_units(self) -> Dict[str, Dict[str, int]]:
        """stage -> outcome -> units, aggregated over tenants — what the
        watchdog's recompute_runaway monitor baselines at arm."""
        out: Dict[str, Dict[str, int]] = {}
        with self._lock:
            for (_t, st, outcome), n in self._units.items():
                out.setdefault(st, {})[outcome] = \
                    out.get(st, {}).get(outcome, 0) + n
        return out

    def redundant_frac(self, stage: str) -> float:
        """redundant units / total units for one stage (0.0 when the
        stage has seen no work)."""
        with self._lock:
            total = red = 0
            for (_t, st, outcome), n in self._units.items():
                if st != stage:
                    continue
                total += n
                if outcome == "redundant":
                    red += n
        return red / total if total else 0.0

    def coverage(self) -> float:
        """Classified share of all traced taxonomy-stage wall (1.0 when
        nothing was traced)."""
        with self._lock:
            wall = sum(w for (w, _u) in self._stage_wall.values())
            unattr = sum(u for (_w, u) in self._stage_wall.values())
        return 1.0 if wall <= 0 else 1.0 - unattr / wall

    def unattributed_ms(self) -> float:
        with self._lock:
            return sum(u for (_w, u) in self._stage_wall.values())

    def snapshot(self) -> dict:
        """JSON-ready aggregate view — /debug/recompute and the bench
        c16 artifact body."""
        with self._lock:
            units: Dict[str, Dict[str, int]] = {}
            tenants: set = set()
            for (t, st, outcome), n in self._units.items():
                tenants.add(t)
                row = units.setdefault(st, {o: 0 for o in OUTCOMES})
                row[outcome] = row.get(outcome, 0) + n
            stages: Dict[str, dict] = {}
            for st in STAGES:
                row = units.get(st)
                if row is None and st not in self._stage_wall:
                    continue
                row = row or {o: 0 for o in OUTCOMES}
                total = sum(row.values())
                wall, unattr = self._stage_wall.get(st, (0.0, 0.0))
                # delta-serving savings estimate: each served unit is
                # priced at the stage's mean PAID (fresh + redundant)
                # per-unit wall — the work the delta plane did not redo
                served = row.get("delta_served", 0)
                paid_units = row.get("fresh", 0) + row.get("redundant", 0)
                paid_ms = (self._ms.get((st, "fresh"), 0.0)
                           + self._ms.get((st, "redundant"), 0.0))
                saved_ms = (served * paid_ms / paid_units
                            if paid_units else 0.0)
                stages[st] = {
                    "units": dict(row),
                    "redundant_frac": round(
                        row.get("redundant", 0) / total, 4) if total
                    else 0.0,
                    "served_frac": round(served / total, 4) if total
                    else 0.0,
                    "saved_ms_est": round(saved_ms, 3),
                    "ms": {o: round(self._ms.get((st, o), 0.0), 3)
                           for o in OUTCOMES},
                    "bytes": {o: int(self._bytes.get((st, o), 0))
                              for o in OUTCOMES},
                    "wall_ms": round(wall, 3),
                    "unattributed_ms": round(unattr, 3),
                }
            wall = sum(w for (w, _u) in self._stage_wall.values())
            unattr = sum(u for (_w, u) in self._stage_wall.values())
            return {
                "stages": stages,
                "coverage": round(1.0 - (unattr / wall if wall > 0
                                         else 0.0), 4),
                "unattributed_ms": round(unattr, 3),
                "taxonomy": list(STAGES),
                "outcomes": list(OUTCOMES),
                "tenants": sorted(tenants),
                "seen_cap": self.seen_cap,
                "traces": self.traces,
                "errors": self.errors,
            }

    def payload(self, query: str = "") -> dict:
        return self.snapshot()

    def report(self) -> str:
        return format_report(self.snapshot())

    def reset(self) -> None:
        with self._lock:
            self._units.clear()
            self._seen.clear()
            self._pending.clear()
            self._ms.clear()
            self._bytes.clear()
            self._stage_wall.clear()
            self.traces = 0
            self.errors = 0


def format_report(snapshot: dict) -> str:
    """The `make recompute-report` table: per stage, the outcome unit
    split, the redundant fraction, the redundant wall (the headroom the
    delta plane spends), and the estimated wall the delta-served units
    did NOT pay (served units priced at the stage's mean paid
    per-unit cost)."""
    out: List[str] = []
    stages = snapshot.get("stages", {})
    if not stages:
        return ("recompute report: no work classified yet (drive a few "
                "reconciles first)")
    out.append("recompute observatory — who redoes identical work")
    out.append(f"  {'stage':<10} {'units':>9} {'fresh':>9} "
               f"{'redundant':>9} {'served':>9} {'red%':>7} "
               f"{'red ms':>10} {'saved ms':>10} {'gap ms':>9}")
    out.append("  " + "-" * 88)
    tot_red_ms = tot_gap = tot_saved = 0.0
    for st in snapshot.get("taxonomy", sorted(stages)):
        row = stages.get(st)
        if row is None:
            out.append(f"  {st:<10} {'-':>9}  (no work observed)")
            continue
        u = row["units"]
        total = sum(u.values())
        red_ms = row["ms"].get("redundant", 0.0)
        saved_ms = row.get("saved_ms_est", 0.0)
        tot_red_ms += red_ms
        tot_saved += saved_ms
        tot_gap += row["unattributed_ms"]
        out.append(
            f"  {st:<10} {total:>9,} {u.get('fresh', 0):>9,} "
            f"{u.get('redundant', 0):>9,} "
            f"{u.get('delta_served', 0):>9,} "
            f"{100.0 * row['redundant_frac']:>6.1f}% "
            f"{red_ms:>10.3f} {saved_ms:>10.3f} "
            f"{row['unattributed_ms']:>9.3f}")
    out.append("  " + "-" * 88)
    out.append(f"  coverage {snapshot.get('coverage', 1.0):.4f} "
               f"(target {COVERAGE_TARGET:g}) | redundant wall "
               f"{tot_red_ms:.3f}ms — the measured headroom | served "
               f"saved ~{tot_saved:.3f}ms | unattributed {tot_gap:.3f}ms")
    if snapshot.get("errors"):
        out.append(f"  WARNING: {snapshot['errors']} trace(s) failed to "
                   "ingest")
    return "\n".join(out)


# THE process-wide ledger, installed as a tracer sink at import (the
# sink only fires while tracing is enabled; classification counters are
# plain dict updates otherwise).
RECOMPUTE = RecomputeLedger()
TRACER.add_sink(RECOMPUTE.ingest)

from .exposition import register_debug_route  # noqa: E402 (after RECOMPUTE)

register_debug_route("/debug/recompute",
                     lambda ledger, query: ledger.payload(query),
                     owner=RECOMPUTE)
