"""Per-tenant SLO / error-budget engine.

The fleet funnels 50+ tenants through one queue (docs/fleet.md) with
isolation asserted by scenario-specific p99 bounds — but no DECLARED
objectives: nothing says what a tenant is owed, how much of it has been
burned, or pages when the burn rate says the budget dies early. This
module closes that: declarative `SloSpec`s evaluated over the existing
tenant-dimensioned metric families, with multi-window burn rates
(fast=5m / slow=1h of SIM time, so chaos runs evaluate on the timeline
that produced the events), error-budget gauges, and
`slo_burn_alerts_total` firings that also land an `slo.burn` trace in
the flight-recorder ring — the alert arrives with its evidence.

Indicators are cumulative (good, total) event counts read from the
process registry; the engine snapshots them on its own clock and works
in deltas, so budgets are PER RUN (baselined at engine construction)
even though the registry is process-cumulative across seeded repeats —
which is what keeps `make fleet-audit`'s repeat contract intact with
the observatory enabled.

Alert condition: classic multi-window — fast-window burn >= fast
threshold AND slow-window burn >= slow threshold. Edge-triggered per
(slo, tenant): one alert per excursion, re-armed when burn subsides.

The fleet noisy-neighbor invariant reads as: the victim tenants' budget
gauges stay high while the noisy tenant's burns and alerts
(fleet/scenarios._noisy_analyze asserts both).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .exposition import register_debug_route
from .tracer import TRACER, Span, Trace

Indicator = Callable[[str], Tuple[float, float]]  # tenant -> (good, total)


@dataclass(frozen=True)
class SloSpec:
    """One declared objective: `objective` is the target good/total
    ratio; `indicator(tenant)` returns CUMULATIVE (good, total) event
    counts for that tenant (monotone non-decreasing)."""

    name: str
    objective: float
    indicator: Indicator
    description: str = ""

    @property
    def allowance(self) -> float:
        return max(1e-9, 1.0 - self.objective)


def default_slos(latency_wait_ms: float = 25.0) -> List[SloSpec]:
    """The standing objective set over the families every tenant already
    emits. Thresholds are deliberately modest — these are floors the
    fair scheduler should clear easily; burning one means isolation or
    the warm path actually regressed."""
    from ..metrics import (FLEET_SOLVE_WAIT, FLEET_SOLVES, FLEET_THROTTLED,
                           WARMPATH_AUDITS, WARMPATH_DECISIONS)

    def solve_latency(tenant: str) -> Tuple[float, float]:
        total = float(FLEET_SOLVE_WAIT.total(tenant=tenant))
        good = float(FLEET_SOLVE_WAIT.cumulative_le(latency_wait_ms,
                                                    tenant=tenant))
        return good, total

    def availability(tenant: str) -> Tuple[float, float]:
        served = FLEET_SOLVES.value(tenant=tenant)
        throttled = FLEET_THROTTLED.value(tenant=tenant)
        return served, served + throttled

    def warm_hit(tenant: str) -> Tuple[float, float]:
        good = (WARMPATH_DECISIONS.sum(path="warm", tenant=tenant)
                + WARMPATH_DECISIONS.sum(path="mixed", tenant=tenant))
        return good, WARMPATH_DECISIONS.sum(tenant=tenant)

    def audit_clean(tenant: str) -> Tuple[float, float]:
        total = WARMPATH_AUDITS.sum(tenant=tenant)
        return WARMPATH_AUDITS.sum(outcome="clean", tenant=tenant), total

    return [
        SloSpec("solve_latency", 0.90, solve_latency,
                f"solve virtual queueing delay <= {latency_wait_ms:g}ms "
                "for >=90% of dispatches"),
        SloSpec("solve_availability", 0.95, availability,
                "solve submissions served (not throttled by the "
                "in-flight cap) for >=95% of attempts"),
        SloSpec("warm_hit_rate", 0.50, warm_hit,
                "warm or mixed admission for >=50% of provisioner "
                "decisions (only meaningful with the warm path on)"),
        SloSpec("audit_divergence", 0.999, audit_clean,
                "warm-path audits clean for >=99.9% of replays"),
    ]


class _History:
    """Time-ordered (t, good, total) snapshots with MONOTONE window-start
    pointers: snapshots only append and windows only move forward, so
    finding each window's earliest in-window snapshot is amortized O(1)
    per tick instead of a linear rescan (a 100-tenant fleet evaluates
    hundreds of these per tick)."""

    __slots__ = ("pts", "fast_i", "slow_i")

    def __init__(self):
        self.pts: List[Tuple[float, float, float]] = []
        self.fast_i = 0
        self.slow_i = 0

    def append(self, now: float, good: float, total: float,
               fast_window: float, slow_window: float) -> None:
        self.pts.append((now, good, total))
        last = len(self.pts) - 1
        while (self.fast_i < last
               and now - self.pts[self.fast_i][0] > fast_window):
            self.fast_i += 1
        while (self.slow_i < last
               and now - self.pts[self.slow_i][0] > slow_window):
            self.slow_i += 1
        # compact dead prefix occasionally (everything before slow_i is
        # outside both windows forever)
        if self.slow_i > 4096:
            del self.pts[:self.slow_i]
            self.fast_i -= self.slow_i
            self.slow_i = 0

    def window_delta(self, fast: bool) -> Tuple[float, float]:
        """(good delta, total delta) from the window's earliest
        in-window snapshot to the latest."""
        i = self.fast_i if fast else self.slow_i
        t0, g0, n0 = self.pts[i]
        _t1, g1, n1 = self.pts[-1]
        return g1 - g0, n1 - n0


class SloEngine:
    """Evaluates declared objectives for a set of tenants on a clock."""

    FAST_WINDOW = 300.0     # 5m of sim time
    SLOW_WINDOW = 3600.0    # 1h of sim time
    FAST_BURN = 4.0         # fast-window burn threshold
    SLOW_BURN = 1.0         # slow-window burn threshold
    # minimum sim-seconds between evaluations: sub-second cadence buys
    # nothing against 5m/1h windows, and indicator reads aren't free at
    # fleet scale (the runner calls tick() every loop iteration)
    MIN_INTERVAL = 1.0

    def __init__(self, clock, slos: Optional[List[SloSpec]] = None,
                 tenants: Tuple[str, ...] = (),
                 fast_window: Optional[float] = None,
                 slow_window: Optional[float] = None,
                 fast_burn: Optional[float] = None,
                 slow_burn: Optional[float] = None,
                 min_interval: Optional[float] = None):
        self.clock = clock
        self.slos = list(slos) if slos is not None else default_slos()
        self.fast_window = (self.FAST_WINDOW if fast_window is None
                            else fast_window)
        self.slow_window = (self.SLOW_WINDOW if slow_window is None
                            else slow_window)
        self.fast_burn = self.FAST_BURN if fast_burn is None else fast_burn
        self.slow_burn = self.SLOW_BURN if slow_burn is None else slow_burn
        self.min_interval = (self.MIN_INTERVAL if min_interval is None
                             else min_interval)
        self.tenants: List[str] = []
        self._history: Dict[Tuple[str, str], _History] = {}
        # (slo, tenant) -> (good, total) at engine construction: the
        # per-run budget baseline over a process-cumulative registry
        self._baseline: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self._alerting: set = set()
        self._last_eval: Optional[float] = None
        self.alerts: List[dict] = []
        for t in tenants:
            self.add_tenant(t)
        register_debug_route("/debug/slo",
                             lambda eng, query: eng.payload(query),
                             owner=self)

    def add_tenant(self, tenant: str) -> None:
        if tenant in self.tenants:
            return
        self.tenants.append(tenant)
        for slo in self.slos:
            key = (slo.name, tenant)
            self._baseline[key] = slo.indicator(tenant)
            self._history[key] = _History()

    # --- evaluation -------------------------------------------------------
    def tick(self, now: Optional[float] = None,
             force: bool = False) -> List[dict]:
        """Snapshot every indicator and evaluate burn/budget; returns
        alerts fired by THIS evaluation (also appended to self.alerts).
        Rate-limited to one evaluation per `min_interval` sim-seconds
        unless `force`d (the runner forces a final evaluation)."""
        from ..metrics import (SLO_BURN_ALERTS, SLO_BURN_RATE,
                               SLO_ERROR_BUDGET)
        now = float(self.clock.now()) if now is None else float(now)
        if (not force and self._last_eval is not None
                and now - self._last_eval < self.min_interval):
            return []
        self._last_eval = now
        fired: List[dict] = []
        for slo in self.slos:
            for tenant in self.tenants:
                key = (slo.name, tenant)
                good, total = slo.indicator(tenant)
                hist = self._history[key]
                hist.append(now, good, total,
                            self.fast_window, self.slow_window)
                burn_fast = self._burn(slo, hist, fast=True)
                burn_slow = self._burn(slo, hist, fast=False)
                SLO_BURN_RATE.set(burn_fast, slo=slo.name, window="fast",
                                  tenant=tenant)
                SLO_BURN_RATE.set(burn_slow, slo=slo.name, window="slow",
                                  tenant=tenant)
                budget = self.budget(slo, tenant, good, total)
                SLO_ERROR_BUDGET.set(budget, slo=slo.name, tenant=tenant)
                alerting = (burn_fast >= self.fast_burn
                            and burn_slow >= self.slow_burn)
                if alerting and key not in self._alerting:
                    self._alerting.add(key)
                    alert = {"slo": slo.name, "tenant": tenant, "at": now,
                             "burn_fast": round(burn_fast, 3),
                             "burn_slow": round(burn_slow, 3),
                             "budget_remaining": round(budget, 4)}
                    self.alerts.append(alert)
                    fired.append(alert)
                    SLO_BURN_ALERTS.inc(slo=slo.name, tenant=tenant)
                    self._flight_record(alert)
                elif not alerting:
                    self._alerting.discard(key)
        return fired

    def _burn(self, slo: SloSpec, hist: _History, fast: bool) -> float:
        """Bad-event rate over the window / the objective's allowance."""
        if not hist.pts:
            return 0.0
        dg, dn = hist.window_delta(fast)
        if dn <= 0:
            return 0.0
        bad_rate = max(0.0, dn - dg) / dn
        return bad_rate / slo.allowance

    def budget(self, slo: SloSpec, tenant: str,
               good: Optional[float] = None,
               total: Optional[float] = None) -> float:
        """Error budget remaining since the engine's baseline, in
        [-inf, 1]: 1 = untouched, 0 = exhausted, negative = overdrawn."""
        if good is None or total is None:
            good, total = slo.indicator(tenant)
        g0, n0 = self._baseline.get((slo.name, tenant), (0.0, 0.0))
        dn = total - n0
        if dn <= 0:
            return 1.0
        bad = max(0.0, dn - (good - g0))
        return 1.0 - (bad / dn) / slo.allowance

    def budgets(self) -> Dict[str, Dict[str, float]]:
        """tenant -> {slo: budget remaining} for reports/assertions."""
        return {t: {s.name: round(self.budget(s, t), 4) for s in self.slos}
                for t in self.tenants}

    def _flight_record(self, alert: dict) -> None:
        """Land an slo.burn marker in the flight-recorder ring — works
        with tracing disabled too (the ring accepts direct offers), so a
        chaos run's alert evidence survives without span overhead."""
        marker = Span(name="slo.burn",
                      trace_id=f"sloburn-{alert['tenant']}-"
                               f"{alert['slo']}-{int(alert['at'])}",
                      span_id=0, parent_id=None, t0=0.0,
                      t1=alert["burn_fast"] / 1e3, ts=alert["at"],
                      attrs=dict(alert))
        TRACER.recorder.offer(Trace(trace_id=marker.trace_id,
                                    spans=[marker]), meter=False)

    # --- exposition -------------------------------------------------------
    def payload(self, query: str = "") -> dict:
        return {
            "slos": [{"name": s.name, "objective": s.objective,
                      "description": s.description} for s in self.slos],
            "windows": {"fast_s": self.fast_window,
                        "slow_s": self.slow_window,
                        "fast_burn": self.fast_burn,
                        "slow_burn": self.slow_burn},
            "budgets": self.budgets(),
            "alerts": list(self.alerts),
            "alerting_now": sorted(f"{s}/{t}" for s, t in self._alerting),
        }
