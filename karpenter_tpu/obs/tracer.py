"""Span-based tracing + solver flight recorder.

The stage-level instrumentation CvxCluster/Tesserae attribute their wins
to (PAPERS.md): one trace per engine tick, nested spans for every stage
of the reconcile/solve hot path (batching, encode, device-put, compile,
dispatch, readback, bind, wire calls), exported as Chrome trace-event
JSON and JSONL, with a bounded in-memory ring of the N slowest traces so
a latency regression always has a captured decomposition to point at.

Design constraints, in order:

- **Zero overhead when disabled.** `TRACER.span()`/`trace()` return a
  shared no-op context manager after one attribute check; no objects are
  allocated, no clocks are read. The engine tick runs thousands of times
  per scale test — tracing must be invisible when off.
- **Sim-clock aware**, like metrics/durations.DurationRecorder: span
  durations always come from `time.perf_counter` (real compute time is
  what a flame graph decomposes), while each span ALSO stamps `ts` from
  an injectable clock (FakeClock in the sim), so a trace aligns with the
  simulated timeline that produced it.
- **Nesting via contextvars**, so the same tracer is correct under the
  asyncio runtime and plain synchronous engines without thread-locals.

Env vars:
  KARPENTER_TPU_TRACE_DIR   when set, the tracer auto-enables and every
                            finished trace appends to <dir>/traces.jsonl
  KARPENTER_TPU_TRACE_RING  flight-recorder capacity (default 16)
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: int
    parent_id: Optional[int]
    t0: float                 # perf_counter at start (duration basis)
    t1: float = 0.0           # perf_counter at end
    ts: float = 0.0           # injectable-clock timestamp at start
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.t1 - self.t0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "ts": round(self.ts, 6),
                "duration": round(self.duration, 6),
                "attrs": self.attrs}


@dataclass
class Trace:
    """One finished trace: the root span plus every descendant, in
    start order (the root is spans[0])."""

    trace_id: str
    spans: List[Span]

    @property
    def root(self) -> Span:
        return self.spans[0]

    @property
    def duration(self) -> float:
        return self.root.duration

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id,
                "root": self.root.name,
                "ts": round(self.root.ts, 6),
                "duration": round(self.duration, 6),
                "spans": [s.to_dict() for s in self.spans]}

    def children(self, span: Span) -> List[Span]:
        return [s for s in self.spans if s.parent_id == span.span_id]


class FlightRecorder:
    """Bounded ring of the N slowest finished traces.

    A new trace always enters while there is room; once full, it must be
    slower than the current fastest resident to get a seat (and the
    fastest is evicted). `slowest()` returns residents by descending
    duration — the crash-dump view an operator reads after a latency
    report. Thread-safe: the async runtime's controllers and a scraping
    HTTP handler touch it concurrently.
    """

    def __init__(self, size: int = 16):
        self.size = max(1, size)
        self._traces: List[Trace] = []
        self._lock = threading.Lock()
        # lifetime count of rejected offers — the ring-overflow meter the
        # invariant watchdog reads (a hot ring too small to retain
        # evidence is an observability failure worth a finding); the
        # per-tenant split lets a fleet watchdog attribute WHOSE hot
        # loop is overflowing the ring
        self.dropped = 0
        self.dropped_by_tenant: Dict[str, int] = {}

    def offer(self, trace: Trace, meter: bool = True) -> bool:
        """`meter=False` is for the observability plane's OWN marker
        traces (watchdog findings, coverage-gap markers): the slowest-N
        ring legitimately rejects a near-zero-duration marker when full
        of real traces, and that self-inflicted rejection must not
        count toward the overflow meters the watchdog reads or export
        as a tenant's drop — findings would manufacture findings."""
        with self._lock:
            if len(self._traces) < self.size:
                self._traces.append(trace)
                return True
            fastest = min(range(len(self._traces)),
                          key=lambda i: self._traces[i].duration)
            if trace.duration > self._traces[fastest].duration:
                self._traces[fastest] = trace
                return True
            if not meter:
                return False
            self.dropped += 1
            try:
                from ..metrics.tenant import current_tenant
                tenant = current_tenant()
            except Exception:  # noqa: BLE001 — interpreter teardown
                tenant = "default"
            self.dropped_by_tenant[tenant] = \
                self.dropped_by_tenant.get(tenant, 0) + 1
        try:
            from ..metrics import TRACE_RING_DROPPED
            TRACE_RING_DROPPED.inc(tenant=tenant)
        except Exception:  # noqa: BLE001 — the ring must never raise
            pass
        return False

    def slowest(self, n: Optional[int] = None) -> List[Trace]:
        with self._lock:
            out = sorted(self._traces, key=lambda t: -t.duration)
        return out if n is None else out[:n]

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


class _NoopSpan:
    """Shared do-nothing span: the entire disabled-tracing cost is one
    `enabled` check and returning this singleton."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class _SpanCtx:
    """Context manager for one live span; pushes itself as the current
    span for the dynamic extent of the `with` block."""

    __slots__ = ("_tracer", "span", "_token", "_is_root")

    def __init__(self, tracer: "Tracer", span: Span, is_root: bool):
        self._tracer = tracer
        self.span = span
        self._is_root = is_root
        self._token = None

    def set(self, **attrs) -> "_SpanCtx":
        self.span.attrs.update(attrs)
        return self

    def __enter__(self) -> "_SpanCtx":
        self._token = self._tracer._current.set(self.span)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.span.t1 = time.perf_counter()
        if exc_type is not None:
            self.span.attrs.setdefault("outcome", "error")
            self.span.attrs.setdefault("error", exc_type.__name__)
        self._tracer._current.reset(self._token)
        if self._is_root:
            self._tracer._finish(self.span)
        return False


class Tracer:
    """Process-wide tracer producing nested spans under a trace id."""

    def __init__(self, enabled: bool = False, ring_size: Optional[int] = None,
                 trace_dir: Optional[str] = None,
                 clock: Optional[Callable[[], float]] = None,
                 drop_empty: bool = True):
        env_dir = os.environ.get("KARPENTER_TPU_TRACE_DIR", "")
        self.trace_dir = trace_dir if trace_dir is not None else env_dir
        self.enabled = enabled or bool(self.trace_dir)
        if ring_size is None:
            ring_size = int(os.environ.get("KARPENTER_TPU_TRACE_RING", "16"))
        self.recorder = FlightRecorder(ring_size)
        # injectable timestamp source for Span.ts (sim clock in tests);
        # durations always use perf_counter regardless
        self.clock: Callable[[], float] = clock or time.time
        # childless root traces (an engine tick where no controller was
        # due) carry no information — drop them instead of flooding sinks
        self.drop_empty = drop_empty
        self._current: contextvars.ContextVar[Optional[Span]] = \
            contextvars.ContextVar("karpenter_tpu_span", default=None)
        self._ids = itertools.count(1)
        self._open: Dict[str, List[Span]] = {}   # trace_id -> spans so far
        self._lock = threading.Lock()
        # sink I/O gets its own lock: a slow/hung filesystem appending
        # traces.jsonl must not block span creation (which takes _lock)
        self._file_lock = threading.Lock()
        self._sinks: List[Callable[[Trace], None]] = []

    # --- configuration ---
    def configure(self, enabled: Optional[bool] = None,
                  clock: Optional[Callable[[], float]] = None,
                  ring_size: Optional[int] = None,
                  trace_dir: Optional[str] = None) -> "Tracer":
        if enabled is not None:
            self.enabled = enabled
        if clock is not None:
            self.clock = clock
        if ring_size is not None:
            self.recorder = FlightRecorder(ring_size)
        if trace_dir is not None:
            self.trace_dir = trace_dir
        return self

    def add_sink(self, fn: Callable[[Trace], None]) -> None:
        self._sinks.append(fn)

    # --- span creation ---
    def span(self, name: str, **attrs):
        """Open a span under the current one; with no trace active, this
        starts a new root trace (so a bare solve_device call still yields
        a decomposed trace). No-op singleton when disabled."""
        if not self.enabled:
            return NOOP_SPAN
        parent = self._current.get()
        if parent is None:
            return self.trace(name, **attrs)
        span = Span(name=name, trace_id=parent.trace_id,
                    span_id=next(self._ids), parent_id=parent.span_id,
                    t0=time.perf_counter(), ts=self.clock(), attrs=attrs)
        with self._lock:
            self._open.setdefault(span.trace_id, []).append(span)
        return _SpanCtx(self, span, is_root=False)

    def trace(self, name: str, **attrs):
        """Open a new root span (fresh trace id), regardless of context."""
        if not self.enabled:
            return NOOP_SPAN
        trace_id = uuid.uuid4().hex[:16]
        span = Span(name=name, trace_id=trace_id, span_id=next(self._ids),
                    parent_id=None, t0=time.perf_counter(),
                    ts=self.clock(), attrs=attrs)
        with self._lock:
            self._open[trace_id] = [span]
        return _SpanCtx(self, span, is_root=True)

    def current_trace_id(self) -> Optional[str]:
        """Trace id of the active span, for histogram exemplars."""
        if not self.enabled:
            return None
        cur = self._current.get()
        return cur.trace_id if cur is not None else None

    # --- finishing ---
    def _finish(self, root: Span) -> None:
        with self._lock:
            spans = self._open.pop(root.trace_id, [root])
        if self.drop_empty and len(spans) == 1:
            return
        trace = Trace(trace_id=root.trace_id, spans=spans)
        self.recorder.offer(trace)
        if self.trace_dir:
            try:
                os.makedirs(self.trace_dir, exist_ok=True)
                line = json.dumps(trace.to_dict())
                with self._file_lock:
                    with open(os.path.join(self.trace_dir,
                                           "traces.jsonl"), "a") as f:
                        f.write(line + "\n")
            except OSError:
                pass  # tracing must never take the control plane down
        for sink in self._sinks:
            sink(trace)


# --- exporters ---------------------------------------------------------


def to_chrome_events(traces: List[Trace]) -> List[dict]:
    """Chrome trace-event JSON (the `chrome://tracing` / Perfetto array
    format): complete events ("ph": "X") with microsecond ts/dur. Each
    trace gets its own tid so concurrent traces don't interleave; ts is
    relative to the earliest root so the file opens at t=0."""
    events: List[dict] = []
    if not traces:
        return events
    epoch = min(t.root.t0 for t in traces)
    for tid, trace in enumerate(traces, start=1):
        for s in trace.spans:
            events.append({
                "name": s.name,
                "ph": "X",
                "pid": 1,
                "tid": tid,
                "ts": round((s.t0 - epoch) * 1e6, 1),
                "dur": round((s.t1 - s.t0) * 1e6, 1),
                "args": {**s.attrs, "trace_id": s.trace_id,
                         "clock_ts": round(s.ts, 6)},
            })
    return events


def write_chrome_trace(traces: List[Trace], path: str,
                       metadata: Optional[dict] = None) -> str:
    """Write {"traceEvents": [...]} — the schema both chrome://tracing
    and Perfetto ingest directly. `metadata` lands under the standard
    top-level "metadata" key (both viewers ignore it): the run stamp —
    schema_version/run_id/seed/provenance — that lets the perf archive
    key this artifact to the bench run that produced it."""
    payload = {"traceEvents": to_chrome_events(traces),
               "displayTimeUnit": "ms"}
    if metadata:
        payload["metadata"] = dict(metadata)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def summarize(trace: Trace) -> Dict[str, float]:
    """Per-span-name total seconds — the trace-report aggregation."""
    out: Dict[str, float] = {}
    for s in trace.spans:
        out[s.name] = out.get(s.name, 0.0) + s.duration
    return out


# THE process-wide tracer every instrumentation point imports. Disabled
# unless KARPENTER_TPU_TRACE_DIR is set or a caller flips it on.
TRACER = Tracer()
