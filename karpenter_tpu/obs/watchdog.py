"""Online invariant watchdog: the chaos invariants, while they happen.

Every correctness invariant this framework enforces — no leaked claims,
store<->cloud consistency, a fully resolved intent journal, warm-path
audit discipline, fleet fairness — has lived as an END-OF-RUN assert in
the chaos/restart/fleet runners: a violation is only visible after the
run, with no timestamp, no severity, and no way to observe it in a
deployed process at all. The watchdog reframes those asserts as
INCREMENTAL monitors evaluated on a sim-clock cadence:

- **claim_leak** — claims stuck launching (no provider id), stuck in a
  pre-Initialized phase, or draining forever, past a sim-time grace
  window; plus the idempotency-token ledger checks (one token minting
  two live instances, one claim backed by two live instances — never
  legitimate, so no grace).
- **store_cloud_drift** — store nodes backing dead/missing cloud
  instances, and karpenter-tagged live instances no claim tracks
  (shielded by open launch intents exactly like the GC sweep).
- **intent_age** — open launch intents older than `INTENT_GRACE`: a
  wedged crash-window launch the restart replay never resolved.
- **warm_audit_lag** — warm admissions recorded but unaudited for
  longer than the lag grace (audit coverage silently drifting behind).
- **warm_divergence** — the auditor's divergence counter moved: the
  incremental admitter disagreed with the full solver (self-repairing,
  but every occurrence must be visible the moment it happens).
- **fleet_starvation** — a tenant's worst virtual queueing delay
  crossed the starvation threshold, or the shared service's queue
  backlog crossed the backlog threshold.
- **pipeline_stall** — the batched dispatcher's async pipeline wedged:
  a device batch has been in flight longer than the pipeline grace
  (dispatched, never drained — a hung tunnel the synchronous pump
  cannot hang on), or a padded shape class keeps co-pending >=2
  tickets per pump without EVER co-batching them (the bucketing that
  justifies the batching's existence is silently not happening).
- **profile_unattributed** — the phase ledger's unattributed gap grew:
  an un-spanned seam appeared on a traced hot path. Baselined and
  evaluated PER TENANT, so a fleet finding names whose path grew it.
- **trace_ring_overflow** — the flight recorder rejected traces since
  arming faster than the overflow threshold: the ring is too small to
  retain the evidence the other monitors point at. Per tenant, like
  the profile meter.
- **devicemem_leak** — a residency-ledger group's OWNER (DeviceCatalog,
  InFlightBatch, ResidentEntry) died while its device buffers stay live
  past the devicemem grace: something else is pinning an evicted
  owner's upload — exactly the leak shape device-resident state can
  introduce, and it now governs ops/resident.py's buffers too.
- **resident_staleness** — a device-resident delta buffer
  (ops/resident.py) whose catalog token no longer matches the newest
  one its facade resolved, lingering past a sim grace: device bytes
  encode an older catalog epoch than the store serves. The serving path
  cannot hand them out (upload() re-keys on token mismatch), so a
  persistent stale entry is held HBM plus a latent-bug signal — the
  refresh that should have re-seeded it never ran.
- **delta_staleness** — a delta-plane memo entry (ops/delta.py) that
  reached its audit cadence and never received a fresh confirm,
  lingering past a sim grace: `serve()` already refuses it, so a
  persistent stale entry means the owning loop stopped closing its
  serve-and-verify audit contract — the recompute that should have
  confirmed (or diverged) the shortcut never ran. Pre-arm residue is
  excluded and the window is ClockJump-absorbed like every other stamp.
- **optimizer_divergence** — the global disruption optimizer's exact
  verification keeps REJECTING the relaxation ranking's picks: a
  tenant's consecutive-reject streak (optimizer/stats.py, reset by any
  accept) crossed the divergence threshold. Every executed disruption
  still passes a real `Solver.solve()` — the invariant polices wasted
  exact solves and a scoring model that has drifted from solve
  semantics, not correctness.
- **overload_unbounded** — an open-loop tenant's waiting-pod depth
  (pending + deferred, loadgen/source.py) sits ABOVE the admission
  controller's shed budget and is still not shrinking (or its oldest
  parked arrival keeps aging) after the overload grace: admission
  control should have engaged and bounded the queue — with shedding
  armed this can never fire (the budgets hold by construction), with
  shedding disabled it is the page that says overload is degrading
  unboundedly instead of predictably.

Cost discipline: the claim watchlist is maintained from the store's
watch feed (O(delta) per event, settled claims leave the list), the
meters are counter deltas, and the cloud sweep is bounded by live
instances on a slower cadence — one rate-limited `tick()` per engine
tick is a single float compare when nothing is due. Findings are
severity-ranked and EDGE-TRIGGERED per (invariant, key): one finding
per excursion, re-armed when the condition clears. Each firing meters
`watchdog_findings_total{invariant,severity}`, lands a
`watchdog.finding` marker trace in the flight-recorder ring (works with
tracing disabled — the ring accepts direct offers), and is readable at
`/debug/watchdog` (weakref route). The watchdog also registers a
readiness probe: a critical verdict flips `/readyz` to 503.

Sim-clock jumps (chaos `ClockJump` rules) are absorbed: a tick that
observes time advancing far beyond the tick cadence shifts every
tracked timestamp by the jump, so skew cannot age a healthy launch into
a fake leak — the zero-false-positive contract over the existing chaos
catalogs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .exposition import register_debug_route, register_readiness
from .tracer import TRACER, Span, Trace

# the taxonomy `make obs-audit` enforces negative coverage for: every
# name here must be tripped by a seeded fault in tests/test_watchdog.py
INVARIANTS: Tuple[str, ...] = (
    "claim_leak",
    "store_cloud_drift",
    "intent_age",
    "warm_audit_lag",
    "warm_divergence",
    "fleet_starvation",
    "pipeline_stall",
    "profile_unattributed",
    "trace_ring_overflow",
    "devicemem_leak",
    "resident_staleness",
    "delta_staleness",
    "overload_unbounded",
    "optimizer_divergence",
    "integrity_breach",
    "recompute_runaway",
    "federation_degraded",
    "federation_rejoin",
)

SEVERITIES = ("info", "warning", "critical")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

# substrings of end-of-run violation texts -> the invariant that should
# have seen the condition live (the runners' "watchdog found it first"
# cross-check); unmapped violations have no online monitor (yet)
_VIOLATION_MAP: Tuple[Tuple[str, str], ...] = (
    ("leaked", "claim_leak"),
    ("stuck in phase", "claim_leak"),
    ("still draining", "claim_leak"),
    ("duplicate launch", "claim_leak"),
    ("backs a dead instance", "store_cloud_drift"),
    ("orphaned", "store_cloud_drift"),
    ("intent(s) still open", "intent_age"),
    ("auditor diverged", "warm_divergence"),
    ("unbounded backlog", "overload_unbounded"),
    ("integrity violation", "integrity_breach"),
    ("wire failure", "federation_degraded"),
    ("stuck degraded", "federation_rejoin"),
)


@dataclass
class Finding:
    invariant: str
    severity: str
    key: str                  # the offending object (claim/tenant/...)
    message: str
    at: float                 # sim time of first detection
    attrs: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"invariant": self.invariant, "severity": self.severity,
                "key": self.key, "message": self.message,
                "at": round(self.at, 3), "attrs": dict(self.attrs)}


class Watchdog:
    """One watchdog per control plane (or per shared fleet service).

    Pass whichever subsystems exist: `store`+`cloud` enable the leak and
    drift monitors, `journal` the intent-age monitor, `warmpath` the
    audit-lag/divergence monitors, `service` the fleet monitors. The
    profile/trace meters are process-global and always on (baselined at
    `arm()` so another run's residue never counts against this one).
    """

    INTERVAL = 5.0            # sim seconds between evaluations
    CLOUD_SWEEP = 30.0        # sim seconds between cloud drift sweeps
    CLAIM_GRACE = 900.0       # launching/draining age before a leak fires
    DRIFT_GRACE = 300.0       # store<->cloud disagreement age
    ORPHAN_GRACE = 900.0      # untracked tagged instance age (> GC sweep)
    AUDIT_LAG_GRACE = 120.0   # recorded-but-unaudited warm batch age
    STARVATION_S = 1.0        # virtual queueing delay (seconds)
    BACKLOG_MAX = 64          # queued tickets in the shared service
    PIPELINE_GRACE = 30.0     # sim seconds a batch may stay in flight
    COBATCH_MIN_PUMPS = 3     # co-pending pumps before a never-co-batched
    #                           shape class counts as a stall
    UNATTRIBUTED_MS = 5.0     # ledger gap growth per excursion
    RING_DROPS = 64           # recorder rejections since arm
    DEVICEMEM_GRACE = 120.0   # orphaned device buffers' age before a leak
    RESIDENT_GRACE = 900.0    # stale resident-state age before a finding
    #                           (generous: a healthy view refreshes at its
    #                           next solve — only a view that NEVER
    #                           refreshes after an epoch bump should fire)
    DELTA_GRACE = 900.0       # audit-due delta-memo age before a finding
    #                           (generous for the same reason: a healthy
    #                           loop closes the audit at its very next
    #                           pass — only a key whose owner stopped
    #                           confirming should fire)
    OVERLOAD_GRACE = 45.0     # sim seconds a tenant's waiting depth may
    #                           sit above the admission budget before a
    #                           still-growing backlog counts as unbounded
    OPTIMIZER_STREAK = 12     # consecutive exact-verify rejects of the
    #                           optimizer's ranked subsets before the
    #                           relaxation scoring counts as diverged —
    #                           deliberately ABOVE one pass's
    #                           VERIFY_LIMIT (8): a single unlucky
    #                           all-reject pass is the over-approximation
    #                           doing its job; persisting across passes
    #                           on CHANGED state (unchanged state skips
    #                           the search entirely) is the divergence
    RECOMPUTE_FRAC = 0.9      # a stage's redundant work fraction above
    #                           this arms a runaway excursion
    RECOMPUTE_GRACE = 900.0   # sim seconds the fraction may sit above
    #                           RECOMPUTE_FRAC before a STILL-RISING
    #                           fraction fires (a steady warm cluster
    #                           legitimately plateaus high — only
    #                           unbounded growth is the runaway)
    RECOMPUTE_RISE = 0.005    # the fraction must have risen by at least
    #                           this much over the grace window to count
    #                           as rising, not noise
    RECOMPUTE_MIN_UNITS = 256  # classified units (since arm) a stage
    #                           needs before its fraction is meaningful
    REJOIN_GRACE = 60.0       # sim seconds the federation wire may sit
    #                           degraded WITH passing healthz probes
    #                           before the recovery ladder counts as
    #                           stuck (probes failing = the server is
    #                           genuinely down, and degraded is the
    #                           correct steady state — only "healthy
    #                           but never rejoined" is the bug)
    JUMP_THRESHOLD = 60.0     # dt above this is a clock jump, not aging
    MAX_FINDINGS = 256        # bounded finding log

    def __init__(self, clock, store=None, cloud=None, journal=None,
                 warmpath=None, service=None, loadgen=None,
                 interval: Optional[float] = None,
                 claim_grace: Optional[float] = None,
                 drift_grace: Optional[float] = None,
                 audit_lag_grace: Optional[float] = None,
                 starvation_s: Optional[float] = None,
                 backlog_max: Optional[int] = None,
                 pipeline_grace: Optional[float] = None,
                 overload_grace: Optional[float] = None):
        self.clock = clock
        self.store = store
        self.cloud = cloud
        self.journal = journal
        self.warmpath = warmpath
        self.service = service
        # loadgen observable: an object with overload_state() ->
        # {tenant: {depth, oldest_age_s, budget, armed}} (the SoakRunner
        # or a single OpenLoopSource-compatible shim)
        self.loadgen = loadgen
        self.interval = self.INTERVAL if interval is None else interval
        self.claim_grace = (self.CLAIM_GRACE if claim_grace is None
                            else claim_grace)
        self.drift_grace = (self.DRIFT_GRACE if drift_grace is None
                            else drift_grace)
        self.audit_lag_grace = (self.AUDIT_LAG_GRACE
                                if audit_lag_grace is None
                                else audit_lag_grace)
        self.starvation_s = (self.STARVATION_S if starvation_s is None
                             else starvation_s)
        self.backlog_max = (self.BACKLOG_MAX if backlog_max is None
                            else int(backlog_max))
        self.pipeline_grace = (self.PIPELINE_GRACE if pipeline_grace is None
                               else float(pipeline_grace))
        self.overload_grace = (self.OVERLOAD_GRACE if overload_grace is None
                               else float(overload_grace))
        self._lock = threading.Lock()
        self.findings: List[Finding] = []
        # ACTIVE excursions: (invariant, key) -> severity. The verdict
        # derives from this map, never from the bounded findings log —
        # trimming old log entries must not amnesty a live violation
        self._active: Dict[Tuple[str, str], str] = {}
        self._fired: Dict[str, int] = {}     # invariant -> lifetime count
        self._claims: Dict[str, float] = {}  # in-transition claim -> since
        self._drift: Dict[str, float] = {}   # drift key -> first seen
        # (auditor.pending_since value, watchdog clock when first seen):
        # the lag is measured on the WATCHDOG's observation clock so a
        # chaos ClockJump can be absorbed like every other stamp
        self._audit_pending: Optional[Tuple[float, float]] = None
        self._last_tick: Optional[float] = None
        self._last_sweep: Optional[float] = None
        self.armed = False
        self.stats = {"ticks": 0, "evals": 0, "findings": 0,
                      "jump_absorbed": 0}
        # meter baselines (set at arm): deltas, never process totals.
        # PER TENANT for the process-global ring/ledger meters, so a
        # fleet finding attributes to the tenant whose path regressed
        self._base_dropped: Dict[str, int] = {}
        self._base_unattr: Dict[str, float] = {}
        self._base_div = 0.0
        # devicemem orphans: group-id -> first-seen (watchdog clock);
        # groups already orphaned at arm are another run's residue and
        # never fire here (zero-false-positive contract)
        self._devmem: Dict[int, float] = {}
        self._devmem_base: frozenset = frozenset()
        # resident-state staleness: entry key -> first-seen (watchdog
        # clock); stale at arm = another run's residue, excluded
        self._resident: Dict[tuple, float] = {}
        self._resident_base: frozenset = frozenset()
        # delta-memo staleness: internal memo key -> first-seen
        # (watchdog clock); audit-due at arm = another run's residue
        self._delta_stale: Dict[tuple, float] = {}
        self._delta_base: frozenset = frozenset()
        # overload excursions: tenant -> (first-seen-over-budget stamp on
        # the watchdog clock, depth at first sight) — jump-absorbed like
        # every other window
        self._overload: Dict[str, Tuple[float, int]] = {}
        # optimizer divergence: per-tenant reject-streak baseline at arm
        # (pre-arm residue from another run never counts here)
        self._optimizer_base: Dict[str, int] = {}
        # integrity breaches: per-tenant violation-counter baseline at
        # arm — counter-delta based like the optimizer monitor, so
        # another run's violations never page this one
        self._integrity_base: Dict[str, int] = {}
        # recompute runaway: stage -> (first-seen-above-frac stamp on the
        # watchdog clock, redundant fraction at that stamp); unit
        # baselines at arm so another run's classified work never counts
        self._recompute: Dict[str, Tuple[float, float]] = {}
        self._recompute_base: Dict[str, Dict[str, int]] = {}

    # --- arming -----------------------------------------------------------
    def arm(self, now: Optional[float] = None) -> "Watchdog":
        """Subscribe to the store watch feed, baseline the meters, and
        register the debug route + readiness probe. Idempotent."""
        if self.armed:
            return self
        self.armed = True
        now = float(self.clock.now()) if now is None else float(now)
        self._last_tick = now
        if self.store is not None:
            self.store.watch("nodeclaim", self._on_claim_event)
            # seed the watchlist with claims that predate arming (a
            # restarted watchdog must still see the adopted fleet)
            for nc in self.store.nodeclaims.values():
                if not self._settled(nc):
                    self._claims[nc.name] = now
        from .devicemem import DEVICEMEM
        from .profile import LEDGER
        self._base_dropped = dict(getattr(TRACER.recorder,
                                          "dropped_by_tenant", {}))
        self._base_unattr = dict(LEDGER.unattributed_by_tenant())
        self._base_div = (float(self.warmpath.stats.get("divergences", 0))
                          if self.warmpath is not None else 0.0)
        self._devmem_base = frozenset(o["group"]
                                      for o in DEVICEMEM.orphans())
        from ..ops.resident import RESIDENT
        self._resident_base = frozenset(s["key"] for s in RESIDENT.stale())
        from ..ops.delta import DELTA
        self._delta_base = frozenset((st,) + tuple(k)
                                     for st, k, _ in DELTA.stale())
        from ..optimizer.stats import OPTIMIZER
        self._optimizer_base = dict(OPTIMIZER.reject_streaks())
        from ..integrity import INTEGRITY
        self._integrity_base = dict(INTEGRITY.violations_by_tenant())
        from .recompute import RECOMPUTE
        self._recompute_base = RECOMPUTE.stage_units()
        register_debug_route("/debug/watchdog",
                             lambda wd, query: wd.payload(query),
                             owner=self)
        # unique probe name per watchdog: a fleet arms one per shard and
        # /readyz must aggregate every LIVE one (dead refs prune lazily)
        register_readiness(f"watchdog-{id(self):x}",
                           lambda wd: wd.readiness(), owner=self)
        return self

    # --- store feed (O(1) per event) --------------------------------------
    @staticmethod
    def _settled(nc) -> bool:
        from ..models.nodeclaim import Phase
        return (bool(nc.provider_id) and nc.phase == Phase.INITIALIZED
                and not nc.is_deleting())

    def _on_claim_event(self, action: str, nc) -> None:
        if action == "delete":
            self._claims.pop(nc.name, None)
            self._clear("claim_leak", nc.name)  # resolved: re-arm edge
            return
        # add/update/delete-mark: (re)open the transition window — age is
        # measured from the LAST observed transition, not claim birth
        self._claims[nc.name] = float(self.clock.now())

    # --- evaluation -------------------------------------------------------
    def tick(self, now: Optional[float] = None,
             force: bool = False) -> List[Finding]:
        """Rate-limited evaluation; returns the findings fired by THIS
        call. The engine calls this every tick — the common case is one
        float compare and out."""
        if not self.armed:
            return []
        now = float(self.clock.now()) if now is None else float(now)
        self.stats["ticks"] += 1
        last = self._last_tick
        if not force and last is not None and now - last < self.interval:
            return []
        if last is not None and now - last > self.JUMP_THRESHOLD:
            self._absorb_jump(now - last)
        self._last_tick = now
        self.stats["evals"] += 1
        fired: List[Finding] = []
        self._check_claims(now, fired)
        self._check_journal(now, fired)
        self._check_warmpath(now, fired)
        self._check_fleet(now, fired)
        self._check_meters(now, fired)
        self._check_devicemem(now, fired)
        self._check_resident(now, fired)
        self._check_delta(now, fired)
        self._check_overload(now, fired)
        self._check_optimizer(now, fired)
        self._check_integrity(now, fired)
        self._check_recompute(now, fired)
        if self._last_sweep is None or force \
                or now - self._last_sweep >= self.CLOUD_SWEEP:
            self._last_sweep = now
            self._check_cloud(now, fired)
        self._publish_verdict()
        return fired

    def _absorb_jump(self, dt: float) -> None:
        """A clock jump (or a long untick'd stretch) must not age every
        tracked window at once: shift the stamps forward so observed
        ages stay continuous with the tick cadence."""
        shift = dt - self.interval
        self.stats["jump_absorbed"] += 1
        self._claims = {k: v + shift for k, v in self._claims.items()}
        self._drift = {k: v + shift for k, v in self._drift.items()}
        self._devmem = {k: v + shift for k, v in self._devmem.items()}
        self._resident = {k: v + shift for k, v in self._resident.items()}
        self._delta_stale = {k: v + shift
                             for k, v in self._delta_stale.items()}
        self._overload = {k: (t + shift, d)
                          for k, (t, d) in self._overload.items()}
        self._recompute = {k: (t + shift, f)
                           for k, (t, f) in self._recompute.items()}
        if self._audit_pending is not None:
            ps, seen = self._audit_pending
            self._audit_pending = (ps, seen + shift)

    # --- monitors ---------------------------------------------------------
    def _check_claims(self, now: float, fired: List[Finding]) -> None:
        if self.store is None:
            return
        for name in list(self._claims):
            nc = self.store.nodeclaims.get(name)
            if nc is None:
                self._claims.pop(name, None)
                continue
            if self._settled(nc):
                self._claims.pop(name, None)
                self._clear("claim_leak", name)
                continue
            age = now - self._claims[name]
            if age < self.claim_grace:
                continue
            if nc.is_deleting():
                what = "draining"
            elif not nc.provider_id:
                what = "unlaunched"
            else:
                what = f"stuck in phase {nc.phase}"
            self._fire(fired, "claim_leak", "critical", name,
                       f"claim {name} {what} for {age:.0f}s "
                       f"(grace {self.claim_grace:g}s)",
                       now, age_s=round(age, 1))

    def _check_cloud(self, now: float, fired: List[Finding]) -> None:
        """The store<->cloud sweep: bounded by live instances + store
        nodes, run on the slow cadence. Also the token-ledger duplicate
        checks — graceless, a duplicate is never in flight."""
        if self.cloud is None or self.store is None:
            return
        from ..models import labels as L
        insts = getattr(self.cloud, "instances", None)
        if insts is not None:  # in-process cloud: the full state map
            live = {iid: inst for iid, inst in insts.items()
                    if inst.state != "terminated"}
        else:  # wire client (RemoteCloud): one describe per slow sweep;
            # a throttled/unreachable cloud skips this sweep, it never
            # takes the watchdog (or the control plane) down
            from ..cloud.provider import CloudError
            try:
                live = {i.id: i for i in self.cloud.describe()
                        if i.state != "terminated"}
            except CloudError:
                return
        claim_iids = {c.provider_id.rsplit("/", 1)[-1]
                      for c in self.store.nodeclaims.values()
                      if c.provider_id}
        open_tokens: frozenset = frozenset()
        open_claims: frozenset = frozenset()
        if self.journal is not None:
            open_tokens = self.journal.open_tokens()
            open_claims = self.journal.open_claim_names()
        seen: set = set()
        by_token: Dict[str, list] = {}
        by_claim: Dict[str, list] = {}
        for iid, inst in live.items():
            tags = getattr(inst, "tags", None) or {}
            tok = tags.get(L.TAG_LAUNCH_TOKEN)
            claim = tags.get(L.TAG_NODECLAIM)
            if tok:
                by_token.setdefault(tok, []).append(iid)
            if claim:
                by_claim.setdefault(claim, []).append(iid)
            if claim and iid not in claim_iids:
                if tok in open_tokens or claim in open_claims:
                    continue  # an open intent owns this instance's fate
                key = f"orphan/{iid}"
                seen.add(key)
                first = self._drift.setdefault(key, now)
                if now - first >= self.ORPHAN_GRACE:
                    self._fire(fired, "store_cloud_drift", "critical", key,
                               f"instance {iid} karpenter-tagged but no "
                               f"claim tracks it for {now - first:.0f}s",
                               now, age_s=round(now - first, 1))
        dup_seen: set = set()
        for tok, iids in by_token.items():
            if len(iids) > 1:
                key = f"token/{tok[:12]}"
                dup_seen.add(key)
                self._fire(fired, "claim_leak", "critical", key,
                           f"idempotency token minted {len(iids)} live "
                           f"instances: {sorted(iids)[:3]}", now)
        for claim, iids in by_claim.items():
            if len(iids) > 1:
                key = f"dup-claim/{claim}"
                dup_seen.add(key)
                self._fire(fired, "claim_leak", "critical", key,
                           f"claim {claim} backed by {len(iids)} live "
                           f"instances: {sorted(iids)[:3]}", now)
        # a resolved duplicate (one copy terminated) clears its
        # excursion — the verdict must not read critical forever
        for inv, key in list(self._active):
            if inv == "claim_leak" and (key.startswith("token/")
                                        or key.startswith("dup-claim/")) \
                    and key not in dup_seen:
                self._clear(inv, key)
        for node in self.store.nodes.values():
            iid = node.provider_id.rsplit("/", 1)[-1]
            if iid in live:
                continue
            key = f"deadnode/{node.name}"
            seen.add(key)
            first = self._drift.setdefault(key, now)
            if now - first >= self.drift_grace:
                self._fire(fired, "store_cloud_drift", "critical", key,
                           f"store node {node.name} backs dead instance "
                           f"{iid} for {now - first:.0f}s", now,
                           age_s=round(now - first, 1))
        for key in list(self._drift):
            if key not in seen:   # condition cleared: re-arm the edge
                self._drift.pop(key, None)
                self._clear("store_cloud_drift", key)

    def _check_journal(self, now: float, fired: List[Finding]) -> None:
        if self.journal is None:
            return
        from ..controllers.gc import INTENT_GRACE
        open_names = set()
        for intent in self.journal.open_intents():
            age = now - intent.created_at
            if age < INTENT_GRACE:
                continue
            key = f"intent/{intent.claim_name}#{intent.seq}"
            open_names.add(key)
            self._fire(fired, "intent_age", "critical", key,
                       f"launch intent for {intent.claim_name} open "
                       f"{age:.0f}s (INTENT_GRACE {INTENT_GRACE:g}s) — "
                       f"wedged past the GC shield", now,
                       age_s=round(age, 1))
        for inv, key in list(self._active):
            if inv == "intent_age" and key not in open_names:
                self._clear("intent_age", key)

    def _check_warmpath(self, now: float, fired: List[Finding]) -> None:
        wp = self.warmpath
        if wp is None:
            return
        pending_since = getattr(wp.auditor, "pending_since", None)
        if pending_since is not None:
            # lag on the watchdog's observation clock (first tick that
            # saw THIS pending window), so _absorb_jump covers it —
            # `now - pending_since` would let a ClockJump age a
            # seconds-old batch into a fake finding
            if (self._audit_pending is None
                    or self._audit_pending[0] != pending_since):
                self._audit_pending = (pending_since, now)
            lag = now - self._audit_pending[1]
            if lag >= self.audit_lag_grace:
                self._fire(fired, "warm_audit_lag", "warning", "auditor",
                           f"warm admissions unaudited for {lag:.0f}s "
                           f"(grace {self.audit_lag_grace:g}s)", now,
                           lag_s=round(lag, 1))
        else:
            self._audit_pending = None
            self._clear("warm_audit_lag", "auditor")
        div = float(wp.stats.get("divergences", 0))
        if div > self._base_div:
            latency = (now - self._audit_pending[1]) \
                if self._audit_pending is not None else 0.0
            self._fire(fired, "warm_divergence", "warning",
                       f"div/{int(div)}",
                       f"warm-path audit divergence #{int(div)} "
                       f"(detection latency {latency:.1f}s) — path forced "
                       f"cold", now, divergences=div)
            self._base_div = div

    def _check_fleet(self, now: float, fired: List[Finding]) -> None:
        svc = self.service
        if svc is None:
            return
        backlog = svc.backlog()
        if backlog > self.backlog_max:
            self._fire(fired, "fleet_starvation", "warning", "backlog",
                       f"solver service backlog {backlog} tickets "
                       f"(max {self.backlog_max})", now, backlog=backlog)
        else:
            self._clear("fleet_starvation", "backlog")
        for tenant, state in svc.tenants.items():
            if state.max_wait >= self.starvation_s:
                self._fire(fired, "fleet_starvation", "warning", tenant,
                           f"tenant {tenant} worst virtual queueing delay "
                           f"{state.max_wait * 1e3:.0f}ms this window "
                           f"(threshold {self.starvation_s * 1e3:g}ms)",
                           now, max_wait_ms=round(state.max_wait * 1e3, 1))
            else:
                self._clear("fleet_starvation", tenant)
        self._check_pipeline(now, fired)
        self._check_federation(now, fired)

    def _check_pipeline(self, now: float, fired: List[Finding]) -> None:
        """The batched dispatcher's pipeline invariants (no-op on a
        serial service): a wedged in-flight batch, and a shape class
        whose co-pending tickets never co-batch."""
        svc = self.service
        state_fn = getattr(svc, "pipeline_state", None)
        if state_fn is None or not getattr(svc, "batch", False):
            return
        st = state_fn()
        age = st.get("inflight_age")
        if age is not None and age >= self.pipeline_grace:
            self._fire(fired, "pipeline_stall", "warning", "inflight",
                       f"device batch in flight for {age:.0f}s without a "
                       f"drain (grace {self.pipeline_grace:g}s)", now,
                       age_s=round(age, 1))
        else:
            self._clear("pipeline_stall", "inflight")
        for sc, cs in st.get("classes", {}).items():
            key = f"class/{sc}"
            if (cs.get("copending_pumps", 0) >= self.COBATCH_MIN_PUMPS
                    and cs.get("cobatched_pumps", 0) == 0):
                self._fire(fired, "pipeline_stall", "warning", key,
                           f"shape class {sc} co-pended >=2 tickets in "
                           f"{cs['copending_pumps']} pumps but never "
                           f"co-batched them", now,
                           copending=cs["copending_pumps"])
            else:
                self._clear("pipeline_stall", key)

    def _check_federation(self, now: float,
                          fired: List[Finding]) -> None:
        """The federation plane's degrade ladder, surfaced ONLINE: a
        wire failure arms the client's cooldown, and this fires while
        any cooldown is armed — so the first degraded bucket pages
        before a tenant SLO burns, and the finding clears itself once
        buckets cross the wire again (no-op on in-process services)."""
        svc = self.service
        state_fn = getattr(svc, "federation_state", None)
        if state_fn is None:
            return
        fs = state_fn()
        if fs.get("degraded"):
            self._fire(fired, "federation_degraded", "warning", "wire",
                       f"federated dispatch degraded to the local path: "
                       f"{fs['failures']} wire failure(s), cooldown "
                       f"{fs['cooldown']} bucket(s) remaining "
                       f"(last: {fs['last_error']})", now,
                       failures=fs["failures"], cooldown=fs["cooldown"])
        else:
            self._clear("federation_degraded", "wire")
        # the LADDER's own invariant: degraded past the grace while the
        # healthz probes come back clean means the breaker is stuck —
        # the recovery machinery itself is the bug, not the server
        degraded_for = fs.get("degraded_for", 0.0)
        if (fs.get("degraded") and degraded_for >= self.REJOIN_GRACE
                and fs.get("probe_ok_degraded", 0) > 0):
            self._fire(fired, "federation_rejoin", "warning", "wire",
                       f"federation stuck degraded for "
                       f"{degraded_for:.0f}s (grace "
                       f"{self.REJOIN_GRACE:g}s) despite "
                       f"{fs['probe_ok_degraded']} clean healthz "
                       f"probe(s) — the rejoin ladder is not closing "
                       f"the breaker (state {fs.get('breaker', '?')})",
                       now, degraded_for=round(degraded_for, 1),
                       probes_ok=fs["probe_ok_degraded"],
                       breaker=fs.get("breaker", ""))
        else:
            self._clear("federation_rejoin", "wire")

    def _check_meters(self, now: float, fired: List[Finding]) -> None:
        from .profile import LEDGER
        cur_unattr = LEDGER.unattributed_by_tenant()
        tenant_fired = False
        for tenant, unattr in cur_unattr.items():
            gap = unattr - self._base_unattr.get(tenant, 0.0)
            if gap >= self.UNATTRIBUTED_MS:
                tenant_fired = True
                self._fire(fired, "profile_unattributed", "info",
                           f"ledger/{tenant}",
                           f"phase ledger unattributed gap for tenant "
                           f"{tenant} grew {gap:.1f}ms since last "
                           f"excursion", now, tenant=tenant,
                           gap_ms=round(gap, 3))
                self._base_unattr[tenant] = unattr
        # DIFFUSE growth: many tenants each under the per-tenant
        # threshold must still trip the process-aggregate one — the
        # per-tenant split must never RAISE the effective threshold by
        # the tenant count. Firing advances every baseline, so the same
        # diffuse excursion is counted once.
        agg_gap = sum(cur_unattr.values()) \
            - sum(self._base_unattr.get(t, 0.0) for t in cur_unattr)
        if not tenant_fired and agg_gap >= self.UNATTRIBUTED_MS:
            self._fire(fired, "profile_unattributed", "info", "ledger",
                       f"phase ledger unattributed gap grew "
                       f"{agg_gap:.1f}ms across tenants since last "
                       f"excursion", now, gap_ms=round(agg_gap, 3))
            self._base_unattr.update(cur_unattr)
        drops = dict(getattr(TRACER.recorder, "dropped_by_tenant", {}))
        tenant_fired = False
        for tenant, dropped in drops.items():
            delta = dropped - self._base_dropped.get(tenant, 0)
            if delta >= self.RING_DROPS:
                tenant_fired = True
                self._fire(fired, "trace_ring_overflow", "info",
                           f"ring/{tenant}",
                           f"flight recorder rejected {delta} of tenant "
                           f"{tenant}'s traces since last excursion "
                           f"(ring size {TRACER.recorder.size})",
                           now, tenant=tenant, dropped=delta)
                self._base_dropped[tenant] = dropped
        agg_drop = sum(drops.values()) \
            - sum(self._base_dropped.get(t, 0) for t in drops)
        if not tenant_fired and agg_drop >= self.RING_DROPS:
            self._fire(fired, "trace_ring_overflow", "info", "ring",
                       f"flight recorder rejected {agg_drop} traces "
                       f"across tenants since last excursion (ring "
                       f"size {TRACER.recorder.size})", now,
                       dropped=agg_drop)
            self._base_dropped.update(drops)

    def _check_devicemem(self, now: float, fired: List[Finding]) -> None:
        """Device buffers outliving their owner (residency-ledger
        orphans) past the devicemem grace — aged on the watchdog's
        observation clock like every other window, pre-arm residue
        excluded."""
        from .devicemem import DEVICEMEM
        seen: set = set()
        for o in DEVICEMEM.orphans():
            gid = o["group"]
            if gid in self._devmem_base:
                continue
            seen.add(gid)
            first = self._devmem.setdefault(gid, now)
            age = now - first
            if age < self.DEVICEMEM_GRACE:
                continue
            self._fire(fired, "devicemem_leak", "warning",
                       f"group/{gid}",
                       f"{o['bytes']} device bytes ({o['kind']}"
                       f"{', token ' + o['token'] if o['token'] else ''}) "
                       f"outlive their dead owner for {age:.0f}s "
                       f"(grace {self.DEVICEMEM_GRACE:g}s)", now,
                       tenant=o.get("tenant"), kind=o["kind"],
                       leaked_bytes=o["bytes"], age_s=round(age, 1))
        for gid in list(self._devmem):
            if gid not in seen:   # buffers finally freed: re-arm edge
                self._devmem.pop(gid, None)
                self._clear("devicemem_leak", f"group/{gid}")

    def _check_resident(self, now: float, fired: List[Finding]) -> None:
        """Device-resident delta buffers whose catalog token the world
        moved past (ops/resident.RESIDENT.stale()) — aged on the
        watchdog's observation clock, jump-absorbed, pre-arm residue
        excluded. A healthy view clears itself: its next solve re-keys
        the entry (full re-upload) or an invalidation drops it."""
        from ..ops.resident import RESIDENT
        seen: set = set()
        for s in RESIDENT.stale():
            key = s["key"]
            if key in self._resident_base:
                continue
            seen.add(key)
            first = self._resident.setdefault(key, now)
            age = now - first
            if age < self.RESIDENT_GRACE:
                continue
            kstr = "/".join(str(t) for t in key)
            self._fire(fired, "resident_staleness", "warning",
                       f"view/{kstr}",
                       f"resident buffer {kstr} encodes catalog token "
                       f"{s['token']} but the store serves {s['base']} — "
                       f"stale for {age:.0f}s "
                       f"(grace {self.RESIDENT_GRACE:g}s)", now,
                       age_s=round(age, 1))
        for key in list(self._resident):
            if key not in seen:   # refreshed or invalidated: re-arm edge
                self._resident.pop(key, None)
                kstr = "/".join(str(t) for t in key)
                self._clear("resident_staleness", f"view/{kstr}")

    def _check_delta(self, now: float, fired: List[Finding]) -> None:
        """Delta-plane memo entries stuck at audit-due
        (ops/delta.DELTA.stale()) — serve() refuses them, so the entry
        costs nothing to correctness, but a lingering one means its
        owning loop stopped running the fresh confirm/diverge pass the
        serve-and-verify contract promises. Aged on the watchdog's
        observation clock, jump-absorbed, pre-arm residue excluded. A
        healthy key clears itself: the owner's next pass confirms (the
        counter resets) or diverges (the entry drops)."""
        from ..ops.delta import DELTA
        seen: set = set()
        for stage, key, since in DELTA.stale():
            ik = (stage,) + tuple(key)
            if ik in self._delta_base:
                continue
            seen.add(ik)
            first = self._delta_stale.setdefault(ik, now)
            age = now - first
            if age < self.DELTA_GRACE:
                continue
            kstr = "/".join(str(t) for t in ik)
            self._fire(fired, "delta_staleness", "warning",
                       f"memo/{kstr}",
                       f"delta memo {kstr} audit-due for {since} serves "
                       f"and unconfirmed for {age:.0f}s "
                       f"(grace {self.DELTA_GRACE:g}s)", now,
                       stage=stage, since_confirm=int(since),
                       age_s=round(age, 1))
        for ik in list(self._delta_stale):
            if ik not in seen:   # confirmed, diverged, or evicted
                self._delta_stale.pop(ik, None)
                kstr = "/".join(str(t) for t in ik)
                self._clear("delta_staleness", f"memo/{kstr}")

    def _check_overload(self, now: float, fired: List[Finding]) -> None:
        """An open-loop tenant's waiting-pod depth above the admission
        budget and still not shrinking (or its oldest parked arrival
        still aging) past the overload grace — admission control should
        have engaged. Aged on the watchdog's observation clock so a
        chaos ClockJump cannot turn one slow window into a finding."""
        lg = self.loadgen
        if lg is None:
            return
        state = lg.overload_state() or {}
        over: set = set()
        for tenant, row in state.items():
            depth = int(row.get("depth", 0))
            budget = int(row.get("budget", 0) or 0)
            if budget <= 0 or depth <= budget:
                continue
            over.add(tenant)
            first = self._overload.get(tenant)
            if first is None:
                self._overload[tenant] = (now, depth)
                continue
            t0, d0 = first
            age = now - t0
            if age < self.overload_grace:
                continue
            oldest = float(row.get("oldest_age_s", 0.0))
            if depth >= d0 or oldest >= self.overload_grace:
                self._fire(fired, "overload_unbounded", "critical", tenant,
                           f"tenant {tenant} waiting-pod depth {depth} "
                           f"above the admission budget {budget} and not "
                           f"shrinking for {age:.0f}s (grace "
                           f"{self.overload_grace:g}s; shedding "
                           f"{'armed' if row.get('armed') else 'DISABLED'})",
                           now, tenant=tenant, depth=depth, budget=budget,
                           age_s=round(age, 1),
                           oldest_age_s=round(oldest, 1),
                           armed=bool(row.get("armed")))
        for tenant in list(self._overload):
            if tenant not in over:   # backlog back under budget: re-arm
                self._overload.pop(tenant, None)
                self._clear("overload_unbounded", tenant)

    def _check_optimizer(self, now: float, fired: List[Finding]) -> None:
        """The global disruption optimizer's exact-verify contract as a
        quality monitor: a tenant whose consecutive-reject streak (the
        relaxation ranking proposing, Solver.solve() refusing) grew past
        the divergence threshold since arm fires a warning; any accept
        resets the streak and clears the excursion. Counter-delta based
        like the ring/ledger meters — no clock window to jump-absorb."""
        from ..optimizer.stats import OPTIMIZER
        streaks = OPTIMIZER.reject_streaks()
        for tenant, streak in streaks.items():
            delta = streak - self._optimizer_base.get(tenant, 0)
            if delta >= self.OPTIMIZER_STREAK:
                self._fire(fired, "optimizer_divergence", "warning",
                           tenant,
                           f"tenant {tenant}: {delta} consecutive "
                           f"optimizer subsets rejected by exact "
                           f"verification (threshold "
                           f"{self.OPTIMIZER_STREAK}) — relaxation "
                           f"scoring has diverged from solve semantics",
                           now, tenant=tenant, streak=streak)
            else:
                self._clear("optimizer_divergence", tenant)
                # a cleared excursion re-baselines: the NEXT divergence
                # is a fresh streak, not the old one plus noise
                if streak == 0:
                    self._optimizer_base.pop(tenant, None)

    def _check_integrity(self, now: float, fired: List[Finding]) -> None:
        """The solution-integrity plane's violation counters as a page:
        a tenant whose oracle/canary/resident-audit violation count
        advanced since arm fires a critical finding (an answer the
        system was about to ship was provably wrong — the recovery path
        contains it, the page says it happened). Counter-delta based;
        the excursion clears once no new violations arrive and every
        past one was recovered (the host re-solve passed the oracle) —
        an UNRECOVERED violation holds the verdict critical."""
        from ..integrity import INTEGRITY
        cur = INTEGRITY.violations_by_tenant()
        for tenant, count in cur.items():
            delta = count - self._integrity_base.get(tenant, 0)
            if delta > 0:
                self._fire(fired, "integrity_breach", "critical", tenant,
                           f"tenant {tenant}: {delta} solution-integrity "
                           f"violation(s) since the last excursion — a "
                           f"device-path answer failed the feasibility "
                           f"oracle / canary / resident audit", now,
                           tenant=tenant, violations=delta)
                self._integrity_base[tenant] = count
            elif INTEGRITY.unrecovered(tenant) == 0:
                self._clear("integrity_breach", tenant)

    def _check_recompute(self, now: float, fired: List[Finding]) -> None:
        """A recompute-taxonomy stage whose REDUNDANT work fraction sits
        above RECOMPUTE_FRAC and is still RISING past the grace window —
        the stage is grinding identical inputs every reconcile and no
        memo/cache/residency layer is serving the delta. A warm steady
        cluster legitimately plateaus high (that plateau IS the measured
        headroom, not a fault), so a steady fraction never fires: only
        growth beyond RECOMPUTE_RISE over the grace does. Unit counts
        baseline at arm (another run's classified residue never counts)
        and the excursion stamp is jump-absorbed like every window."""
        from .recompute import RECOMPUTE
        units = RECOMPUTE.stage_units()
        for stage, row in units.items():
            base = self._recompute_base.get(stage, {})
            total = red = 0
            for outcome, n in row.items():
                d = n - base.get(outcome, 0)
                total += d
                if outcome == "redundant":
                    red += d
            if total < self.RECOMPUTE_MIN_UNITS:
                continue
            frac = red / total
            if frac <= self.RECOMPUTE_FRAC:
                self._recompute.pop(stage, None)
                self._clear("recompute_runaway", stage)
                continue
            first = self._recompute.get(stage)
            if first is None:
                self._recompute[stage] = (now, frac)
                continue
            t0, f0 = first
            age = now - t0
            if age >= self.RECOMPUTE_GRACE and frac > f0 + self.RECOMPUTE_RISE:
                self._fire(fired, "recompute_runaway", "warning", stage,
                           f"stage {stage}: redundant work fraction "
                           f"{frac:.3f} above {self.RECOMPUTE_FRAC:g} and "
                           f"still rising (was {f0:.3f} {age:.0f}s ago, "
                           f"grace {self.RECOMPUTE_GRACE:g}s) over "
                           f"{total} classified units — the stage "
                           f"recomputes unchanged inputs every pass and "
                           f"nothing serves the delta", now,
                           stage=stage, frac=round(frac, 4),
                           first_frac=round(f0, 4), units=total,
                           age_s=round(age, 1))

    # --- firing / clearing ------------------------------------------------
    def _fire(self, fired: List[Finding], invariant: str, severity: str,
              key: str, message: str, now: float, **attrs) -> None:
        edge = (invariant, key)
        with self._lock:
            if edge in self._active:
                return
            self._active[edge] = severity
            f = Finding(invariant=invariant, severity=severity, key=key,
                        message=message, at=now, attrs=attrs)
            self.findings.append(f)
            if len(self.findings) > self.MAX_FINDINGS:
                del self.findings[:len(self.findings) - self.MAX_FINDINGS]
            self._fired[invariant] = self._fired.get(invariant, 0) + 1
            self.stats["findings"] += 1
        fired.append(f)
        from ..metrics import WATCHDOG_FINDINGS
        tenant = attrs.get("tenant")
        if tenant:
            # a finding about a SPECIFIC tenant's meter attributes to
            # that tenant even when the ticking thread is unscoped (a
            # service-level watchdog watching process-global meters)
            WATCHDOG_FINDINGS.inc(invariant=invariant, severity=severity,
                                  tenant=str(tenant))
        else:
            WATCHDOG_FINDINGS.inc(invariant=invariant, severity=severity)
        self._flight_record(f)

    def _clear(self, invariant: str, key: str) -> None:
        with self._lock:
            self._active.pop((invariant, key), None)

    def _flight_record(self, f: Finding) -> None:
        marker = Span(name="watchdog.finding",
                      trace_id=f"watchdog-{f.invariant}-{f.key}-"
                               f"{int(f.at)}",
                      span_id=0, parent_id=None, t0=0.0, t1=1e-6,
                      ts=f.at, attrs=f.to_dict())
        # meter=False: a rejected self-marker must not count toward the
        # overflow meter the watchdog itself reads (findings would
        # manufacture findings) nor export as a tenant's drop
        TRACER.recorder.offer(Trace(trace_id=marker.trace_id,
                                    spans=[marker]), meter=False)

    # --- read side --------------------------------------------------------
    def fired(self, invariant: str) -> int:
        """Lifetime finding count for one invariant (the runners'
        found-it-first cross-check reads this)."""
        return self._fired.get(invariant, 0)

    def findings_at_least(self, severity: str = "warning") -> int:
        rank = _SEV_RANK[severity]
        with self._lock:
            return sum(1 for f in self.findings
                       if _SEV_RANK[f.severity] >= rank)

    def verdict(self) -> str:
        """Worst severity among ACTIVE excursions: 'ok', 'warning', or
        'critical' — the readiness signal. Reads the excursion map, not
        the bounded findings log: trimming old log entries must never
        amnesty a live violation."""
        with self._lock:
            worst = max((_SEV_RANK[s] for s in self._active.values()),
                        default=-1)
        if worst < 0:
            return "ok"
        return SEVERITIES[worst]

    def readiness(self) -> Tuple[bool, dict]:
        v = self.verdict()
        return v != "critical", {"verdict": v,
                                 "active": len(self._active),
                                 "findings": self.stats["findings"]}

    def _publish_verdict(self) -> None:
        from ..metrics import WATCHDOG_VERDICT
        WATCHDOG_VERDICT.set(float(_SEV_RANK.get(self.verdict(), 0)))

    def cross_check(self, violations: List[str]) -> List[str]:
        """The end-of-run asserts as 'watchdog found it first' checks:
        every violation with an online monitor must have fired a finding
        during (or at the end of) the run; a miss is a watchdog blind
        spot — itself a violation of the verification plane."""
        blind: List[str] = []
        missed: set = set()
        for v in violations:
            for needle, invariant in _VIOLATION_MAP:
                if needle in v and not self.fired(invariant):
                    missed.add((invariant, needle))
        for invariant, needle in sorted(missed):
            blind.append(f"watchdog blind spot: end-of-run '{needle}' "
                         f"violation but the {invariant} monitor never "
                         f"fired")
        return blind

    def payload(self, query: str = "") -> dict:
        with self._lock:
            findings = [f.to_dict() for f in self.findings]
        findings.sort(key=lambda f: (-_SEV_RANK[f["severity"]], -f["at"]))
        return {"armed": self.armed,
                "verdict": self.verdict(),
                "invariants": list(INVARIANTS),
                "interval_s": self.interval,
                "graces": {"claim_s": self.claim_grace,
                           "drift_s": self.drift_grace,
                           "orphan_s": self.ORPHAN_GRACE,
                           "audit_lag_s": self.audit_lag_grace,
                           "starvation_s": self.starvation_s,
                           "backlog_max": self.backlog_max,
                           "pipeline_s": self.pipeline_grace,
                           "devicemem_s": self.DEVICEMEM_GRACE,
                           "resident_s": self.RESIDENT_GRACE,
                           "delta_s": self.DELTA_GRACE,
                           "overload_s": self.overload_grace,
                           "optimizer_streak": self.OPTIMIZER_STREAK,
                           "recompute_s": self.RECOMPUTE_GRACE,
                           "recompute_frac": self.RECOMPUTE_FRAC},
                "stats": dict(self.stats),
                "fired": dict(self._fired),
                "watchlist": {"claims": len(self._claims),
                              "drift": len(self._drift)},
                "findings": findings}
