"""TPU solver kernels: tensor encoding, feasibility, bin-pack, consolidation."""

from .encode import (CatalogTensors, EncodedPods, PodGroup, compat_mask,
                     encode_catalog, encode_pods, group_pods)

__all__ = ["CatalogTensors", "EncodedPods", "PodGroup", "compat_mask",
           "encode_catalog", "encode_pods", "group_pods"]
