"""Zone-level required pod (anti-)affinity as allow_zone mask surgery.

The reference's core scheduler evaluates full k8s inter-pod (anti-)affinity
inside its per-node simulation loop (website/content/en/docs/concepts/
scheduling.md — "podAffinity/podAntiAffinity"; hostname-level terms are
handled by encode.build_conflicts + per-node caps). Zone-topology terms
couple placements through the *zone* axis instead of the node axis, so the
TPU-first lowering is a host-side pre-pass that rewrites each group's
allow_zone mask before the kernels run — the kernels never see affinity,
only zone masks:

  Positive zone affinity (anti=False, required, topology_key=zone):
    - zones already hosting a matching resident pod restrict allow_zone
      (k8s: the pod may only land in a topology domain with a match);
    - when the only matches arrive in the same solve (other incoming
      groups), the group and its targets are co-pinned to one common
      feasible zone — sound (constraint guaranteed) though narrower than
      k8s's sequential scheduler, which could use several zones;
    - a self-matching group with no other match anywhere bootstraps pinned
      to a single zone: k8s's first-pod special case places pod 1 anywhere
      and every later pod must join its domain, which at group granularity
      is exactly "all in one zone";
    - no match anywhere and no self-match → unschedulable (k8s rejects).

  Zone anti-affinity (anti=True, required, topology_key=zone):
    - zones hosting a conflicting resident are removed (both directions:
      the resident's own zone-anti terms repel the group symmetrically,
      matching k8s's symmetric enforcement);
    - mutually-conflicting incoming groups are greedily pinned to disjoint
      zones in group (FFD) order — disjoint masks are the only way a
      deferred-zone solver can *guarantee* the constraint;
    - a self-conflicting group (own selector matches own labels — max one
      pod per zone) splits into one-pod-per-zone subgroups across its
      feasible zones; excess pods become an all-False-zone subgroup, which
      every backend reports unschedulable.

Runs before split_spread_groups (spread then balances within the surviving
zones). Group splits here reference the SAME PodGroup object from multiple
rows; facade._decode draws disjoint pod slices per row by sharing one
cursor per PodGroup.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models import labels as L
from ..models.pod import Pod, PodAffinityTerm
from ..models.pod import term_selects as _selects
from .encode import (CatalogTensors, EncodedPods, TermMatcher,
                     build_conflicts, feasible_zones)

Occupancy = Sequence[Tuple[Optional[str], Sequence[Pod]]]


def _zone_terms(rep: Pod, anti: bool) -> List[PodAffinityTerm]:
    return [t for t in rep.affinity_terms
            if t.anti == anti and t.required and t.topology_key == L.ZONE]


class _OccupancyIndex:
    """Zone-scattering wrapper over the shared columnar TermMatcher
    (ops/encode.py — THE vectorized term_selects): the cluster's
    resident pods flatten once into matcher columns + a zone index, and
    each (namespace, selector) term resolves to the zones holding ≥1
    match, memoized per distinct term. At c8 scale (thousands of
    residents × a handful of terms) this replaces the
    O(pods × groups × terms) Python quadruple loop that dominated the
    affinity pre-pass."""

    def __init__(self, occupancy: Occupancy, zidx: Dict[str, int], Z: int):
        pods: List[Pod] = []
        zones: List[int] = []
        for zone, pods_on in occupancy:
            zi = zidx.get(zone or "")
            if zi is None or not pods_on:
                continue
            pods.extend(pods_on)
            zones.extend([zi] * len(pods_on))
        self.pods = pods
        self.Z = Z
        self.zone = np.asarray(zones, np.int32) if pods else \
            np.zeros(0, np.int32)
        self._matcher = TermMatcher(pods)
        self._zmemo: Dict[tuple, np.ndarray] = {}

    def zones_matching(self, term: PodAffinityTerm,
                       namespace: str) -> Optional[np.ndarray]:
        """bool [Z] zones holding ≥1 resident the term selects from
        `namespace` (term_selects semantics), or None when no resident
        matches anywhere."""
        if not self.pods:
            return None
        key = (namespace, tuple(sorted(term.label_selector.items())))
        hit = self._zmemo.get(key)
        if hit is not None:
            return hit if hit.any() else None
        m = self._matcher.matches(namespace, term.label_selector)
        out = np.zeros(self.Z, bool)
        if m.any():
            out[np.unique(self.zone[m])] = True
        self._zmemo[key] = out
        return out if out.any() else None


def apply_zone_affinity(enc: EncodedPods, cat: CatalogTensors,
                        occupancy: Optional[Occupancy] = None,
                        capture: Optional[dict] = None) -> EncodedPods:
    """Rewrite allow_zone for zone-topology (anti-)affinity; split
    self-conflicting groups. Returns enc unchanged when no group carries
    zone terms (the common fast path).

    capture: delta-plane out-param (ops/delta.py) — filled with the
    transformation DESCRIPTOR this pass decided (the _rebuild arguments,
    or a noop sentinel), so an unchanged-input pass can replay it
    against a future enc via `replay_zone_affinity` without redoing the
    occupancy matching. Captured arrays are copies: downstream passes
    (preference relaxation) mutate the returned enc's rows in place."""
    G = enc.G
    pos = [_zone_terms(g.representative, anti=False) for g in enc.groups]
    neg = [_zone_terms(g.representative, anti=True) for g in enc.groups]
    # residents' own zone-anti terms repel groups even when the group has
    # no terms of its own, so the fast path must also scan occupancy
    # (once per pod — this runs every solve; the truthiness guard keeps
    # the common no-affinity resident at one attribute read, no list
    # allocation)
    resident_anti = []
    for zone, pods_on in (occupancy or []):
        if zone not in cat.zones:
            continue
        for p in pods_on:
            if not p.affinity_terms:
                continue
            ts = _zone_terms(p, anti=True)
            if ts:
                resident_anti.append((zone, p, ts))
    if not any(pos) and not any(neg) and not resident_anti:
        if capture is not None:
            capture["noop"] = True
        return enc

    allow = enc.allow_zone.copy()
    # affinity decisions are HARD: they must survive the facade's
    # preferred-affinity relaxation, so the zone_hard rows get the same
    # surgery as the working rows
    allow_hard = enc.zone_hard.copy() if enc.zone_hard is not None else None
    zidx = {z: i for i, z in enumerate(cat.zones)}

    def set_row(i: int, mask: np.ndarray) -> None:
        allow[i] = mask
        if allow_hard is not None:
            allow_hard[i] = mask

    def and_row(i: int, mask) -> None:
        allow[i] = allow[i] & mask
        if allow_hard is not None:
            allow_hard[i] = allow_hard[i] & mask

    # --- resident matches per group ---------------------------------------
    # pos_resident[i][k]: bool [Z] zones holding a match for term k (or None
    # when no resident matches that term anywhere). Matching runs through
    # the columnar occupancy index — one interned-label pass per key,
    # memoized per distinct (namespace, selector) term
    pos_resident: List[List[Optional[np.ndarray]]] = [
        [None] * len(ts) for ts in pos]
    anti_resident = np.zeros((G, cat.Z), bool)
    occ = (_OccupancyIndex(occupancy, zidx, cat.Z)
           if occupancy and (any(pos) or any(neg)) else None)
    if occ is not None:
        for i in range(G):
            rep = enc.groups[i].representative
            for k, t in enumerate(pos[i]):
                pos_resident[i][k] = occ.zones_matching(t, rep.namespace)
            for t in neg[i]:
                zs = occ.zones_matching(t, rep.namespace)
                if zs is not None:
                    anti_resident[i] |= zs
    for zone, p, p_terms in resident_anti:
        zi = zidx[zone]
        for i in range(G):
            rep = enc.groups[i].representative
            if any(_selects(t, p.namespace == rep.namespace, rep.labels)
                   for t in p_terms):
                anti_resident[i, zi] = True

    # --- positive terms ----------------------------------------------------
    # union-find for co-pin clusters (group ↔ incoming targets)
    parent = list(range(G))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: int, b: int) -> None:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    must_pin = np.zeros(G, bool)   # group belongs to a co-pin cluster
    initiator = np.zeros(G, bool)  # group carries the positive term
    for i in range(G):
        if not pos[i]:
            continue
        rep = enc.groups[i].representative
        for k, t in enumerate(pos[i]):
            if pos_resident[i][k] is not None:
                and_row(i, pos_resident[i][k])
                continue
            incoming = [j for j in range(G) if j != i and _selects(
                t, enc.groups[j].representative.namespace == rep.namespace,
                enc.groups[j].representative.labels)]
            self_match = _selects(t, True, rep.labels)
            if incoming:
                must_pin[i] = initiator[i] = True
                for j in incoming:
                    must_pin[j] = True
                    union(i, j)
            elif self_match:
                # bootstrap: group colocates with itself in one zone
                must_pin[i] = initiator[i] = True
            else:
                # no match anywhere → unschedulable
                set_row(i, np.zeros(cat.Z, bool))

    # --- anti terms: resident bans ------------------------------------------
    allow &= ~anti_resident
    if allow_hard is not None:
        allow_hard &= ~anti_resident

    # --- co-pin clusters to one common feasible zone -------------------------
    if must_pin.any():
        clusters: Dict[int, List[int]] = {}
        for i in np.flatnonzero(must_pin):
            clusters.setdefault(find(int(i)), []).append(int(i))
        for members in clusters.values():
            common = np.ones(cat.Z, bool)
            for i in members:
                common &= feasible_zones(enc, cat, i, allow[i])
            zs = np.flatnonzero(common)
            if not len(zs) and allow_hard is not None:
                # a soft zone preference must never fail a required
                # affinity: retry the intersection on the hard rows
                common = np.ones(cat.Z, bool)
                for i in members:
                    common &= feasible_zones(enc, cat, i, allow_hard[i])
                zs = np.flatnonzero(common)
            if len(zs):
                pin = np.zeros(cat.Z, bool)
                pin[zs[0]] = True
                for i in members:
                    set_row(i, pin.copy())
            else:
                # no zone serves the whole cluster: the initiating groups
                # cannot satisfy their term; targets keep their own masks
                for i in members:
                    if initiator[i]:
                        set_row(i, np.zeros(cat.Z, bool))

    # --- anti terms: cross-group disjointness + self splits ------------------
    self_anti = np.zeros(G, bool)
    conflict = np.zeros((G, G), bool)
    for i in range(G):
        rep = enc.groups[i].representative
        if any(_selects(t, True, rep.labels) for t in neg[i]):
            self_anti[i] = True
        for j in range(i + 1, G):
            rj = enc.groups[j].representative
            same_ns = rep.namespace == rj.namespace
            if (any(_selects(t, same_ns, rj.labels) for t in neg[i])
                    or any(_selects(t, same_ns, rep.labels) for t in neg[j])):
                conflict[i, j] = conflict[j, i] = True

    # zones each group will occupy (for the greedy disjoint partition);
    # rows [G] of Optional[bool [Z]]
    claimed: List[Optional[np.ndarray]] = [None] * G
    # groups the positive pass (or resident restrictions) already pinned to
    # a single zone claim it up front, so the greedy routes their conflict
    # partners around them regardless of processing order. Two conflicting
    # groups both pre-pinned to the SAME zone cannot coexist — the later
    # one goes unschedulable rather than silently violating the term.
    # Pinned-ness is judged on the HARD row: a soft zone preference that
    # narrowed allow to one zone is not a pin — it can be relaxed.
    for j in range(G):
        hard_j = allow[j] if allow_hard is None else allow_hard[j]
        if not conflict[j].any() or hard_j.sum() != 1:
            continue
        partners = np.flatnonzero(conflict[j])
        taken = any(claimed[p] is not None
                    and bool((claimed[p] & hard_j).any())
                    for p in partners)
        if taken:
            set_row(j, np.zeros(cat.Z, bool))
            claimed[j] = np.zeros(cat.Z, bool)
        else:
            claimed[j] = hard_j.copy()
    split_zones: Dict[int, List[int]] = {}
    for i in range(G):
        partners = np.flatnonzero(conflict[i])
        if not len(partners) and not self_anti[i]:
            continue
        if claimed[i] is not None and not self_anti[i]:
            continue  # pre-pinned; partners avoid its zone instead

        def _feas(base: np.ndarray) -> np.ndarray:
            eff = base.copy()
            for j in partners:
                if claimed[j] is not None:
                    eff &= ~claimed[j]
            return np.flatnonzero(feasible_zones(enc, cat, i, eff))

        zs = _feas(allow[i])
        need = int(enc.counts[i]) if self_anti[i] else 1
        if len(zs) < need and allow_hard is not None and (
                allow_hard[i] != allow[i]).any():
            # soft preference starves the pin/split: widen to the hard
            # row, keeping preferred zones first (prefer, never block)
            zs = np.concatenate(
                [zs, np.setdiff1d(_feas(allow_hard[i]), zs)])
        if self_anti[i]:
            use = zs[: int(enc.counts[i])]
            split_zones[i] = use.tolist()
            claim = np.zeros(cat.Z, bool)
            claim[use] = True
            claimed[i] = claim
            # allow stays; the split below pins each subgroup
        elif len(partners):
            if len(zs) == 0:
                set_row(i, np.zeros(cat.Z, bool))
                claimed[i] = np.zeros(cat.Z, bool)
            else:
                pin = np.zeros(cat.Z, bool)
                pin[zs[0]] = True
                set_row(i, pin)
                claimed[i] = pin

    zc = conflict if conflict.any() else None
    if not split_zones:
        if capture is not None:
            capture.update(
                allow=allow.copy(),
                allow_hard=None if allow_hard is None else allow_hard.copy(),
                zone_conflict=None if zc is None else zc.copy(),
                rows=None, self_anti=None)
        return _rebuild(enc, allow, allow_hard=allow_hard, zone_conflict=zc)

    # --- expand self-anti groups into one-pod-per-zone subgroups -------------
    rows: List[Tuple[int, int, np.ndarray]] = []  # (orig idx, count, zone row)
    for i in range(G):
        if i not in split_zones:
            rows.append((i, int(enc.counts[i]), allow[i]))
            continue
        used = split_zones[i]
        for z in used:
            row = np.zeros(cat.Z, bool)
            row[z] = True
            rows.append((i, 1, row))
        excess = int(enc.counts[i]) - len(used)
        if excess > 0:
            rows.append((i, excess, np.zeros(cat.Z, bool)))
    if capture is not None:
        capture.update(
            allow=allow.copy(),
            allow_hard=None if allow_hard is None else allow_hard.copy(),
            zone_conflict=None if zc is None else zc.copy(),
            rows=[(i, c, r.copy()) for i, c, r in rows],
            self_anti=self_anti.copy())
    return _rebuild(enc, allow, rows, allow_hard=allow_hard, zone_conflict=zc,
                    self_anti=self_anti)


def replay_zone_affinity(enc: EncodedPods, cat: CatalogTensors,
                         desc: dict) -> Optional[EncodedPods]:
    """Apply a captured zone-affinity descriptor to the CURRENT enc —
    the delta plane's serve half. The memo key fingerprints the enc
    content, so the descriptor fits by construction; the shape checks
    are defensive (a mismatch returns None and the caller recomputes,
    treating it as a divergence). Arrays are copied on the way in:
    downstream mutation must never reach the stored descriptor."""
    if desc.get("noop"):
        return enc
    allow = desc.get("allow")
    if allow is None or allow.shape != enc.allow_zone.shape:
        return None
    allow_hard = desc.get("allow_hard")
    if (allow_hard is None) != (enc.zone_hard is None):
        return None
    zc = desc.get("zone_conflict")
    rows = desc.get("rows")
    if rows is None:
        return _rebuild(enc, allow.copy(),
                        allow_hard=None if allow_hard is None
                        else allow_hard.copy(),
                        zone_conflict=None if zc is None else zc.copy())
    if any(i >= enc.G for i, _, _ in rows):
        return None
    return _rebuild(enc, allow.copy(),
                    [(i, c, r.copy()) for i, c, r in rows],
                    allow_hard=None if allow_hard is None
                    else allow_hard.copy(),
                    zone_conflict=None if zc is None else zc.copy(),
                    self_anti=desc["self_anti"].copy())


def descriptor_fingerprint(desc: dict) -> int:
    """Content digest of a zone-affinity descriptor — the affinity
    memo's audit comparator (ops/delta.py)."""
    from ..obs.recompute import fingerprint, fingerprint_bytes

    def afp(a) -> int:
        if a is None:
            return 0x9E3779B97F4A7C15
        a = np.ascontiguousarray(a)
        return fingerprint_bytes(a.tobytes()) ^ fingerprint(a.dtype.str,
                                                            a.shape)

    rows = desc.get("rows")
    return fingerprint(
        bool(desc.get("noop")), afp(desc.get("allow")),
        afp(desc.get("allow_hard")), afp(desc.get("zone_conflict")),
        afp(desc.get("self_anti")),
        None if rows is None else [(i, c, afp(r)) for i, c, r in rows])


def _rebuild(enc: EncodedPods, allow: np.ndarray,
             rows: Optional[List[Tuple[int, int, np.ndarray]]] = None,
             allow_hard: Optional[np.ndarray] = None,
             zone_conflict: Optional[np.ndarray] = None,
             self_anti: Optional[np.ndarray] = None) -> EncodedPods:
    """New EncodedPods with rewritten allow_zone (+ its hard rows and the
    zone-conflict matrix); optionally re-rowed (orig_idx, count, zone_row)
    for self-anti group splits."""
    if rows is None:
        return EncodedPods(
            groups=enc.groups, requests=enc.requests, counts=enc.counts,
            compat=enc.compat, allow_zone=allow, allow_cap=enc.allow_cap,
            max_per_node=enc.max_per_node, spread_zone=enc.spread_zone,
            conflict=enc.conflict, spread_soft=enc.spread_soft,
            compat_hard=enc.compat_hard, zone_hard=allow_hard,
            cap_hard=enc.cap_hard, zone_conflict=zone_conflict)
    groups = [enc.groups[i] for i, _, _ in rows]
    n = len(rows)
    Z = allow.shape[1]
    orig = [i for i, _, _ in rows]
    oi = np.asarray(orig, np.intp)  # one fancy-index gather per tensor
    zc = None
    if zone_conflict is not None or (self_anti is not None and self_anti.any()):
        base = (zone_conflict if zone_conflict is not None
                else np.zeros((enc.G, enc.G), bool))
        if self_anti is not None:
            # subgroup rows of one self-anti group conflict with each other
            base = base.copy()
            base[np.diag_indices(enc.G)] = self_anti
        o = np.asarray(orig)
        zc = base[np.ix_(o, o)].copy()
        np.fill_diagonal(zc, False)
        if not zc.any():
            zc = None
    # a split row's single-zone pin is a hard decision; unsplit rows keep
    # their hard row
    hard_rows = None
    if allow_hard is not None:
        split = {i for i, _, _ in rows if self_anti is not None
                 and i < len(self_anti) and self_anti[i]}
        hard_rows = np.array(
            [r if i in split else allow_hard[i] for i, _, r in rows],
            bool).reshape(n, Z)
    return EncodedPods(
        groups=groups,
        requests=enc.requests[oi],
        counts=np.fromiter((c for _, c, _ in rows), np.int32, n),
        compat=enc.compat[oi],
        allow_zone=np.array([r for _, _, r in rows], bool).reshape(n, Z),
        allow_cap=enc.allow_cap[oi],
        max_per_node=enc.max_per_node[oi],
        spread_zone=enc.spread_zone[oi],
        conflict=build_conflicts(groups),
        spread_soft=(enc.spread_soft[oi]
                     if enc.spread_soft is not None else None),
        compat_hard=(enc.compat_hard[oi]
                     if enc.compat_hard is not None else None),
        zone_hard=hard_rows,
        cap_hard=(enc.cap_hard[oi]
                  if enc.cap_hard is not None else None),
        zone_conflict=zc)
