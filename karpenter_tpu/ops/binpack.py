"""Host bin-packing oracle — the correctness reference for the TPU kernel.

Semantics (the `Solve()` policy both backends implement; reference behavior:
designs/bin-packing.md:18-42 — sort pods by size desc, first-fit into
in-flight nodes, else open a new virtual node; launch picks the cheapest
offering):

 1. Pods are exact-dedupe grouped and FFD-ordered (encode.group_pods).
 2. Each pod first-fits into open nodes in creation order. A node accepts a
    pod iff the node's committed instance type is compatible with the pod's
    requirements, remaining allocatable covers the request, the node's
    deferred (zone, capacity-type) masks still intersect the pod's, at least
    one available offering survives the intersection, and the group's
    per-node cap (anti-affinity / hostname spread) is not exceeded.
 3. If no node fits, a new node opens committed to the instance type
    minimizing price-per-pod-slot over all available (type, zone, captype)
    offerings compatible with the pod — the cost-argmin. Zone and capacity
    type remain deferred rectangular masks; the launch step later picks the
    cheapest surviving offering (reserved offerings are priced ~0 by the
    catalog, so price-argmin reproduces the reference's reserved→spot→od
    preference, instance.go:530-546).
 4. Zone topology-spread groups are pre-split into zone-pinned subgroups by
    `split_spread_groups` before either backend runs.

Design note (TPU-first): committing the node's type at open (instead of the
reference's deferred multi-type nodes) keeps the device state rectangular —
type id + cum requests + zone/captype masks — which is what makes the group
scan a fixed-shape `lax.scan` with O(N·T) work per step and no ragged
structures. The cost is occasionally one extra node vs deferred-type FFD;
the benchmark grid tracks node-count parity.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .encode import (CatalogTensors, EncodedPods, align_resources,
                     align_zone_overhead, build_conflicts, feasible_zones)

BIG = 10**9


@dataclass
class VirtualNode:
    type_idx: int
    zone_mask: np.ndarray      # bool [Z] — deferred zone choice
    cap_mask: np.ndarray       # bool [C]
    cum: np.ndarray            # f32 [R]
    # placements from THIS solve, keyed by the current enc's group indices
    pods_by_group: Dict[int, int] = field(default_factory=dict)
    existing_name: Optional[str] = None  # set for in-flight/live nodes
    # prior occupancy of an existing node, keyed by the CURRENT enc's group
    # indices (facade maps prior pods by constraint signature). Consumed by
    # the per-node caps (anti-affinity / hostname spread) so a node that
    # already hosts a matching pod can't take another across reconciles;
    # resources are accounted separately via cum.
    prior_by_group: Dict[int, int] = field(default_factory=dict)
    # bool [G] over the CURRENT enc's groups: groups this node may not take
    # because a resident pod's (or the group's own) required anti-affinity
    # forbids co-location. Facade-computed from the node's actual resident
    # pods — covers residents that map to NO current group (their labels
    # still repel incoming pods). None = nothing banned.
    banned_groups: Optional[np.ndarray] = None

    def pod_count(self) -> int:
        return sum(self.pods_by_group.values())


@dataclass
class SolveResult:
    nodes: List[VirtualNode]
    unschedulable: Dict[int, int]  # group idx -> count
    # resolved launch decisions (filled by finalize_offerings)
    launches: List[Tuple[int, int, int, float]] = field(default_factory=list)
    # (type_idx, zone_idx, cap_idx, price) per *new* node

    def new_nodes(self) -> List[VirtualNode]:
        return [n for n in self.nodes if n.existing_name is None]


def _water_fill(offsets: np.ndarray, total: int) -> np.ndarray:
    """Distribute `total` new pods over zones with existing per-zone counts
    `offsets` so every increment lands on a currently-least-occupied zone
    (the k8s topology-spread admission rule: placing on a min-count domain
    always keeps skew ≤ maxSkew). Returns per-zone additional counts.

    Closed form instead of a pod-by-pod loop: find the highest water level L
    with sum(max(0, L - offsets)) ≤ total, fill to L, then hand the
    remainder one-per-zone to zones sitting exactly at L (ascending index —
    deterministic)."""
    off = np.asarray(offsets, np.int64)
    k = len(off)
    if k == 0 or total <= 0:
        return np.zeros(k, np.int64)
    lo, hi = int(off.min()), int(off.min()) + total
    while lo < hi:  # binary search on the level
        mid = (lo + hi + 1) // 2
        if int(np.maximum(0, mid - off).sum()) <= total:
            lo = mid
        else:
            hi = mid - 1
    add = np.maximum(0, lo - off)
    rem = total - int(add.sum())
    at_level = np.flatnonzero(off + add == lo)
    add[at_level[:rem]] += 1
    return add


@dataclass
class SpreadConstraintCounts:
    """One zone-spread constraint of a group, with prior domain occupancy.

    counts: i64 [Z] — matching pods already in each zone (cluster-wide,
    computed by the facade from live + in-flight nodes).
    self_matches: whether the group's own pods match the constraint's
    selector — if so, each placement increments the domain count; if not,
    placements are checked against the (static) counts but don't move them
    (k8s computes skew over *matching* pods only).
    """

    counts: np.ndarray
    max_skew: int = 1
    self_matches: bool = True
    # ScheduleAnyway: never gates admission, only steers the zone choice
    soft: bool = False


def _assign_spread(zones: np.ndarray, total: int,
                   constraints: List[SpreadConstraintCounts],
                   ) -> Tuple[np.ndarray, int]:
    """Per-zone additional counts honoring every hard constraint; returns
    (adds [len(zones)], n_unassignable).

    Single self-matching constraint → closed-form water-fill (placing on a
    current-min domain always keeps skew ≤ maxSkew). Multiple constraints →
    per-pod greedy: a zone is admissible iff every HARD constraint passes
    the k8s rule (count_z + Δ − min ≤ maxSkew); among admissible zones the
    choice minimizes (soft-constraint violations, max domain count, index)
    so ScheduleAnyway constraints steer but never block — an element-wise
    merge of the count vectors cannot express either property."""
    if len(constraints) == 1 and constraints[0].self_matches:
        return _water_fill(constraints[0].counts[zones],
                           int(total)), 0
    cnt = [c.counts[zones].astype(np.int64).copy() for c in constraints]
    adds = np.zeros(len(zones), np.int64)
    for _ in range(int(total)):
        best, best_key = -1, None
        for j in range(len(zones)):
            ok = True
            soft_viol = 0
            for c, cc in zip(constraints, cnt):
                delta = 1 if c.self_matches else 0
                if cc[j] + delta - int(cc.min()) > c.max_skew:
                    if c.soft:
                        soft_viol += 1
                    else:
                        ok = False
                        break
            if not ok:
                continue
            key = (soft_viol,
                   max(int(cc[j]) for cc in cnt) if cnt else 0, j)
            if best_key is None or key < best_key:
                best, best_key = j, key
        if best < 0:
            return adds, int(total) - int(adds.sum())
        adds[best] += 1
        for c, cc in zip(constraints, cnt):
            if c.self_matches:
                cc[best] += 1
    return adds, 0


def split_spread_groups(enc: EncodedPods, cat: CatalogTensors,
                        spread_counts: Optional[
                            Dict[int, List[SpreadConstraintCounts]]] = None,
                        ) -> EncodedPods:
    """Expand zone-topology-spread groups into per-zone pinned subgroups with
    balanced counts (skew ≤ 1 ≤ maxSkew). Host-side transformation so the
    kernels never see spread constraints — only zone-pinned groups.

    spread_counts: optional per-group list of SpreadConstraintCounts
    (computed by the facade from cluster state). Balancing water-fills
    against these prior domain counts, so a cluster with 10 replicas in
    zone-a sends new replicas to the other zones first — the reference core
    scheduler seeds its topology domain counts from live nodes the same way
    (scheduling.md topology section). Pods no admissible zone can take are
    emitted as a zone-less subgroup (all-False allow_zone), which both
    solver backends report unschedulable.
    """
    idx_keep = [i for i in range(enc.G) if not enc.spread_zone[i]]
    if len(idx_keep) == enc.G:
        return enc
    rows = {"requests": [], "counts": [], "compat": [], "allow_zone": [],
            "allow_cap": [], "max_per_node": [], "spread_zone": [],
            "compat_hard": [], "zone_hard": [], "cap_hard": []}
    groups = []
    orig: List[int] = []  # original group index per output row

    def push(i, count, zone_row, pinned=False):
        groups.append(enc.groups[i])
        orig.append(i)
        rows["requests"].append(enc.requests[i])
        rows["counts"].append(count)
        rows["compat"].append(enc.compat[i])
        rows["allow_zone"].append(zone_row)
        rows["allow_cap"].append(enc.allow_cap[i])
        rows["max_per_node"].append(enc.max_per_node[i])
        rows["spread_zone"].append(False)
        rows["compat_hard"].append(
            enc.compat[i] if enc.compat_hard is None else enc.compat_hard[i])
        # a zone-pinned subgroup's pin IS hard (relaxing a soft zone
        # preference must not widen it); unpinned rows keep their hard row
        rows["zone_hard"].append(
            zone_row if pinned or enc.zone_hard is None else enc.zone_hard[i])
        rows["cap_hard"].append(
            enc.allow_cap[i] if enc.cap_hard is None else enc.cap_hard[i])

    for i in range(enc.G):
        if not enc.spread_zone[i]:
            push(i, int(enc.counts[i]), enc.allow_zone[i])
            continue
        zones = np.flatnonzero(enc.allow_zone[i])
        soft = enc.spread_soft is not None and bool(enc.spread_soft[i])
        if soft:
            # ScheduleAnyway: pin only to zones where the group actually
            # has an available, compatible, FITTING offering — an
            # infeasible zone must fall back to the others, never to
            # unschedulable. Judged on the HARD type/captype masks: a soft
            # preference must not steer (or collapse) the split.
            feas = feasible_zones(enc, cat, i, enc.allow_zone[i])
            zones = zones[feas[zones]]
        if len(zones) == 0:
            push(i, int(enc.counts[i]), enc.allow_zone[i])
            continue
        total = int(enc.counts[i])
        cons = (spread_counts or {}).get(i) or [
            SpreadConstraintCounts(counts=np.zeros(cat.Z, np.int64))]
        adds, n_unassignable = _assign_spread(zones, total, cons)
        if n_unassignable and soft:
            # preference exhausted: remaining pods go wherever fits
            push(i, n_unassignable, enc.allow_zone[i])
            n_unassignable = 0
        for j, z in enumerate(zones):
            cnt = int(adds[j])
            if cnt == 0:
                continue
            row = np.zeros(cat.Z, bool)
            row[z] = True
            push(i, cnt, row, pinned=True)
        if n_unassignable:
            push(i, n_unassignable, np.zeros(cat.Z, bool), pinned=True)

    n = len(groups)
    zone_conflict = None
    if enc.zone_conflict is not None:
        o = np.asarray(orig)
        zone_conflict = enc.zone_conflict[np.ix_(o, o)].copy()
        np.fill_diagonal(zone_conflict, False)
    return EncodedPods(groups=groups,
              requests=np.array(rows["requests"], np.float32).reshape(n, -1),
              counts=np.array(rows["counts"], np.int32),
              compat=np.array(rows["compat"], bool).reshape(n, -1),
              allow_zone=np.array(rows["allow_zone"], bool).reshape(n, -1),
              allow_cap=np.array(rows["allow_cap"], bool).reshape(n, -1),
              max_per_node=np.array(rows["max_per_node"], np.int32),
              spread_zone=np.array(rows["spread_zone"], bool),
              conflict=build_conflicts(groups),
              compat_hard=(
                  np.array(rows["compat_hard"], bool).reshape(n, -1)
                  if enc.compat_hard is not None else None),
              zone_hard=(
                  np.array(rows["zone_hard"], bool).reshape(n, -1)
                  if enc.zone_hard is not None else None),
              cap_hard=(
                  np.array(rows["cap_hard"], bool).reshape(n, -1)
                  if enc.cap_hard is not None else None),
              zone_conflict=zone_conflict)


EPS = np.float32(1e-4)  # f32 division slack; shared with the device kernel


def _fit_count(alloc_t: np.ndarray, cum: np.ndarray, req: np.ndarray) -> int:
    """Additional pods of `req` fitting in `alloc_t - cum` (f32 math, same
    expression as the kernel's k_cap so the two backends agree bitwise)."""
    with_req = np.where(req > 0, req, np.float32(1.0))
    k = np.where(req > 0,
                 np.floor((alloc_t - cum) / with_req + EPS),
                 np.float32(BIG)).min()
    return int(max(k, 0.0))


def clone_nodes(existing: Optional[List[VirtualNode]],
                R: int) -> List[VirtualNode]:
    """Solve-input copies of existing nodes: pods_by_group starts empty
    (result nodes report only THIS solve's placements); prior occupancy
    enters via cum and prior_by_group. Shared by solve_host and the
    warm-path admitter so the two build bit-identical node state."""
    for n in (existing or []):
        assert len(n.cum) <= R, (
            f"existing node cum has {len(n.cum)} resources but the current "
            f"axis is {R} — the resource axis only grows within a process")
    return [
        VirtualNode(type_idx=n.type_idx, zone_mask=n.zone_mask.copy(),
                    cap_mask=n.cap_mask.copy(),
                    cum=np.pad(n.cum, (0, max(0, R - len(n.cum)))).astype(np.float32),
                    pods_by_group={},
                    prior_by_group=dict(n.prior_by_group),
                    banned_groups=n.banned_groups,
                    existing_name=n.existing_name)
        for n in (existing or [])]


def first_fit_group(nodes: List[VirtualNode], g: int, enc: EncodedPods,
                    cat: CatalogTensors, alloc: np.ndarray,
                    zovh: Optional[np.ndarray], rem: int) -> int:
    """Fill open `nodes` in index order with group g's pods (step 1 of the
    solve policy — first-fit into existing/open nodes). Mutates the nodes
    it places on; returns the count it could NOT place. This is the ONE
    implementation of existing-node filling: solve_host runs it before
    opening new nodes, and the warm-path admitter runs it alone (its
    remainder escalates to the full solver instead of opening nodes), so
    warm and cold placement onto standing capacity cannot diverge."""
    avail = cat.available
    conflict = enc.conflict
    req = enc.requests[g].astype(np.float32)
    cap_per_node = int(enc.max_per_node[g]) or BIG
    for n in nodes:
        if rem == 0:
            break
        t = n.type_idx
        if not enc.compat[g, t]:
            continue
        if n.banned_groups is not None and n.banned_groups[g]:
            continue
        if conflict is not None and any(
                conflict[g, h] for h in n.pods_by_group):
            continue
        zmask = n.zone_mask & enc.allow_zone[g]
        cmask = n.cap_mask & enc.allow_cap[g]
        if not (avail[t] & zmask[:, None] & cmask[None, :]).any():
            continue
        alloc_t = alloc[t]
        if zovh is not None:
            # post-take zone mask (zmask): taking the pod commits the
            # node to it, so the reservation maxes over exactly those
            alloc_t = alloc_t - zovh[t][zmask].max(axis=0)
        take = min(_fit_count(alloc_t, n.cum, req),
                   cap_per_node - n.prior_by_group.get(g, 0)
                   - n.pods_by_group.get(g, 0), rem)
        if take < 1:
            continue
        n.cum = n.cum + np.float32(take) * req
        n.zone_mask = zmask
        n.cap_mask = cmask
        n.pods_by_group[g] = n.pods_by_group.get(g, 0) + take
        rem -= take
    return rem


def solve_host(cat: CatalogTensors, enc: EncodedPods,
               existing: Optional[List[VirtualNode]] = None) -> SolveResult:
    """Group-level first-fit-decreasing with the policy above — equivalent
    to per-pod FFD since pods within a group are interchangeable. Sequential
    and deliberately simple: this is the oracle the TPU kernel must agree
    with exactly (same f32 expressions, same argmin tie-breaks).

    `enc` must already be spread-free (callers run split_spread_groups
    first, so result group indices match the enc they hold). Existing nodes
    are copied, not mutated.
    """
    assert not enc.spread_zone.any(), "run split_spread_groups before solve"
    R = enc.requests.shape[1]
    alloc = align_resources(cat.allocatable, R)
    avail = cat.available  # [T, Z, C]
    price = cat.price
    # zone-varying daemonset reservation: a node charges the elementwise
    # max over its remaining zone mask (narrowing zones restores headroom)
    zovh = align_zone_overhead(cat, R)

    nodes: List[VirtualNode] = clone_nodes(existing, R)
    unschedulable: Dict[int, int] = {}

    for g in range(enc.G):
        req = enc.requests[g].astype(np.float32)
        cap_per_node = int(enc.max_per_node[g]) or BIG
        # 1. fill open nodes in index order (first-fit)
        rem = first_fit_group(nodes, g, enc, cat, alloc, zovh,
                              int(enc.counts[g]))
        if rem == 0:
            continue
        # 2. open new nodes at the cost-per-slot argmin offering, identical
        #    f32 arithmetic + flat-argmin tie-break as the kernel
        adm = (avail & enc.compat[g][:, None, None]
               & enc.allow_zone[g][None, :, None]
               & enc.allow_cap[g][None, None, :])
        with_req = np.where(req > 0, req, np.float32(1.0))
        alloc_eff = alloc
        if zovh is not None:
            # a new node's zone mask becomes gzone & type-available zones;
            # reserve the max over exactly those (same as the kernel)
            zm_open = enc.allow_zone[g][None, :] & avail.any(axis=2)  # [T, Z]
            alloc_eff = alloc - np.where(zm_open[:, :, None], zovh,
                                         np.float32(0.0)).max(axis=1)
        slots_t = np.where(req[None, :] > 0,
                           np.floor(alloc_eff / with_req[None, :] + EPS),
                           np.float32(BIG)).min(axis=1)
        slots_t = np.minimum(np.maximum(slots_t, 0.0).astype(np.int64), cap_per_node)
        feasible = adm & (slots_t[:, None, None] >= 1)
        cps = np.where(feasible,
                       price / np.maximum(slots_t, 1)[:, None, None].astype(np.float32),
                       np.float32(np.finfo(np.float32).max))
        flat = int(np.argmin(cps.reshape(-1)))
        if cps.reshape(-1)[flat] >= np.finfo(np.float32).max:
            unschedulable[g] = unschedulable.get(g, 0) + rem
            continue
        t_star = flat // (cat.Z * cat.C)
        s = max(int(slots_t[t_star]), 1)
        zmask_new = enc.allow_zone[g] & avail[t_star].any(axis=1)
        cmask_new = enc.allow_cap[g] & avail[t_star].any(axis=0)
        while rem > 0:
            take = min(s, rem)
            nodes.append(VirtualNode(
                type_idx=t_star, zone_mask=zmask_new.copy(),
                cap_mask=cmask_new.copy(),
                cum=np.float32(take) * req,
                pods_by_group={g: take}))
            rem -= take

    result = SolveResult(nodes=nodes, unschedulable=unschedulable)
    finalize_offerings(result, cat)
    return result


def cheapest_offerings(t: np.ndarray, zm: np.ndarray, cm: np.ndarray,
                       cat: CatalogTensors) -> List[Tuple[int, int, int, float]]:
    """The launch decision, array-level: cheapest available (zone, captype)
    per node given type ids [M], zone masks [M, Z], cap masks [M, C]
    (reference launch path picks cheapest via CreateFleet's lowest-price
    strategy over the override list). The ONE implementation both the host
    oracle (finalize_offerings) and solve_device's decode use, so a
    tie-break or pricing change can't diverge the two paths."""
    masked = np.where(zm[:, :, None] & cm[:, None, :] & cat.available[t],
                      cat.price[t], np.inf)            # [M, Z, C]
    flat = masked.reshape(t.shape[0], -1)
    k = np.argmin(flat, axis=1)
    prices = flat[np.arange(t.shape[0]), k]
    return [(int(ti), int(ki // cat.C), int(ki % cat.C), float(p))
            for ti, ki, p in zip(t.tolist(), k.tolist(), prices.tolist())]


def finalize_offerings(result: SolveResult, cat: CatalogTensors) -> None:
    """Pick the cheapest surviving (zone, captype) for each new node.
    Vectorized over all new nodes: this runs on every solve and a per-node
    Python loop costs more than the TPU kernel at 100k-pod scale."""
    new = result.new_nodes()
    result.launches = []
    if not new:
        return
    t = np.array([n.type_idx for n in new])
    zm = np.stack([n.zone_mask for n in new])          # [M, Z]
    cm = np.stack([n.cap_mask for n in new])           # [M, C]
    result.launches = cheapest_offerings(t, zm, cm, cat)


def validate_solution(cat: CatalogTensors, enc: EncodedPods,
                      result: SolveResult) -> List[str]:
    """Independent feasibility audit of a solve result (used by tests and
    the race-free double-check in the provisioner): every placement must be
    compatible, within capacity, and launchable on an available offering."""
    errors = []
    R = enc.requests.shape[1]
    alloc = align_resources(cat.allocatable, R)
    zovh = align_zone_overhead(cat, R)
    placed_per_group: Dict[int, int] = {}
    for idx, n in enumerate(result.nodes):
        t = n.type_idx
        gs = [g for g, c in n.pods_by_group.items() if c > 0]
        if n.banned_groups is not None:
            for g in gs:
                if n.banned_groups[g]:
                    errors.append(f"node {idx}: banned group {g} placed")
        if enc.conflict is not None:
            for a in range(len(gs)):
                for b in range(a + 1, len(gs)):
                    if enc.conflict[gs[a], gs[b]]:
                        errors.append(
                            f"node {idx}: conflicting groups {gs[a]},{gs[b]} colocated")
        for g, cnt in n.pods_by_group.items():
            placed_per_group[g] = placed_per_group.get(g, 0) + cnt
            if not enc.compat[g, t]:
                errors.append(f"node {idx}: group {g} incompatible with type {cat.names[t]}")
            if enc.max_per_node[g] and cnt > enc.max_per_node[g]:
                errors.append(f"node {idx}: group {g} count {cnt} > cap {enc.max_per_node[g]}")
            if not (n.zone_mask & enc.allow_zone[g]).any():
                errors.append(f"node {idx}: group {g} zone constraint violated")
            if not (n.cap_mask & enc.allow_cap[g]).any():
                errors.append(f"node {idx}: group {g} capacity-type constraint violated")
        # final cum (prior occupancy + this solve) must fit the committed
        # type, minus the zone-varying daemonset reservation the node's
        # final zone mask still exposes it to
        cap_t = alloc[t]
        if zovh is not None and n.zone_mask.any():
            cap_t = cap_t - zovh[t][n.zone_mask].max(axis=0)
        if np.any(n.cum[: alloc.shape[1]] > cap_t + 2e-3):
            errors.append(f"node {idx}: over capacity on {cat.names[t]}")
        if not (cat.available[t] & n.zone_mask[:, None] & n.cap_mask[None, :]).any():
            errors.append(f"node {idx}: no available offering survives masks")
    for g in range(enc.G):
        want = int(enc.counts[g])
        got = placed_per_group.get(g, 0) + result.unschedulable.get(g, 0)
        if got != want:
            errors.append(f"group {g}: {got} accounted != {want} pods")
    if enc.zone_conflict is not None:
        # zone anti-affinity: any node hosting group i must have a zone mask
        # disjoint from every node hosting a zone-conflicting group j
        # (deferred masks — overlap means the launch step COULD violate)
        hosts: Dict[int, List[int]] = {}
        for idx, n in enumerate(result.nodes):
            for g, c in n.pods_by_group.items():
                if c > 0:
                    hosts.setdefault(g, []).append(idx)
        for i in hosts:
            for j in hosts:
                if j <= i or not enc.zone_conflict[i, j]:
                    continue
                for a in hosts[i]:
                    for b in hosts[j]:
                        if (result.nodes[a].zone_mask
                                & result.nodes[b].zone_mask).any():
                            errors.append(
                                f"nodes {a},{b}: zone-conflicting groups "
                                f"{i},{j} may share a zone")
    return errors
