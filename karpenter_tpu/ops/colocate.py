"""Hostname-level required positive pod affinity: the co-location planner.

k8s semantics (the reference's core scheduler evaluates inter-pod affinity
inside its per-node simulation loop — website/content/en/docs/concepts/
scheduling.md "podAffinity"): a pod with a required podAffinity term at
topology_key=hostname may only land on a node already hosting a pod that
matches the term's selector (same namespace), with the standard bootstrap
exception — when NO pod in the cluster matches the selector, a pod whose
own labels match may seed a fresh domain and later pods join it.

TPU-first lowering: co-location couples placements through the NODE axis,
which the rectangular group-scan kernels deliberately do not model (they
track only per-node resource sums + deferred offering masks). Affinity-
coupled pods are rare and few, so this planner peels them OFF the tensor
path entirely and places them host-side before the kernels run — the hot
100k-pod path never pays for the feature. Decisions, in order:

  1. residents — existing nodes already hosting a match for EVERY term
     take the group's pods while type-compat/capacity/offering masks allow
     (k8s: any node of a matching topology domain qualifies);
  2. bundling — terms whose only matches are other PENDING groups open
     fresh nodes carrying >=1 pod of each term's target group plus as many
     initiator pods as fit; consumed target pods leave the tensor path.
     Multiple nodes may open while targets remain (each node independently
     hosts matches, so the real scheduler can bind in any order);
  3. self-match bootstrap — a group whose own labels satisfy a term, with
     no other match anywhere, packs onto ONE node: under sequential
     scheduling pod 1 places anywhere (bootstrap) and every later pod must
     join its node. Any self-only term therefore caps the group at one
     node; excess pods are unschedulable (k8s leaves them Pending);
  4. no resident, no target, no self-match — unschedulable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models import labels as L
from ..models.pod import Pod, PodAffinityTerm, Taint, tolerates_all
from ..models.pod import anti_blocks, term_selects as _selects
from ..models.requirements import Requirements
from .binpack import BIG, EPS, VirtualNode, _fit_count
from .encode import (CatalogTensors, _axis_allow, align_resources,
                     compat_mask, exotic_mask, group_pods, wants_exotic)


def _pos_terms(p: Pod) -> List[PodAffinityTerm]:
    return [t for t in p.affinity_terms
            if not t.anti and t.required and t.topology_key == L.HOSTNAME]


def _per_node_cap(rep: Pod) -> int:
    """Max pods of the group per node — mirrors encode_pods' max_per_node:
    self-anti-affinity caps at 1; hostname DoNotSchedule spread caps at
    maxSkew (conservative empty-node bound)."""
    cap = 1 if rep.has_self_anti_affinity() else BIG
    for tsc in rep.topology_spread:
        if (tsc.topology_key == L.HOSTNAME
                and tsc.when_unsatisfiable == "DoNotSchedule"):
            cap = min(cap, max(1, tsc.max_skew))
    return cap


def _anti_blocks(a: Pod, b: Pod) -> bool:
    return anti_blocks(a, b, L.HOSTNAME)


def has_colocation(pods: Sequence[Pod]) -> bool:
    return any(_pos_terms(p) for p in pods)


@dataclass
class BundleNode:
    """A host-planned node: committed type + deferred offering masks +
    the concrete pods riding on it (same contract as VirtualNode, plus the
    pod list and the AND of the members' compat rows for overrides)."""
    type_idx: int
    zone_mask: np.ndarray   # bool [Z]
    cap_mask: np.ndarray    # bool [C]
    pods: List[Pod]
    cum: np.ndarray         # f32 [R]
    group_compat: np.ndarray  # bool [T]


@dataclass
class ColocationPlan:
    bundles: List[BundleNode] = field(default_factory=list)
    # existing node name -> pods newly placed there by the planner
    existing_placements: Dict[str, List[Pod]] = field(default_factory=dict)
    unschedulable: List[Pod] = field(default_factory=list)
    remaining: List[Pod] = field(default_factory=list)


def plan_colocation(pods: Sequence[Pod], cat: CatalogTensors,
                    extra_requirements: Optional[Requirements] = None,
                    taints: Optional[List[Taint]] = None,
                    existing: Optional[List[VirtualNode]] = None,
                    existing_pods: Optional[Dict[str, List[Pod]]] = None,
                    type_cap: Optional[np.ndarray] = None,
                    template_labels: Optional[Dict[str, str]] = None,
                    ) -> ColocationPlan:
    """Place every pod carrying a required positive hostname-affinity term;
    everything else (including consumed-target leftovers) goes back out via
    `remaining` for the tensor path. Mutates `existing` nodes' cum/masks in
    place for resident placements so the SAME objects handed to the main
    solve see the consumed capacity — the facade passes throwaway copies
    (callers like disruption reuse their VirtualNodes across solves).

    type_cap: optional bool [T] — NodePool-limit headroom mask ANDed into
    every compat row (mirrors the facade's capacity_cap narrowing)."""
    plan = ColocationPlan()
    carriers = [p for p in pods if _pos_terms(p)]
    if not carriers:
        plan.remaining = list(pods)
        return plan
    # pods that don't tolerate the pool taints stay in `remaining`: the
    # encoder's taint filter reports them through the normal dropped path
    if taints:
        intolerant = [p for p in pods if not tolerates_all(p.tolerations, taints)]
        pods = [p for p in pods if tolerates_all(p.tolerations, taints)]
    else:
        intolerant = []

    groups = group_pods(pods)
    G = len(groups)
    terms = [_pos_terms(g.representative) for g in groups]
    # materialize every vector first: to_vector may auto-register resources,
    # growing the global axis (same ordering rule as encode_pods)
    vecs = {i: groups[i].representative.requests.to_vector() for i in range(G)}
    from ..models.resources import num_resources
    R = max(num_resources(), cat.allocatable.shape[1])
    alloc = align_resources(cat.allocatable, R)

    def g_req(i: int) -> np.ndarray:
        v = vecs[i]
        out = np.zeros(R, np.float32)
        out[: len(v)] = v[:R]
        return out

    reqs_cache: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
    exotic = exotic_mask(cat)

    def g_masks(i: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        hit = reqs_cache.get(i)
        if hit is None:
            rep = groups[i].representative
            r = rep.scheduling_requirements()
            if extra_requirements is not None:
                r = r.union_with(extra_requirements)
            comp = compat_mask(r, cat, template_labels)
            if type_cap is not None:
                comp = comp & type_cap
            if exotic.any() and not wants_exotic(rep, r):
                comp = comp & ~exotic  # same rule as encode_pods
            hit = (comp, _axis_allow(r, L.ZONE, cat.zones),
                   _axis_allow(r, L.CAPACITY_TYPE, cat.captypes))
            reqs_cache[i] = hit
        return hit

    # remaining pod budget per group, drawn front-to-back from group.pods
    rem = {i: groups[i].count for i in range(G)}
    cursor = {i: 0 for i in range(G)}

    def take(i: int, n: int) -> List[Pod]:
        at = cursor[i]
        cursor[i] = at + n
        rem[i] -= n
        return groups[i].pods[at: at + n]

    # --- per-term match discovery -------------------------------------------
    # resident_ok[i]: node indices (into `existing`) hosting a match for
    # EVERY term of group i; targets[i]: per term, the pending groups whose
    # labels match (excluding i itself); selfm[i]: per term, own-label match
    ex = list(existing or [])
    ex_pods = existing_pods or {}
    resident_ok: Dict[int, List[int]] = {}
    targets: Dict[int, List[List[int]]] = {}
    selfm: Dict[int, List[bool]] = {}
    for i in range(G):
        if not terms[i]:
            continue
        rep = groups[i].representative
        ok_nodes = []
        for ni, vn in enumerate(ex):
            residents = ex_pods.get(vn.existing_name or "", [])
            if residents and all(
                    any(_selects(t, p.namespace == rep.namespace, p.labels)
                        for p in residents) for t in terms[i]):
                ok_nodes.append(ni)
        resident_ok[i] = ok_nodes
        targets[i] = [[j for j in range(G) if j != i and _selects(
            t, groups[j].representative.namespace == rep.namespace,
            groups[j].representative.labels)] for t in terms[i]]
        selfm[i] = [_selects(t, True, rep.labels) for t in terms[i]]

    # --- placement, initiator groups in FFD order ---------------------------
    for i in range(G):
        if not terms[i] or rem[i] <= 0:
            continue
        req = g_req(i)
        comp, zmask, cmask = g_masks(i)

        rep = groups[i].representative
        cap_i = _per_node_cap(rep)

        # 1. fill resident-satisfying nodes
        for ni in resident_ok[i]:
            if rem[i] <= 0:
                break
            vn = ex[ni]
            t = vn.type_idx
            if not comp[t]:
                continue
            residents = ex_pods.get(vn.existing_name or "", [])
            if any(_anti_blocks(rep, p) for p in residents):
                continue  # required anti-affinity repels, symmetrically
            nz = vn.zone_mask & zmask
            nc = vn.cap_mask & cmask
            if not (cat.available[t] & nz[:, None] & nc[None, :]).any():
                continue
            cum = np.pad(vn.cum.astype(np.float32),
                         (0, max(0, R - len(vn.cum))))
            already = sum(1 for p in residents
                          if p.constraint_signature()
                          == rep.constraint_signature())
            k = min(_fit_count(alloc[t], cum, req), rem[i],
                    cap_i - already)
            if k < 1:
                continue
            placed = take(i, k)
            vn.cum = cum + np.float32(k) * req
            vn.zone_mask = nz
            vn.cap_mask = nc
            name = vn.existing_name or ""
            plan.existing_placements.setdefault(name, []).extend(placed)
        if rem[i] <= 0:
            continue

        # 1b. already-opened bundle nodes whose pods satisfy every term
        #     (an earlier initiator may have consumed this group's target;
        #     its node hosts the match, so later pods can join it)
        for b in plan.bundles:
            if rem[i] <= 0:
                break
            if not comp[b.type_idx]:
                continue
            if not all(any(_selects(t, p.namespace == rep.namespace,
                                    p.labels) for p in b.pods)
                       for t in terms[i]):
                continue
            if any(_anti_blocks(rep, p) for p in b.pods):
                continue
            nz = b.zone_mask & zmask
            nc = b.cap_mask & cmask
            if not (cat.available[b.type_idx]
                    & nz[:, None] & nc[None, :]).any():
                continue
            already = sum(1 for p in b.pods
                          if p.constraint_signature()
                          == rep.constraint_signature())
            k = min(_fit_count(alloc[b.type_idx], b.cum, req), rem[i],
                    cap_i - already)
            if k < 1:
                continue
            b.pods.extend(take(i, k))
            b.cum = b.cum + np.float32(k) * req
            b.zone_mask, b.cap_mask = nz, nc
            b.group_compat = b.group_compat & comp
        if rem[i] <= 0:
            continue

        # 2. classify the leftover terms
        need_target: List[int] = []   # term idx needing a pending target
        self_only = False
        dead = False
        for k_t in range(len(terms[i])):
            has_target = any(rem[j] > 0 for j in targets[i][k_t])
            if has_target:
                need_target.append(k_t)
            elif selfm[i][k_t]:
                self_only = True
            else:
                # no pending target, no self-match: resident capacity (if
                # any matched) ran out above — nowhere else qualifies
                dead = True
        if dead:
            plan.unschedulable.extend(take(i, rem[i]))
            continue

        # Adding a target to the bundle may pull in ITS OWN required
        # positive terms' targets transitively (a→b→c chains: k8s's
        # sequential scheduler can realize them, so the bundle must carry
        # the whole closure). _close adds group j plus whatever its terms
        # need, backtracking on failure; anti-affinity gates every add.
        def _close(j: int, members: List[Pod], adding: List[int]) -> bool:
            rj = groups[j].representative
            if any(_anti_blocks(rj, m) for m in members):
                return False
            m_len, a_len = len(members), len(adding)
            members.append(rj)
            adding.append(j)
            for t in _pos_terms(rj):
                if any(_selects(t, m.namespace == rj.namespace, m.labels)
                       for m in members if m is not rj):
                    continue
                ok = False
                for k in range(G):
                    rk = groups[k].representative
                    if rem[k] <= 0 or any(m is rk for m in members):
                        continue
                    if _selects(t, rk.namespace == rj.namespace, rk.labels) \
                            and _close(k, members, adding):
                        ok = True
                        break
                if not ok:
                    del members[m_len:]
                    del adding[a_len:]
                    return False
            return True

        # 3. open bundle nodes: one pod per needed target group (plus its
        #    closure) + fill with initiator pods; self-only terms cap the
        #    group at ONE node
        max_nodes = 1 if (self_only or not need_target) else BIG
        opened = 0
        while rem[i] > 0 and opened < max_nodes:
            picked: List[int] = []
            members: List[Pod] = [rep]
            ok = True
            for k_t in need_target:
                t = terms[i][k_t]
                if any(_selects(t, m.namespace == rep.namespace, m.labels)
                       for m in members if m is not rep):
                    continue  # an earlier pick already satisfies this term
                if not any(rem[j] > 0 and _close(j, members, picked)
                           for j in targets[i][k_t]
                           if not any(m is groups[j].representative
                                      for m in members)):
                    ok = False
                    break
            if not ok:
                break
            node = _open_bundle(cat, alloc, i, picked, g_req, g_masks,
                                rem, take, self_only, cap_i)
            if node is None:
                break
            plan.bundles.append(node)
            opened += 1
        if rem[i] > 0:
            plan.unschedulable.extend(take(i, rem[i]))

    # whatever was not consumed returns to the tensor path
    for i in range(G):
        if rem[i] > 0:
            plan.remaining.extend(take(i, rem[i]))
    plan.remaining.extend(intolerant)
    return plan


def _open_bundle(cat: CatalogTensors, alloc: np.ndarray, i: int,
                 target_groups: List[int], g_req, g_masks, rem, take,
                 one_shot: bool, cap_i: int = BIG) -> Optional[BundleNode]:
    """Open one node hosting 1 pod of each target group + initiator pods
    (at most cap_i — the initiator's per-node anti-affinity/spread cap).

    Offering choice mirrors binpack's new-node rule: cost-per-initiator-slot
    argmin over admissible (type, zone, captype); when the node is capped at
    one (`one_shot`, the self-match bootstrap), prefer fitting the WHOLE
    remaining group — cheapest among full-fit types, else max-slot types."""
    req_i = g_req(i)
    comp, zmask, cmask = g_masks(i)
    base = np.zeros_like(req_i)
    for j in target_groups:
        comp_j, zm_j, cm_j = g_masks(j)
        comp = comp & comp_j
        zmask = zmask & zm_j
        cmask = cmask & cm_j
        base = base + g_req(j)
    # the reserved target footprint must fit in EVERY resource dim —
    # including dims the initiator doesn't request (slots below only
    # guards dims where req_i > 0)
    comp = comp & (alloc >= base[None, :] - 1e-6).all(axis=1)
    adm = (cat.available & comp[:, None, None]
           & zmask[None, :, None] & cmask[None, None, :])
    if not adm.any():
        return None
    # initiator slots per type after reserving the target pods
    with_req = np.where(req_i > 0, req_i, np.float32(1.0))
    slots = np.where(req_i[None, :] > 0,
                     np.floor((alloc - base[None, :]) / with_req[None, :] + EPS),
                     np.float32(BIG)).min(axis=1)
    slots = np.minimum(np.maximum(slots, 0.0), np.float32(cap_i)).astype(np.int64)
    feasible = adm & (slots[:, None, None] >= 1)
    if not feasible.any():
        return None
    if one_shot and (feasible & (slots[:, None, None] >= rem[i])).any():
        feasible = feasible & (slots[:, None, None] >= rem[i])
    elif one_shot:
        best = slots[feasible.any(axis=(1, 2))].max()
        feasible = feasible & (slots[:, None, None] >= best)
    cps = np.where(feasible,
                   cat.price / np.maximum(slots, 1)[:, None, None].astype(np.float32),
                   np.float32(np.finfo(np.float32).max))
    flat = int(np.argmin(cps.reshape(-1)))
    t_star = flat // (cat.Z * cat.C)
    k = int(min(slots[t_star], rem[i]))
    members = take(i, k)
    for j in target_groups:
        members = take(j, 1) + members
    cum = np.float32(k) * req_i + base
    avail_t = (cat.available[t_star] & zmask[:, None] & cmask[None, :])
    return BundleNode(
        type_idx=t_star,
        zone_mask=zmask & avail_t.any(axis=1),
        cap_mask=cmask & avail_t.any(axis=0),
        pods=members, cum=cum, group_compat=comp)
