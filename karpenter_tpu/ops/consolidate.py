"""Batched consolidation screening — all candidates in one kernel call.

The reference's consolidation evaluates candidates one at a time with a
CPU scheduling simulation (designs/consolidation.md). TPU-native, the
dominant question — "could node n's pods re-schedule onto the other
nodes' spare capacity?" — is a dense [N, G] computation evaluated for
EVERY candidate simultaneously:

    k[m, g]   = pods of group g that fit node m's headroom (0 if m is
                incompatible with g or no offering survives the masks)
    screen[n] = ∀g with pods on n:  count[n, g] ≤ Σ_{m≠n} k[m, g]

The screen over-approximates (headroom is counted per-group without
cross-group contention), so it's a *filter + priority order*, not a
verdict: the disruption controller exact-verifies screened candidates
with the real solver (cheapest-savings first) under its budget. This
turns 5k sequential simulations into one kernel call + a handful of
exact re-solves.

Emptiness falls out for free: a node with no pods screens trivially.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .binpack import BIG, EPS, VirtualNode
from .encode import CatalogTensors, EncodedPods, align_resources


@jax.jit
def _screen_kernel(alloc, avail, node_type, node_cum, node_zmask, node_cmask,
                   node_active, group_req, compat, allow_zone, allow_cap,
                   node_groups):
    """Returns ONE packed f32 vector: [0:N] screen (1.0 = candidate may
    consolidate), [N:N+N*G] headroom slack (others' capacity minus need,
    row-major [N, G]) — consolidation_screen unpacks it after a single
    host read."""
    talloc = alloc[node_type]                                 # [N, R]
    headroom = talloc - node_cum                              # [N, R]
    with_req = jnp.where(group_req > 0, group_req, 1.0)       # [G, R]
    # k_cap[m, g] = min over r of floor(headroom[m,r] / req[g,r])
    ratios = jnp.where(group_req[None, :, :] > 0,
                       jnp.floor(headroom[:, None, :] / with_req[None, :, :] + EPS),
                       jnp.asarray(BIG, jnp.float32))         # [N, G, R]
    k = jnp.maximum(ratios.min(axis=2), 0.0)                  # [N, G]
    # eligibility: compat + an available offering surviving both masks
    ok_t = compat[:, node_type].T                             # [N, G]
    a = avail[node_type]                                      # [N, Z, C]
    off = jnp.einsum("nz,gz,nc,gc,nzc->ng",
                     node_zmask.astype(jnp.float32), allow_zone.astype(jnp.float32),
                     node_cmask.astype(jnp.float32), allow_cap.astype(jnp.float32),
                     a.astype(jnp.float32)) > 0               # [N, G]
    k = jnp.where(ok_t & off & node_active[:, None], k, 0.0)  # [N, G]
    total = k.sum(axis=0)                                     # [G]
    others = total[None, :] - k                               # [N, G]
    need = node_groups.astype(jnp.float32)                    # [N, G]
    screen = ((need <= others) | (need == 0)).all(axis=1) & node_active
    # ONE packed output buffer: each host read of a separate array costs a
    # full round trip when the chip sits behind a network tunnel (~70ms),
    # and this screen used to ship two
    return jnp.concatenate([screen.astype(jnp.float32),
                            (others - need).reshape(-1)])


def consolidation_screen(cat: CatalogTensors, enc: EncodedPods,
                         views: "List",
                         group_counts: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """views: NodeView list; group_counts [N, G] = pods of group g on node n.
    Returns (screen [N] bool, slack [N, G])."""
    R = enc.requests.shape[1]
    N = len(views)
    if N == 0:
        return np.zeros(0, bool), np.zeros((0, enc.G), np.float32)
    alloc = align_resources(cat.allocatable, R)
    node_type = np.array([v.virtual.type_idx for v in views], np.int32)
    node_cum = np.zeros((N, R), np.float32)
    node_zmask = np.zeros((N, cat.Z), bool)
    node_cmask = np.zeros((N, cat.C), bool)
    for i, v in enumerate(views):
        node_cum[i, : len(v.virtual.cum)] = v.virtual.cum
        node_zmask[i] = v.virtual.zone_mask
        node_cmask[i] = v.virtual.cap_mask
    active = np.ones(N, bool)
    packed = _screen_kernel(
        jnp.asarray(alloc), jnp.asarray(cat.available),
        jnp.asarray(node_type), jnp.asarray(node_cum),
        jnp.asarray(node_zmask), jnp.asarray(node_cmask),
        jnp.asarray(active), jnp.asarray(enc.requests.astype(np.float32)),
        jnp.asarray(enc.compat), jnp.asarray(enc.allow_zone),
        jnp.asarray(enc.allow_cap), jnp.asarray(group_counts))
    buf = np.asarray(packed)  # ONE host read
    screen = buf[:N] > 0.5
    slack = buf[N:].reshape(N, enc.G)
    return screen, slack
