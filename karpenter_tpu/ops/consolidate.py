"""Batched consolidation screening — all candidates in one kernel call.

The reference's consolidation evaluates candidates one at a time with a
CPU scheduling simulation (designs/consolidation.md). TPU-native, the
dominant question — "could node n's pods re-schedule onto the other
nodes' spare capacity?" — is a dense [N, G] computation evaluated for
EVERY candidate simultaneously:

    k[m, g]   = pods of group g that fit node m's headroom (0 if m is
                incompatible with g or no offering survives the masks)
    screen[n] = ∀g with pods on n:  count[n, g] ≤ Σ_{m≠n} k[m, g]

The screen over-approximates (headroom is counted per-group without
cross-group contention), so it's a *filter + priority order*, not a
verdict: the disruption controller exact-verifies screened candidates
with the real solver (cheapest-savings first) under its budget. This
turns 5k sequential simulations into one kernel call + a handful of
exact re-solves.

Emptiness falls out for free: a node with no pods screens trivially.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .binpack import BIG, EPS, VirtualNode
from .encode import (CatalogTensors, EncodedPods, align_resources,
                     align_zone_overhead)


def _screen_kernel_impl(alloc, avail, node_type, node_cum, node_zmask,
                        node_cmask, node_active, group_req, compat,
                        allow_zone, allow_cap, node_groups,
                        use_pallas: bool = False,
                        pallas_interpret: bool = False):
    """Returns ONE packed f32 vector: [0:N] screen (1.0 = candidate may
    consolidate), [N:N+N*G] headroom slack (others' capacity minus need,
    row-major [N, G]) — consolidation_screen unpacks it after a single
    host read.

    use_pallas: route the k-cap reduction through the VMEM-resident
    Pallas kernel (ops/pallas_screen) instead of materializing the
    [N, G, R] ratio tensor in HBM — same math, chosen by availability
    + measurement at the call site."""
    talloc = alloc[node_type]                                 # [N, R]
    headroom = talloc - node_cum                              # [N, R]
    # eligibility: compat + an available offering surviving both masks
    ok_t = compat[:, node_type].T                             # [N, G]
    a = avail[node_type]                                      # [N, Z, C]
    off = jnp.einsum("nz,gz,nc,gc,nzc->ng",
                     node_zmask.astype(jnp.float32), allow_zone.astype(jnp.float32),
                     node_cmask.astype(jnp.float32), allow_cap.astype(jnp.float32),
                     a.astype(jnp.float32)) > 0               # [N, G]
    elig = ok_t & off & node_active[:, None]                  # [N, G]
    if use_pallas:
        from .pallas_screen import screen_k
        k = screen_k(headroom, group_req, elig,
                     interpret=pallas_interpret)              # [N, G]
    else:
        with_req = jnp.where(group_req > 0, group_req, 1.0)   # [G, R]
        # k_cap[m, g] = min over r of floor(headroom[m,r] / req[g,r])
        ratios = jnp.where(group_req[None, :, :] > 0,
                           jnp.floor(headroom[:, None, :]
                                     / with_req[None, :, :] + EPS),
                           jnp.asarray(BIG, jnp.float32))     # [N, G, R]
        k = jnp.where(elig, jnp.maximum(ratios.min(axis=2), 0.0), 0.0)
    total = k.sum(axis=0)                                     # [G]
    others = total[None, :] - k                               # [N, G]
    need = node_groups.astype(jnp.float32)                    # [N, G]
    screen = ((need <= others) | (need == 0)).all(axis=1) & node_active
    # ONE packed output buffer: each host read of a separate array costs a
    # full round trip when the chip sits behind a network tunnel (~70ms),
    # and this screen used to ship two
    return jnp.concatenate([screen.astype(jnp.float32),
                            (others - need).reshape(-1)])


_screen_kernel = jax.jit(_screen_kernel_impl,
                         static_argnames=("use_pallas", "pallas_interpret"))


# --- single-upload dispatch (same tunnel economics as solver._solve_onebuf:
# upload COUNT, not bytes, is the latency budget — the 12-array call above
# cost ~10 round-trips' worth of transfer latency per screen) ---


def _pack_screen_nodes(node_type, node_cum, node_zmask, node_cmask, active,
                       counts, cols) -> np.ndarray:
    """One f32 [Np, 1+Rk+Z+C+1+G] matrix of all node-side screen inputs."""
    return np.concatenate([
        node_type[:, None].astype(np.float32),
        node_cum[:, cols].astype(np.float32),
        node_zmask.astype(np.float32),
        node_cmask.astype(np.float32),
        active[:, None].astype(np.float32),
        counts.astype(np.float32),
    ], axis=1)


def _pack_screen_groups(req, compat, allow_zone, allow_cap,
                        cols) -> np.ndarray:
    """One f32 [G, Rk+T+Z+C] matrix of all group-side screen inputs."""
    return np.concatenate([
        req[:, cols].astype(np.float32),
        compat.astype(np.float32),
        allow_zone.astype(np.float32),
        allow_cap.astype(np.float32),
    ], axis=1)


def _screen_onebuf_impl(alloc, avail, nbuf, gbuf, cols: tuple,
                        use_pallas: bool = False,
                        pallas_interpret: bool = False):
    """Unpack by static offsets (resource columns projected to `cols` —
    dropped columns carry no requests so they can never bind, same
    argument as solver._solve_onebuf) and run the screen body."""
    T, Z, C = avail.shape
    Rk = len(cols)
    G = gbuf.shape[0]
    cix = jnp.asarray(np.asarray(cols, np.int32))
    alloc_k = alloc[:, cix]
    req = gbuf[:, :Rk]
    o = Rk
    compat = gbuf[:, o:o + T] > 0; o += T
    allow_zone = gbuf[:, o:o + Z] > 0; o += Z
    allow_cap = gbuf[:, o:o + C] > 0
    node_type = nbuf[:, 0].astype(jnp.int32)
    o = 1
    node_cum = nbuf[:, o:o + Rk]; o += Rk
    node_zmask = nbuf[:, o:o + Z] > 0; o += Z
    node_cmask = nbuf[:, o:o + C] > 0; o += C
    active = nbuf[:, o] > 0; o += 1
    counts = nbuf[:, o:o + G]
    return _screen_kernel_impl(alloc_k, avail, node_type, node_cum,
                               node_zmask, node_cmask, active, req, compat,
                               allow_zone, allow_cap, counts,
                               use_pallas=use_pallas,
                               pallas_interpret=pallas_interpret)


_screen_onebuf = jax.jit(_screen_onebuf_impl,
                         static_argnames=("cols", "use_pallas",
                                          "pallas_interpret"))

# mesh-jitted screens, keyed on the (hashable) Mesh itself and capped —
# id() keys break under address reuse and pin dead meshes forever
_mesh_screen_cache: dict = {}
_MESH_SCREEN_CACHE_MAX = 16


def _mesh_screen_fn(mesh, cols: tuple):
    """Node-axis-sharded ONEBUF screen: the packed node matrix shards over
    the mesh (each chip computes its rows' k[m, g]); the total-over-nodes
    reduction becomes a psum GSPMD inserts; the packed output replicates
    for the single host read. Same 2-upload budget as single-device."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    key = (mesh, cols)
    fn = _mesh_screen_cache.get(key)
    if fn is None:
        if len(_mesh_screen_cache) >= _MESH_SCREEN_CACHE_MAX:
            _mesh_screen_cache.clear()
        fn = jax.jit(partial(_screen_onebuf_impl, cols=cols),
                     out_shardings=NamedSharding(mesh, P()))
        _mesh_screen_cache[key] = fn
    return fn


def screen_device_time(cat: CatalogTensors, enc: EncodedPods, views,
                       group_counts: np.ndarray, iters: int = 40) -> float:
    """Per-call device time for the screen, in seconds (solver.slope_time
    over 8 variants with perturbed node cum — see that helper for why the
    RTT cancels and why inputs must vary). Times the production onebuf
    dispatch so the published number can't drift from the real path."""
    from .solver import _auto_dcat, _put, _request_cols, slope_time

    R = enc.requests.shape[1]
    dcat = _auto_dcat(cat, R)
    cols = _request_cols(enc, cat)
    (_, _, node_type, node_cum, node_zmask, node_cmask, active,
     req, compat, allow_zone, allow_cap, counts) = _screen_args(
        cat, enc, views, group_counts)
    gbuf = _put(_pack_screen_groups(req, compat, allow_zone, allow_cap,
                                    list(cols)))
    variants = []
    for i in range(8):
        cum = node_cum.copy()
        cum[:, 0] += np.float32(i) * np.float32(0.001)
        variants.append(_put(_pack_screen_nodes(
            node_type, cum, node_zmask, node_cmask, active, counts,
            list(cols))))
    return slope_time(
        lambda i: _screen_onebuf(dcat.alloc, dcat.avail, variants[i % 8],
                                 gbuf, cols=cols),
        iters=iters)


def _screen_args(cat: CatalogTensors, enc: EncodedPods, views,
                 group_counts: np.ndarray, Np: int = 0):
    """Numpy screen inputs (padded to Np rows when Np > N) — the ONE
    construction the production path and the bench's device-time seam
    share, so the published timing can't drift from production shapes."""
    R = enc.requests.shape[1]
    N = len(views)
    Np = max(Np, N)
    alloc = align_resources(cat.allocatable, R)
    node_type = np.zeros(Np, np.int32)
    node_cum = np.zeros((Np, R), np.float32)
    node_zmask = np.zeros((Np, cat.Z), bool)
    node_cmask = np.zeros((Np, cat.C), bool)
    for i, v in enumerate(views):
        node_type[i] = v.virtual.type_idx
        node_cum[i, : len(v.virtual.cum)] = v.virtual.cum
        node_zmask[i] = v.virtual.zone_mask
        node_cmask[i] = v.virtual.cap_mask
    zovh = align_zone_overhead(cat, R)
    if zovh is not None:
        # zone-varying daemonset reservation: charge each node's headroom
        # with the max over its zone mask (host-side — the kernel then
        # sees it as consumed capacity, same as the solve's view)
        node_cum = node_cum + np.where(
            node_zmask[:, :, None], zovh[node_type], np.float32(0.0)
        ).max(axis=1)
    active = np.zeros(Np, bool)
    active[:N] = True
    counts = group_counts if Np == N else np.concatenate(
        [group_counts, np.zeros((Np - N, enc.G), group_counts.dtype)])
    return (alloc, cat.available, node_type, node_cum, node_zmask,
            node_cmask, active, enc.requests.astype(np.float32), enc.compat,
            enc.allow_zone, enc.allow_cap, counts)


def consolidation_screen(cat: CatalogTensors, enc: EncodedPods,
                         views: "List",
                         group_counts: np.ndarray,
                         mesh=None) -> Tuple[np.ndarray, np.ndarray]:
    """views: NodeView list; group_counts [N, G] = pods of group g on node n.
    Returns (screen [N] bool, slack [N, G]).

    mesh: shard the candidate-node axis across the mesh's chips (inactive
    padding rows make N divisible); the production multi-chip path for
    large-cluster consolidation."""
    N = len(views)
    if N == 0:
        return np.zeros(0, bool), np.zeros((0, enc.G), np.float32)
    Np = N if mesh is None else -(-N // int(mesh.size)) * int(mesh.size)
    args = _screen_args(cat, enc, views, group_counts, Np=Np)
    from . import solver as _solver_mod
    from .solver import (_auto_dcat, _put, _put_sharded, _read,
                         _request_cols)
    # same fault seam as the solve kernels: a chaos plan can take the
    # device out at screen dispatch too (the disruption controller's
    # best-effort wrapper degrades to cost order and meters it)
    if _solver_mod._dispatch_fault_hook is not None:
        _solver_mod._dispatch_fault_hook("screen")
    R = enc.requests.shape[1]
    cols = _request_cols(enc, cat)
    (_, _, node_type, node_cum, node_zmask, node_cmask, active,
     req, compat, allow_zone, allow_cap, counts) = args
    nbuf_np = _pack_screen_nodes(node_type, node_cum, node_zmask,
                                 node_cmask, active, counts, list(cols))
    gbuf_np = _pack_screen_groups(req, compat, allow_zone, allow_cap,
                                  list(cols))
    from ..obs import devicemem as _dm
    if mesh is not None:
        # same 2-upload budget as single-device: the node matrix shards
        # over the mesh, the group matrix + catalog replicate (catalog
        # from the mesh-keyed epoch cache)
        from jax.sharding import NamedSharding, PartitionSpec as P
        dcat = _auto_dcat(cat, R, mesh=mesh)
        with _dm.attributed(reason="screen_upload"):
            nbuf = _put_sharded(nbuf_np,
                                NamedSharding(mesh, P("nodes", None)))
            gbuf = _put_sharded(gbuf_np, NamedSharding(mesh, P()))
            buf = _read(_mesh_screen_fn(mesh, cols)(dcat.alloc, dcat.avail,
                                                    nbuf, gbuf))
    else:
        # single-device path: TWO packed uploads (node-side + group-side;
        # catalog tensors ride the solver's per-epoch device cache) and
        # one packed read. May route the k-cap reduction through the
        # opt-in Pallas kernel; the mesh path stays fused-XLA (the
        # kernel is not GSPMD-partitioned — flag is inert there). A
        # failure at the REAL shape (the probe compiles a toy one) falls
        # back to the XLA path, as the pallas_screen contract promises.
        from . import pallas_screen
        dcat = _auto_dcat(cat, R)
        with _dm.attributed(reason="screen_upload"):
            nbuf = _put(nbuf_np)
            gbuf = _put(gbuf_np)
        if pallas_screen.available():
            try:
                packed = _screen_onebuf(dcat.alloc, dcat.avail, nbuf, gbuf,
                                        cols=cols, use_pallas=True)
            except Exception:
                # latch OFF: jit does not cache failed compiles, so
                # re-attempting every screen would pay a failed Mosaic
                # compile on each disruption cycle
                pallas_screen._status = False
                packed = _screen_onebuf(dcat.alloc, dcat.avail, nbuf, gbuf,
                                        cols=cols)
        else:
            packed = _screen_onebuf(dcat.alloc, dcat.avail, nbuf, gbuf,
                                    cols=cols)
        buf = _read(packed)
    # ONE host read either way; shared unpack of the packed layout
    screen = buf[:N] > 0.5
    slack = buf[Np: Np + N * enc.G].reshape(N, enc.G)
    return screen, slack
