"""Delta plane: serve-and-verify memos for the steady-state reconcile.

The recompute observatory (obs/recompute.py) measured the headroom —
under the c16 regime the solve stage is ~95% redundant, affinity ~86%,
spread ~84%: most of every reconcile recomputes inputs that did not
change. This module SPENDS that headroom (ROADMAP item 3, the
CvxCluster thesis: reconcile cost should scale with the delta, not the
population). The fingerprints the ledger already computes per stage
become MEMO KEYS: an unchanged-input pass serves the prior output
instead of recomputing it, and the outcome meters as
`recompute_work_total{outcome="delta_served"}`.

Serving is never trusted, it is POLICED — the Gavel template of letting
measurement, not hope, govern the shortcut:

- **integrity oracle on every served solve** — a served SolveResult
  still flows through `facade.finish_solve` → `_verify_integrity`, so
  the PR 14 feasibility oracle validates each served placement exactly
  like a freshly dispatched one;
- **audit cadence** — every `audit_every`-th serve of a key is refused:
  the caller recomputes fresh and calls `confirm()` (fingerprints
  match) or `diverge()` (they don't). A divergence invalidates the
  entry AND opens a per-key cooldown during which re-memoization is
  declined — the warm path's never-wrong-twice ladder;
- **watchdog** — an entry that reached its audit cadence and never got
  a fresh confirm is reported by `stale()`; the `delta_staleness`
  invariant (obs/watchdog.py) pages when one lingers past a sim-time
  grace;
- **invalidation ladder** — every eviction meters
  `delta_invalidations_total{stage,reason}` with a reason from
  INVALIDATION_REASONS; `make obs-audit` asserts each reason is
  constructed by tests/test_delta.py.

No wall-clock anywhere: staleness is counted in serves-since-confirm,
and the watchdog applies its own sim-time grace — a memo must never
make a repeat-determinism contract time-dependent.

Opt-out: `KARPENTER_TPU_DELTA=0` disarms the plane process-wide (every
stage recomputes, byte-identical to the pre-delta pipeline);
`KARPENTER_TPU_DELTA_AUDIT` sets the audit cadence (0 = audit every
serve, i.e. the memo never serves).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..obs.recompute import (encoded_fingerprint, fingerprint,
                             fingerprint_bytes, fingerprint_fold)

# Memo domains — the four high-redundancy stages the c16 regime
# measured (docs/delta.md). Keys are namespaced (stage, *owner_key).
DOMAINS: Tuple[str, ...] = ("solve", "affinity", "spread", "optimizer")

# Why an entry left the memo. docs/delta.md documents the ladder;
# `make obs-audit` asserts every reason is constructed by
# tests/test_delta.py (the same canonical-test contract as the
# recompute taxonomy).
INVALIDATION_REASONS: Tuple[str, ...] = (
    "divergence",   # audit recompute disagreed with the stored output
    "epoch",        # same key re-stored under a NEW input fingerprint
    "quarantine",   # integrity violation quarantined the owning facade
    "capacity",     # LRU bound pushed the entry out
    "disarm",       # explicit force-cold / plane-wide invalidation
)

# serves allowed between fresh confirms (KARPENTER_TPU_DELTA_AUDIT
# overrides; 0 = every pass recomputes)
AUDIT_EVERY = 16
# stores declined after a divergence before the key may memoize again —
# the same never-wrong-twice constant as facade.FALLBACK_COOLDOWN
COOLDOWN = 8
# memo entries kept (LRU). Entries are host-cheap (a decoded result or
# a mask descriptor), but unbounded growth across facades/pools would
# still be a leak; evictions meter reason="capacity".
MAX_ENTRIES = 1024


class _Entry:
    __slots__ = ("fp", "value", "check_fp", "serves", "since_confirm",
                 "confirms")

    def __init__(self, fp: int, value: Any, check_fp: Optional[int]):
        self.fp = fp
        self.value = value
        self.check_fp = check_fp
        self.serves = 0          # lifetime serves of this entry
        self.since_confirm = 0   # serves since the last fresh confirm
        self.confirms = 0


class DeltaPlane:
    """Process-wide serve-and-verify memo store (singleton DELTA,
    /debug/delta route). Thread-safe; seed-deterministic — outcomes
    depend only on the call sequence, never on time or RNG."""

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        # internal key (stage, *key) -> _Entry, LRU-ordered
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        # internal key -> stores still to decline (never-wrong-twice)
        self._cooldown: Dict[tuple, int] = {}
        self.stats = {
            "serves": 0, "misses": 0, "stores": 0, "confirms": 0,
            "divergences": 0, "audits_due": 0, "declined": 0,
        }
        self._invalidations: Dict[Tuple[str, str], int] = {}

    # --- knobs (read per call: tests flip the env mid-process) -------------
    @property
    def armed(self) -> bool:
        return os.environ.get("KARPENTER_TPU_DELTA", "1") != "0"

    @property
    def audit_every(self) -> int:
        try:
            return int(os.environ.get("KARPENTER_TPU_DELTA_AUDIT",
                                      str(AUDIT_EVERY)))
        except ValueError:
            return AUDIT_EVERY

    # --- the serve/verify protocol -----------------------------------------
    def serve(self, stage: str, key: tuple,
              fp: int) -> Optional[Tuple[Any, bool]]:
        """Try to serve `stage` work for `key` at input fingerprint
        `fp`. Returns None on a miss (no entry, fingerprint changed,
        plane disarmed) — the caller computes fresh and `store()`s.
        Returns (value, audit_due): audit_due=False is a clean serve
        (the caller uses the value and meters delta_served);
        audit_due=True means the cadence expired — the caller must
        recompute fresh and call `confirm()` or `diverge()`, NOT use
        the value."""
        if not self.armed:
            return None
        ik = (stage,) + tuple(key)
        with self._lock:
            ent = self._entries.get(ik)
            if ent is None or ent.fp != int(fp):
                self.stats["misses"] += 1
                return None
            self._entries.move_to_end(ik)
            if ent.since_confirm >= self.audit_every:
                self.stats["audits_due"] += 1
                self._meter(stage, "audit")
                return ent.value, True
            ent.serves += 1
            ent.since_confirm += 1
            self.stats["serves"] += 1
        self._meter(stage, "served")
        return ent.value, False

    def store(self, stage: str, key: tuple, fp: int, value: Any,
              check_fp: Optional[int] = None) -> bool:
        """Memoize freshly computed `stage` output. Declined (False)
        while the key's divergence cooldown is open or the plane is
        disarmed. Replacing an entry under a NEW fingerprint meters an
        `epoch` invalidation (the world moved; the old output is
        unservable by construction)."""
        if not self.armed:
            return False
        ik = (stage,) + tuple(key)
        with self._lock:
            cd = self._cooldown.get(ik, 0)
            if cd > 0:
                self._cooldown[ik] = cd - 1
                if cd == 1:
                    del self._cooldown[ik]
                self.stats["declined"] += 1
                return False
            prior = self._entries.pop(ik, None)
            if prior is not None and prior.fp != int(fp):
                self._count_invalidation(stage, "epoch")
            self._entries[ik] = _Entry(int(fp), value, check_fp)
            self.stats["stores"] += 1
            evicted: List[tuple] = []
            while len(self._entries) > self.max_entries:
                old_ik, _ = self._entries.popitem(last=False)
                evicted.append(old_ik)
            for old_ik in evicted:
                self._count_invalidation(old_ik[0], "capacity")
        self._meter(stage, "stored")
        return True

    def confirm(self, stage: str, key: tuple, fp: int,
                value: Any = None,
                check_fp: Optional[int] = None) -> None:
        """An audit recompute MATCHED the stored output: reset the
        serve-since-confirm counter (and refresh the stored value —
        the fresh copy is at least as good as the old one)."""
        ik = (stage,) + tuple(key)
        with self._lock:
            ent = self._entries.get(ik)
            if ent is None or ent.fp != int(fp):
                return
            ent.since_confirm = 0
            ent.confirms += 1
            if value is not None:
                ent.value = value
            if check_fp is not None:
                ent.check_fp = check_fp
            self.stats["confirms"] += 1
        self._meter(stage, "confirmed")

    def diverge(self, stage: str, key: tuple) -> None:
        """An audit recompute DISAGREED with the stored output: drop
        the entry (reason `divergence`) and open the never-wrong-twice
        cooldown — the next COOLDOWN stores for this key are declined,
        so a systematically wrong shortcut cannot re-arm itself."""
        ik = (stage,) + tuple(key)
        with self._lock:
            self._entries.pop(ik, None)
            self._cooldown[ik] = COOLDOWN
            self.stats["divergences"] += 1
            self._count_invalidation(stage, "divergence")

    def invalidate(self, prefix: tuple = (), *,
                   reason: str = "disarm") -> int:
        """Drop every entry whose internal key starts with `prefix`
        (empty prefix = the whole plane). The facade's integrity
        quarantine calls this with reason="quarantine"; bench cold
        phases and force_cold hooks use reason="disarm"."""
        assert reason in INVALIDATION_REASONS, reason
        p = tuple(prefix)
        n = len(p)
        with self._lock:
            victims = [ik for ik in self._entries if ik[:n] == p]
            for ik in victims:
                del self._entries[ik]
                self._count_invalidation(ik[0], reason)
        return len(victims)

    # --- read side ----------------------------------------------------------
    def stale(self) -> List[Tuple[str, tuple, int]]:
        """Entries that reached their audit cadence and have NOT been
        freshly confirmed — `serve()` refuses them, but one lingering
        means the owning loop stopped closing its audit contract. The
        watchdog's `delta_staleness` invariant feeds on this (the
        sim-time grace lives there, not here)."""
        out: List[Tuple[str, tuple, int]] = []
        with self._lock:
            cadence = self.audit_every
            for ik, ent in self._entries.items():
                if ent.since_confirm >= cadence:
                    out.append((ik[0], ik[1:], ent.since_confirm))
        return out

    def entries(self, stage: Optional[str] = None) -> int:
        with self._lock:
            if stage is None:
                return len(self._entries)
            return sum(1 for ik in self._entries if ik[0] == stage)

    def snapshot(self) -> dict:
        with self._lock:
            per_stage: Dict[str, int] = {}
            for ik in self._entries:
                per_stage[ik[0]] = per_stage.get(ik[0], 0) + 1
            inval = {}
            for (st, reason), n in sorted(self._invalidations.items()):
                inval.setdefault(st, {})[reason] = n
            return {
                "armed": self.armed,
                "audit_every": self.audit_every,
                "entries": len(self._entries),
                "per_stage": per_stage,
                "cooldowns": len(self._cooldown),
                "invalidations": inval,
                "domains": list(DOMAINS),
                "reasons": list(INVALIDATION_REASONS),
                **self.stats,
            }

    def payload(self, query: str = "") -> dict:
        return self.snapshot()

    def reset(self) -> None:
        """Test/bench hook: forget everything WITHOUT metering — a
        reset models a fresh process, not an invalidation event."""
        with self._lock:
            self._entries.clear()
            self._cooldown.clear()
            for k in self.stats:
                self.stats[k] = 0
            self._invalidations.clear()

    # --- metering -----------------------------------------------------------
    def _count_invalidation(self, stage: str, reason: str) -> None:
        # under self._lock
        key = (stage, reason)
        self._invalidations[key] = self._invalidations.get(key, 0) + 1
        from ..metrics import DELTA_INVALIDATIONS
        DELTA_INVALIDATIONS.inc(stage=stage, reason=reason)

    def _meter(self, stage: str, event: str) -> None:
        from ..metrics import DELTA_MEMO
        DELTA_MEMO.inc(stage=stage, event=event)


# --- fingerprint / copy helpers for the solve memo --------------------------
# The ledger's solve fingerprint (encoded_fingerprint) deliberately
# digests only the request/compat/zone/cap rows — enough to meter
# redundancy, NOT enough to key a memo: max_per_node, conflict
# matrices, spread flags, and the hard-row fallbacks all change solver
# output without changing those rows. The memo key digests everything
# the solver reads.
_ENC_MEMO_ATTRS: Tuple[str, ...] = (
    "max_per_node", "spread_zone", "conflict", "spread_soft",
    "compat_hard", "zone_hard", "cap_hard", "zone_conflict",
)


def _array_fp(arr) -> int:
    if arr is None:
        return 0x9E3779B97F4A7C15
    import numpy as np
    a = np.ascontiguousarray(arr)
    return fingerprint_bytes(a.tobytes()) ^ fingerprint(a.dtype.str,
                                                        a.shape)


def solve_memo_fingerprint(enc, *extra) -> int:
    """The solve-memo key fingerprint: the ledger's encoded content
    digest folded with every remaining solver-visible encoding field
    plus caller context (catalog key, backend, gating flags)."""
    parts = [encoded_fingerprint(enc)]
    parts.extend(_array_fp(getattr(enc, name, None))
                 for name in _ENC_MEMO_ATTRS)
    if extra:
        parts.append(fingerprint(*extra))
    return fingerprint_fold(parts)


def group_terms_fingerprint(enc) -> int:
    """Digest of the per-group scheduling-constraint identity (each
    group representative's constraint signature, in encoding order):
    the occupancy signature the affinity/spread memos key on is
    zone+count only, so the group side must carry the selector
    semantics that decide what those occupants match. Signatures are
    name-free — same-signature pod churn keeps the memo warm."""
    return fingerprint(*[repr(g.representative.constraint_signature())
                         for g in getattr(enc, "groups", ())])


def solve_result_fingerprint(result) -> int:
    """Content digest of a SolveResult — the audit comparator AND the
    stored check fingerprint a divergence is judged against. Covers
    everything commit consumes: launches, unschedulable counts, and
    each virtual node's identity, masks, cumulative load, and
    placement maps."""
    parts: list = [tuple(tuple(l) for l in result.launches),
                   tuple(sorted(result.unschedulable.items()))]
    for n in result.nodes:
        parts.append((
            n.existing_name, int(n.type_idx),
            _array_fp(n.zone_mask), _array_fp(n.cap_mask),
            _array_fp(n.cum),
            tuple(sorted(n.pods_by_group.items())),
            tuple(sorted(n.prior_by_group.items())),
            _array_fp(n.banned_groups),
        ))
    return fingerprint(*parts)


def existing_context_fingerprint(existing) -> int:
    """Content digest of the standing-fleet context a solve consumes —
    the prepared VirtualNodes AFTER attach_existing_context populated
    prior_by_group (resident pods mapped onto the current enc's groups)
    and banned_groups (resident anti-affinity bans). Everything the
    packer reads off an existing node is covered, including its name
    (the memoized result's existing_placements reference it), so an
    unchanged-fingerprint serve replays against a byte-identical
    cluster context. Deliberately order-SENSITIVE: the packer walks the
    node list in order, so a reordered context is a different input
    even when the set matches."""
    if not existing:
        return 0
    return fingerprint(*[
        (vn.existing_name or "", int(vn.type_idx),
         _array_fp(vn.zone_mask), _array_fp(vn.cap_mask),
         _array_fp(vn.cum),
         tuple(sorted(vn.pods_by_group.items())),
         tuple(sorted(vn.prior_by_group.items())),
         _array_fp(vn.banned_groups))
        for vn in existing])


def copy_spread_constraints(cons):
    """Independent copy of a facade _spread_constraints() output
    (Dict[group idx -> List[SpreadConstraintCounts]] or None): the
    spread split water-fills against the counts vectors, so the memo
    must never hand out its own arrays."""
    if cons is None:
        return None
    from .binpack import SpreadConstraintCounts
    return {gi: [SpreadConstraintCounts(counts=c.counts.copy(),
                                        max_skew=c.max_skew,
                                        self_matches=c.self_matches,
                                        soft=c.soft)
                 for c in lst]
            for gi, lst in cons.items()}


def spread_constraints_fingerprint(cons) -> int:
    """Content digest of a _spread_constraints() output — the spread
    memo's audit comparator."""
    if cons is None:
        return 0x9E3779B97F4A7C15
    parts = []
    for gi in sorted(cons):
        for c in cons[gi]:
            parts.append((gi, _array_fp(c.counts), int(c.max_skew),
                          bool(c.self_matches), bool(c.soft)))
    return fingerprint(*parts)


def copy_solve_result(result):
    """Independent copy of a SolveResult: the memo must never alias
    node objects the caller goes on to mutate (bind/commit extends
    pods_by_group in place)."""
    from ..state.cluster import copy_virtual_node
    from .binpack import SolveResult
    return SolveResult(
        nodes=[copy_virtual_node(n) for n in result.nodes],
        unschedulable=dict(result.unschedulable),
        launches=[tuple(l) for l in result.launches])


# THE process-wide plane.
DELTA = DeltaPlane()

from ..obs.exposition import register_debug_route  # noqa: E402 (after DELTA)

register_debug_route("/debug/delta",
                     lambda plane, query: plane.payload(query),
                     owner=DELTA)
