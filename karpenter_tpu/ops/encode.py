"""Tensorization: flatten catalog + pods into dense arrays for the solver.

This is the host→device boundary of the build plan (SURVEY.md §7):

  catalog  →  allocatable[T,R], price[T,Z,C], available[T,Z,C],
              label_val[T,L] (int-coded categorical), label_num[T,Ln]
  pods     →  exact-dedupe groups (constraint_signature) →
              requests[G,R], counts[G], compat[G,T], allow_zone[G,Z],
              allow_cap[G,C], max_per_node[G]

The Requirements set-algebra (In/NotIn/Exists/DoesNotExist/Gt/Lt) lowers to
vocabulary-interned integer comparisons: each categorical label key gets a
vocab (value→id), each instance type a single value id per key (types are
built from single-valued labels), and each pod constraint becomes a boolean
allowed-vector over the vocab gathered through the type's value ids. Numeric
keys additionally keep float values so Gt/Lt stay exact. Zone and
capacity-type constraints map onto the offering axes (Z, C) instead of the
label mask — they vary per offering, not per type (reference models this the
same way: Offering carries its own zone/capacity-type requirements,
offering/offering.go:140-149).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models import labels as L
from ..models.instancetype import InstanceType
from ..models.pod import Pod, Taint, intern_pods, term_selects, tolerates_all
from ..models.requirements import (Operator, Requirement, Requirements,
                                   ValueSet, _tolerates_absence)
from ..models.resources import Resources, num_resources, resource_axis

ABSENT = -1
CAPACITY_TYPES = (L.CAPACITY_ON_DEMAND, L.CAPACITY_SPOT, L.CAPACITY_RESERVED)

# exotic-instance filter (reference filter.go:279 ExoticInstanceFilter):
# metal and accelerator-carrying types are excluded unless the pod requests
# the resource or its requirements show explicit intent via these keys
from ..models.resources import GPU as _R_GPU
from ..models.resources import NVIDIA_GPU as _R_NVIDIA
from ..models.resources import TPU_CHIP as _R_TPU

EXOTIC_RESOURCES = (_R_NVIDIA, _R_GPU, _R_TPU)
EXOTIC_INTENT_KEYS = frozenset({
    L.INSTANCE_TYPE, L.INSTANCE_FAMILY, L.INSTANCE_SIZE,
    L.INSTANCE_GPU_NAME, L.INSTANCE_GPU_MANUFACTURER, L.INSTANCE_GPU_COUNT,
    L.INSTANCE_GPU_MEMORY, L.INSTANCE_ACCELERATOR_NAME,
    L.INSTANCE_ACCELERATOR_MANUFACTURER, L.INSTANCE_ACCELERATOR_COUNT,
})


def wants_exotic(rep: Pod, reqs: "Requirements") -> bool:
    """Does a pod express intent for exotic (metal/accelerator) types —
    either by requesting an exotic resource or by constraining an
    exotic-intent label key? The ONE definition both the encoder and the
    co-location planner consult."""
    return (any(rep.requests.get(r, 0.0) > 0 for r in EXOTIC_RESOURCES)
            or any(k in EXOTIC_INTENT_KEYS for k in reqs.keys()))


def exotic_mask(cat: "CatalogTensors") -> np.ndarray:
    """bool [T]: metal or accelerator-carrying types (reference
    filter.go:279: these only serve pods that ask for them — a cheap spot
    GPU box must not absorb plain web pods)."""
    ex = np.zeros(cat.T, bool)
    for rname in EXOTIC_RESOURCES:
        if rname in cat.resources:
            ex |= cat.allocatable[:, cat.resources.index(rname)] > 0
    if L.INSTANCE_SIZE in cat.label_keys:
        metal_id = cat.vocab[L.INSTANCE_SIZE].get("metal")
        if metal_id is not None:
            ex |= cat.label_val[:, cat.label_keys.index(L.INSTANCE_SIZE)] == metal_id
    return ex


@dataclass
class CatalogTensors:
    names: List[str]                      # [T]
    zones: List[str]                      # [Z]
    captypes: Tuple[str, ...]             # [C]
    resources: Tuple[str, ...]            # [R] axis snapshot
    allocatable: np.ndarray               # f32 [T, R]
    price: np.ndarray                     # f32 [T, Z, C], +inf = no offering
    available: np.ndarray                 # bool [T, Z, C]
    reservation_cap: np.ndarray           # i32 [T, Z, C]
    label_keys: List[str]                 # [Lc] categorical keys
    vocab: Dict[str, Dict[str, int]]      # key -> value -> id
    label_val: np.ndarray                 # i32 [T, Lc], ABSENT where missing
    numeric_keys: List[str]               # [Ln]
    label_num: np.ndarray                 # f32 [T, Ln], nan where missing
    name_to_idx: Dict[str, int] = field(default_factory=dict)
    # bool [T, Z, C]: the offering is a capacity-block reservation
    # (reference CapacityReservationType capacity-block, filter.go:163-228
    # — blocks only serve launches that explicitly target reserved
    # capacity; the facade masks these out of `available` otherwise)
    is_block: Optional[np.ndarray] = None
    # f32 [T, Z, R]: zone-VARYING daemonset reservation (zone-pinned
    # daemonsets that only partially overlap the pool's zones). A node
    # reserves the elementwise max over its remaining zone mask, so a
    # node whose zones narrow away from the daemonset's zones gets its
    # headroom back — more accurate than the reference, which charges
    # any template-compatible daemonset unconditionally (core scheduler
    # daemonset simulation). Zone-invariant overhead is baked into
    # `allocatable` instead (apply_daemonset_overhead). None = absent.
    zone_overhead: Optional[np.ndarray] = None
    # encode-cache key for THIS immutable catalog view (ops/encode_cache):
    # the facade stamps it from the (nodeclass-hash, catalog-epoch) tensor
    # key and extends it for every derived view (block gating, daemonset
    # overhead). None = view not cache-addressable; encode_pods then
    # computes every row fresh. Callers that mutate tensors in place
    # (tests poking availability holes) must clear or re-key it.
    cache_token: Optional[tuple] = None

    @property
    def T(self) -> int:
        return len(self.names)

    @property
    def Z(self) -> int:
        return len(self.zones)

    @property
    def C(self) -> int:
        return len(self.captypes)


def encode_catalog(types: Sequence[InstanceType],
                   zones: Optional[Sequence[str]] = None) -> CatalogTensors:
    if zones is None:
        zs: List[str] = sorted({o.zone for t in types for o in t.offerings})
    else:
        zs = list(zones)
    zidx = {z: i for i, z in enumerate(zs)}
    cidx = {c: i for i, c in enumerate(CAPACITY_TYPES)}

    # collect label keys and vocabularies across the whole catalog
    label_keys: List[str] = []
    numeric_keys: List[str] = []
    seen_keys = set()
    for t in types:
        for k in t.requirements.keys():
            if k in L.OFFERING_LABELS or k in seen_keys:
                continue
            seen_keys.add(k)
            label_keys.append(k)
            if k in L.NUMERIC_LABELS:
                numeric_keys.append(k)
    vocab: Dict[str, Dict[str, int]] = {k: {} for k in label_keys}
    for t in types:
        for k in label_keys:
            vs = t.requirements.get(k)
            if vs is not None and not vs.complement:
                for v in vs.values:
                    vocab[k].setdefault(v, len(vocab[k]))

    # allocatable vectors first (to_vector may auto-register resources);
    # read the axis length only after all vectors are built
    alloc_vecs = [t.allocatable().to_vector() for t in types]
    R = num_resources()
    T = len(types)
    allocatable = np.zeros((T, R), np.float32)
    for i, v in enumerate(alloc_vecs):
        allocatable[i, : len(v)] = v

    kidx = {k: j for j, k in enumerate(label_keys)}
    nidx = {k: j for j, k in enumerate(numeric_keys)}
    label_val = np.full((T, len(label_keys)), ABSENT, np.int32)
    label_num = np.full((T, len(numeric_keys)), np.nan, np.float32)
    price = np.full((T, len(zs), len(CAPACITY_TYPES)), np.inf, np.float32)
    available = np.zeros((T, len(zs), len(CAPACITY_TYPES)), bool)
    reservation_cap = np.zeros((T, len(zs), len(CAPACITY_TYPES)), np.int32)
    is_block = np.zeros((T, len(zs), len(CAPACITY_TYPES)), bool)

    for i, t in enumerate(types):
        for k in label_keys:
            vs = t.requirements.get(k)
            if vs is None or vs.complement or len(vs.values) != 1:
                continue  # multi-valued/complement type labels stay ABSENT
            (v,) = vs.values
            label_val[i, kidx[k]] = vocab[k][v]
            if k in nidx:
                try:
                    label_num[i, nidx[k]] = float(v)
                except ValueError:
                    pass
        for o in t.offerings:
            zi = zidx.get(o.zone)
            ci = cidx.get(o.capacity_type)
            if zi is None or ci is None:
                continue
            price[i, zi, ci] = o.price
            available[i, zi, ci] = o.available
            reservation_cap[i, zi, ci] = o.reservation_capacity
            # last-write-wins like the sibling per-cell fields — a sticky
            # OR here could mark a colliding non-block reserved offering
            # as a block and gate it away for unconstrained pools
            is_block[i, zi, ci] = (o.reservation_id is not None
                                   and o.reservation_type == "capacity-block")

    return CatalogTensors(
        names=[t.name for t in types], zones=zs, captypes=CAPACITY_TYPES,
        resources=tuple(resource_axis()), allocatable=allocatable, price=price,
        available=available, reservation_cap=reservation_cap,
        is_block=is_block,
        label_keys=label_keys, vocab=vocab, label_val=label_val,
        numeric_keys=numeric_keys, label_num=label_num,
        name_to_idx={t.name: i for i, t in enumerate(types)},
    )


# --- pod grouping -----------------------------------------------------------


@dataclass
class PodGroup:
    pods: List[Pod]
    representative: Pod

    @property
    def count(self) -> int:
        return len(self.pods)


def group_pods(pods: Sequence[Pod]) -> List[PodGroup]:
    """Exact-dedupe pods into interchangeable groups (see
    Pod.constraint_signature). Order is deterministic: groups sorted by
    descending cpu-then-memory of the representative — the FFD 'decreasing'
    ordering (reference designs/bin-packing.md sorts pods by size desc).

    Grouping keys on the interned int group id (Pod.group_key): pods the
    store already admitted cost one attribute read each; raw pods go
    through the batched intern_pods fast path first."""
    intern_pods(pods)
    by_gid: Dict[int, List[Pod]] = {}
    for p in pods:
        lst = by_gid.get(p._gid)
        if lst is None:
            by_gid[p._gid] = [p]
        else:
            lst.append(p)
    return _finalize_groups(
        [PodGroup(pods=v, representative=v[0]) for v in by_gid.values()])


def groups_from_lists(lists: Sequence[Sequence[Pod]]) -> List[PodGroup]:
    """PodGroups from pre-bucketed pod lists (the store's admission-time
    pending-group index) — no per-pod pass. Each inner list must be one
    signature-equal set; the lists are consumed (may be mutated)."""
    return _finalize_groups(
        [PodGroup(pods=list(ps) if not isinstance(ps, list) else ps,
                  representative=ps[0]) for ps in lists if ps])


def _finalize_groups(groups: List[PodGroup]) -> List[PodGroup]:
    if len(groups) > 1:
        # intern-rotation safety: the gid table rotates at capacity, so
        # pods admitted across a rotation can hold DIFFERENT gids for
        # equal signatures; merge such split groups by the
        # representatives' (cached) signatures so grouping stays exactly
        # signature-equality — splitting one interchangeable set would
        # silently weaken combined topology-spread/anti-affinity caps
        by_sig: Dict[tuple, PodGroup] = {}
        merged: List[PodGroup] = []
        for g in groups:
            sig = g.representative.constraint_signature()
            prev = by_sig.get(sig)
            if prev is None:
                by_sig[sig] = g
                merged.append(g)
            else:
                prev.pods.extend(g.pods)
        groups = merged
    groups.sort(key=lambda g: (-g.representative.requests.get("cpu"),
                               -g.representative.requests.get("memory"),
                               g.representative.name))
    return groups


@dataclass
class EncodedPods:
    groups: List[PodGroup]
    requests: np.ndarray      # f32 [G, R]
    counts: np.ndarray        # i32 [G]
    compat: np.ndarray        # bool [G, T]
    allow_zone: np.ndarray    # bool [G, Z]
    allow_cap: np.ndarray     # bool [G, C]
    max_per_node: np.ndarray  # i32 [G], 0 = unlimited
    spread_zone: np.ndarray   # bool [G] — zone topology-spread requested
    # symmetric bool [G, G] (None = no cross-group anti-affinity anywhere):
    # conflict[i, j] → groups i and j may not share a node (hostname
    # anti-affinity whose selector matches the other group's labels).
    # The diagonal is False — within-group exclusion is max_per_node.
    conflict: Optional[np.ndarray] = None
    # bool [G] (None = all-False): the zone spread flagged in spread_zone is
    # ScheduleAnyway — split balances over feasible zones only and never
    # produces unschedulable subgroups
    spread_soft: Optional[np.ndarray] = None
    # bool [G, T] (None = identical to compat): the type mask BEFORE
    # preferred-node-affinity narrowing. Downstream narrowing (zone-split
    # pinning, NodePool-limit caps) can invalidate a preference that looked
    # feasible at encode time; the facade falls back to this row so a soft
    # preference never blocks scheduling.
    compat_hard: Optional[np.ndarray] = None
    # bool [G, Z] / [G, C] (None = identical to allow_zone / allow_cap):
    # the offering-axis masks before preferred narrowing — zone and
    # capacity-type preferences narrow these axes the way type preferences
    # narrow compat, with the same hard-row fallback.
    zone_hard: Optional[np.ndarray] = None
    cap_hard: Optional[np.ndarray] = None
    # symmetric bool [G, G] (None = none anywhere): groups that may not
    # share a ZONE (zone-topology anti-affinity; set by
    # affinity.apply_zone_affinity, consumed by validate_solution — the
    # solvers themselves rely on the pre-pass's disjoint allow_zone masks)
    zone_conflict: Optional[np.ndarray] = None
    # pod keys the taint filter dropped (whole signature-groups whose
    # representative doesn't tolerate the NodePool taints) — the facade
    # reads this instead of re-scanning O(pods) for the difference
    dropped_keys: Optional[List[str]] = None
    # encode-cache accounting for THIS encode (groups served from /
    # inserted into the EncodeContext); zero when encoded uncached.
    # Informational only — rebuilt encodings (affinity/spread splits)
    # don't carry it forward
    cache_hits: int = 0
    cache_misses: int = 0

    @property
    def G(self) -> int:
        return len(self.groups)


class TermMatcher:
    """Columnar batch twin of models.pod.term_selects over a fixed pod
    population: namespaces and every selector-queried label key are
    interned to int id columns ONCE, and each (namespace, selector)
    evaluates as one vectorized compare-and-reduce, memoized per
    distinct term. The ONE vectorized selector implementation — both
    the conflict-matrix build and the zone-affinity occupancy matching
    route through it, and its semantics MUST stay identical to the
    scalar `term_selects` oracle (same-namespace gate + full selector
    containment; a randomized agreement test pins the pair)."""

    def __init__(self, pods: Sequence[Pod]):
        self.pods = list(pods)
        n = len(self.pods)
        self._ns_vocab: Dict[str, int] = {}
        ns = np.empty(n, np.int32)
        for j, p in enumerate(self.pods):
            ns[j] = self._ns_vocab.setdefault(p.namespace,
                                              len(self._ns_vocab))
        self._ns = ns
        self._cols: Dict[str, Tuple[np.ndarray, Dict[str, int]]] = {}
        self._memo: Dict[tuple, np.ndarray] = {}

    def _col(self, key: str) -> Tuple[np.ndarray, Dict[str, int]]:
        hit = self._cols.get(key)
        if hit is None:
            vocab: Dict[str, int] = {}
            ids = np.empty(len(self.pods), np.int32)
            for j, p in enumerate(self.pods):
                v = p.labels.get(key)
                ids[j] = -1 if v is None else vocab.setdefault(v, len(vocab))
            hit = (ids, vocab)
            self._cols[key] = hit
        return hit

    def matches(self, namespace: str,
                selector: Dict[str, str]) -> np.ndarray:
        """bool [N]: pods a term with `selector`, evaluated from a pod
        in `namespace`, selects (== term_selects per pod)."""
        key = (namespace, tuple(sorted(selector.items())))
        m = self._memo.get(key)
        if m is not None:
            return m
        ns_id = self._ns_vocab.get(namespace)
        if ns_id is None:
            m = np.zeros(len(self.pods), bool)
        else:
            m = self._ns == ns_id
            for k, v in selector.items():
                if not m.any():
                    break
                ids, vocab = self._col(k)
                vid = vocab.get(v)
                m = (m & (ids == vid)) if vid is not None \
                    else np.zeros(len(self.pods), bool)
        self._memo[key] = m
        return m


def build_conflicts(groups: List[PodGroup]) -> Optional[np.ndarray]:
    """Symmetric cross-group hostname-anti-affinity conflicts.

    k8s enforces required anti-affinity symmetrically: an incoming pod is
    rejected from a node if an existing pod's anti-affinity selector matches
    it, not only the other way around — so conflict[i, j] is set when
    EITHER group's term selects the other's labels (same namespace).
    Returns None when no group carries anti terms (the common case), which
    lets every backend skip conflict tracking entirely.

    Vectorized through TermMatcher — the O(G² × terms) Python pair walk
    was the whole re-encode cost at 2000-signature fleets."""
    G = len(groups)
    anti = [[t for t in g.representative.affinity_terms
             if t.anti and t.required and t.topology_key == L.HOSTNAME]
            for g in groups]
    if not any(anti):
        return None
    reps = [g.representative for g in groups]
    matcher = TermMatcher(reps)
    per_group: Dict[int, np.ndarray] = {}  # i -> OR of its terms' matches
    for i in range(G):
        for t in anti[i]:
            m = matcher.matches(reps[i].namespace, t.label_selector)
            prev = per_group.get(i)
            per_group[i] = m if prev is None else (prev | m)
    if not per_group:
        return None
    # both symmetry directions land as two batched ORs (idx is unique, so
    # the fancy-index read-modify-write is safe) — per-term strided
    # column writes were the scaling wall at 2000-signature fleets
    conflict = np.zeros((G, G), bool)
    idx = np.fromiter(per_group.keys(), np.intp, len(per_group))
    M = np.stack([per_group[int(i)] for i in idx])
    conflict[idx] |= M
    conflict[:, idx] |= M.T
    np.fill_diagonal(conflict, False)
    return conflict if conflict.any() else None


def _vocab_values(cat: CatalogTensors, key: str) -> np.ndarray:
    """Unicode array of a key's vocab values ordered by id, memoized on
    the CatalogTensors instance — the columnar side of the set-algebra
    lowering (one np.isin pass replaces per-value ValueSet.contains)."""
    memo = getattr(cat, "_vocab_arrays", None)
    if memo is None:
        memo = {}
        cat._vocab_arrays = memo
    arr = memo.get(key)
    if arr is None:
        vocab = cat.vocab[key]
        arr = np.empty(len(vocab), dtype=object)
        for v, i in vocab.items():
            arr[i] = v
        arr = arr.astype(str) if len(vocab) else np.empty(0, dtype="<U1")
        memo[key] = arr
    return arr


def _allowed_vector(vs: ValueSet, vocab: Dict[str, int],
                    cat: Optional[CatalogTensors] = None,
                    key: Optional[str] = None) -> np.ndarray:
    if (cat is not None and key is not None
            and vs.gt is None and vs.lt is None and not vs.dne):
        # vectorized membership over the memoized id-ordered value array;
        # bounds/DoesNotExist fall through to the exact scalar oracle
        arr = _vocab_values(cat, key)
        if not vs.values:
            base = np.zeros(len(arr), bool)
        else:
            base = np.isin(arr, tuple(vs.values))
        return ~base if vs.complement else base
    out = np.zeros(len(vocab), bool)
    for v, i in vocab.items():
        out[i] = vs.contains(v)
    return out


def _key_mask(vs: ValueSet, key: str, cat: CatalogTensors,
              template: Optional[Dict[str, str]] = None) -> np.ndarray:
    """bool [T]: which instance types satisfy one requirement key.

    template: NodePool-template node labels (spec labels + single-valued
    requirements). A key NO instance type carries resolves against the
    template — every launched node wears those labels, so a pod
    nodeSelector on one must schedule (the reference satisfies pod
    requirements from the NodeClaimTemplate the same way,
    scheduling.md:17-31). Catalog-known keys ignore the template: node
    labels never override instance properties."""
    T = cat.T
    absent_ok = _tolerates_absence(vs)
    has_bounds = vs.gt is not None or vs.lt is not None
    if has_bounds and key in cat.numeric_keys:
        col = cat.label_num[:, cat.numeric_keys.index(key)]
        mask = np.ones(T, bool)
        if vs.gt is not None:
            mask &= col > vs.gt
        if vs.lt is not None:
            mask &= col < vs.lt
        # NaN comparisons are False already; absent handled below
        if vs.values and key in cat.vocab:  # bounds + In/NotIn combination
            mask &= _categorical_mask(vs, key, cat, handle_absent=False)
        absent = np.isnan(col)
        return np.where(absent, absent_ok, mask)
    if key not in cat.vocab or not cat.vocab[key]:
        if template is not None and key in template:
            return np.full(T, vs.contains(template[key]), bool)
        # key no instance type carries: satisfied only if absence tolerated
        return np.full(T, absent_ok, bool)
    return _categorical_mask(vs, key, cat)


def _categorical_mask(vs: ValueSet, key: str, cat: CatalogTensors,
                      handle_absent: bool = True) -> np.ndarray:
    ids = cat.label_val[:, cat.label_keys.index(key)]
    allowed = _allowed_vector(vs, cat.vocab[key], cat, key)
    mask = np.where(ids >= 0, allowed[np.clip(ids, 0, None)], False)
    if handle_absent:
        mask = np.where(ids == ABSENT, _tolerates_absence(vs), mask)
    return mask


def compat_mask(reqs: Requirements, cat: CatalogTensors,
                template: Optional[Dict[str, str]] = None) -> np.ndarray:
    """bool [T]: types compatible with a Requirements conjunction
    (zone/capacity-type keys excluded — they map to the offering axes;
    template = NodePool-template node labels, see _key_mask)."""
    mask = np.ones(cat.T, bool)
    for key in reqs.keys():
        if key in L.OFFERING_LABELS:
            continue
        mask &= _key_mask(reqs.get(key), key, cat, template)
    return mask


def _axis_allow(reqs: Requirements, key: str, axis_values: Sequence[str]) -> np.ndarray:
    vs = reqs.get(key)
    if vs is None:
        return np.ones(len(axis_values), bool)
    return np.array([vs.contains(v) for v in axis_values], bool)


@dataclass
class _Row:
    """One signature's tensor row — the pure function of
    (constraint_signature, catalog view, pool context) the EncodeContext
    persists. `differs_*` record whether preferred-affinity narrowing
    changed each axis (they reproduce the batch-level
    `(hard != work).any()` hard-rows-or-None decision on gather)."""
    compat: np.ndarray
    zone: np.ndarray
    capm: np.ndarray
    hard_t: np.ndarray
    hard_z: np.ndarray
    hard_c: np.ndarray
    req: np.ndarray
    max_per_node: int
    spread_zone: bool
    spread_soft: bool
    differs_t: bool
    differs_z: bool
    differs_c: bool


def _group_row(rep: Pod, cat: CatalogTensors,
               extra_requirements: Optional[Requirements],
               template_labels: Optional[Dict[str, str]],
               exotic: Optional[np.ndarray],
               raw_vec, R: int) -> _Row:
    reqs = rep.scheduling_requirements()
    if extra_requirements is not None:
        reqs = reqs.union_with(extra_requirements)
    compat = compat_mask(reqs, cat, template_labels)
    if exotic is not None and not wants_exotic(rep, reqs):
        compat &= ~exotic
    zone = _axis_allow(reqs, L.ZONE, cat.zones)
    capm = _axis_allow(reqs, L.CAPACITY_TYPE, cat.captypes)
    req = np.zeros(R, np.float32)
    req[: len(raw_vec)] = raw_vec
    hard_t, hard_z, hard_c = compat, zone, capm  # pre-preference rows
    narrowed = _apply_preferred(rep, compat, zone, capm, req, cat,
                                template_labels)
    if narrowed is not None:
        compat, zone, capm = narrowed  # fresh arrays; hard_* keep originals
    max_per_node = 1 if rep.has_self_anti_affinity() else 0
    spread_zone = False
    any_hard_zone = False
    for tsc in rep.topology_spread:
        if tsc.topology_key == L.ZONE:
            spread_zone = True
            if tsc.when_unsatisfiable == "DoNotSchedule":
                any_hard_zone = True
        if tsc.topology_key == L.HOSTNAME and tsc.when_unsatisfiable == "DoNotSchedule":
            # Conservative encoding of hostname maxSkew as a per-node
            # cap: while any eligible node has zero matching pods (always
            # true the moment the provisioner opens a fresh node), skew =
            # max-count − 0, so count per node may not exceed maxSkew.
            # This can over-spread relative to a cluster with no empty
            # eligible nodes (where k8s would allow denser layouts) but
            # never violates the constraint.
            cap = max(1, tsc.max_skew)
            max_per_node = cap if max_per_node == 0 else min(max_per_node, cap)
    return _Row(
        compat=compat, zone=zone, capm=capm,
        hard_t=hard_t, hard_z=hard_z, hard_c=hard_c, req=req,
        max_per_node=max_per_node, spread_zone=spread_zone,
        spread_soft=spread_zone and not any_hard_zone,
        differs_t=compat is not hard_t and bool((compat != hard_t).any()),
        differs_z=zone is not hard_z and bool((zone != hard_z).any()),
        differs_c=capm is not hard_c and bool((capm != hard_c).any()))


def encode_pods(pods: Sequence[Pod], cat: CatalogTensors,
                extra_requirements: Optional[Requirements] = None,
                taints: Optional[List[Taint]] = None,
                pregrouped: Optional[Sequence[Sequence[Pod]]] = None,
                template_labels: Optional[Dict[str, str]] = None,
                cache=None, arena=None,
                ) -> EncodedPods:
    """Group + tensorize pods against a catalog.

    extra_requirements: the NodePool template requirements, conjoined into
    every group (the reference scheduler layers NodePool requirements onto
    pod requirements the same way, scheduling.md:17-31). Pods that don't
    tolerate `taints` are dropped from the encoding per GROUP — tolerations
    are part of the constraint signature, so the representative's verdict
    is every member's verdict (caller routes dropped pods to another
    NodePool via EncodedPods.dropped_keys).

    pregrouped: optional pre-bucketed signature-equal pod lists (the
    store's admission-time pending-group index) — skips the per-pod
    grouping pass entirely; `pods` is then ignored for grouping.

    cache: an ops.encode_cache.EncodeContext for this exact
    (catalog view, extra_requirements, taints, template) combination —
    per-signature rows persist across solves and a warm re-encode
    becomes one gather. The caller owns the keying contract (the facade
    derives it from CatalogTensors.cache_token); rows returned are
    never aliased into the cache, so downstream in-place narrowing
    stays private to this encode.

    arena: an ops.encode_cache.EncodeArena supplying reusable staging
    buffers. Arrays in the returned EncodedPods are then valid only
    until the next encode that leases the same arena.
    """
    groups = (groups_from_lists(pregrouped) if pregrouped is not None
              else group_pods(pods))
    lease = arena is not None and arena.acquire()
    try:
        return _encode_groups(groups, cat, extra_requirements, taints,
                              template_labels, cache,
                              arena if lease else None)
    finally:
        if lease:
            arena.release()


def _take(arena, name, shape, dtype, zero=False):
    if arena is not None:
        return arena.take(name, shape, dtype, zero=zero)
    return np.zeros(shape, dtype) if zero else np.empty(shape, dtype)


def _encode_groups(groups: List[PodGroup], cat: CatalogTensors,
                   extra_requirements, taints, template_labels,
                   cache, arena) -> EncodedPods:
    from .encode_cache import DROPPED
    dropped_keys: List[str] = []
    hits = misses = 0

    if cache is not None:
        # --- cached path: lookup per signature, compute only the misses,
        # then ONE vectorized gather over the context's columnar rows ---
        cache.begin()  # batch boundary: a full row store rotates here
        kept: List[PodGroup] = []
        row_ids: List[Optional[int]] = []
        pend: List[Tuple[int, PodGroup, tuple]] = []  # (kept-slot, g, sig)
        miss_sigs: List[tuple] = []
        for g in groups:
            sig = g.representative.constraint_signature()
            rid = cache.lookup(sig)
            if rid is None:
                misses += 1
                miss_sigs.append(sig)
                if taints and not tolerates_all(
                        g.representative.tolerations, taints):
                    cache.insert_dropped(sig)
                    dropped_keys.extend(f"{p.namespace}/{p.name}"
                                        for p in g.pods)
                    continue
                pend.append((len(kept), g, sig))
                kept.append(g)
                row_ids.append(None)
            elif rid == DROPPED:
                hits += 1
                dropped_keys.extend(f"{p.namespace}/{p.name}"
                                    for p in g.pods)
            else:
                hits += 1
                kept.append(g)
                row_ids.append(rid)
        # settle the resource axis BEFORE computing rows: to_vector may
        # auto-register custom resources (cached reps registered theirs
        # at first encode — the axis only grows within a process)
        pend_vecs = [g.representative.requests.to_vector()
                     for _, g, _ in pend]
        R = num_resources()
        if pend:
            exotic = exotic_mask(cat)
            exotic = exotic if exotic.any() else None
            for (slot, g, sig), vec in zip(pend, pend_vecs):
                row = _group_row(g.representative, cat, extra_requirements,
                                 template_labels, exotic, vec, R)
                row_ids[slot] = cache.insert(sig, row)
        cache.stats["hits"] += hits
        cache.stats["misses"] += misses
        groups = kept
        G = len(groups)
        if G == 0:
            enc = EncodedPods(
                groups=[], requests=np.zeros((0, R), np.float32),
                counts=np.zeros(0, np.int32),
                compat=np.zeros((0, cat.T), bool),
                allow_zone=np.zeros((0, cat.Z), bool),
                allow_cap=np.zeros((0, cat.C), bool),
                max_per_node=np.zeros(0, np.int32),
                spread_zone=np.zeros(0, bool),
                spread_soft=np.zeros(0, bool),
                dropped_keys=dropped_keys or None)
        else:
            got = cache.gather(row_ids, R, arena)
            counts = np.fromiter((g.count for g in groups), np.int32, G)
            gs = groups  # bind for the memo-miss builder
            conflict = cache.conflicts(tuple(row_ids),
                                       lambda: build_conflicts(gs))
            enc = EncodedPods(groups=groups, counts=counts,
                              conflict=conflict,
                              dropped_keys=dropped_keys or None, **got)
        enc.cache_hits, enc.cache_misses = hits, misses
        _meter_cache(hits, misses)
        _meter_recompute_cached(hits, miss_sigs)
        return enc

    # --- cold path: every row computed fresh (identical bytes to the
    # cached path by construction — both run _group_row) ---
    if taints:
        filtered = []
        for g in groups:
            if tolerates_all(g.representative.tolerations, taints):
                filtered.append(g)
            else:
                dropped_keys.extend(f"{p.namespace}/{p.name}"
                                    for p in g.pods)
        groups = filtered

    req_vecs = [g.representative.requests.to_vector() for g in groups]
    R = num_resources()
    G = len(groups)
    requests = _take(arena, "requests", (G, R), np.float32, zero=True)
    counts = (np.fromiter((g.count for g in groups), np.int32, G)
              if G else np.zeros(0, np.int32))
    compat = _take(arena, "compat", (G, cat.T), bool)
    allow_zone = _take(arena, "zone", (G, cat.Z), bool)
    allow_cap = _take(arena, "capm", (G, cat.C), bool)
    max_per_node = np.zeros(G, np.int32)
    spread_zone = np.zeros(G, bool)
    spread_soft = np.zeros(G, bool)
    hard = _take(arena, "hard_t", (G, cat.T), bool)
    hard_z = _take(arena, "hard_z", (G, cat.Z), bool)
    hard_c = _take(arena, "hard_c", (G, cat.C), bool)
    any_dt = any_dz = any_dc = False

    exotic = exotic_mask(cat)
    exotic = exotic if exotic.any() else None
    for i, g in enumerate(groups):
        row = _group_row(g.representative, cat, extra_requirements,
                         template_labels, exotic, req_vecs[i], R)
        requests[i] = row.req
        compat[i] = row.compat
        allow_zone[i] = row.zone
        allow_cap[i] = row.capm
        hard[i] = row.hard_t
        hard_z[i] = row.hard_z
        hard_c[i] = row.hard_c
        max_per_node[i] = row.max_per_node
        spread_zone[i] = row.spread_zone
        spread_soft[i] = row.spread_soft
        any_dt |= row.differs_t
        any_dz |= row.differs_z
        any_dc |= row.differs_c

    from ..obs.tracer import TRACER
    with TRACER.span("encode.conflicts", groups=G):
        conflict = build_conflicts(groups)
    _meter_recompute_cold(requests, compat, allow_zone, allow_cap)
    return EncodedPods(groups=groups, requests=requests, counts=counts,
                       compat=compat, allow_zone=allow_zone, allow_cap=allow_cap,
                       max_per_node=max_per_node, spread_zone=spread_zone,
                       conflict=conflict, spread_soft=spread_soft,
                       compat_hard=hard if any_dt else None,
                       zone_hard=hard_z if any_dz else None,
                       cap_hard=hard_c if any_dc else None,
                       dropped_keys=dropped_keys or None)


def _meter_cache(hits: int, misses: int) -> None:
    from ..metrics import ENCODE_CACHE
    if hits:
        ENCODE_CACHE.inc(hits, event="hit")
    if misses:
        ENCODE_CACHE.inc(misses, event="miss")


def _meter_recompute_cached(hits: int, miss_sigs) -> None:
    """Work provenance of the cached encode path: hits are encodes an
    existing cache row served (delta_served); each miss is classified by
    its constraint signature — a signature re-lowered after eviction or
    a `begin()` rotation shows up as redundant encode work."""
    from ..obs.recompute import RECOMPUTE, fingerprint
    if hits:
        RECOMPUTE.classify("encode", served=True, units=hits)
    for sig in miss_sigs:
        RECOMPUTE.classify("encode", fingerprint(sig))


def _meter_recompute_cold(requests, compat, allow_zone, allow_cap) -> None:
    """Work provenance of the cold encode path: one vectorized combined
    row digest per group (NOT per-group constraint_signature calls — the
    cold path's cost profile must not change), plus one conflict-build
    classification over the folded row set."""
    from ..obs.recompute import (RECOMPUTE, fingerprint_fold,
                                 fingerprint_rows)
    if len(requests) == 0:
        return
    fps = fingerprint_rows(requests, compat, allow_zone, allow_cap)
    RECOMPUTE.classify_rows("encode", fps)
    RECOMPUTE.classify("conflict", fingerprint_fold(fps))


def _apply_preferred(rep: Pod, compat_row: np.ndarray, zone_row: np.ndarray,
                     cap_row: np.ndarray, req: np.ndarray,
                     cat: CatalogTensors,
                     template: Optional[Dict[str, str]] = None,
                     ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Narrow a group's (type, zone, captype) masks to its preferred
    node-affinity terms, greedily in descending weight, keeping each
    narrowing only while ≥1 available offering that FITS the pod survives —
    'prefer, never block'. (k8s scores preferences per node; against a
    catalog the analogue is restricting the candidate axes when the
    restriction is satisfiable.) Zone-key preferences are skipped for pods
    carrying a zone topology-spread constraint: spread domains come from
    hard filters only (k8s likewise computes eligible domains before
    scoring). Returns (compat, zone, cap) rows, or None if no preference
    applied."""
    if not rep.preferred_node_affinity:
        return None
    fits = (align_resources(cat.allocatable, len(req))
            >= req[None, :] - 1e-6).all(axis=1)
    cur_t, cur_z, cur_c = compat_row, zone_row, cap_row
    has_zone_spread = any(t.topology_key == L.ZONE
                          for t in rep.topology_spread)
    terms = sorted(rep.preferred_node_affinity,
                   key=lambda t: -t.get("weight", 1))
    changed = False
    for term in terms:
        r = Requirements()
        r.add(Requirement(term["key"], Operator(term["operator"]),
                          tuple(term.get("values", ()))))
        cand_t, cand_z, cand_c = cur_t, cur_z, cur_c
        if term["key"] == L.ZONE:
            if has_zone_spread:
                continue
            cand_z = cur_z & _axis_allow(r, L.ZONE, cat.zones)
        elif term["key"] == L.CAPACITY_TYPE:
            cand_c = cur_c & _axis_allow(r, L.CAPACITY_TYPE, cat.captypes)
        else:
            cand_t = cur_t & compat_mask(r, cat, template)
        feasible = (cat.available & (cand_t & fits)[:, None, None]
                    & cand_z[None, :, None] & cand_c[None, None, :]).any()
        if feasible:
            cur_t, cur_z, cur_c = cand_t, cand_z, cand_c
            changed = True
    return (cur_t, cur_z, cur_c) if changed else None


def feasible_zones(enc: EncodedPods, cat: CatalogTensors, i: int,
                   zone_mask: np.ndarray) -> np.ndarray:
    """bool [Z]: zones in zone_mask where group i has ≥1 available,
    compatible, FITTING offering — judged on the HARD type/captype masks,
    so a soft node-affinity preference can neither steer a spread split
    nor fail a required zone-affinity pin (the facade relaxes infeasible
    preferences afterwards)."""
    alloc = align_resources(cat.allocatable, enc.requests.shape[1])
    fits = (alloc >= enc.requests[i][None, :] - 1e-6).all(axis=1)
    comp = enc.compat[i] if enc.compat_hard is None else enc.compat_hard[i]
    cap = enc.allow_cap[i] if enc.cap_hard is None else enc.cap_hard[i]
    ok_t = comp & fits
    per_zone = (cat.available & ok_t[:, None, None]
                & cap[None, None, :]).any(axis=(0, 2))
    return per_zone & zone_mask


def align_zone_overhead(cat: CatalogTensors, R: int) -> "Optional[np.ndarray]":
    """cat.zone_overhead ([T, Z, R_cat]) zero-padded to R resource columns,
    or None when absent — the shared accessor every backend uses."""
    z = cat.zone_overhead
    if z is None:
        return None
    if z.shape[2] >= R:
        return z
    return np.pad(z, ((0, 0), (0, 0), (0, R - z.shape[2])))


def align_resources(alloc: np.ndarray, R: int) -> np.ndarray:
    """Zero-pad the catalog's [T, R_cat] allocatable to R columns.

    The resource axis can grow between catalog encoding (cached on device)
    and pod encoding (auto-registers custom resources). Zero capacity for the
    new columns is the correct semantics: a type whose catalog entry predates
    the resource offers none of it, so pods requesting it can't fit there.
    """
    if alloc.shape[1] >= R:
        return alloc
    pad = np.zeros((alloc.shape[0], R - alloc.shape[1]), alloc.dtype)
    return np.concatenate([alloc, pad], axis=1)
