"""Signature-keyed encode cache + staging arena: the columnar encode
pipeline's persistence layer.

BENCH_r05 inverted the solve hot path: the device kernel runs in ~2 ms
while host-side tensorization costs 80-150 ms — the Python/numpy encode
layer became the ceiling. On a steady cluster, though, almost every
reconcile re-encodes the SAME constraint signatures against the SAME
catalog view: the per-group tensor rows (compat[T], allow_zone[Z],
allow_cap[C], max_per_node, spread flags, the padded request vector and
the pre-preference hard rows) are a pure function of

    (constraint_signature, catalog view, pool context)

so this module persists them columnarly and turns a warm re-encode into
one vectorized gather. Encode cost then scales with *churn* (new
signatures), not population — the same amortization CvxCluster gets
from keeping the problem dense end-to-end and Tesserae gets from
amortizing constraint lowering across placement rounds (PAPERS.md).

Keying & invalidation ride the machinery that already exists:

- the *catalog token* is `CatalogTensors.cache_token` — the facade
  stamps it from the `(nodeclass-hash, catalog-epoch)` key of
  `Solver.tensors()` and extends it for every derived view (capacity-
  block gating, daemonset-overhead baking). An ICE mark, price move,
  reservation change or overlay bump rotates the epoch, hence the
  token, hence the context — no bespoke invalidation protocol.
- the *pool token* appends the NodePool requirements / taints /
  template-labels fingerprints (they enter every row via
  `extra_requirements`, the taint filter and selector resolution).

One `EncodeContext` holds the rows for one full token; the cache keeps
a small LRU of contexts so clusters alternating a few (pool, class)
views every reconcile don't thrash. Within a context, rows live in
capacity-doubling row-major matrices — the gather is `np.take` over
row indices, and the cached row storage is never aliased into the
returned `EncodedPods` (downstream passes mutate enc arrays in place).

`EncodeArena` is the zero-realloc companion: encode staging arrays are
large (`[G, T]` at 850 types) and rebuilt every solve; the arena hands
out reusable buffers so cold encodes stop paying realloc + page-fault
cost. Arrays served from an arena stay valid only until the next encode
that leases the same arena — the facade's consumers are all transient
within one solve, and a nested solve (reserved-capacity retry) simply
bypasses a leased arena and allocates fresh.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

DROPPED = -1  # index sentinel: signature fails the pool's taint filter


def requirements_token(reqs) -> Optional[tuple]:
    """Hashable fingerprint of a Requirements conjunction (ValueSet is a
    frozen dataclass, so the per-key sets hash structurally)."""
    if reqs is None:
        return None
    return tuple(sorted(
        ((k, reqs.get(k), reqs.min_values(k)) for k in reqs.keys()),
        key=lambda kv: kv[0]))


def taints_token(taints) -> tuple:
    return tuple(sorted((t.key, t.value, t.effect) for t in (taints or ())))


def labels_token(labels) -> tuple:
    return tuple(sorted((labels or {}).items()))


class EncodeArena:
    """Reusable dense staging buffers for the encode pipeline.

    `take()` returns a view of a flat capacity-doubling buffer keyed by
    name. The arena is leased for the duration of one encode
    (`acquire`/`release`); a nested encode that finds the arena leased
    falls back to fresh allocations, so re-entrancy (the facade's
    reserved-capacity retry solves, auditor replays) can never hand two
    live `EncodedPods` the same memory. Arrays taken from the arena are
    valid until the NEXT encode leases it — every consumer in the solve
    pipeline is transient within one solve, which is the contract.
    """

    def __init__(self) -> None:
        self._bufs: Dict[str, np.ndarray] = {}
        self._leased = False

    def acquire(self) -> bool:
        if self._leased:
            return False
        self._leased = True
        return True

    def release(self) -> None:
        self._leased = False

    def take(self, name: str, shape: Tuple[int, ...], dtype,
             zero: bool = False) -> np.ndarray:
        need = 1
        for d in shape:
            need *= int(d)
        buf = self._bufs.get(name)
        if buf is None or buf.dtype != np.dtype(dtype) or buf.size < need:
            cap = need if buf is None else max(need, 2 * buf.size)
            buf = np.empty(max(cap, 1), dtype)
            self._bufs[name] = buf
        out = buf[:need].reshape(shape)
        if zero:
            out.fill(0)
        return out

    def nbytes(self) -> int:
        return sum(b.nbytes for b in self._bufs.values())


class EncodeContext:
    """Columnar row store for ONE (catalog view, pool context) token.

    Rows are keyed by the pod group's constraint signature. Matrices
    grow by doubling; when the row population exceeds `max_rows`
    (per-pod-unique signatures — StatefulSet name labels, rolling
    template hashes — would otherwise accrete forever) the index
    rotates like the pod-signature intern table: cached rows are
    recomputed on next sight, correctness never depends on a hit.
    """

    GROW_START = 64

    def __init__(self, token: tuple, T: int, Z: int, C: int,
                 stats: Dict[str, int], max_rows: int = 4096) -> None:
        self.token = token
        self.T, self.Z, self.C = T, Z, C
        self.stats = stats
        self.max_rows = max_rows
        self._index: Dict[tuple, int] = {}
        self._n = 0
        self._cap = 0
        self._R = 0
        # row-major matrices, allocated on first insert
        self._compat = self._hard_t = None    # bool [cap, T]
        self._zone = self._hard_z = None      # bool [cap, Z]
        self._capm = self._hard_c = None      # bool [cap, C]
        self._req: Optional[np.ndarray] = None  # f32 [cap, R]
        self._maxpn: Optional[np.ndarray] = None  # i32 [cap]
        self._spread = self._soft = None      # bool [cap]
        # per-row "preferred narrowing changed this axis" flags — they
        # reproduce the cold encoder's hard-rows-or-None decision exactly
        self._dt = self._dz = self._dc = None  # bool [cap]
        # (row-id tuple, matrix-or-None): the cross-group anti-affinity
        # conflict matrix for the LAST row-id sequence — on a steady
        # cluster the group set is identical every reconcile, and the
        # O(G²)-shaped build is the one encode cost rows can't amortize
        self._conflict_memo: Optional[Tuple[tuple, object]] = None

    # --- index ---
    def begin(self) -> None:
        """Start one encode batch: rotate a full row store NOW, never
        mid-batch — row ids handed to an in-flight encode must stay
        valid until its gather. A single batch with more distinct
        signatures than max_rows grows past the cap transiently and
        rotates at the next batch boundary."""
        if len(self._index) >= self.max_rows:
            self._index.clear()
            self._n = 0
            self._conflict_memo = None  # row ids are reissued after rotation
            self.stats["rotations"] = self.stats.get("rotations", 0) + 1

    def lookup(self, sig: tuple) -> Optional[int]:
        return self._index.get(sig)

    def insert_dropped(self, sig: tuple) -> int:
        self._index[sig] = DROPPED
        return DROPPED

    def _grow(self, R: int) -> None:
        if self._n < self._cap and R <= self._R:
            return
        cap = max(self.GROW_START, self._cap * 2, self._n + 1)
        Rc = max(R, self._R)

        def regrow(old, cols, dtype):
            new = np.zeros((cap, cols), dtype)
            if old is not None and self._n:
                new[: self._n, : old.shape[1]] = old[: self._n]
            return new

        self._compat = regrow(self._compat, self.T, bool)
        self._hard_t = regrow(self._hard_t, self.T, bool)
        self._zone = regrow(self._zone, self.Z, bool)
        self._hard_z = regrow(self._hard_z, self.Z, bool)
        self._capm = regrow(self._capm, self.C, bool)
        self._hard_c = regrow(self._hard_c, self.C, bool)
        self._req = regrow(self._req, Rc, np.float32)
        for name in ("_maxpn", "_spread", "_soft", "_dt", "_dz", "_dc"):
            old = getattr(self, name)
            dtype = np.int32 if name == "_maxpn" else bool
            new = np.zeros(cap, dtype)
            if old is not None and self._n:
                new[: self._n] = old[: self._n]
            setattr(self, name, new)
        self._cap = cap
        self._R = Rc

    def insert(self, sig: tuple, row) -> int:
        """Persist one computed group row (see encode._group_row); the
        row's arrays are COPIED into the columnar store."""
        R = len(row.req)
        self._grow(R)
        i = self._n
        self._compat[i] = row.compat
        self._hard_t[i] = row.hard_t
        self._zone[i] = row.zone
        self._hard_z[i] = row.hard_z
        self._capm[i] = row.capm
        self._hard_c[i] = row.hard_c
        self._req[i, :R] = row.req
        if R < self._R:
            self._req[i, R:] = 0.0
        self._maxpn[i] = row.max_per_node
        self._spread[i] = row.spread_zone
        self._soft[i] = row.spread_soft
        self._dt[i] = row.differs_t
        self._dz[i] = row.differs_z
        self._dc[i] = row.differs_c
        self._n = i + 1
        self._index[sig] = i
        return i

    @property
    def rows(self) -> int:
        return self._n

    def conflicts(self, key: tuple, build):
        """The conflict matrix for this exact row-id sequence, memoized
        (1-deep — reconciles repeat the same group set back to back).
        The memoized matrix is shared read-only across encodes: every
        consumer reads it (splits/rebuilds derive NEW matrices), and the
        write lock turns a future in-place mutation into a loud error
        instead of silent cross-solve corruption."""
        from ..obs.recompute import RECOMPUTE, fingerprint
        hit = self._conflict_memo
        if hit is not None and hit[0] == key:
            RECOMPUTE.classify("conflict", served=True)
            return hit[1]
        from ..obs.tracer import TRACER
        with TRACER.span("encode.conflicts", groups=len(key)):
            m = build()
        RECOMPUTE.classify("conflict", fingerprint(key))
        if m is not None:
            m.setflags(write=False)
        self._conflict_memo = (key, m)
        return m

    def gather(self, ids: List[int], R: int,
               arena: Optional[EncodeArena] = None) -> dict:
        """One vectorized gather of cached rows → fresh (never aliased)
        encode arrays, padded to R resource columns. The hard arrays are
        materialized only when some row's preferred narrowing actually
        changed that axis — byte-identical to the cold encoder's
        `(hard != work).any()` decision."""
        idx = np.asarray(ids, np.intp)
        G = len(ids)

        def out(name, cols, dtype, src):
            if arena is not None:
                buf = arena.take(name, (G, cols), dtype)
            else:
                buf = np.empty((G, cols), dtype)
            np.take(src[: self._n], idx, axis=0, out=buf)
            return buf

        compat = out("compat", self.T, bool, self._compat)
        zone = out("zone", self.Z, bool, self._zone)
        capm = out("capm", self.C, bool, self._capm)
        Rc = min(self._R, R)
        if arena is not None:
            req = arena.take("requests", (G, R), np.float32, zero=R > Rc)
        else:
            req = np.zeros((G, R), np.float32) if R > Rc \
                else np.empty((G, R), np.float32)
        req[:, :Rc] = self._req[: self._n, :Rc][idx]
        dt = self._dt[: self._n][idx]
        dz = self._dz[: self._n][idx]
        dc = self._dc[: self._n][idx]
        return {
            "requests": req, "compat": compat,
            "allow_zone": zone, "allow_cap": capm,
            "max_per_node": self._maxpn[: self._n][idx].copy(),
            "spread_zone": self._spread[: self._n][idx].copy(),
            "spread_soft": self._soft[: self._n][idx].copy(),
            "compat_hard": (out("hard_t", self.T, bool, self._hard_t)
                            if dt.any() else None),
            "zone_hard": (out("hard_z", self.Z, bool, self._hard_z)
                          if dz.any() else None),
            "cap_hard": (out("hard_c", self.C, bool, self._hard_c)
                         if dc.any() else None),
        }


class EncodeCache:
    """LRU of EncodeContexts keyed by the full encode token.

    A handful of contexts stay warm so clusters that alternate a few
    (NodePool, NodeClass) views per reconcile don't thrash — the same
    rationale as the facade's catalog-tensor LRU. Stats are shared
    across contexts (hits/misses/rotations/evictions) and mirrored into
    karpenter_tpu.metrics by the encoder."""

    MAX_CONTEXTS = 4

    def __init__(self, max_contexts: Optional[int] = None) -> None:
        self.max_contexts = max_contexts or self.MAX_CONTEXTS
        self._ctxs: "OrderedDict[tuple, EncodeContext]" = OrderedDict()
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "rotations": 0, "evictions": 0}

    def context(self, token: tuple, T: int, Z: int, C: int) -> EncodeContext:
        ctx = self._ctxs.get(token)
        if ctx is None:
            ctx = EncodeContext(token, T, Z, C, self.stats)
            self._ctxs[token] = ctx
            while len(self._ctxs) > self.max_contexts:
                self._ctxs.popitem(last=False)
                self.stats["evictions"] += 1
        else:
            self._ctxs.move_to_end(token)
        return ctx

    def context_for(self, cat, extra_requirements=None, taints=None,
                    template_labels=None) -> Optional[EncodeContext]:
        """The context for a facade-derived CatalogTensors view, or None
        when the view carries no cache token (direct encode_catalog
        callers own their invalidation and must key explicitly)."""
        if getattr(cat, "cache_token", None) is None:
            return None
        token = cat.cache_token + (
            requirements_token(extra_requirements),
            taints_token(taints),
            labels_token(template_labels))
        return self.context(token, cat.T, cat.Z, cat.C)

    @property
    def resident_rows(self) -> int:
        return sum(c.rows for c in self._ctxs.values())

    def hit_rate(self) -> float:
        seen = self.stats["hits"] + self.stats["misses"]
        return self.stats["hits"] / seen if seen else 0.0

    def snapshot(self) -> dict:
        """JSON-ready effectiveness view — the per-tenant encode-cache
        panel the fleet's /debug/fleet serves (and the queryable form of
        the ledger's encode_cold vs encode_cached split): per-context
        resident rows plus the shared hit/miss/rotation/eviction
        counters."""
        return {
            "hit_rate": round(self.hit_rate(), 4),
            "resident_rows": self.resident_rows,
            "contexts": len(self._ctxs),
            "stats": dict(self.stats),
        }
