"""Solver facade: pods + NodePool + catalog → launch decisions.

The `Solver` interface of the north star: the control plane owns all
mutable state and calls solve() statelessly with (pods, catalog-epoch);
this module hides encoding, spread-splitting, device-tensor caching, and
backend selection (TPU kernel vs host oracle — identical semantics).

Output maps tensor results back to the object world: one NodeLaunch per
new virtual node, carrying the committed instance type, the cheapest
surviving offering, a price-sorted override list for launch resilience
(reference sends ≤60 override rows per CreateFleet, instance.go:58-63),
and the concrete pods nominated to it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..catalog.provider import CatalogProvider
from ..models import labels as L
from ..models.instancetype import InstanceType
from ..models.nodeclaim import NodeClaim
from ..models.nodepool import NodeClassSpec, NodePool
from ..models.pod import Pod, term_selects
from ..models.requirements import Requirements
from ..models.resources import Resources
from ..obs.tracer import NOOP_SPAN, TRACER
from .affinity import apply_zone_affinity
from .binpack import (SolveResult, SpreadConstraintCounts, VirtualNode,
                      solve_host, split_spread_groups, validate_solution)
from .colocate import (BundleNode, ColocationPlan, has_colocation,
                       plan_colocation)
from .encode import (CatalogTensors, EncodedPods, align_resources,
                     encode_catalog, encode_pods)

MAX_OVERRIDES = 60  # reference MaxInstanceTypes (instance.go:62)
_MESH_UNSET = object()


class SharedCatalogCache:
    """Content-keyed CatalogTensors shared across Solver facades — the
    fleet's one-catalog-many-tenants seam (docs/fleet.md).

    A fleet runs N tenant control planes, each with its own
    CatalogProvider (own ICE marks, own pricing clocks), through one
    process. Tenants running identical pools would each pay
    encode_catalog (and a device upload, and — via fresh shapes — an XLA
    compile) for byte-identical views. This cache keys views by
    (nodeclass-hash, availability fingerprint): tenants whose resolved
    catalogs AGREE share one CatalogTensors object, hence one
    device-resident DeviceCatalog (ops/solver._auto_dcat keys on the
    content token) and one compiled executable; tenants whose views
    diverge (an ICE mark, a price move) fingerprint differently and get
    their own entry — per-tenant isolation is preserved by content, not
    trust.

    Entries carry a content-authoritative `cache_token`
    ("shared", nodeclass-hash, fingerprint): unlike the per-facade
    (nodeclass-hash, epoch) token, it is collision-free ACROSS providers
    (two tenants' epoch counters can agree while their availability
    differs), which is what makes process-global device caching on the
    token sound."""

    MAX_ENTRIES = 16

    def __init__(self):
        from collections import OrderedDict
        self._entries: "OrderedDict[tuple, CatalogTensors]" = OrderedDict()
        self.stats: Dict[str, int] = {"hits": 0, "misses": 0}

    @staticmethod
    def fingerprint(types: Sequence[InstanceType]) -> str:
        """Digest of everything encode_catalog reads from a resolved
        type list: names, requirements, capacity, overhead, and every
        offering's (zone, captype, price, availability, reservation)
        tuple. ~1e4 offerings hash in well under a millisecond — paid
        only on a facade-local epoch miss, never per solve."""
        import hashlib
        h = hashlib.blake2b(digest_size=16)
        for t in types:
            h.update(t.name.encode())
            for key in sorted(t.requirements.keys()):
                vs = t.requirements.get(key)
                h.update(f"|{key}:{sorted(vs.values)}:{vs.complement}"
                         f":{vs.gt}:{vs.lt}".encode())
            for k in sorted(t.capacity):
                h.update(f"|{k}={t.capacity.get(k)}".encode())
            for k, v in sorted(t.overhead.total().items()):
                h.update(f"|oh:{k}={v}".encode())
            for o in t.offerings:
                h.update(f"|{o.zone}/{o.capacity_type}/{o.price}"
                         f"/{o.available}/{o.reservation_id}"
                         f"/{o.reservation_capacity}/{o.reservation_type}"
                         f"/{o.reservation_ends}".encode())
            h.update(b";")
        return h.hexdigest()

    def get_or_encode(self, nc_hash: str,
                      types: Sequence[InstanceType]) -> CatalogTensors:
        from ..metrics import FLEET_CATALOG_SHARED
        key = (nc_hash, self.fingerprint(types))
        hit = self._entries.get(key)
        if hit is not None:
            self._entries.move_to_end(key)
            self.stats["hits"] += 1
            FLEET_CATALOG_SHARED.inc(event="hit")
            return hit
        cat = encode_catalog(list(types))
        cat.cache_token = ("shared",) + key
        self._entries[key] = cat
        self.stats["misses"] += 1
        FLEET_CATALOG_SHARED.inc(event="miss")
        while len(self._entries) > self.MAX_ENTRIES:
            old_key, _old = self._entries.popitem(last=False)
            # a dead shared view must not pin device buffers until the
            # token FIFO happens to trim them: release every device-
            # resident variant of this view (base + noblocks/daemonset-
            # derived tokens) the moment the view itself is evicted
            from .solver import release_shared_views
            release_shared_views(("shared",) + old_key)
        return cat


def _daemonset_overhead_parts(
        cat: CatalogTensors, daemonsets, nodepool: NodePool,
        template: Dict[str, str],
        ) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """(base [T, R], zone_var [T, Z, R]) daemonset reservations.

    base: daemonsets that run in EVERY zone this pool's nodes can land
    in (no zone selector, or full overlap with the pool's zones) — a
    flat per-type reservation the solve bakes into allocatable.
    zone_var: zone-pinned daemonsets whose zones only PARTIALLY overlap
    the pool's — reserved per (type, zone); a node charges the
    elementwise max over its remaining zone mask, so nodes whose zones
    narrow away from the daemonset get their headroom back (the
    reference charges any template-compatible daemonset on every
    virtual node — core scheduler daemonset simulation — so this is
    strictly tighter packing at equal safety).

    Per-type, not per-pool: a gpu-selector daemonset reserves only on
    gpu-carrying types. Each compatible daemonset also consumes one pod
    slot. Either part is None when nothing applies."""
    from ..models.pod import tolerates_all
    from ..models.resources import PODS, Resources
    from .encode import compat_mask
    taints = nodepool.taints + nodepool.startup_taints
    pool_zvs = nodepool.requirements.get(L.ZONE)
    pool_zones = [z for z in cat.zones
                  if pool_zvs is None or pool_zvs.contains(z)]
    R = cat.allocatable.shape[1]
    base = None
    zvar = None
    for ds in daemonsets:
        if taints and not tolerates_all(ds.tolerations, taints):
            continue
        reqs = ds.scheduling_requirements()
        ds_zvs = reqs.get(L.ZONE)
        partial = None  # zone indices, when only partially overlapping
        if ds_zvs is not None:
            possible = [z for z in pool_zones if ds_zvs.contains(z)]
            if not possible:
                continue
            if len(possible) < len(pool_zones):
                partial = [cat.zones.index(z) for z in possible]
        mask = compat_mask(reqs, cat, template)
        if not mask.any():
            continue
        vec = ds.requests.add(Resources({PODS: 1.0})).to_vector()
        v = np.zeros(R, np.float32)
        n = min(len(vec), R)
        v[:n] = vec[:n]
        if partial is None:
            if base is None:
                base = np.zeros((cat.T, R), np.float32)
            base[mask] += v
        else:
            if zvar is None:
                zvar = np.zeros((cat.T, cat.Z, R), np.float32)
            for zi in partial:
                zvar[mask, zi] += v
    return base, zvar


def daemonset_overhead(cat: CatalogTensors, daemonsets, nodepool: NodePool,
                       template: Dict[str, str]) -> Optional[np.ndarray]:
    """f32 [T, R]: the zone-INVARIANT per-instance-type daemonset
    reservation (reference core: the scheduler adds daemonset pods to
    every virtual node before placing workloads). Zone-pinned daemonsets
    with partial pool overlap are excluded here — they live on
    CatalogTensors.zone_overhead (see _daemonset_overhead_parts).
    Returns None when nothing applies."""
    base, _ = _daemonset_overhead_parts(cat, daemonsets, nodepool, template)
    return base


def apply_daemonset_overhead(cat: CatalogTensors, daemonsets,
                             nodepool: NodePool,
                             template: Dict[str, str]) -> CatalogTensors:
    """Shrink the catalog's allocatable by the pool's zone-invariant
    daemonset overhead and attach the zone-varying part as
    `zone_overhead` — the ONE transformation both the solve and the
    consolidation screen apply, so their headroom views can't diverge.
    Returns `cat` itself when nothing applies."""
    if not daemonsets:
        return cat
    base, zvar = _daemonset_overhead_parts(cat, daemonsets, nodepool,
                                           template)
    if base is None and zvar is None:
        return cat
    from dataclasses import replace as _dc_replace
    alloc = (np.maximum(cat.allocatable - base, 0.0)
             if base is not None else cat.allocatable)
    # derived view → derived encode-cache token: the overhead bytes pin
    # the view's identity. A content DIGEST, not Python hash(): the
    # digest is the only part of the token carrying this identity, so a
    # collision would silently alias two different allocatable views
    # onto one EncodeContext — blake2b makes that a non-event
    token = None
    if cat.cache_token is not None:
        import hashlib
        h = hashlib.blake2b(digest_size=16)
        h.update(base.tobytes() if base is not None else b"-")
        h.update(zvar.tobytes() if zvar is not None else b"-")
        token = cat.cache_token + ("ds", h.hexdigest())
    return _dc_replace(cat, allocatable=alloc, zone_overhead=zvar,
                       cache_token=token)


def targets_reserved(requirements: Optional[Requirements]) -> bool:
    """Does a Requirements conjunction EXPLICITLY name the reserved
    capacity type (an In requirement listing "reserved")? This is the
    capacity-block gate of the reference launch filters
    (filter.go:163-228 shouldFilter: requirements.Get(capacity-type)
    .Has(reserved)): prepaid capacity blocks only serve launches that
    opted into reserved capacity — an unconstrained pool must never
    spill plain pods onto a block just because its price rounds to
    zero. Exists / NotIn do not count: they don't *name* reserved."""
    if requirements is None:
        return False
    vs = requirements.get(L.CAPACITY_TYPE)
    return (vs is not None and not vs.complement
            and L.CAPACITY_RESERVED in vs.values)


def min_values_floors(requirements: Optional[Requirements],
                      ) -> List[Tuple[str, int]]:
    """(key, minValues) floors of a Requirements conjunction — the single
    extraction both the node-opening caps and the override-row selection
    share, so the two enforcement points can't diverge."""
    if requirements is None:
        return []
    return [(k, requirements.min_values(k)) for k in requirements.keys()
            if requirements.min_values(k)]


@dataclass
class NodeLaunch:
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    overrides: List[Tuple[str, str, str, float]]  # (type, zone, captype, price)
    pod_keys: List[str]
    requests: Resources
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class SolveOutput:
    launches: List[NodeLaunch]
    existing_placements: Dict[str, List[str]]  # existing node name -> pod keys
    unschedulable: List[str]                   # pod keys
    stats: Dict[str, float] = field(default_factory=dict)


@dataclass
class PreparedSolve:
    """A solve() staged up to (but not including) its backend run — the
    seam the fleet's batched dispatcher works through: prepare_solve()
    does every host-side step (catalog view, gates, colocation, encode,
    spread, backend choice), run_prepared()/a batched device call
    produces the SolveResult, finish_solve() decodes and applies the
    post-passes. solve() composes the three, so the serial path and the
    batched path are the same program by construction.

    `output` non-None means the solve terminated during preparation
    (empty catalog, colocation-only, zero groups) — the value is FINAL
    (merge + reserved-retry already applied)."""

    output: Optional[SolveOutput] = None
    cat: Optional[CatalogTensors] = None
    cat_key: tuple = ()              # facade catalog-LRU key AT prepare time
    enc: Optional[EncodedPods] = None
    existing: Optional[List[VirtualNode]] = None
    plan: Optional[ColocationPlan] = None
    dropped: List[str] = field(default_factory=list)
    blocks_gated: bool = False
    ds_fp: int = 0
    all_pods: Sequence[Pod] = ()
    nodepool: Optional[NodePool] = None
    node_class: Optional[NodeClassSpec] = None
    spread_occupancy: Optional[list] = None
    daemonsets: Optional[list] = None
    backend: str = ""
    t0: float = 0.0
    # delta-plane bookkeeping (ops/delta.py): the solve-memo key/fp
    # this prepared solve settles in finish_solve — store on a miss,
    # confirm/diverge on an audit-due recompute. delta_served marks a
    # result already answered FROM the memo (finish must not re-store).
    delta_key: Optional[tuple] = None
    delta_fp: int = 0
    delta_audit: bool = False
    delta_check: int = 0
    delta_served: bool = False


def _pod_key(p: Pod) -> str:
    return f"{p.namespace}/{p.name}"


class Solver:
    # below this many pods the device path's fixed dispatch+readback
    # latency (a full RTT when the chip sits behind a network tunnel)
    # exceeds the native solver's whole runtime — "auto" routes small
    # solves native/host and reserves the TPU for the large ones
    DEVICE_MIN_PODS = 4096

    # encoded-catalog views kept warm (LRU): clusters alternating a few
    # NodeClass views per reconcile must not re-encode the catalog (and
    # re-upload device tensors) on every flip — a single-slot cache
    # thrashed exactly that way
    CAT_CACHE_SIZE = 4

    def __init__(self, catalog: CatalogProvider, backend: str = "auto",
                 device_min_pods: Optional[int] = None,
                 profile_dir: str = "", encode_cache: bool = True,
                 shared_catalog: Optional[SharedCatalogCache] = None):
        from collections import OrderedDict
        self.catalog = catalog
        # fleet seam: when set, catalog views resolve through the
        # process-shared content-keyed cache, so facades of tenants with
        # identical pools share encoded tensors, device uploads, and
        # compiled executables (SolverService wires one cache across all
        # tenant facades); None = classic per-facade encoding
        self._shared_catalog = shared_catalog
        self.device_min_pods = (self.DEVICE_MIN_PODS if device_min_pods is None
                                else device_min_pods)
        # non-empty: every solve runs under jax.profiler.trace(profile_dir)
        self.profile_dir = profile_dir
        if backend == "auto":
            backend = self._detect_backend()
        self.backend = backend
        self._cat_cache: "OrderedDict[tuple, CatalogTensors]" = OrderedDict()
        self._dcat_cache: Dict[tuple, object] = {}  # device-resident tensors
        self._last_cat_key: tuple = ()
        # columnar encode pipeline (ops/encode_cache): per-signature rows
        # persist across solves, staged through one reusable arena
        from .encode_cache import EncodeArena, EncodeCache
        self._encode_cache = EncodeCache() if encode_cache else None
        self._arena = EncodeArena()
        self._mesh_obj = _MESH_UNSET
        # degraded mode: >0 while device/mesh dispatches are rerouted to
        # the fallback backend after a mid-solve device fault; decremented
        # per rerouted solve, so the device path is re-probed after
        # FALLBACK_COOLDOWN solves (count-based, hence sim-deterministic)
        self._device_suspended = 0
        # solution-integrity plane (karpenter_tpu/integrity/): the canary
        # sampler and the resident-audit cadence counter are per facade,
        # so quarantine only ever degrades the affected tenant's path
        self._canary = None
        self._integrity_solves = 0
        self.stats: Dict[str, int] = {"catalog_rebuilds": 0,
                                      "device_fallbacks": 0,
                                      "integrity_violations": 0,
                                      "integrity_recoveries": 0}

    @staticmethod
    def _accel_attached() -> bool:
        try:
            import jax
            return any(d.platform != "cpu" for d in jax.devices())
        except Exception:
            return False

    @classmethod
    def _detect_backend(cls) -> str:
        """auto: size-adaptive (hybrid) when an accelerator is attached,
        else the compiled C++ solver, else the numpy oracle."""
        if cls._accel_attached():
            return "hybrid"
        from . import native
        return "native" if native.available() else "host"

    def mesh(self):
        """The multi-chip mesh this solver shards over, or None single-chip.
        Built lazily on first use; a "nodes"-axis Mesh over every attached
        device (parallel/mesh.py)."""
        if self._mesh_obj is _MESH_UNSET:
            self._mesh_obj = None
            try:
                import jax
                if len(jax.devices()) > 1:
                    from ..parallel.mesh import make_mesh
                    self._mesh_obj = make_mesh()
            except Exception:
                pass
        return self._mesh_obj

    # screen sharding threshold in CANDIDATE NODES — deliberately separate
    # from device_min_pods (a pod-count calibration for solve routing):
    # the [N, G] screen's cost model is per-node rows, and tuning one
    # knob must not silently retune the other
    SCREEN_MESH_MIN_NODES = 1024

    def screen_mesh(self, n_nodes: int):
        """Mesh for the consolidation screen's node axis, or None when the
        single-device path is the right call (small clusters, no mesh)."""
        if self.backend == "mesh":
            return self.mesh()
        if (self.backend == "hybrid"
                and n_nodes >= self.SCREEN_MESH_MIN_NODES):
            return self.mesh()
        return None

    # solves routed to the fallback backend after a device fault before
    # the device path is probed again (count-based: deterministic in sim)
    FALLBACK_COOLDOWN = 8

    def _fallback_backend(self, cat: Optional[CatalogTensors] = None) -> str:
        """The degraded-mode target: the compiled C++ FFD when it can
        serve this solve, else the numpy host oracle."""
        if cat is not None and cat.zone_overhead is not None:
            return "host"  # native takes a flat [T, R] allocatable only
        from . import native
        return "native" if native.available() else "host"

    def _resolve_backend(self, total_pods: int) -> str:
        backend = self._resolve_backend_healthy(total_pods)
        if backend in ("device", "mesh") and self._device_suspended > 0:
            # degraded mode after a mid-solve device fault: reroute and
            # burn down the cooldown; the gauge clears when it reaches
            # zero (the NEXT device-sized solve re-probes the device)
            self._device_suspended -= 1
            if self._device_suspended == 0:
                from ..metrics import DEGRADED_MODE
                DEGRADED_MODE.set(0, component="solver")
            return self._fallback_backend()
        return backend

    def _degrade(self, from_backend: str, cat: CatalogTensors,
                 err: Exception, run_sp) -> str:
        """A device/mesh dispatch faulted mid-solve: pick the fallback
        backend, meter the event (fallback counter + degraded-mode gauge +
        trace attribution), and suspend the device path for a cooldown so
        every subsequent solve doesn't re-pay the fault latency while the
        backend is down. Returns the backend to re-run this solve on."""
        to = self._fallback_backend(cat)
        self._device_suspended = self.FALLBACK_COOLDOWN
        from ..metrics import DEGRADED_MODE, SOLVER_FALLBACKS
        DEGRADED_MODE.set(1, component="solver")
        SOLVER_FALLBACKS.inc(from_backend=from_backend, to_backend=to)
        self.stats["device_fallbacks"] += 1
        run_sp.set(backend=to, fallback_from=from_backend,
                   outcome="degraded", fault=type(err).__name__)
        import logging
        logging.getLogger("karpenter_tpu.solver").warning(
            "%s backend faulted mid-solve (%s: %s); re-running on %s and "
            "suspending the device path for %d solves",
            from_backend, type(err).__name__, err, to,
            self.FALLBACK_COOLDOWN)
        return to

    def _resolve_backend_healthy(self, total_pods: int) -> str:
        if self.backend == "mesh":
            return "mesh"
        if self.backend != "hybrid":
            return self.backend
        if total_pods >= self.device_min_pods:
            # multi-chip attached → shard the node axis over the mesh; the
            # same facade call the provisioner makes reaches all chips
            return "mesh" if self.mesh() is not None else "device"
        from . import native
        return "native" if native.available() else "host"

    def tensors(self, node_class: Optional[NodeClassSpec] = None) -> CatalogTensors:
        nc = node_class or NodeClassSpec()
        # hydrate BEFORE keying: the first raw-catalog pull bumps the
        # epoch (pricing hydration), and a key computed pre-pull would
        # cache the first view under a token no later solve reproduces
        self.catalog.raw_types()
        key = (nc.hash(),) + tuple(self.catalog.epoch)
        hit = self._cat_cache.get(key)
        if hit is None:
            types = self.catalog.list(nc)
            if self._shared_catalog is not None:
                # fleet: content-keyed lookup across every tenant facade
                # — a hit reuses another tenant's encoded view (its
                # "shared"-rooted cache_token makes the device tensors
                # shareable too); the local epoch-keyed LRU still fronts
                # it so the per-solve fast path stays two dict lookups
                hit = self._shared_catalog.get_or_encode(nc.hash(), types)
            else:
                hit = encode_catalog(types)
                hit.cache_token = key  # encode-cache lineage for derived views
            self._cat_cache[key] = hit
            # small LRU, not single-slot: two NodeClass views alternating
            # each reconcile must both stay resident (a clear-on-new-key
            # policy re-encoded — and re-uploaded — on every flip); the
            # evicted view's device-resident variants go with it
            while len(self._cat_cache) > self.CAT_CACHE_SIZE:
                old_key, _ = self._cat_cache.popitem(last=False)
                from ..metrics import DCAT_EVICTIONS
                for k in [k for k in self._dcat_cache
                          if k[: len(old_key)] == old_key]:
                    del self._dcat_cache[k]
                    DCAT_EVICTIONS.inc(reason="facade_lru")
            # availability-tensor rebuild counter: chaos tests assert an
            # ICE mark re-keys this (and the device upload cache) exactly
            # once per epoch change, not once per solve
            self.stats["catalog_rebuilds"] += 1
        else:
            self._cat_cache.move_to_end(key)
        self._last_cat_key = key
        # device-resident staleness feed (ops/resident.py): record the
        # newest catalog token this facade resolved for the view, so an
        # idle resident buffer whose epoch the world moved past is
        # visible to the watchdog's resident_staleness invariant. Both
        # the cold path (prepare_solve) and the warm path (prepare_warm
        # via warm_catalog) land here.
        # Facade-prefixed entries ONLY: one facade has exactly one
        # current token per nodeclass, so base and entry granularity
        # agree. The process-shared ("dcat", "shared", ...) entries are
        # deliberately NOT observed — during a persistent view split
        # two live fingerprints of one nodeclass legitimately alternate
        # through one resident key, and a single last-observer base
        # would flag that healthy state stale forever (their lifecycle
        # is governed by release_shared_views/invalidate_token instead).
        tok = hit.cache_token
        if tok:
            from .resident import RESIDENT
            RESIDENT.observe_view(("facade", id(self), key[0]), tuple(tok))
        return hit

    def solve(self, pods: Sequence[Pod], nodepool: NodePool,
              node_class: Optional[NodeClassSpec] = None,
              existing: Optional[List[VirtualNode]] = None,
              capacity_cap: Optional[Resources] = None,
              existing_pods: Optional[Dict[str, List[Pod]]] = None,
              spread_occupancy: Optional[
                  List[Tuple[Optional[str], List[Pod]]]] = None,
              pregrouped: Optional[List[List[Pod]]] = None,
              daemonsets: Optional[list] = None,
              _gate_blocks: bool = True) -> SolveOutput:
        """capacity_cap: only open nodes whose total capacity fits within it
        (the NodePool-limits headroom; the reference scheduler stops opening
        virtual nodes that would breach spec.limits the same way).

        existing_pods: pods already on each existing node (by existing_name)
        — matched by constraint signature into the current groups so
        per-node caps (anti-affinity/hostname-spread) hold across
        reconciles, not just within one solve.

        spread_occupancy: cluster-wide (zone, pods) per node — ALL nodes
        including other pools' and unmanaged ones — used to seed topology-
        spread domain counts. Defaults to deriving from `existing` (this
        solve's nodes only), which under-counts in multi-pool clusters;
        the provisioner passes the full view."""
        prep = self.prepare_solve(
            pods, nodepool, node_class, existing, capacity_cap,
            existing_pods, spread_occupancy, pregrouped, daemonsets,
            _gate_blocks)
        if prep.output is not None:
            return prep.output
        result, backend = self.run_prepared(prep)
        return self.finish_solve(prep, result, backend)

    def prepare_solve(self, pods: Sequence[Pod], nodepool: NodePool,
                      node_class: Optional[NodeClassSpec] = None,
                      existing: Optional[List[VirtualNode]] = None,
                      capacity_cap: Optional[Resources] = None,
                      existing_pods: Optional[Dict[str, List[Pod]]] = None,
                      spread_occupancy: Optional[
                          List[Tuple[Optional[str], List[Pod]]]] = None,
                      pregrouped: Optional[List[List[Pod]]] = None,
                      daemonsets: Optional[list] = None,
                      _gate_blocks: bool = True) -> PreparedSolve:
        """Everything solve() does BEFORE the backend run: catalog view +
        gates, colocation planning, encode, spread split, backend choice.
        Host-side work only — safe to interleave across many requests
        (the batched dispatcher stages every queued solve through here
        before a single device call serves them all)."""
        cat = self.tensors(node_class)
        if cat.T == 0 or not pods:
            return PreparedSolve(
                output=SolveOutput([], {}, [_pod_key(p) for p in pods]))
        # capacity-block gate (reference filter.go:163-228): unless the
        # pool explicitly targets reserved capacity, block offerings are
        # removed from the availability tensor BEFORE the solve — the
        # cost-argmin must never commit a prepaid block for a pool that
        # didn't select it (and the override list can't resurrect one)
        blocks_gated = False
        if (_gate_blocks and cat.is_block is not None and cat.is_block.any()
                and not targets_reserved(nodepool.requirements)):
            from dataclasses import replace as _dc_replace
            cat = _dc_replace(cat, available=cat.available & ~cat.is_block,
                              cache_token=(cat.cache_token + ("noblocks",)
                                           if cat.cache_token is not None
                                           else None))
            blocks_gated = True
        all_pods = pods  # reference, captured before the colocation path
        # rebinds the local; only read if the reserved retry fires
        # NodePool-template node labels — pod selectors on keys the
        # catalog doesn't carry resolve against these (every launched
        # node wears them; NodePool.template_labels is the one source)
        template = nodepool.template_labels()
        # daemonset overhead: reserve per-node resources for daemonset
        # pods BEFORE placing workloads, by shrinking the allocatable
        # tensor (equivalent to starting every node's cum at the
        # overhead; covers every backend uniformly, and existing-node
        # views see the same reduced headroom since their daemonsets
        # run too)
        ds_fp = 0
        if daemonsets:
            reduced = apply_daemonset_overhead(cat, daemonsets, nodepool,
                                               template)
            if reduced is not cat:
                cat = reduced
                ds_fp = hash((cat.allocatable.tobytes(),
                              None if cat.zone_overhead is None
                              else cat.zone_overhead.tobytes()))
        fits_cap = None
        if capacity_cap is not None:
            types = self.catalog.list(node_class or NodeClassSpec())
            fits_cap = np.array(
                [all(t.capacity.get(k, 0.0) <= v + 1e-9
                     for k, v in capacity_cap.items())
                 for t in types], bool)
        # required positive hostname affinity: the host-side co-location
        # planner peels coupled pods off the tensor path (ops/colocate.py).
        # Positive affinity terms are part of the constraint signature, so
        # with pre-bucketed input probing one representative per group is
        # exact — no O(pods) scan
        plan = None
        bundle_occupancy: List[Tuple[Optional[str], List[Pod]]] = []
        colo_probe = ([ps[0] for ps in pregrouped if ps]
                      if pregrouped is not None else pods)
        if has_colocation(colo_probe):
            pregrouped = None  # the planner consumes the raw pod list
            # the planner writes resident placements into the nodes' cum /
            # masks so the main solve sees consumed capacity — work on
            # copies: callers (disruption) reuse their VirtualNodes across
            # many solves in one reconcile
            from ..state.cluster import copy_virtual_node
            existing = [copy_virtual_node(vn) for vn in (existing or [])]
            existing_pods = dict(existing_pods or {})
            cat_plan = cat
            if cat.zone_overhead is not None:
                # the planner sizes concrete bundle nodes host-side;
                # give it the conservative (max-over-zones) reservation
                from dataclasses import replace as _dc_replace
                cat_plan = _dc_replace(
                    cat, allocatable=np.maximum(
                        cat.allocatable - cat.zone_overhead.max(axis=1),
                        0.0),
                    zone_overhead=None)
            plan = plan_colocation(
                pods, cat_plan, extra_requirements=nodepool.requirements,
                taints=nodepool.taints + nodepool.startup_taints,
                existing=existing, existing_pods=existing_pods,
                type_cap=fits_cap, template_labels=template)
            for name, placed in plan.existing_placements.items():
                # planner placements count as residents for the main solve's
                # per-node caps and occupancy
                existing_pods[name] = list(existing_pods.get(name, [])) + placed
            # pin each bundle to its concrete zone NOW so bundle pods are
            # visible to the zone-affinity pre-pass and topology-spread
            # domain counts of the same solve (a deferred zone cannot feed
            # either); launch keeps the cheapest offering within the pin
            for b in plan.bundles:
                zi = self._pin_bundle_zone(b, cat)
                bundle_occupancy.append((cat.zones[zi], b.pods))
            pods = plan.remaining
            if not pods:
                out = self._merge_plan(SolveOutput([], {}, []), plan,
                                       cat, nodepool)
                return PreparedSolve(output=self._retry_reserved_unschedulable(
                    out, blocks_gated, all_pods, nodepool, node_class,
                    spread_occupancy, daemonsets))
        taints = nodepool.taints + nodepool.startup_taints
        enc_ctx = (self._encode_cache.context_for(
                       cat, nodepool.requirements, taints, template)
                   if self._encode_cache is not None else None)
        sp = (TRACER.span("solve.encode", pods=len(pods),
                          pregrouped=pregrouped is not None)
              if TRACER.enabled else NOOP_SPAN)
        with sp:
            lsp = (TRACER.span("encode.lower") if TRACER.enabled
                   else NOOP_SPAN)
            with lsp:
                enc = encode_pods(pods, cat,
                                  extra_requirements=nodepool.requirements,
                                  taints=taints,
                                  pregrouped=pregrouped,
                                  template_labels=template,
                                  cache=enc_ctx, arena=self._arena)
                lsp.set(groups=int(enc.G), cache_hits=enc.cache_hits,
                        cache_misses=enc.cache_misses)
            if TRACER.enabled and enc.cache_hits:
                # a dedicated marker span so the flight recorder can
                # attribute a fast encode to the gather path at a glance
                with TRACER.span("encode.cache_hit", rows=enc.cache_hits):
                    pass
            sp.set(groups=int(enc.G))
        self._meter_encode_rows(enc_ctx)
        if fits_cap is not None:
            enc.compat &= fits_cap[None, :]
            if enc.compat_hard is not None:
                enc.compat_hard = enc.compat_hard & fits_cap[None, :]
        self._apply_min_values_caps(enc, cat, nodepool.requirements)
        # pods dropped by the taint filter are unschedulable for this pool
        dropped = list(enc.dropped_keys or ())
        occupancy = (list(spread_occupancy) if spread_occupancy is not None
                     else self._occupancy_from_existing(existing, existing_pods, cat))
        if plan is not None:
            occupancy += bundle_occupancy
            if spread_occupancy is not None:
                # a caller-supplied cluster view predates the planner's
                # resident placements — append them (new pods only; the
                # resident pods themselves are already in the view)
                occupancy += [
                    (self._zone_of(name, existing, cat), placed)
                    for name, placed in plan.existing_placements.items()]
        from ..obs.recompute import RECOMPUTE, encoded_fingerprint, fingerprint
        occ_sig = tuple(sorted((zone, len(placed))
                               for zone, placed in occupancy))
        sp = (TRACER.span("solve.spread") if TRACER.enabled else NOOP_SPAN)
        with sp:
            asp = (TRACER.span("encode.affinity") if TRACER.enabled
                   else NOOP_SPAN)
            with asp:
                enc = self._delta_affinity(enc, cat, occupancy, occ_sig,
                                           nodepool.name)
            enc = self._delta_spread(enc, cat, occupancy, occ_sig,
                                     nodepool.name)
            sp.set(groups=int(enc.G))
        post_fp = encoded_fingerprint(enc)
        if enc.G == 0:
            out = self._merge_plan(SolveOutput([], {}, dropped), plan,
                                   cat, nodepool)
            return PreparedSolve(output=self._retry_reserved_unschedulable(
                out, blocks_gated, all_pods, nodepool, node_class,
                spread_occupancy, daemonsets))
        self._relax_infeasible_preferences(enc, cat)

        self.attach_existing_context(enc, existing, existing_pods)

        import time as _time
        t0 = _time.perf_counter()
        backend = self._resolve_backend(int(enc.counts.sum()))
        if backend == "native" and cat.zone_overhead is not None:
            # the C++ FFD takes a flat [T, R] allocatable; zone-varying
            # reservations need the masked-max path — host oracle instead
            backend = "host"
        prep = PreparedSolve(
            cat=cat, cat_key=self._last_cat_key, enc=enc,
            existing=existing, plan=plan, dropped=dropped,
            blocks_gated=blocks_gated, ds_fp=ds_fp, all_pods=all_pods,
            nodepool=nodepool, node_class=node_class,
            spread_occupancy=spread_occupancy, daemonsets=daemonsets,
            backend=backend, t0=t0)
        # delta plane: an unchanged-input solve serves the memoized
        # result (still oracle-verified in finish_solve) instead of
        # dispatching; miss/audit marks the prep so finish_solve
        # settles the memo protocol
        from .delta import existing_context_fingerprint
        ex_fp = existing_context_fingerprint(existing)
        served = self._delta_serve_solve(prep, post_fp, ex_fp)
        if served is not None:
            return served
        # the FULL solve input identity a dispatch is about to grind —
        # encoded content AND the standing-fleet context (two what-ifs
        # over the same pods against different hypothetical clusters
        # are different solves, not redundancy): an unchanged
        # fingerprint re-solved from scratch is the redundant work the
        # delta memo should have served
        RECOMPUTE.classify("solve", fingerprint(
            post_fp, self._last_cat_key, backend, int(enc.counts.sum()),
            ex_fp))
        return prep

    def _device_dcat(self, prep: PreparedSolve, mesh):
        """Device-resident catalog tensors for a prepared solve — the ONE
        residency-cache policy the serial run and the batched stage
        share. Keys on prep.cat_key (captured at prepare time), so
        interleaved prepares of different views cannot cross-wire."""
        from .solver import _auto_dcat, device_catalog
        cat = prep.cat
        R = prep.enc.requests.shape[1]
        if (self._shared_catalog is not None
                and cat.cache_token is not None
                and cat.cache_token[0] == "shared"):
            # fleet: device residency keys on the content token in the
            # PROCESS-global cache (ops/solver._auto_dcat), so tenant
            # facades sharing this view — and its gated/daemonset-
            # derived tokens — share one upload and one compiled
            # executable
            return _auto_dcat(cat, R, mesh=mesh)
        # keyed on (nodeclass hash, catalog epoch, R, placement, block
        # gating) — NOT id(cat): a freed CatalogTensors' address can be
        # reused by its successor
        dkey = prep.cat_key + (R, mesh is not None, prep.blocks_gated,
                               prep.ds_fp)
        dcat = self._dcat_cache.get(dkey)
        if dcat is None:
            # device residency follows the host LRU: every variant
            # (block-gating states, mesh vs single) of any CACHED
            # catalog view may stay — mixed pools and alternating
            # NodeClasses must not thrash a full host→device transfer
            # per solve
            n = len(prep.cat_key)
            from ..metrics import DCAT_EVICTIONS
            for k in [k for k in self._dcat_cache
                      if k[:n] not in self._cat_cache]:
                del self._dcat_cache[k]
                DCAT_EVICTIONS.inc(reason="facade_lru")
            rk = None if mesh is not None else self._resident_key(prep)
            dcat = device_catalog(
                cat, R, mesh=mesh,
                resident_key=rk + ("dcat",) if rk is not None else None)
            self._dcat_cache[dkey] = dcat
        return dcat

    def _resident_key(self, prep: PreparedSolve) -> Optional[tuple]:
        """Key prefix for this facade's device-resident state (one per
        (nodeclass, block-gating, daemonset-view) — the catalog EPOCH is
        deliberately absent: an epoch bump is exactly the moment a delta
        patch beats a full re-upload, and the entry's stored cache_token
        forces the conservative full path when content lineage breaks."""
        if not prep.cat_key:
            return None
        return ("facade", id(self), prep.cat_key[0], prep.blocks_gated,
                prep.ds_fp)

    def invalidate_resident(self, reason: str = "invalidated") -> int:
        """Drop every device-resident view this facade seeded — called
        by the warm-path engine when its auditor diverges (the
        incremental pipeline disagreed with a cold solve, so no
        incremental device state may be trusted either) and available to
        chaos/restart machinery. Returns the entries dropped."""
        from .resident import RESIDENT
        return RESIDENT.invalidate(("facade", id(self)), reason=reason)

    # --- delta plane (ops/delta.py): serve-and-verify memos ----------------
    # The four prepare-time stages the c16 regime measured as >84%
    # redundant serve their prior outputs when the input fingerprints
    # are unchanged. Every shortcut is policed: served solves still run
    # the integrity oracle (finish_solve), and the plane's audit
    # cadence forces a fresh recompute with a confirm/diverge verdict
    # (divergence invalidates + opens the never-wrong-twice cooldown).

    def _delta_solve_key(self, prep: PreparedSolve, ex_fp: int) -> tuple:
        # the existing-context fingerprint is part of the KEY, not the
        # validation fingerprint: one reconcile runs many concurrent
        # solves against DIFFERENT hypothetical cluster contexts (the
        # disruption controller's what-ifs), and a single key would make
        # them evict each other every pass. Distinct contexts memoize
        # side by side; pod-content drift within one context re-stores
        # under a new fp (the metered epoch)
        return ("facade", id(self),
                prep.cat_key[0] if prep.cat_key else None,
                prep.nodepool.name if prep.nodepool is not None else None,
                prep.blocks_gated, prep.ds_fp, ex_fp)

    def _delta_serve_solve(self, prep: PreparedSolve, post_fp: int,
                           ex_fp: int) -> Optional[PreparedSolve]:
        """Solve-memo serve half. None = not served (miss, audit due,
        ineligible) — the caller dispatches normally and finish_solve
        settles the memo via the prep's delta_* fields. A clean hit
        decodes the memoized SolveResult against the CURRENT prep
        (fresh pod identities) through the full finish_solve pipeline —
        the integrity oracle validates every served result."""
        from .delta import (DELTA, copy_solve_result,
                            solve_memo_fingerprint,
                            solve_result_fingerprint)
        # colocation plans thread planner state through finish_solve
        # the memo cannot key — they always recompute. Existing-node
        # solves (full reconciles, disruption what-ifs — the bulk of
        # the c16 headroom) ARE served: attach_existing_context ran
        # before this point, so the prepared VirtualNodes carry the
        # full solver-visible standing-fleet state and the context
        # fingerprint below folds it into the memo key
        if (not DELTA.armed or prep.plan is not None
                or self.profile_dir):
            return None
        key = self._delta_solve_key(prep, ex_fp)
        fp = solve_memo_fingerprint(prep.enc, prep.cat_key, prep.backend,
                                    prep.blocks_gated, prep.ds_fp)
        hit = DELTA.serve("solve", key, fp)
        if hit is None:
            prep.delta_key, prep.delta_fp = key, fp
            return None
        (result, backend), audit_due = hit
        if audit_due:
            prep.delta_key, prep.delta_fp = key, fp
            prep.delta_audit = True
            prep.delta_check = solve_result_fingerprint(result)
            return None
        from ..obs.recompute import RECOMPUTE
        RECOMPUTE.classify("solve", served=True)
        prep.delta_served = True
        out = self.finish_solve(prep, copy_solve_result(result), backend)
        return PreparedSolve(output=out)

    def _delta_record_solve(self, prep: PreparedSolve,
                            result: SolveResult, backend: str) -> None:
        """Settle the solve memo for a freshly computed (and already
        integrity-verified) result: store on a miss, confirm/diverge on
        an audit-due recompute."""
        if prep.delta_key is None or prep.delta_served:
            return
        from .delta import (DELTA, copy_solve_result,
                            solve_result_fingerprint)
        check = solve_result_fingerprint(result)
        if prep.delta_audit:
            if check == prep.delta_check:
                DELTA.confirm("solve", prep.delta_key, prep.delta_fp,
                              value=(copy_solve_result(result), backend),
                              check_fp=check)
            else:
                DELTA.diverge("solve", prep.delta_key)
            return
        DELTA.store("solve", prep.delta_key, prep.delta_fp,
                    (copy_solve_result(result), backend), check_fp=check)

    def _delta_affinity(self, enc: EncodedPods, cat: CatalogTensors,
                        occupancy, occ_sig: tuple,
                        pool: str) -> EncodedPods:
        """Zone-affinity pre-pass through the delta memo: an unchanged
        (enc content, occupancy signature, zones) pass REPLAYS the
        memoized transformation descriptor against the CURRENT enc —
        pod identities stay fresh while the O(occupancy) selector
        matching and cluster/union-find work is served."""
        from ..obs.recompute import (RECOMPUTE, encoded_fingerprint,
                                     fingerprint)
        from .affinity import descriptor_fingerprint, replay_zone_affinity
        from .delta import DELTA, group_terms_fingerprint
        led_fp = fingerprint(encoded_fingerprint(enc), occ_sig)
        if not DELTA.armed:
            RECOMPUTE.classify("affinity", led_fp)
            return apply_zone_affinity(enc, cat, occupancy)
        # the occupancy signature is zone+count only — the group-terms
        # digest carries the selector semantics, and the audit cadence
        # polices what neither catches. The content fingerprint is part
        # of the KEY: one reconcile's what-if solves run this pass over
        # many (enc, occupancy) variants, and a per-(facade, pool)
        # entry would thrash instead of serving the repeats
        mfp = fingerprint(led_fp, tuple(cat.zones))
        key = ("facade", id(self), pool, group_terms_fingerprint(enc),
               mfp)
        hit = DELTA.serve("affinity", key, mfp)
        if hit is not None and not hit[1]:
            out = replay_zone_affinity(enc, cat, hit[0])
            if out is not None:
                RECOMPUTE.classify("affinity", served=True)
                return out
            # the descriptor no longer fits the enc it was keyed to —
            # a memo-key defect: treat exactly like an audit divergence
            DELTA.diverge("affinity", key)
            hit = None
        capture: dict = {}
        out = apply_zone_affinity(enc, cat, occupancy, capture=capture)
        RECOMPUTE.classify("affinity", led_fp)
        cfp = descriptor_fingerprint(capture)
        if hit is not None:  # audit-due: judge the stored descriptor
            if cfp == descriptor_fingerprint(hit[0]):
                DELTA.confirm("affinity", key, mfp, value=capture,
                              check_fp=cfp)
            else:
                DELTA.diverge("affinity", key)
        else:
            DELTA.store("affinity", key, mfp, capture, check_fp=cfp)
        return out

    def _delta_spread(self, enc: EncodedPods, cat: CatalogTensors,
                      occupancy, occ_sig: tuple, pool: str) -> EncodedPods:
        """Topology-spread pass through the delta memo: the memo serves
        the O(cluster pods) selector-counting half (_spread_constraints);
        the cheap structural split always runs against the current enc."""
        from ..obs.recompute import (RECOMPUTE, encoded_fingerprint,
                                     fingerprint)
        from .delta import (DELTA, copy_spread_constraints,
                            group_terms_fingerprint,
                            spread_constraints_fingerprint)

        def _classify_fresh(out_enc):
            RECOMPUTE.classify("spread", fingerprint(
                encoded_fingerprint(out_enc), occ_sig, "spread"))

        if not (DELTA.armed and enc.G and bool(enc.spread_zone.any())):
            out = split_spread_groups(
                enc, cat, self._spread_constraints(enc, cat, occupancy))
            _classify_fresh(out)
            return out
        mfp = fingerprint(encoded_fingerprint(enc), occ_sig,
                          tuple(cat.zones), "spread")
        # content fp in the KEY, same rationale as _delta_affinity:
        # concurrent what-if variants must memoize side by side
        key = ("facade", id(self), pool, group_terms_fingerprint(enc),
               mfp)
        hit = DELTA.serve("spread", key, mfp)
        if hit is not None and not hit[1]:
            out = split_spread_groups(
                enc, cat, copy_spread_constraints(hit[0]))
            RECOMPUTE.classify("spread", served=True)
            return out
        cons = self._spread_constraints(enc, cat, occupancy)
        cfp = spread_constraints_fingerprint(cons)
        if hit is not None:  # audit-due
            if cfp == spread_constraints_fingerprint(hit[0]):
                DELTA.confirm("spread", key, mfp,
                              value=copy_spread_constraints(cons),
                              check_fp=cfp)
            else:
                DELTA.diverge("spread", key)
        else:
            DELTA.store("spread", key, mfp,
                        copy_spread_constraints(cons), check_fp=cfp)
        out = split_spread_groups(enc, cat, cons)
        _classify_fresh(out)
        return out

    def stage_batchable(self, prep: PreparedSolve):
        """ops.solver.BatchableSolve for a prepared solve, or None when
        it must run serially (host/native/mesh backends, existing-node
        resume, per-solve jax profiling). Staging performs the device
        UPLOAD (catalog residency + nothing else) so a pipelined caller
        overlaps it with in-flight device work."""
        if (prep.output is not None or prep.backend != "device"
                or prep.existing or self.profile_dir):
            return None
        from .solver import prepare_batchable
        try:
            # meter key: "the previous upload for this catalog view,
            # from THIS facade" — co-batched tenants sharing a device
            # catalog still hash against their own upload history
            return prepare_batchable(prep.cat, prep.enc,
                                     dcat=self._device_dcat(prep, None),
                                     meter_key=(("facade", id(self))
                                                + tuple(prep.cat_key)))
        except Exception:  # noqa: BLE001 — staging is an optimization;
            # any surprise falls back to the serial path, never crashes
            return None

    def run_prepared(self, prep: PreparedSolve):
        """The backend run of a prepared solve, with the device-fault
        degradation machinery. Returns (SolveResult, backend actually
        used)."""
        from ..utils.profiling import maybe_trace
        cat, enc, existing = prep.cat, prep.enc, prep.existing
        backend = prep.backend
        run_sp = (TRACER.span("solve.run", backend=backend,
                              pods=int(enc.counts.sum()), groups=int(enc.G))
                  if TRACER.enabled else NOOP_SPAN)
        with run_sp, maybe_trace(self.profile_dir):
            if backend == "host":
                result = solve_host(cat, enc, existing)
            elif backend == "native":
                from .native import solve_native
                result = solve_native(cat, enc, existing)
            else:
                try:
                    from .solver import solve_device
                    mesh = self.mesh() if backend == "mesh" else None
                    dcat = self._device_dcat(prep, mesh)
                    result = solve_device(
                        cat, enc, existing, dcat=dcat, mesh=mesh,
                        resident_key=(None if mesh is not None
                                      else self._resident_key(prep)))
                except Exception as e:  # noqa: BLE001 — graceful degradation:
                    # the TPU backend faulting mid-solve (tunnel drop,
                    # device reset, injected fault) must cost ONE rerouted
                    # solve, not a crashed reconcile
                    backend = self._degrade(backend, cat, e, run_sp)
                    if backend == "native":
                        from .native import solve_native
                        result = solve_native(cat, enc, existing)
                    else:
                        result = solve_host(cat, enc, existing)
        return result, backend

    def finish_solve(self, prep: PreparedSolve, result: SolveResult,
                     backend: str,
                     duration_s: Optional[float] = None) -> SolveOutput:
        """Decode + post-passes of a prepared solve whose SolveResult is
        in hand (serial run or a batched device call).

        duration_s: this solve's OWN cost, supplied by a pipelined
        caller — under batched dispatch, `now - prep.t0` spans other
        tickets' staging and other buckets' device work, which would
        inflate the histogram by up to the whole pump wall."""
        import time as _time

        from ..metrics import SOLVE_DURATION, SOLVE_PODS
        cat, enc = prep.cat, prep.enc
        # exemplar: a fat solve-duration bucket points at the captured
        # trace in the flight recorder (None when tracing is off)
        SOLVE_DURATION.observe(duration_s if duration_s is not None
                               else _time.perf_counter() - prep.t0,
                               backend=backend,
                               exemplar=TRACER.current_trace_id())
        SOLVE_PODS.observe(float(enc.counts.sum()))

        # solution-integrity oracle: every SolveResult — serial, a
        # batched row, or a warm-window cold pass — is validated here
        # BEFORE anything decodes into launches/nominations. A violation
        # quarantines this facade's device path and recovers the solve
        # through the fallback backend; KARPENTER_TPU_INTEGRITY=0 makes
        # this a single env check (today's path byte-for-byte)
        result, backend = self._verify_integrity(prep, result, backend)
        # delta plane: memoize (or audit-settle) the verified result —
        # an unchanged-input reconcile serves it without a dispatch
        self._delta_record_solve(prep, result, backend)

        out = self._decode(cat, enc, result, prep.nodepool, prep.dropped)
        out = self._merge_plan(out, prep.plan, cat, prep.nodepool)
        # decision provenance: per-pod placement records + the constraint
        # elimination funnel, bounded and read-only (obs/explain.py) —
        # solves above the recorder's pod cap are skipped, and the
        # colocation-only early return in prepare_solve is not recorded
        # (bundle placement is the planner's, not the funnel's)
        from ..obs.explain import RECORDER
        if RECORDER.enabled:
            RECORDER.record_solve(cat, enc, out)
        return self._retry_reserved_unschedulable(
            out, prep.blocks_gated, prep.all_pods, prep.nodepool,
            prep.node_class, prep.spread_occupancy, prep.daemonsets)

    def _retry_reserved_unschedulable(
            self, out: SolveOutput, blocks_gated: bool, all_pods: List[Pod],
            nodepool: NodePool, node_class: Optional[NodeClassSpec],
            spread_occupancy, daemonsets: Optional[list] = None,
            ) -> SolveOutput:
        """Pods the gated solve left unschedulable that EXPLICITLY target
        reserved capacity (a pod-level capacity-type selector naming
        "reserved" under a pool that doesn't) get one ungated re-solve
        onto fresh nodes: the reference gate evaluates the MERGED
        nodeclaim requirements (filter.go shouldFilter), so a pod's own
        reserved intent must open capacity blocks even when its pool
        stays silent. Fresh nodes only — blocks never live on existing
        capacity, and reusing the first solve's mutated node views would
        double-count headroom."""
        if not blocks_gated or not out.unschedulable:
            return out
        by_key = {_pod_key(p): p for p in all_pods}
        retry = [by_key[k] for k in out.unschedulable
                 if k in by_key
                 and targets_reserved(by_key[k].scheduling_requirements())]
        if not retry:
            return out
        second = self.solve(retry, nodepool, node_class,
                            spread_occupancy=spread_occupancy,
                            daemonsets=daemonsets, _gate_blocks=False)
        retried = {_pod_key(p) for p in retry}
        out.launches += second.launches
        for name, keys in second.existing_placements.items():
            out.existing_placements.setdefault(name, []).extend(keys)
        out.unschedulable = [k for k in out.unschedulable
                             if k not in retried] + second.unschedulable
        return out

    # --- solution-integrity plane (karpenter_tpu/integrity/) --------------
    def _verify_integrity(self, prep: PreparedSolve, result: SolveResult,
                          backend: str):
        """Feasibility oracle + canary + resident audit for one solve.
        Returns the (possibly recovered) (result, backend). Read-only on
        the happy path; a violation re-runs the solve on the fallback
        backend and suspends the device path (the same never-wrong-twice
        suspension a mid-solve device fault earns)."""
        from ..integrity import integrity_enabled
        if not integrity_enabled() or prep.enc is None:
            return result, backend
        from ..integrity import (CanarySampler, INTEGRITY, audit_every,
                                 verify_result)
        sp = (TRACER.span("integrity.verify", backend=backend)
              if TRACER.enabled else NOOP_SPAN)
        with sp:
            violations = verify_result(prep.cat, prep.enc, result)
            device_backed = backend in ("device", "mesh")
            if not violations and device_backed and not prep.existing:
                if self._canary is None:
                    self._canary = CanarySampler()
                if self._canary.due():
                    violations = self._canary.check(prep.cat, prep.enc,
                                                    result)
            # resident-state digest audit, on a deterministic per-facade
            # cadence: corruption found there taints THIS solve too (its
            # inputs came off those buffers), so it recovers like an
            # oracle violation
            self._integrity_solves += 1
            every = audit_every()
            audit_violations = []
            if (device_backed and every > 0
                    and self._integrity_solves % every == 0):
                audit_violations = self._audit_resident_state()
            if not violations and not audit_violations:
                INTEGRITY.record_ok()
                sp.set(outcome="ok")
                return result, backend
            # breach accounting: the violating SOLVE is one context,
            # and each corrupt resident ENTRY is its own — a single
            # audit pass catching two rotted buffers must count as two
            # detections against two injected corruptions
            if violations:
                INTEGRITY.record_breach_event()
            for _ in audit_violations:
                INTEGRITY.record_breach_event()
            violations += audit_violations
            self.stats["integrity_violations"] += len(violations)
            for vio in violations:
                INTEGRITY.record_violation(vio.check, vio.detail)
            import logging
            logging.getLogger("karpenter_tpu.integrity").warning(
                "integrity violation on %s-backed solve (%s) — "
                "quarantining the device path and recovering on the "
                "fallback backend",
                backend, "; ".join(str(v) for v in violations[:4]))
            sp.set(outcome="violation",
                   checks=",".join(sorted({v.check for v in violations})))
            if not device_backed:
                # the host/native result IS the ground truth path: there
                # is no better oracle to recover through — surface the
                # violation loudly (unrecovered outcome + watchdog
                # breach) and ship what we have
                INTEGRITY.record_recovery(False)
                return result, backend
            # forensic audit BEFORE the quarantine wipes the evidence:
            # a violating device solve may have consumed MORE rotted
            # buffers than the one that tripped the oracle (or than the
            # bounded cadence slice covered), and each corrupt entry is
            # its own breach context — invalidating everything first
            # would erase the attribution. Runs even when the cadence
            # audit already found entries: the manager drops corrupt
            # entries on detection, so the sweep only ever reports
            # NEW rot, never double-counts.
            for vio in self._audit_resident_state(full=True):
                INTEGRITY.record_breach_event()
                INTEGRITY.record_violation(vio.check, vio.detail)
                self.stats["integrity_violations"] += 1
            self._integrity_quarantine(prep, backend)
            fallback = self._fallback_backend(prep.cat)
            if fallback == "native":
                from .native import solve_native
                recovered = solve_native(prep.cat, prep.enc, prep.existing)
            else:
                recovered = solve_host(prep.cat, prep.enc, prep.existing)
            still = verify_result(prep.cat, prep.enc, recovered)
            INTEGRITY.record_recovery(not still)
            if still:
                for vio in still:
                    INTEGRITY.record_violation(vio.check, vio.detail)
                logging.getLogger("karpenter_tpu.integrity").error(
                    "fallback re-solve STILL fails the oracle (%s) — "
                    "encode-level defect, shipping the host result",
                    "; ".join(str(v) for v in still[:4]))
            else:
                self.stats["integrity_recoveries"] += 1
            sp.set(recovered_backend=fallback)
            return recovered, fallback

    def _audit_resident_state(self, full: bool = False):
        """Digest-audit this facade's device-resident views (and the
        shared catalog entries it may be consuming). Corrupt entries are
        dropped by the manager; the caller treats any finding as a
        violation of the in-flight solve. `full` lifts the per-pass row
        bound — the forensic sweep a violating solve triggers must cover
        every entry, not a round-robin slice."""
        from ..integrity import AUDIT_ROWS, INTEGRITY, Violation
        from .resident import RESIDENT
        if not RESIDENT.armed:
            return []
        rows = None if full else AUDIT_ROWS
        rep = RESIDENT.audit(("facade", id(self)), max_rows=rows)
        shared = RESIDENT.audit(("dcat",), max_rows=rows)
        corrupt = list(rep["corrupt"]) + list(shared["corrupt"])
        INTEGRITY.record_audit(rep["rows"] + shared["rows"], len(corrupt))
        return [Violation("resident_audit",
                          f"resident row digests diverged on "
                          f"{'/'.join(str(t) for t in key)}")
                for key in corrupt]

    def _integrity_quarantine(self, prep: PreparedSolve,
                              backend: str) -> None:
        """Contain a device-path integrity violation: drop every device
        buffer this facade could have consumed (its resident views, its
        cached DeviceCatalogs, and the shared content-token variants of
        the offending view) and suspend the device path for the standard
        cooldown — only THIS facade degrades; co-tenants' paths are
        untouched until their own checks say otherwise."""
        from ..metrics import SOLVER_FALLBACKS
        tok = prep.cat.cache_token if prep.cat is not None else None
        self._quarantine_device_state(tok)
        SOLVER_FALLBACKS.inc(from_backend=backend,
                             to_backend=self._fallback_backend(prep.cat))
        self.stats["device_fallbacks"] += 1

    def _quarantine_device_state(self, tok=None) -> None:
        """The backend-independent half of the quarantine: drop this
        facade's resident views and cached DeviceCatalogs (both may
        reference corrupted buffers), release the shared content-token
        variants of the offending view, and suspend the device path for
        the standard never-wrong-twice cooldown."""
        from ..metrics import DEGRADED_MODE
        from .delta import DELTA
        from .resident import RESIDENT
        RESIDENT.invalidate(("facade", id(self)), reason="corruption")
        # memoized solve results may have been decoded from the same
        # corrupted device state — they die with it (host-side
        # affinity/spread memos are untouched: nothing device-backed
        # feeds them)
        DELTA.invalidate(("solve", "facade", id(self)),
                         reason="quarantine")
        # cached DeviceCatalogs may still reference a corrupted resident
        # buffer — the cache entries must die with the entries
        if self._dcat_cache:
            from ..metrics import DCAT_EVICTIONS
            for _ in range(len(self._dcat_cache)):
                DCAT_EVICTIONS.inc(reason="integrity")
            self._dcat_cache.clear()
        if tok and tok[0] == "shared":
            from .solver import release_shared_views
            release_shared_views(tuple(tok[:2]))
        self._device_suspended = self.FALLBACK_COOLDOWN
        DEGRADED_MODE.set(1, component="solver")

    def warm_integrity_tick(self) -> int:
        """Advance the per-facade audit cadence by one verified commit.
        Cold solves tick inside _verify_integrity; warm admissions tick
        here — without this, a fleet whose arrivals the warm path fully
        absorbs would audit its device-resident state exactly once per
        catalog epoch, and resident rot could sit undetected until the
        next cold solve consumed it. Findings quarantine this facade's
        device path (the rotted entries are already invalidated by the
        audit itself) and return the corrupt-entry count; the warm
        result being judged is host-computed and stays shipped."""
        from ..integrity import INTEGRITY, audit_every, integrity_enabled
        if not integrity_enabled():
            return 0
        self._integrity_solves += 1
        every = audit_every()
        if every <= 0 or self._integrity_solves % every:
            return 0
        violations = self._audit_resident_state()
        if not violations:
            return 0
        self.stats["integrity_violations"] += len(violations)
        for vio in violations:
            INTEGRITY.record_breach_event()
            INTEGRITY.record_violation(vio.check, vio.detail)
        import logging
        logging.getLogger("karpenter_tpu.integrity").warning(
            "resident-state audit found %d corrupt device entr%s during "
            "a warm window — quarantining this facade's device path",
            len(violations), "y" if len(violations) == 1 else "ies")
        self._quarantine_device_state()
        # the corruption never reached a shipped answer (the audit got
        # there first) — that IS the recovery
        INTEGRITY.record_recovery(True)
        return len(violations)

    def _meter_encode_rows(self, enc_ctx) -> None:
        """Refresh the resident-rows gauge after ANY cached encode —
        warm-path admissions dominate steady state, so solve()-only
        updates would report hours-stale residency there."""
        if enc_ctx is not None:
            from ..metrics import ENCODE_CACHE_ROWS
            ENCODE_CACHE_ROWS.set(float(self._encode_cache.resident_rows))

    # --- warm-path seam ---------------------------------------------------
    # The warm-path subsystem (karpenter_tpu/warmpath/) admits arrival-only
    # reconciles against a standing headroom ledger instead of paying a
    # full solve. These two methods are the facade's contract with it: the
    # ledger snapshots warm_catalog() at commit time, and each warm batch
    # is encoded by prepare_warm() — the exact encode pipeline solve()
    # runs for the plain (no colocation, no capacity-cap) case, in the
    # same order. The Auditor replays accumulated warm admissions through
    # solve() itself, so any drift between this pipeline and solve()'s
    # surfaces as metered divergence, not silent misplacement.

    def warm_catalog(self, nodepool: NodePool,
                     node_class: Optional[NodeClassSpec],
                     daemonsets: Optional[list] = None) -> CatalogTensors:
        """The availability/headroom view solve() would compute for this
        (pool, class): capacity-block gate applied unless the pool targets
        reserved capacity, then daemonset overhead baked into allocatable
        (zone-varying part on zone_overhead)."""
        cat = self.tensors(node_class)
        if (cat.is_block is not None and cat.is_block.any()
                and not targets_reserved(nodepool.requirements)):
            from dataclasses import replace as _dc_replace
            # same token suffix as solve()'s gate — warm and cold paths
            # share one encode context per (pool, class) view
            cat = _dc_replace(cat, available=cat.available & ~cat.is_block,
                              cache_token=(cat.cache_token + ("noblocks",)
                                           if cat.cache_token is not None
                                           else None))
        if daemonsets:
            cat = apply_daemonset_overhead(cat, daemonsets, nodepool,
                                           nodepool.template_labels())
        return cat

    def prepare_warm(self, pregrouped: List[List[Pod]], nodepool: NodePool,
                     cat: CatalogTensors,
                     occupancy: List[Tuple[Optional[str], List[Pod]]],
                     existing: Optional[List[VirtualNode]] = None,
                     existing_pods: Optional[Dict[str, List[Pod]]] = None,
                     ) -> EncodedPods:
        """Encode an arrival batch exactly the way solve() would: group →
        minValues caps → zone-affinity pre-pass → topology-spread split →
        infeasible-preference relaxation → resident priors/bans. `cat`
        must be this pool's warm_catalog(). Taint-dropped pods surface on
        EncodedPods.dropped_keys (they fall through to the next pool, as
        in the cold path)."""
        template = nodepool.template_labels()
        taints = nodepool.taints + nodepool.startup_taints
        enc_ctx = (self._encode_cache.context_for(
                       cat, nodepool.requirements, taints, template)
                   if self._encode_cache is not None else None)
        lsp = (TRACER.span("encode.lower", warm=True) if TRACER.enabled
               else NOOP_SPAN)
        with lsp:
            enc = encode_pods([p for g in pregrouped for p in g], cat,
                              extra_requirements=nodepool.requirements,
                              taints=taints,
                              pregrouped=pregrouped,
                              template_labels=template,
                              cache=enc_ctx, arena=self._arena)
            lsp.set(groups=int(enc.G), cache_hits=enc.cache_hits,
                    cache_misses=enc.cache_misses)
        self._meter_encode_rows(enc_ctx)
        self._apply_min_values_caps(enc, cat, nodepool.requirements)
        dropped = enc.dropped_keys  # split_spread_groups rebuilds the enc
        occ_sig = tuple(sorted((zone, len(placed))
                               for zone, placed in occupancy))
        asp = (TRACER.span("encode.affinity", warm=True) if TRACER.enabled
               else NOOP_SPAN)
        with asp:
            enc = self._delta_affinity(enc, cat, occupancy, occ_sig,
                                       nodepool.name)
        enc = self._delta_spread(enc, cat, occupancy, occ_sig,
                                 nodepool.name)
        enc.dropped_keys = dropped
        if enc.G:
            self._relax_infeasible_preferences(enc, cat)
            self.attach_existing_context(enc, existing, existing_pods)
        return enc

    @staticmethod
    def attach_existing_context(enc: EncodedPods,
                                existing: Optional[List[VirtualNode]],
                                existing_pods: Optional[Dict[str, List[Pod]]],
                                ) -> None:
        """Map each existing node's resident pods onto the CURRENT enc's
        group indices (prior_by_group — per-node caps hold across
        reconciles) and compute resident anti-affinity bans. Shared by
        solve() and the warm-path admitter."""
        if not (existing and existing_pods):
            return
        sig_to_groups: Dict[tuple, List[int]] = {}
        for gi, grp in enumerate(enc.groups):
            sig_to_groups.setdefault(
                grp.representative.constraint_signature(), []).append(gi)
        for vn in existing:
            counts: Dict[int, int] = {}
            for p in existing_pods.get(vn.existing_name or "", []):
                for gi in sig_to_groups.get(p.constraint_signature(), []):
                    counts[gi] = counts.get(gi, 0) + 1
            vn.prior_by_group = counts
        Solver._apply_resident_bans(enc, existing, existing_pods)

    def _merge_plan(self, out: SolveOutput, plan: Optional[ColocationPlan],
                    cat: CatalogTensors, nodepool: NodePool) -> SolveOutput:
        """Fold the co-location planner's decisions into a SolveOutput:
        bundle nodes become NodeLaunches (cheapest surviving offering +
        price-sorted overrides, same launch contract as solver nodes)."""
        if plan is None:
            return out
        for b in plan.bundles:
            vn = VirtualNode(type_idx=b.type_idx, zone_mask=b.zone_mask,
                             cap_mask=b.cap_mask, cum=b.cum)
            masked = np.where(
                b.zone_mask[:, None] & b.cap_mask[None, :]
                & cat.available[b.type_idx],
                cat.price[b.type_idx], np.inf)
            zi, ci = np.unravel_index(np.argmin(masked), masked.shape)
            reqs = Resources()
            for p in b.pods:
                reqs = reqs.add(p.requests)
            out.launches.append(NodeLaunch(
                instance_type=cat.names[b.type_idx], zone=cat.zones[int(zi)],
                capacity_type=cat.captypes[int(ci)],
                price=float(masked[zi, ci]),
                overrides=self._overrides(cat, vn, b.group_compat,
                                          nodepool.requirements),
                pod_keys=[_pod_key(p) for p in b.pods], requests=reqs,
                labels=self._node_labels(cat, vn, nodepool)))
        for name, placed in plan.existing_placements.items():
            keys = out.existing_placements.setdefault(name, [])
            keys.extend(_pod_key(p) for p in placed)
        out.unschedulable.extend(_pod_key(p) for p in plan.unschedulable)
        return out

    @staticmethod
    def _spread_constraints(enc: EncodedPods, cat: CatalogTensors,
                            occupancy: List[Tuple[Optional[str], List[Pod]]],
                            ) -> Optional[Dict[int, List[SpreadConstraintCounts]]]:
        """Per-group zone-spread constraints seeded with cluster-wide domain
        occupancy. `occupancy` is (zone, pods) per live/in-flight node —
        ALL nodes, not just this pool's, since k8s counts matching pods
        wherever they run; a node whose zone is still deferred (None)
        contributes to no domain yet.

        Selector semantics follow TopologySpreadConstraint.label_selector:
        None spreads the group against itself only (zero prior counts
        unless its own labels are visible in `occupancy` — they are not,
        by definition of None matching no external pods); {} counts every
        pod in the namespace; non-empty counts label matches. Matching is
        memoized per (namespace, selector) — one pass over the cluster's
        pods regardless of how many groups share a selector."""
        if not enc.spread_zone.any():
            return None
        # bucket the cluster's pods by zone once
        pods_by_zone: List[Tuple[int, List[Pod]]] = []
        for zone, pods_on in occupancy:
            zi = cat.zones.index(zone) if zone in cat.zones else -1
            if zi >= 0 and pods_on:
                pods_by_zone.append((zi, pods_on))
        memo: Dict[tuple, np.ndarray] = {}

        def counts_for(namespace: str, selector: Optional[Dict[str, str]],
                       ) -> np.ndarray:
            if selector is None:
                return np.zeros(cat.Z, np.int64)
            key = (namespace, tuple(sorted(selector.items())))
            hit = memo.get(key)
            if hit is None:
                hit = np.zeros(cat.Z, np.int64)
                for zi, pods_on in pods_by_zone:
                    for p in pods_on:
                        if p.namespace == namespace and all(
                                p.labels.get(k) == v for k, v in selector.items()):
                            hit[zi] += 1
                memo[key] = hit
            return hit

        out: Dict[int, List[SpreadConstraintCounts]] = {}
        for i, grp in enumerate(enc.groups):
            if not enc.spread_zone[i]:
                continue
            rep = grp.representative
            cons = []
            for tsc in rep.topology_spread:
                if tsc.topology_key != L.ZONE:
                    continue
                # ScheduleAnyway constraints also seed domain counts — they
                # steer balancing; the split's soft path guarantees they
                # never block
                cons.append(SpreadConstraintCounts(
                    counts=counts_for(rep.namespace, tsc.label_selector),
                    max_skew=max(1, tsc.max_skew),
                    self_matches=(tsc.label_selector is None
                                  or tsc.matches(rep.labels)),
                    soft=tsc.when_unsatisfiable != "DoNotSchedule"))
            if cons:
                out[i] = cons
        return out or None

    @staticmethod
    def _relax_infeasible_preferences(enc: EncodedPods,
                                      cat: CatalogTensors) -> None:
        """Preferred node affinity must never block: after zone-affinity
        surgery, zone-split pinning, and NodePool-limit caps have further
        narrowed the problem, any group whose preference-narrowed
        (type, zone, captype) masks no longer reach an available, fitting
        offering falls back to its hard rows (the pre-preference masks, as
        rewritten by the hard affinity passes). k8s drops unsatisfiable
        preferences the same way — they only score, never filter."""
        if (enc.compat_hard is None and enc.zone_hard is None
                and enc.cap_hard is None):
            return
        alloc = align_resources(cat.allocatable, enc.requests.shape[1])
        for i in range(enc.G):
            ch = enc.compat[i] if enc.compat_hard is None else enc.compat_hard[i]
            zh = enc.allow_zone[i] if enc.zone_hard is None else enc.zone_hard[i]
            cch = enc.allow_cap[i] if enc.cap_hard is None else enc.cap_hard[i]
            if ((enc.compat[i] == ch).all()
                    and (enc.allow_zone[i] == zh).all()
                    and (enc.allow_cap[i] == cch).all()):
                continue
            fits = (alloc >= enc.requests[i][None, :] - 1e-6).all(axis=1)
            ok = (cat.available
                  & (enc.compat[i] & fits)[:, None, None]
                  & enc.allow_zone[i][None, :, None]
                  & enc.allow_cap[i][None, None, :]).any()
            if not ok:
                enc.compat[i] = ch
                enc.allow_zone[i] = zh
                enc.allow_cap[i] = cch

    @staticmethod
    def _apply_resident_bans(enc: EncodedPods,
                             existing: List[VirtualNode],
                             existing_pods: Dict[str, List[Pod]]) -> None:
        """Set VirtualNode.banned_groups from actual resident pods: node n
        may not take group g if a resident's required hostname anti-affinity
        selects g's labels, or g's own term selects a resident's labels —
        k8s enforces both directions. Residents that map to NO current
        group (prior_by_group can't see them) still repel this way."""
        hostname_anti = [
            [t for t in grp.representative.affinity_terms
             if t.anti and t.required and t.topology_key == L.HOSTNAME]
            for grp in enc.groups]
        any_group_anti = any(hostname_anti)
        for vn in existing:
            vn.banned_groups = None  # never carry stale bans across encodings
            residents = existing_pods.get(vn.existing_name or "", [])
            res_anti = [(p, [t for t in p.affinity_terms
                             if t.anti and t.required
                             and t.topology_key == L.HOSTNAME])
                        for p in residents]
            if not any_group_anti and not any(ts for _, ts in res_anti):
                continue
            banned = np.zeros(enc.G, bool)
            for gi, grp in enumerate(enc.groups):
                rep = grp.representative
                for p, p_terms in res_anti:
                    same_ns = p.namespace == rep.namespace
                    if any(term_selects(t, same_ns, p.labels)
                           for t in hostname_anti[gi]) or \
                       any(term_selects(t, same_ns, rep.labels)
                           for t in p_terms):
                        banned[gi] = True
                        break
            if banned.any():
                vn.banned_groups = banned

    @staticmethod
    def _pin_bundle_zone(b: BundleNode, cat: CatalogTensors) -> int:
        """Narrow a bundle's deferred zone mask to its cheapest available
        zone; returns the zone index."""
        masked = np.where(
            b.zone_mask[:, None] & b.cap_mask[None, :]
            & cat.available[b.type_idx],
            cat.price[b.type_idx], np.inf)
        if np.isinf(masked).all():  # offerings vanished mid-solve: keep mask
            return int(np.flatnonzero(b.zone_mask)[0])
        zi = int(np.unravel_index(np.argmin(masked), masked.shape)[0])
        pin = np.zeros(cat.Z, bool)
        pin[zi] = True
        b.zone_mask = pin
        return zi

    @staticmethod
    def _zone_of(name: str, existing: Optional[List[VirtualNode]],
                 cat: CatalogTensors) -> Optional[str]:
        for vn in existing or []:
            if vn.existing_name == name:
                zs = np.flatnonzero(vn.zone_mask)
                return cat.zones[int(zs[0])] if len(zs) == 1 else None
        return None

    @staticmethod
    def _occupancy_from_existing(existing: Optional[List[VirtualNode]],
                                 existing_pods: Optional[Dict[str, List[Pod]]],
                                 cat: CatalogTensors,
                                 ) -> List[Tuple[Optional[str], List[Pod]]]:
        """Fallback occupancy when the caller didn't supply a cluster-wide
        view: derive (zone, pods) from the solve's own existing nodes."""
        out: List[Tuple[Optional[str], List[Pod]]] = []
        for vn in existing or []:
            zs = np.flatnonzero(vn.zone_mask)
            zone = cat.zones[int(zs[0])] if len(zs) == 1 else None
            out.append((zone, (existing_pods or {}).get(vn.existing_name or "", [])))
        return out

    # --- result mapping ---
    def _decode(self, cat: CatalogTensors, enc: EncodedPods,
                result: SolveResult, nodepool: NodePool,
                dropped: List[str]) -> SolveOutput:
        # Per-group pod cursors for deterministic nomination. Keyed by the
        # PodGroup object, not the row index: split_spread_groups emits
        # multiple rows referencing ONE PodGroup, and those rows must draw
        # disjoint pod slices from its list.
        cursors: Dict[int, int] = {}

        def take_pods(g: int, cnt: int) -> List[Pod]:
            grp = enc.groups[g]
            k = id(grp)
            at = cursors.get(k, 0)
            cursors[k] = at + cnt
            return grp.pods[at: at + cnt]
        launches: List[NodeLaunch] = []
        existing_placements: Dict[str, List[str]] = {}
        li = 0
        for node in result.nodes:
            keys = []
            reqs = Resources()
            for g, cnt in sorted(node.pods_by_group.items()):
                take = take_pods(g, cnt)
                keys.extend(_pod_key(p) for p in take)
                for p in take:
                    reqs = reqs.add(p.requests)
            if node.existing_name is not None:
                if keys:
                    existing_placements[node.existing_name] = keys
                continue
            t, zi, ci, price = result.launches[li]
            li += 1
            it_name = cat.names[node.type_idx]
            labels = self._node_labels(cat, node, nodepool)
            # alternates must satisfy every pod on the node, not just fit its
            # resource sum — AND the groups' compat masks
            group_compat = np.ones(cat.T, bool)
            for g in node.pods_by_group:
                group_compat &= enc.compat[g]
            launches.append(NodeLaunch(
                instance_type=it_name, zone=cat.zones[zi],
                capacity_type=cat.captypes[ci], price=price,
                overrides=self._overrides(cat, node, group_compat,
                                          nodepool.requirements),
                pod_keys=keys, requests=reqs, labels=labels))
        unschedulable = list(dropped)
        for g, cnt in result.unschedulable.items():
            unschedulable.extend(_pod_key(p) for p in take_pods(g, cnt))
        return SolveOutput(launches=launches,
                           existing_placements=existing_placements,
                           unschedulable=unschedulable)

    def _overrides(self, cat: CatalogTensors, node: VirtualNode,
                   group_compat: np.ndarray,
                   requirements: Optional[Requirements] = None,
                   ) -> List[Tuple[str, str, str, float]]:
        """Price-sorted alternate offerings for this node's pod set: any
        type compatible with every pod on the node that holds node.cum, and
        any surviving (zone, captype). Gives the launch path ICE resilience
        without a re-solve.

        requirements: the NodePool requirements; keys carrying minValues
        turn the 60-row cap into constrained selection (reference
        InstanceTypes.Truncate at instance.go:293) — the kept rows must
        span >= minValues distinct values per key, so a launch keeps its
        flexibility floor (e.g. the >=15-type spot-to-spot gate). Selection
        is best-effort: when the floor is unreachable within the cap, the
        plain cheapest rows ship rather than failing the launch."""
        alloc = align_resources(cat.allocatable, len(node.cum))
        fits = (alloc >= node.cum[None, :] - 1e-4).all(axis=1)  # [T]
        ok = fits & group_compat
        mask = (cat.available & ok[:, None, None]
                & node.zone_mask[None, :, None] & node.cap_mask[None, None, :])
        t_idx, z_idx, c_idx = np.nonzero(mask)
        prices = cat.price[t_idx, z_idx, c_idx]
        by_price = np.argsort(prices, kind="stable")
        order = self._floor_rows(cat, t_idx, z_idx, c_idx, by_price,
                                 min_values_floors(requirements))
        primary = node.type_idx
        rows = [(cat.names[t_idx[j]], cat.zones[z_idx[j]],
                 cat.captypes[c_idx[j]], float(prices[j])) for j in order]
        # ONE row of the committed type — its cheapest — leads (the
        # solver's pick); every alternate stays in global price order.
        # The cloud walks the list in order, so leading with ALL of the
        # committed type's rows would make an ICE fallback pay for a
        # pricier sibling of the committed type while a cheaper viable
        # row of another type sits further down.
        rows.sort(key=lambda r: r[3])
        for j, r in enumerate(rows):
            if r[0] == cat.names[primary]:
                rows.insert(0, rows.pop(j))
                break
        return rows[:MAX_OVERRIDES]

    @staticmethod
    def _apply_min_values_caps(enc: EncodedPods, cat: CatalogTensors,
                               requirements: Requirements) -> None:
        """minValues as a NODE-OPENING constraint (the reference scheduler
        keeps each virtual node's remaining compatible-type set above every
        minValues floor, opening a new node rather than shrinking below it):
        cap each group's pods-per-node so a node's load still fits the
        N-th-best compatible VALUE of each minValues key — then >= N
        distinct values survive into the launch overrides. Exact for
        single-group nodes (the dominant dense case); mixed-group nodes can
        combine loads that narrow further, where the override floor stays
        best-effort."""
        mv = min_values_floors(requirements)
        if not mv:
            return
        from .binpack import BIG, EPS
        alloc = align_resources(cat.allocatable, enc.requests.shape[1])
        for i in range(enc.G):
            req = enc.requests[i].astype(np.float32)
            with_req = np.where(req > 0, req, np.float32(1.0))
            slots = np.where(req[None, :] > 0,
                             np.floor(alloc / with_req[None, :] + EPS),
                             np.float32(BIG)).min(axis=1)       # [T]
            slots = np.where(enc.compat[i], np.maximum(slots, 0.0), 0.0)
            cap = BIG
            for key, need in mv:
                if key == L.INSTANCE_TYPE:
                    per_value = slots[slots > 0]
                elif key in cat.label_keys:
                    ids = cat.label_val[:, cat.label_keys.index(key)]
                    vals = np.unique(ids[(ids >= 0) & (slots > 0)])
                    per_value = np.array(
                        [slots[ids == v].max() for v in vals])
                else:
                    # offering-axis floors (zone/capacity-type) don't bound
                    # node SIZE — _floor_rows spans them in the override
                    # list instead
                    continue
                if len(per_value) < need:
                    continue  # floor unreachable: solver proceeds, launch
                    # ships best-effort rows (reference errors the create)
                nth = np.sort(per_value)[-need]  # N-th largest value's slots
                cap = min(cap, int(nth))
            if cap < BIG and cap >= 1:
                cur = int(enc.max_per_node[i])
                enc.max_per_node[i] = cap if cur == 0 else min(cur, cap)

    @staticmethod
    def _floor_rows(cat: CatalogTensors, t_idx, z_idx, c_idx, by_price,
                    mv: List[Tuple[str, int]]) -> np.ndarray:
        """Override-row order honoring every minValues floor within the
        60-row cap: reserve the cheapest row contributing each still-
        missing distinct value per key — INSTANCE_TYPE = the row's type,
        zone / capacity-type = the row's OFFERING axis (offering-axis
        floors are real: minValues=3 on zone must ship rows spanning 3
        zones), other keys = the row's type label — then fill the rest
        cheapest-first. A floor the candidate rows cannot span falls back
        to plain price order (best-effort; the reference errors the
        create)."""
        if not mv or len(by_price) == 0:
            return by_price[:MAX_OVERRIDES]

        def value_of(j: int, key: str):
            t = int(t_idx[j])
            if key == L.INSTANCE_TYPE:
                return cat.names[t]
            if key == L.ZONE:
                return int(z_idx[j])
            if key == L.CAPACITY_TYPE:
                return int(c_idx[j])
            if key in cat.label_keys:
                v = int(cat.label_val[t, cat.label_keys.index(key)])
                return v if v >= 0 else None
            return None

        selected: List[int] = []
        chosen = set()
        for key, need in mv:
            start = len(selected)
            have = {value_of(j, key) for j in selected} - {None}
            for j in by_price:
                if len(have) >= need:
                    break
                j = int(j)
                if j in chosen:
                    continue
                v = value_of(j, key)
                if v is not None and v not in have:
                    selected.append(j)
                    chosen.add(j)
                    have.add(v)
            if len(have) < need or len(selected) > MAX_OVERRIDES:
                # THIS floor is unreachable: drop only its reservations —
                # floors other keys already secured must still ship
                chosen.difference_update(selected[start:])
                del selected[start:]
        for j in by_price:
            if len(selected) >= MAX_OVERRIDES:
                break
            if int(j) not in chosen:
                selected.append(int(j))
        return np.array(selected, dtype=int)

    def _node_labels(self, cat: CatalogTensors, node: VirtualNode,
                     nodepool: NodePool) -> Dict[str, str]:
        labels = nodepool.template_labels()
        labels[L.INSTANCE_TYPE] = cat.names[node.type_idx]
        return labels


def virtual_node_from_claim(claim: NodeClaim, cat: CatalogTensors,
                            used: Resources) -> Optional[VirtualNode]:
    """Reconstruct an in-flight NodeClaim as solver input so repeated
    reconciles keep filling it instead of over-provisioning (the reference
    scheduler simulates against in-flight nodes the same way)."""
    idx = cat.name_to_idx.get(claim.instance_type or "")
    if idx is None:
        return None
    zone_mask = np.array([z == claim.zone for z in cat.zones], bool) \
        if claim.zone else np.ones(cat.Z, bool)
    cap_mask = np.array([c == claim.capacity_type for c in cat.captypes], bool) \
        if claim.capacity_type else np.ones(cat.C, bool)
    vec = used.to_vector()
    cum = np.zeros(len(cat.resources), np.float32)
    cum[: len(vec)] = vec[: len(cum)]
    return VirtualNode(type_idx=idx, zone_mask=zone_mask, cap_mask=cap_mask,
                       cum=cum, existing_name=claim.name)
