"""Solver facade: pods + NodePool + catalog → launch decisions.

The `Solver` interface of the north star: the control plane owns all
mutable state and calls solve() statelessly with (pods, catalog-epoch);
this module hides encoding, spread-splitting, device-tensor caching, and
backend selection (TPU kernel vs host oracle — identical semantics).

Output maps tensor results back to the object world: one NodeLaunch per
new virtual node, carrying the committed instance type, the cheapest
surviving offering, a price-sorted override list for launch resilience
(reference sends ≤60 override rows per CreateFleet, instance.go:58-63),
and the concrete pods nominated to it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..catalog.provider import CatalogProvider
from ..models import labels as L
from ..models.instancetype import InstanceType
from ..models.nodeclaim import NodeClaim
from ..models.nodepool import NodeClassSpec, NodePool
from ..models.pod import Pod
from ..models.requirements import Requirements
from ..models.resources import Resources
from .binpack import (SolveResult, VirtualNode, solve_host,
                      split_spread_groups, validate_solution)
from .encode import (CatalogTensors, EncodedPods, align_resources,
                     encode_catalog, encode_pods)

MAX_OVERRIDES = 60  # reference MaxInstanceTypes (instance.go:62)


@dataclass
class NodeLaunch:
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    overrides: List[Tuple[str, str, str, float]]  # (type, zone, captype, price)
    pod_keys: List[str]
    requests: Resources
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class SolveOutput:
    launches: List[NodeLaunch]
    existing_placements: Dict[str, List[str]]  # existing node name -> pod keys
    unschedulable: List[str]                   # pod keys
    stats: Dict[str, float] = field(default_factory=dict)


def _pod_key(p: Pod) -> str:
    return f"{p.namespace}/{p.name}"


class Solver:
    def __init__(self, catalog: CatalogProvider, backend: str = "auto"):
        self.catalog = catalog
        if backend == "auto":
            backend = self._detect_backend()
        self.backend = backend
        self._cat_cache: Dict[tuple, CatalogTensors] = {}
        self._dcat_cache: Dict[tuple, object] = {}  # device-resident tensors
        self._last_cat_key: tuple = ()

    @staticmethod
    def _detect_backend() -> str:
        """auto: TPU kernel when an accelerator is attached, else the
        compiled C++ solver, else the numpy oracle."""
        try:
            import jax
            if any(d.platform != "cpu" for d in jax.devices()):
                return "device"
        except Exception:
            pass
        from . import native
        return "native" if native.available() else "host"

    def tensors(self, node_class: Optional[NodeClassSpec] = None) -> CatalogTensors:
        nc = node_class or NodeClassSpec()
        key = (nc.hash(),) + tuple(self.catalog.epoch)
        hit = self._cat_cache.get(key)
        if hit is None:
            types = self.catalog.list(nc)
            hit = encode_catalog(types)
            self._cat_cache.clear()  # one epoch's views at a time
            self._cat_cache[key] = hit
        self._last_cat_key = key
        return hit

    def solve(self, pods: Sequence[Pod], nodepool: NodePool,
              node_class: Optional[NodeClassSpec] = None,
              existing: Optional[List[VirtualNode]] = None,
              capacity_cap: Optional[Resources] = None,
              existing_pods: Optional[Dict[str, List[Pod]]] = None) -> SolveOutput:
        """capacity_cap: only open nodes whose total capacity fits within it
        (the NodePool-limits headroom; the reference scheduler stops opening
        virtual nodes that would breach spec.limits the same way).

        existing_pods: pods already on each existing node (by existing_name)
        — matched by constraint signature into the current groups so
        per-node caps (anti-affinity/hostname-spread) hold across
        reconciles, not just within one solve."""
        cat = self.tensors(node_class)
        if cat.T == 0 or not pods:
            return SolveOutput([], {}, [_pod_key(p) for p in pods])
        enc = encode_pods(pods, cat,
                          extra_requirements=nodepool.requirements,
                          taints=nodepool.taints + nodepool.startup_taints)
        if capacity_cap is not None:
            types = self.catalog.list(node_class or NodeClassSpec())
            fits_cap = np.array(
                [all(t.capacity.get(k, 0.0) <= v + 1e-9
                     for k, v in capacity_cap.items())
                 for t in types], bool)
            enc.compat &= fits_cap[None, :]
        # pods dropped by the taint filter are unschedulable for this pool
        enc_keys = {_pod_key(p) for g in enc.groups for p in g.pods}
        dropped = [_pod_key(p) for p in pods if _pod_key(p) not in enc_keys]
        enc = split_spread_groups(enc, cat)
        if enc.G == 0:
            return SolveOutput([], {}, dropped)

        if existing and existing_pods:
            sig_to_groups: Dict[tuple, List[int]] = {}
            for gi, grp in enumerate(enc.groups):
                sig_to_groups.setdefault(
                    grp.representative.constraint_signature(), []).append(gi)
            for vn in existing:
                counts: Dict[int, int] = {}
                for p in existing_pods.get(vn.existing_name or "", []):
                    for gi in sig_to_groups.get(p.constraint_signature(), []):
                        counts[gi] = counts.get(gi, 0) + 1
                vn.prior_by_group = counts

        import time as _time

        from ..metrics import SOLVE_DURATION, SOLVE_PODS
        t0 = _time.perf_counter()
        if self.backend == "host":
            result = solve_host(cat, enc, existing)
        elif self.backend == "native":
            from .native import solve_native
            result = solve_native(cat, enc, existing)
        else:
            from .solver import device_catalog, solve_device
            R = enc.requests.shape[1]
            # keyed on (nodeclass hash, catalog epoch, R) — NOT id(cat):
            # a freed CatalogTensors' address can be reused by its successor
            dkey = self._last_cat_key + (R,)
            dcat = self._dcat_cache.get(dkey)
            if dcat is None:
                self._dcat_cache.clear()  # one epoch resident at a time
                dcat = device_catalog(cat, R)
                self._dcat_cache[dkey] = dcat
            result = solve_device(cat, enc, existing, dcat=dcat)
        SOLVE_DURATION.observe(_time.perf_counter() - t0, backend=self.backend)
        SOLVE_PODS.observe(float(enc.counts.sum()))

        return self._decode(cat, enc, result, nodepool, dropped)

    # --- result mapping ---
    def _decode(self, cat: CatalogTensors, enc: EncodedPods,
                result: SolveResult, nodepool: NodePool,
                dropped: List[str]) -> SolveOutput:
        # per-group pod cursors for deterministic nomination
        cursors = [0] * enc.G
        launches: List[NodeLaunch] = []
        existing_placements: Dict[str, List[str]] = {}
        li = 0
        for node in result.nodes:
            keys = []
            reqs = Resources()
            for g, cnt in sorted(node.pods_by_group.items()):
                grp = enc.groups[g]
                take = grp.pods[cursors[g]: cursors[g] + cnt]
                cursors[g] += cnt
                keys.extend(_pod_key(p) for p in take)
                for p in take:
                    reqs = reqs.add(p.requests)
            if node.existing_name is not None:
                if keys:
                    existing_placements[node.existing_name] = keys
                continue
            t, zi, ci, price = result.launches[li]
            li += 1
            it_name = cat.names[node.type_idx]
            labels = self._node_labels(cat, node, nodepool)
            # alternates must satisfy every pod on the node, not just fit its
            # resource sum — AND the groups' compat masks
            group_compat = np.ones(cat.T, bool)
            for g in node.pods_by_group:
                group_compat &= enc.compat[g]
            launches.append(NodeLaunch(
                instance_type=it_name, zone=cat.zones[zi],
                capacity_type=cat.captypes[ci], price=price,
                overrides=self._overrides(cat, node, group_compat),
                pod_keys=keys, requests=reqs, labels=labels))
        unschedulable = list(dropped)
        for g, cnt in result.unschedulable.items():
            grp = enc.groups[g]
            take = grp.pods[cursors[g]: cursors[g] + cnt]
            cursors[g] += cnt
            unschedulable.extend(_pod_key(p) for p in take)
        return SolveOutput(launches=launches,
                           existing_placements=existing_placements,
                           unschedulable=unschedulable)

    def _overrides(self, cat: CatalogTensors, node: VirtualNode,
                   group_compat: np.ndarray) -> List[Tuple[str, str, str, float]]:
        """Price-sorted alternate offerings for this node's pod set: any
        type compatible with every pod on the node that holds node.cum, and
        any surviving (zone, captype). Gives the launch path ICE resilience
        without a re-solve."""
        alloc = align_resources(cat.allocatable, len(node.cum))
        fits = (alloc >= node.cum[None, :] - 1e-4).all(axis=1)  # [T]
        ok = fits & group_compat
        mask = (cat.available & ok[:, None, None]
                & node.zone_mask[None, :, None] & node.cap_mask[None, None, :])
        t_idx, z_idx, c_idx = np.nonzero(mask)
        prices = cat.price[t_idx, z_idx, c_idx]
        order = np.argsort(prices, kind="stable")[:MAX_OVERRIDES]
        out = []
        primary = node.type_idx
        # ensure the committed type's cheapest offering is first
        rows = [(cat.names[t_idx[j]], cat.zones[z_idx[j]],
                 cat.captypes[c_idx[j]], float(prices[j])) for j in order]
        rows.sort(key=lambda r: (r[0] != cat.names[primary], r[3]))
        return rows[:MAX_OVERRIDES]

    def _node_labels(self, cat: CatalogTensors, node: VirtualNode,
                     nodepool: NodePool) -> Dict[str, str]:
        labels = dict(nodepool.labels)
        labels.update(nodepool.requirements.single_values())
        labels[L.NODEPOOL] = nodepool.name
        labels[L.INSTANCE_TYPE] = cat.names[node.type_idx]
        return labels


def virtual_node_from_claim(claim: NodeClaim, cat: CatalogTensors,
                            used: Resources) -> Optional[VirtualNode]:
    """Reconstruct an in-flight NodeClaim as solver input so repeated
    reconciles keep filling it instead of over-provisioning (the reference
    scheduler simulates against in-flight nodes the same way)."""
    idx = cat.name_to_idx.get(claim.instance_type or "")
    if idx is None:
        return None
    zone_mask = np.array([z == claim.zone for z in cat.zones], bool) \
        if claim.zone else np.ones(cat.Z, bool)
    cap_mask = np.array([c == claim.capacity_type for c in cat.captypes], bool) \
        if claim.capacity_type else np.ones(cat.C, bool)
    vec = used.to_vector()
    cum = np.zeros(len(cat.resources), np.float32)
    cum[: len(vec)] = vec[: len(cum)]
    return VirtualNode(type_idx=idx, zone_mask=zone_mask, cap_mask=cap_mask,
                       cum=cum, existing_name=claim.name)
