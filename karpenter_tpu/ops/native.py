"""ctypes bridge to the native C++ group-FFD solver (native/ffd.cpp).

Builds the shared library on first use (g++ -O3, cached next to the
source); falls back cleanly when no compiler is present. Semantics are
bit-identical to solve_host / the TPU kernel, so the golden tests run
across all three backends.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

import numpy as np

from .binpack import SolveResult, VirtualNode, finalize_offerings
from .encode import CatalogTensors, EncodedPods, align_resources
from .solver import _bucket

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "ffd.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "libffd.so")

_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            subprocess.run(
                ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                 "-o", _LIB, _SRC],
                check=True, capture_output=True, text=True)
        lib = ctypes.CDLL(_LIB)
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.ffd_solve.restype = ctypes.c_int32
        u8p_or_null = ctypes.c_void_p  # nullable uint8* (banned / conflict)
        lib.ffd_solve.argtypes = [
            f32p, f32p, u8p, f32p, i32p, u8p, u8p, u8p, i32p, i32p,
            u8p_or_null, u8p_or_null,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            i32p, f32p, u8p, u8p, i32p, i32p,
            ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
    except (subprocess.CalledProcessError, OSError) as e:
        _build_error = str(e)
    return _lib


def available() -> bool:
    return _load() is not None


def solve_native(cat: CatalogTensors, enc: EncodedPods,
                 existing: Optional[List[VirtualNode]] = None,
                 n_max: Optional[int] = None) -> SolveResult:
    """Same contract as solve_host/solve_device."""
    lib = _load()
    assert lib is not None, f"native solver unavailable: {_build_error}"
    assert not enc.spread_zone.any(), "run split_spread_groups before solve"
    existing = existing or []
    R = enc.requests.shape[1]
    G, T, Z, C = enc.G, cat.T, cat.Z, cat.C
    Ne = len(existing)
    total = int(enc.counts.sum())
    if n_max is None:
        n_max = _bucket(Ne + total)  # native state is cheap; no retry loop

    alloc = np.ascontiguousarray(align_resources(cat.allocatable, R), np.float32)
    price = np.ascontiguousarray(cat.price, np.float32)
    avail = np.ascontiguousarray(cat.available, np.uint8)
    requests = np.ascontiguousarray(enc.requests, np.float32)
    counts = np.ascontiguousarray(enc.counts, np.int32)
    compat = np.ascontiguousarray(enc.compat, np.uint8)
    allow_zone = np.ascontiguousarray(enc.allow_zone, np.uint8)
    allow_cap = np.ascontiguousarray(enc.allow_cap, np.uint8)
    mpn = np.ascontiguousarray(enc.max_per_node, np.int32)

    prior = np.zeros((G, n_max), np.int32)
    node_type = np.zeros(n_max, np.int32)
    node_cum = np.zeros((n_max, R), np.float32)
    node_zmask = np.zeros((n_max, Z), np.uint8)
    node_cmask = np.zeros((n_max, C), np.uint8)
    for i, n in enumerate(existing):
        assert len(n.cum) <= R, (
            f"existing node cum has {len(n.cum)} resources but the current "
            f"axis is {R} — the resource axis only grows within a process")
        node_type[i] = n.type_idx
        node_cum[i, : len(n.cum)] = n.cum
        node_zmask[i] = n.zone_mask.astype(np.uint8)
        node_cmask[i] = n.cap_mask.astype(np.uint8)
        for g, cnt in n.prior_by_group.items():
            if g < G:
                prior[g, i] = cnt

    banned = None
    if any(n.banned_groups is not None for n in existing):
        banned = np.zeros((G, n_max), np.uint8)
        for i, n in enumerate(existing):
            if n.banned_groups is not None:
                banned[: len(n.banned_groups), i] = n.banned_groups
    conflict = (np.ascontiguousarray(enc.conflict, np.uint8)
                if enc.conflict is not None else None)

    def _ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p) if a is not None else None

    takes = np.zeros((G, n_max), np.int32)
    unsched = np.zeros(G, np.int32)
    n_used = ctypes.c_int64(0)
    lib.ffd_solve(alloc, price, avail, requests, counts, compat, allow_zone,
                  allow_cap, mpn, np.ascontiguousarray(prior),
                  _ptr(banned), _ptr(conflict),
                  G, T, Z, C, R, n_max, Ne,
                  node_type, node_cum, node_zmask, node_cmask,
                  takes, unsched, ctypes.byref(n_used))

    nodes: List[VirtualNode] = []
    for i in range(int(n_used.value)):
        pods = {g: int(takes[g, i]) for g in range(G) if takes[g, i] > 0}
        nodes.append(VirtualNode(
            type_idx=int(node_type[i]),
            zone_mask=node_zmask[i].astype(bool),
            cap_mask=node_cmask[i].astype(bool),
            cum=node_cum[i].copy(), pods_by_group=pods,
            banned_groups=existing[i].banned_groups if i < Ne else None,
            existing_name=existing[i].existing_name if i < Ne else None))
    result = SolveResult(
        nodes=nodes,
        unschedulable={g: int(unsched[g]) for g in range(G) if unsched[g] > 0})
    finalize_offerings(result, cat)
    return result
