"""Pallas TPU kernel for the consolidation screen's k-cap computation.

The screen's hot loop materializes an [N, G, R] ratio tensor
(5k nodes x 128 groups x 8 resources ≈ 20 MB f32) just to min-reduce it
over R (ops/consolidate._screen_kernel_impl). This kernel keeps the
computation VMEM-resident: the node axis is tiled over the grid, the
group axis rides the 128-wide lane dimension, and the R reduction is a
statically unrolled loop of [TILE_N, G] vector ops — the intermediate
never exists in HBM.

    k[m, g] = max(min_r cond(req[g,r] > 0,
                             floor(headroom[m,r] / req[g,r] + EPS),
                             BIG),
                  0)                      gated by elig[m, g]

OPT-IN: ops/consolidate's single-device path routes through it only
when KARPENTER_TPU_PALLAS=1 AND a TPU backend is attached AND the probe
kernel compiles (see available()); a failure at the real shape falls
back to the fused-XLA path with identical semantics. The mesh
(multi-chip) screen always uses the XLA path — this kernel is not
GSPMD-partitioned. Tests run the interpreter (interpret=True) on CPU
and assert bit-parity with the XLA path; bench.py reports a
pallas-vs-XLA screen comparison when the flag is on and the probe
passes.

Measured state on the current rig (v5e behind the axon tunnel,
2026-07): XLA already fuses this reduction to ~0.03 ms device time at
[20k nodes x 128 groups x 8 resources] — the op is memory-bandwidth
floor either way — and the tunnel's remote-compile helper cannot lower
gridded Mosaic kernels (HTTP 500; a minimal ungridded kernel compiles).
The availability probe therefore correctly selects the XLA path here;
this kernel is the escape hatch for shapes/hardware where the fused
path regresses, not today's fast path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .binpack import BIG, EPS

TILE_N = 256   # node rows per grid step (f32 sublane multiple)
LANES = 128    # group axis rides the lane dimension


def _k_kernel(head_ref, req_ref, elig_ref, out_ref, *, R: int):
    """One node tile: head [TILE_N, Rp], req [G, Rp], elig [TILE_N, G]
    -> k [TILE_N, G]. R is static; the reduction unrolls into R
    [TILE_N, G] vector ops on the VPU. (The resource axis is padded to
    the 128-lane tile — Mosaic rejects narrower last dims — but only
    the first R lanes are read.)"""
    k = jnp.full(out_ref.shape, jnp.float32(BIG))
    for r in range(R):
        h = head_ref[:, r][:, None]                     # [TILE_N, 1]
        q = req_ref[:, r][None, :]                      # [1, G]
        safe = jnp.where(q > 0, q, jnp.float32(1.0))
        ratio = jnp.where(q > 0,
                          jnp.floor(h / safe + jnp.float32(EPS)),
                          jnp.float32(BIG))
        k = jnp.minimum(k, ratio)
    out_ref[:] = jnp.where(elig_ref[:] > 0, jnp.maximum(k, 0.0), 0.0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def screen_k(headroom: jax.Array, group_req: jax.Array,
             elig: jax.Array, interpret: bool = False) -> jax.Array:
    """f32 [N, G] per-(node, group) fit counts, eligibility-gated.

    headroom: f32 [N, R] (allocatable of the node's type minus its load)
    group_req: f32 [G, R]
    elig: f32/bool [N, G] — compat & offering-surviving & active
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, R = headroom.shape
    G = group_req.shape[0]
    Np = -(-N // TILE_N) * TILE_N
    Gp = -(-G // LANES) * LANES
    Rp = LANES  # resource axis rides (padded) lanes; R is always small
    head = jnp.zeros((Np, Rp), jnp.float32).at[:N, :R].set(
        headroom.astype(jnp.float32))
    req = jnp.zeros((Gp, Rp), jnp.float32).at[:G, :R].set(
        group_req.astype(jnp.float32))
    el = jnp.zeros((Np, Gp), jnp.float32).at[:N, :G].set(
        elig.astype(jnp.float32))

    out = pl.pallas_call(
        functools.partial(_k_kernel, R=R),
        grid=(Np // TILE_N,),
        in_specs=[
            pl.BlockSpec((TILE_N, Rp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Gp, Rp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_N, Gp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_N, Gp), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Np, Gp), jnp.float32),
        interpret=interpret,
    )(head, req, el)
    return out[:N, :G]


_status = None  # None = unprobed; True/False after probe


def available() -> bool:
    """Can the Pallas path run here? OPT-IN via KARPENTER_TPU_PALLAS=1:
    the probe compiles a tiny kernel, and on a rig whose remote-compile
    helper is broken for Mosaic (the tunneled dev chip) that compile can
    HANG, not just fail — a default-on probe would stall the first
    consolidation screen of the process. Operators on hardware with a
    healthy local Mosaic toolchain set the flag; everyone else gets the
    fused-XLA path (which measures at the memory-bandwidth floor for
    this op anyway — see module docstring)."""
    global _status
    if _status is not None:
        return _status
    if os.environ.get("KARPENTER_TPU_PALLAS", "0") != "1":
        _status = False
        return False
    try:
        if not any(d.platform != "cpu" for d in jax.devices()):
            _status = False
            return False
        k = screen_k(jnp.ones((8, 4)), jnp.ones((4, 4)),
                     jnp.ones((8, 4)))
        jax.block_until_ready(k)
        _status = True
    except Exception:
        _status = False
    return _status
