"""Device-resident cluster state: delta uploads + donated in-place patches.

ROADMAP item 1, the optimization the PR 10 telemetry plane was built to
judge. Cold 100k-pod solves spend 30-50x the ~2-3ms kernel on host
orchestration and H2D transfer, and the upload-redundancy meter
(`obs/devicemem.UploadMeter`) shows most warm-upload bytes are
byte-identical to the previous tick — bytes the device already holds.
This module spends that measured headroom: the feasibility/occupancy/
request tensors stay RESIDENT on device across reconciles, and each
solve ships only the rows that changed.

Mechanics (`ResidentStateManager`, process singleton `RESIDENT`):

- every resident view is a `ResidentEntry` keyed per facade/catalog
  view (the same key discipline as the upload meter), holding the
  device buffer, the uint64 per-row content digests of its CURRENT
  bytes (the `UploadMeter._row_digests` checksum — the row classifier
  the warm path's DeltaTracker-adjacent machinery feeds), and the
  catalog `cache_token` the bytes were encoded against;
- `upload(key, matrix, token)` digests the new host matrix, diffs it
  against the entry, uploads ONLY the changed rows (one [k, W] block +
  one [k] index vector), and applies them with a jitted scatter whose
  `donate_argnums` donates the resident buffer — the update mutates the
  device allocation in place instead of reallocating (SNIPPETS.md [1]);
  zero changed rows means ZERO device traffic;
- full re-upload fallbacks, each metered on
  `resident_fallback_total{reason}`: `first_sight` (no entry),
  `token_change` (catalog epoch bump / ICE or price re-fingerprint —
  the entry's token no longer matches the view's), `shape_change`
  (padded shape-class growth or resource-axis width growth),
  `dtype_change`, `dense` (more than `PATCH_MAX_FRAC` of rows changed:
  a patch would ship most of the matrix anyway, and the full path keeps
  one transfer instead of two), and `invalidated` (an explicit
  `invalidate()` — SharedCatalogCache view splits/evictions, warm-path
  audit divergence);
- catalog tensors patch too (`device_catalog(resident_key=...)` routes
  alloc/price/avail/zone-overhead through the manager), but WITHOUT
  donation: a shared view's previous `DeviceCatalog` may still serve a
  co-tenant (an ICE divergence splits views, it doesn't retire them),
  and donating a buffer another tenant still reads would corrupt it.
  The transfer saving — only changed type rows cross the tunnel — is
  identical either way; batched buckets therefore patch their shared
  catalog once per epoch bump (the first staged ticket's `_auto_dcat`
  miss), not per ticket;
- every resident buffer registers with the PR 10 residency ledger under
  the new owner kind `resident_state` (owner = the entry), so the HBM
  watermark, the live-bytes gauges, and the watchdog's `devicemem_leak`
  invariant govern resident state exactly like every other device
  allocation; patch traffic is attributed under the new transfer reason
  `resident_patch` and metered on `devicemem_patch_bytes_total{outcome}`
  (patched = changed-row bytes shipped, avoided = identical bytes NOT
  shipped, full = fallback re-upload bytes).

Correctness: a patched buffer's bytes equal the cold upload's by
construction — changed rows are written verbatim, unchanged rows are
unchanged because their 64-bit content digests match (accidental
collision odds ~2^-64 per row pair, far below anything observable; the
byte-parity fuzz in tests/test_resident.py is the gate, and the
warm-path auditor's divergence hook invalidates resident state the
moment the incremental pipeline disagrees with a cold solve).

Staleness: `observe_view(prefix, base_token)` records the newest token
the facade resolved for a view; entries under the prefix whose token no
longer starts with that base are STALE (device bytes encode an older
catalog epoch than the store serves). A stale entry can never be
*served* — `upload()` re-keys on token mismatch — but one lingering
past a grace is the watchdog's `resident_staleness` invariant: HBM held
for a view the world moved past.

Opt-out: `KARPENTER_TPU_RESIDENT=0` disarms the manager process-wide
(every caller falls back to the classic full-upload path).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import devicemem as dm
from ..obs.tracer import NOOP_SPAN, TRACER

# above this changed-row fraction a patch ships most of the matrix in
# two transfers (rows + indices) where a full re-upload ships it in one
PATCH_MAX_FRAC = 0.5
# resident views kept (LRU). Sized ABOVE the default fleet's working
# set (50 tenants x (gbuf [+conflict]) + the shared catalog tensors):
# an LRU smaller than a round-robin working set thrashes on every
# access — each upload would fall back to first_sight and the delta
# path would never engage at exactly the scale it targets. Entries are
# host-cheap (digest vector + a device-buffer reference); evictions are
# counted in stats["evictions"], so a fleet outgrowing the bound is a
# visible number, not a silent perf cliff.
MAX_ENTRIES = 512

FALLBACK_REASONS: Tuple[str, ...] = (
    "first_sight", "token_change", "shape_change", "dtype_change",
    "dense", "invalidated", "corruption",
)


def _jit_scatter():
    import jax

    def _scatter(buf, idx, rows):
        return buf.at[idx].set(rows)

    donate = partial(jax.jit, donate_argnums=(0,))(_scatter)  # graftlint: disable=jit-in-hot-path -- built exactly once; _scatter_fn memoizes both variants in module globals
    plain = jax.jit(_scatter)  # graftlint: disable=jit-in-hot-path -- see above: one-shot construction behind _scatter_fn's None-check memo
    return donate, plain


_scatter_donate = None
_scatter_plain = None


def _scatter_fn(donate: bool):  # graftlint: donates=0
    """The jitted row scatter; the donating variant only off-CPU (CPU
    backends warn on donation, same gate as the batched dispatch).
    Callers: the returned callable CONSUMES its first argument (the
    resident buffer) when donating — the `# graftlint: donates=0`
    annotation above makes the use-after-donate rule track call sites,
    so a read of the donated buffer between dispatch and rebind fails
    `make lint`."""
    global _scatter_donate, _scatter_plain
    if _scatter_plain is None:
        _scatter_donate, _scatter_plain = _jit_scatter()
    if not donate:
        return _scatter_plain
    try:
        import jax
        cpu = jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — backend probing must not crash a solve
        cpu = True
    return _scatter_plain if cpu else _scatter_donate


@dataclass
class ResidentEntry:
    """One device-resident view: the buffer, its row digests, and the
    catalog token its bytes were encoded against. The entry OWNS its
    buffer in the residency ledger's sense — the entry dying while the
    bytes stay live is the devicemem_leak orphan condition."""

    key: tuple
    token: Optional[tuple]
    shape: tuple
    dtype: object
    digests: np.ndarray            # uint64 [rows]
    buf: object                    # jax.Array
    group: int                     # residency-ledger group id
    shape_class: Optional[str] = None
    # explicit device layout (jax.sharding.NamedSharding) the buffer was
    # committed with, None = default single-device placement. A layout
    # change re-seeds like a shape change: patching a replicated buffer
    # with sharded row blocks would silently commit to the wrong devices
    sharding: object = None
    stats: Dict[str, int] = field(default_factory=lambda: {
        "patches": 0, "full": 0, "clean": 0,
        "rows_patched": 0, "rows_total": 0})


class ResidentStateManager:
    """Process-wide resident-view registry — see module docstring."""

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, ResidentEntry]" = OrderedDict()
        # view prefix -> newest base token, LRU-ordered: re-observation
        # refreshes position, so the prune below drops dead facades'
        # residue, never an active view's staleness baseline
        self._latest: "OrderedDict[tuple, tuple]" = OrderedDict()
        # keys dropped by invalidate(): the NEXT upload for one meters
        # its fallback under the invalidation reason (one logical
        # re-upload = one counter increment, never invalidated AND
        # first_sight for the same event)
        self._pending_reason: Dict[tuple, str] = {}
        # integrity audit round-robin cursors, per audited prefix
        self._audit_cursor: Dict[tuple, int] = {}
        self.max_entries = max_entries
        self.stats: Dict[str, int] = {
            "patches": 0, "full_uploads": 0, "clean_hits": 0,
            "rows_patched": 0, "rows_total": 0,
            "patched_bytes": 0, "avoided_bytes": 0, "full_bytes": 0,
            "invalidations": 0, "evictions": 0}

    @property
    def armed(self) -> bool:
        return os.environ.get("KARPENTER_TPU_RESIDENT", "1") != "0"

    # --- the write side ---------------------------------------------------
    def upload(self, key: tuple, matrix: np.ndarray,
               token: Optional[tuple] = None,
               shape_class: Optional[str] = None,
               donate: bool = True,
               patch_across_tokens: bool = False,
               sharding=None):
        """Return a device array holding `matrix`'s bytes: the patched
        resident buffer when the view matches, a full (re-)upload on any
        fallback trigger. `matrix` is digested on axis 0 (rows = pod
        groups, instance types, ...); higher-rank tensors patch whole
        axis-0 rows.

        patch_across_tokens: a token mismatch RE-KEYS the entry and
        patches instead of re-uploading — for the CATALOG tensors, whose
        token IS a content fingerprint (every epoch bump mints a new
        one), a strict token gate would mean they never patch at all.
        Correctness never rides the token either way: the digest diff
        compares the new host bytes against the resident copy's, so a
        patch always lands the new content exactly. Request matrices
        keep the conservative default (epoch bump => full re-upload).

        sharding: commit (and patch) the buffer under an explicit
        jax.sharding layout — the mesh-path residency seam (PR 11
        follow-up): the federation server's batched request stacks live
        sharded over the batch mesh, and their patches ship per-shard
        row blocks through _put_sharded instead of re-uploading full.
        A layout change re-seeds under the shape_change reason."""
        token = tuple(token) if token is not None else None
        mat = np.ascontiguousarray(matrix)
        with self._lock:
            ent = self._entries.get(key)
            reason = None
            if ent is None:
                reason = "first_sight"
            elif ent.shape != mat.shape:
                reason = "shape_change"
            elif ent.sharding != sharding:
                # device layout changed (mesh grew, replicated -> sharded):
                # the resident bytes live on the wrong devices — same
                # re-seed class as the shape growing
                reason = "shape_change"
            elif ent.dtype != mat.dtype:
                reason = "dtype_change"
            elif ent.token != token and not patch_across_tokens:
                reason = "token_change"
            if reason == "first_sight":
                # an invalidated view re-seeding counts under the
                # invalidation reason, not as a brand-new sighting
                reason = self._pending_reason.pop(key, reason)
        if reason is not None:
            return self._corruption_seam(
                key, self._full_upload(key, mat, token, shape_class,
                                       reason, sharding=sharding))
        digests = dm.UploadMeter._row_digests(mat.reshape(mat.shape[0], -1))
        changed = np.nonzero(digests != ent.digests)[0]
        rows = int(mat.shape[0])
        row_bytes = mat.nbytes // max(rows, 1)
        if changed.size > rows * PATCH_MAX_FRAC:
            return self._corruption_seam(
                key, self._full_upload(key, mat, token, shape_class,
                                       "dense", digests=digests,
                                       sharding=sharding))
        try:
            return self._corruption_seam(
                key, self._patch(ent, mat, digests, changed, row_bytes,
                                 shape_class, donate, token))
        except BaseException:
            # a device fault mid-patch (tunnel drop during the row
            # upload or the donated scatter) may have consumed the
            # resident buffer AND re-keyed the entry's token — the
            # entry is unusable and must not poison every later solve
            # for this view. Drop it so the next acquire re-seeds cold;
            # the raising solve degrades through the facade's normal
            # fallback machinery.
            with self._lock:
                self._entries.pop(key, None)
                self._pending_reason[key] = "invalidated"
                self._trim_pending()
            raise

    def _full_upload(self, key: tuple, mat: np.ndarray,
                     token: Optional[tuple], shape_class: Optional[str],
                     reason: str, digests: Optional[np.ndarray] = None,
                     sharding=None):
        from ..metrics import DEVICEMEM_PATCH, RESIDENT_FALLBACKS
        from . import solver as _ops
        RESIDENT_FALLBACKS.inc(reason=reason)
        if digests is None:
            digests = dm.UploadMeter._row_digests(
                mat.reshape(mat.shape[0], -1))
        with dm.attributed(kind="resident_state",
                           shape_class=shape_class) as grp:
            buf = (_ops._put_sharded(mat, sharding) if sharding is not None
                   else _ops._put(mat))
        # shipped-bytes redundancy metering: with residency armed the
        # meter sees what actually crosses the tunnel, so a steady warm
        # path collapses upload_redundant_frac toward zero changed bytes.
        # Full uploads and patches observe under DISTINCT keys — the
        # meter compares row i against row i of the previous observation
        # for the same key, and a full matrix diffed against a previous
        # patch's arbitrary changed-row set would be positional noise
        dm.UPLOADS.observe(key + ("resident", "full"),
                           mat.reshape(mat.shape[0], -1))
        with self._lock:
            ent = self._entries.get(key)
        if ent is None:
            ent = ResidentEntry(key=key, token=token, shape=mat.shape,
                                dtype=mat.dtype, digests=digests, buf=buf,
                                group=grp, shape_class=shape_class,
                                sharding=sharding)
        else:
            # refresh IN PLACE: the entry object stays the ledger owner
            # of its previous groups, so a predecessor buffer another
            # holder still reads (a split view's old DeviceCatalog)
            # never presents as an owner-dead orphan
            ent.token, ent.shape, ent.dtype = token, mat.shape, mat.dtype
            ent.digests, ent.buf, ent.group = digests, buf, grp
            ent.shape_class = shape_class
            ent.sharding = sharding
        dm.DEVICEMEM.adopt(grp, ent)
        ent.stats["full"] += 1
        ent.stats["rows_total"] += int(mat.shape[0])
        with self._lock:
            self._entries[key] = ent
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.stats["evictions"] += 1
            self.stats["full_uploads"] += 1
            self.stats["full_bytes"] += int(mat.nbytes)
            self.stats["rows_total"] += int(mat.shape[0])
        DEVICEMEM_PATCH.inc(float(mat.nbytes), outcome="full")
        return buf

    @staticmethod
    def _axis0_shards(sharding) -> int:
        """Shard count along axis 0 of a NamedSharding (1 = replicated /
        unsharded axis). Defensive: any layout this can't read patches
        through the flat (replicated-index) path, which is correct under
        every layout — GSPMD just ships the index vector everywhere."""
        try:
            spec = sharding.spec
            if not spec or spec[0] is None:
                return 1
            names = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
            n = 1
            for nm in names:
                n *= int(sharding.mesh.shape[nm])
            return n
        except Exception:  # noqa: BLE001 — layout introspection best-effort
            return 1

    def _patch(self, ent: ResidentEntry, mat: np.ndarray,
               digests: np.ndarray, changed: np.ndarray, row_bytes: int,
               shape_class: Optional[str], donate: bool,
               token: Optional[tuple]):
        from ..metrics import DEVICEMEM_PATCH
        rows = int(mat.shape[0])
        avoided = (rows - int(changed.size)) * row_bytes
        ent.token = token  # patch-across-tokens re-keys the lineage
        if changed.size == 0:
            # nothing moved: the device already holds every byte —
            # zero transfers, the steady-state fast path
            with self._lock:
                self.stats["clean_hits"] += 1
                self.stats["avoided_bytes"] += avoided
                self.stats["rows_total"] += rows
                if ent.key in self._entries:
                    self._entries.move_to_end(ent.key, last=True)
            ent.stats["clean"] += 1
            ent.stats["rows_total"] += rows
            if avoided:
                DEVICEMEM_PATCH.inc(float(avoided), outcome="avoided")
            return ent.buf
        from . import solver as _ops
        n_sh = (self._axis0_shards(ent.sharding)
                if ent.sharding is not None else 1)
        grouped = n_sh > 1 and rows % n_sh == 0
        if grouped:
            # per-shard row blocks: shard s owns rows [s*q, (s+1)*q) of
            # the axis-0-sharded buffer, so its changed indices group
            # into ITS slot of a [n_sh, k] index matrix — each device
            # then receives only the rows it will write (h2d per chip
            # shrinks with the mesh). Groups pad to the widest with
            # IDEMPOTENT duplicates: a repeated index rewrites the same
            # new row, an empty group rewrites one of its own UNCHANGED
            # rows with its current bytes — byte-identical no-ops either
            # way, so the scatter's duplicate-write order can't matter.
            q = rows // n_sh
            groups = [changed[(changed >= s * q) & (changed < (s + 1) * q)]
                      for s in range(n_sh)]
            k = max(int(g.size) for g in groups)
            idx_np = np.empty((n_sh, k), np.int32)
            for s, g in enumerate(groups):
                fill = int(g[0]) if g.size else s * q
                idx_np[s, :g.size] = g
                idx_np[s, g.size:] = fill
            rows_np = np.ascontiguousarray(mat[idx_np])  # [n_sh, k, ...]
            changed_rows = np.ascontiguousarray(mat[changed])
        else:
            idx_np = changed.astype(np.int32)
            rows_np = changed_rows = np.ascontiguousarray(mat[changed])
        sp = (TRACER.span("solve.resident_patch", rows=int(changed.size),
                          total_rows=rows,
                          donate=bool(donate), shards=n_sh)
              if TRACER.enabled else NOOP_SPAN)
        with sp:
            b0 = dm.TRANSFERS.totals()[0]
            with dm.attributed(reason="resident_patch",
                               kind="resident_state",
                               shape_class=shape_class):
                if grouped:
                    idx_dev = _ops._put_sharded(idx_np, ent.sharding)
                    rows_dev = _ops._put_sharded(rows_np, ent.sharding)
                else:
                    idx_dev = _ops._put(idx_np)
                    rows_dev = _ops._put(rows_np)
            new_buf = _scatter_fn(donate)(ent.buf, idx_dev, rows_dev)
            # the dispatch CONSUMED ent.buf when donating — rebind the
            # entry to the scatter output IMMEDIATELY so no later
            # statement can read the dead handle (use-after-donate
            # contract; the donated input's bytes release via its
            # finalizer, non-donated catalog patches keep the
            # predecessor alive for whoever still reads it)
            ent.buf = new_buf
            # the scatter output replaces the resident buffer inside the
            # entry's ledger group
            dm.DEVICEMEM.track("resident_state", [new_buf], owner=ent,
                               shape_class=shape_class, group=ent.group)
            sp.set(h2d_bytes=dm.TRANSFERS.totals()[0] - b0)
        dm.UPLOADS.observe(ent.key + ("resident", "patch"),
                           changed_rows.reshape(changed_rows.shape[0], -1))
        ent.digests = digests
        patched = int(changed.size) * row_bytes
        ent.stats["patches"] += 1
        ent.stats["rows_patched"] += int(changed.size)
        ent.stats["rows_total"] += rows
        with self._lock:
            self.stats["patches"] += 1
            self.stats["rows_patched"] += int(changed.size)
            self.stats["rows_total"] += rows
            self.stats["patched_bytes"] += patched
            self.stats["avoided_bytes"] += avoided
            if ent.key in self._entries:
                self._entries.move_to_end(ent.key, last=True)
        DEVICEMEM_PATCH.inc(float(patched), outcome="patched")
        if avoided:
            DEVICEMEM_PATCH.inc(float(avoided), outcome="avoided")
        return new_buf

    def _corruption_seam(self, key: tuple, buf):
        """Chaos seam (faults/plan.CorruptionFault): when the process
        corruption hook is armed it may return a REPLACEMENT device
        buffer whose bytes silently diverge from the entry's stored row
        digests — modeling a bit-flip/rot event the integrity plane must
        then detect (oracle on the next solve, or the digest audit).
        Nil-guarded: an unarmed process pays one attribute check."""
        from . import solver as _ops
        if _ops._corruption_hook is None:
            return buf
        corrupted = _ops._corruption_hook("resident", buf, key)
        if corrupted is None or corrupted is buf:
            return buf
        with self._lock:
            ent = self._entries.get(key)
        if ent is not None and ent.buf is buf:
            # digests deliberately NOT updated: they describe the clean
            # bytes — exactly the divergence audit() exists to catch
            ent.buf = corrupted
        return corrupted

    # --- the integrity plane's digest audit -------------------------------
    def audit(self, prefix: tuple = (), max_rows: Optional[int] = None,
              ) -> dict:
        """Read back device-resident entries under `prefix` and compare
        their actual row digests against the stored (host-computed)
        ones. A mismatch is silent data corruption: the entry is dropped
        (its next acquire re-seeds cold under the 'corruption' fallback
        reason) and its key is reported. Bounded by `max_rows` with a
        round-robin cursor so a steady cadence eventually covers every
        entry without unbounded d2h per call."""
        from . import solver as _ops
        n = len(prefix)
        with self._lock:
            keys = [k for k in self._entries if k[:n] == prefix]
            cursor = self._audit_cursor.get(prefix, 0)
        if not keys:
            return {"entries": 0, "rows": 0, "corrupt": []}
        corrupt: List[tuple] = []
        rows = 0
        audited = 0
        order = keys[cursor % len(keys):] + keys[:cursor % len(keys)]
        for key in order:
            if max_rows is not None and rows >= max_rows and audited:
                break
            with self._lock:
                ent = self._entries.get(key)
            if ent is None:
                continue
            try:
                arr = _ops._read(ent.buf)
            except BaseException:  # noqa: BLE001 — a dead device buffer
                # is itself a corruption-class event for this entry
                corrupt.append(key)
                audited += 1
                continue
            audited += 1
            rows += int(arr.shape[0])
            digests = dm.UploadMeter._row_digests(
                np.ascontiguousarray(arr).reshape(arr.shape[0], -1))
            if digests.shape != ent.digests.shape \
                    or (digests != ent.digests).any():
                corrupt.append(key)
        with self._lock:
            self._audit_cursor[prefix] = (cursor + audited) % len(keys)
            for key in corrupt:
                if self._entries.pop(key, None) is not None:
                    self._pending_reason[key] = "corruption"
                    self.stats["invalidations"] += 1
            self.stats["audits"] = self.stats.get("audits", 0) + 1
            self.stats["audit_rows"] = (self.stats.get("audit_rows", 0)
                                        + rows)
            self.stats["audit_corrupt"] = (
                self.stats.get("audit_corrupt", 0) + len(corrupt))
            self._trim_pending()
        return {"entries": audited, "rows": rows, "corrupt": corrupt}

    # --- invalidation -----------------------------------------------------
    def invalidate(self, prefix: tuple, reason: str = "invalidated") -> int:
        """Drop every entry whose KEY starts with `prefix` (a facade's
        views on audit divergence, a dead fleet's residue). The next
        acquire re-uploads cold and meters its fallback under `reason`
        (deferred — one logical re-upload is one counter increment,
        and an invalidation nothing ever re-seeds meters nothing);
        freed entries release their ledger claim when the buffers die."""
        n = len(prefix)
        with self._lock:
            victims = [k for k in self._entries if k[:n] == prefix]
            for k in victims:
                del self._entries[k]
                self._pending_reason[k] = reason
            # audit cursors die with the views they walked — a dead
            # facade's cursor would otherwise accumulate forever in a
            # long-lived fleet process (the _latest-map residue class)
            for k in [k for k in self._audit_cursor if k[:n] == prefix]:
                del self._audit_cursor[k]
            self.stats["invalidations"] += len(victims)
            self._trim_pending()
        return len(victims)

    def invalidate_token(self, prefix: tuple,
                         reason: str = "invalidated") -> int:
        """Drop every entry whose catalog TOKEN starts with `prefix` —
        the SharedCatalogCache's seam: evicting (or splitting) a shared
        view must release the resident tensors encoded against its
        ("shared", ...) token, so a stale resident catalog can never
        outlive the view it mirrors."""
        n = len(prefix)
        with self._lock:
            victims = [k for k, e in self._entries.items()
                       if e.token is not None and e.token[:n] == prefix]
            for k in victims:
                del self._entries[k]
                self._pending_reason[k] = reason
            self.stats["invalidations"] += len(victims)
            self._trim_pending()
        return len(victims)

    def _trim_pending(self) -> None:
        """Bound the deferred-reason map (lock held): reasons for keys
        that never re-seed must not accumulate forever."""
        while len(self._pending_reason) > 4 * self.max_entries:
            self._pending_reason.pop(next(iter(self._pending_reason)))

    # --- staleness (the watchdog's resident_staleness observable) ---------
    def observe_view(self, prefix: tuple, base_token: tuple) -> None:
        """Record the newest catalog token base a facade resolved for
        the views under `prefix` — called from `Solver.tensors()` on
        both the cold and warm (prepare_warm -> warm_catalog) paths, so
        the staleness picture tracks the store's catalog epoch even
        while a view idles."""
        with self._lock:
            self._latest[prefix] = tuple(base_token)
            # LRU, not insertion order: re-observation refreshes the
            # prefix's position, so the prune drops dead facades'
            # residue — never an active view's staleness baseline
            self._latest.move_to_end(prefix)
            while len(self._latest) > 4 * self.max_entries:
                self._latest.popitem(last=False)

    def stale(self) -> List[dict]:
        """Entries whose token no longer starts with the newest base
        observed for their view prefix: device bytes encoding a catalog
        epoch older than the one the store serves. Served-path safety
        does not depend on this (upload() re-keys on token mismatch);
        lingering staleness is held HBM + a latent-bug signal — the
        watchdog ages it past a sim grace."""
        out: List[dict] = []
        with self._lock:
            for key, ent in self._entries.items():
                for prefix, base in self._latest.items():
                    if key[: len(prefix)] != prefix:
                        continue
                    tok = ent.token
                    if tok is None or tok[: len(base)] != base:
                        out.append({"key": key, "token": tok,
                                    "base": base})
                    break
        return out

    # --- read side --------------------------------------------------------
    def patched_rows_frac(self) -> float:
        """Patched rows / total rows over every resident acquire — the
        bench's c8_patched_rows_frac (informational in the perf gate:
        workload churn moves it, latency does not)."""
        with self._lock:
            total = self.stats["rows_total"]
            return self.stats["rows_patched"] / total if total else 0.0

    def snapshot(self) -> dict:
        stale_n = len(self.stale())
        with self._lock:
            entries = [{
                "key": "/".join(str(t) for t in e.key),
                "rows": int(e.shape[0]),
                "shape": list(e.shape),
                "shape_class": e.shape_class,
                "stats": dict(e.stats),
            } for e in self._entries.values()]
            stats = dict(self.stats)
        total = stats["rows_total"]
        return {"armed": self.armed,
                "entries": entries,
                "stale": stale_n,
                "patched_rows_frac": round(
                    stats["rows_patched"] / total, 4) if total else 0.0,
                "stats": stats}

    def reset(self) -> None:
        """Forget every resident view and counter — bench regime
        isolation (mirrors the residency ledger's reset discipline)."""
        with self._lock:
            self._entries.clear()
            self._latest.clear()
            self._pending_reason.clear()
            self._audit_cursor.clear()
            self.stats.update({k: 0 for k in self.stats})


RESIDENT = ResidentStateManager()


def payload(query: str = "") -> dict:
    return RESIDENT.snapshot()


from ..obs.exposition import register_debug_route  # noqa: E402

register_debug_route("/debug/resident", lambda query: payload(query))
