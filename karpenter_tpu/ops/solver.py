"""TPU solver: the provisioning Solve() as a jitted group-scan.

The pod axis collapses to G dedupe groups (encode.group_pods); the kernel is
a `lax.scan` over groups with all per-step work vectorized over the node
axis N and the offering axes (T, Z, C) — dense masked arithmetic the XLA
TPU backend maps onto the VPU/MXU, no ragged structures, no data-dependent
shapes. Semantics match ops/binpack.solve_host exactly (golden tests assert
node-for-node agreement); see that module's docstring for the policy.

Per group step:
  1. fill open nodes in index order (vectorized first-fit: per-node max
     take, prefix-cumsum allocation against the group's pod count)
  2. remaining pods open new nodes committed to the cost-per-slot argmin
     (type, zone, captype) offering — the vmap'd cost-argmin of the north
     star — sized slots-per-node, written with broadcasted-iota masks.

Static shapes: [G, N, T, Z, C, R] all padded; recompilation happens only
when the padded bucket changes, not per solve (pad_groups/pad buckets).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import devicemem as dm
from ..obs.tracer import NOOP_SPAN, TRACER
from .binpack import BIG, EPS, SolveResult, VirtualNode
from .encode import CatalogTensors, EncodedPods, align_resources

_F32_MAX = jnp.finfo(jnp.float32).max

# host↔device traffic counters — the hot-boundary discipline
# (cloud/metering.py meters wire calls; this meters the device tunnel the
# same way so a transfer regression is a red test, not a judge finding).
# Call COUNTS live here; byte volume is attributed per (reason, tenant,
# shape-class) by the device telemetry plane (obs/devicemem.TRANSFERS),
# whose totals transfer_bytes() serves.
_TRANSFERS = 0   # host→device array uploads issued by this module
_READS = 0       # device→host blocking reads issued by this module


def transfer_stats() -> Tuple[int, int]:
    """(uploads, reads) issued by the solver since import — diff around a
    solve to count its device-boundary crossings. Covers the single-device
    AND mesh paths (mesh device_puts go through _put_sharded)."""
    return _TRANSFERS, _READS


def provenance() -> dict:
    """Backend/platform provenance for bench artifacts and profile
    reports: which backend jax actually resolved, the device kind, and
    host/device counts. Stamped into every BENCH_*.json and
    profile_bench.json so a CPU-fallback run (no tunnel RTT, no real
    kernel) can never masquerade as a comparable TPU number again
    (BENCH_r05 did exactly that silently)."""
    out: dict = {"backend": None, "device_kind": None, "device_count": 0,
                 "host_count": 1, "cpu_fallback": True}
    try:
        out["backend"] = jax.default_backend()
        devices = jax.devices()
        out["device_count"] = len(devices)
        out["device_kind"] = devices[0].device_kind if devices else None
        out["host_count"] = jax.process_count()
        out["cpu_fallback"] = out["backend"] == "cpu"
    except Exception as e:  # noqa: BLE001 — provenance must not crash a bench
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def transfer_bytes() -> Tuple[int, int]:
    """(host→device, device→host) bytes since import — the companion to
    transfer_stats(): call COUNT is the RTT budget, byte volume is the
    bandwidth budget. Diff around a solve; solve_device publishes the
    per-solve deltas on the transfer-bytes gauges. Served from the
    transfer-attribution ledger (obs/devicemem.TRANSFERS), so the same
    bytes are also decomposable per (reason, tenant, shape-class)."""
    return dm.TRANSFERS.totals()


def _put(x) -> jax.Array:
    """Host→device upload, counted + attributed. On the deployment rig
    the TPU sits behind a network tunnel where every independent upload
    can cost a full RTT (~70-100 ms measured) — per-solve upload COUNT,
    not bytes, is the latency budget; the byte volume lands on the
    device telemetry plane's transfer/residency ledgers."""
    global _TRANSFERS
    _TRANSFERS += 1
    out = jnp.asarray(x)
    dm.on_upload(out)
    return out


def _put_sharded(x, sharding) -> jax.Array:
    """Counted jax.device_put with an explicit sharding (mesh path)."""
    global _TRANSFERS
    _TRANSFERS += 1
    out = jax.device_put(x, sharding)
    dm.on_upload(out, sharded=True)
    return out


def _read(arr) -> np.ndarray:
    global _READS
    _READS += 1
    out = np.asarray(arr)
    dm.on_readback(out.nbytes)
    return out


# compile-cache observability: jax.jit keys its executable cache on
# (statics, input shapes/dtypes); this mirrors that key so every packed
# dispatch can be classified hit/miss BEFORE the call — _bucket()'s
# quantum-64 re-padding exists precisely so production solves converge to
# all-hits, and the COMPILE_CACHE counter makes that a scrapeable fact
# instead of a test-only assertion.
_compile_seen: set = set()

# fault-injection seam (faults/injector.device_fault_hook): when armed,
# called with the backend name immediately before every kernel dispatch;
# raising aborts the dispatch and the facade's degraded-mode fallback
# re-runs the solve on native/host. None (the default) costs one
# identity check per solve — the zero-overhead-when-disabled contract.
_dispatch_fault_hook = None


def set_dispatch_fault_hook(fn) -> None:
    global _dispatch_fault_hook
    _dispatch_fault_hook = fn


# corruption-injection seam (faults/plan.CorruptionFault, armed via
# faults/injector.corruption_fault_hook): when set, called with
# (target, device_buffer) immediately after a staged upload — target
# "gbuf" for non-resident request matrices (serial path and the batched
# dispatcher's stacked gstack), "resident" for ops/resident.py buffers
# (consulted there). Returns a replacement buffer (silently corrupted —
# modeling SDC/bit-rot the integrity plane must detect) or the input
# unchanged. None (the default) costs one identity check per upload —
# the zero-overhead-when-disabled contract.
_corruption_hook = None


def set_corruption_hook(fn) -> None:
    global _corruption_hook
    _corruption_hook = fn


def _maybe_corrupt(target: str, buf):
    if _corruption_hook is None:
        return buf
    out = _corruption_hook(target, buf)
    return buf if out is None else out


def _dispatch_cache_event(key: tuple) -> str:
    """Classify a packed-kernel dispatch as 'hit'/'miss' and count it."""
    from ..metrics import COMPILE_CACHE
    if key in _compile_seen:
        COMPILE_CACHE.inc(event="hit")
        return "hit"
    _compile_seen.add(key)
    COMPILE_CACHE.inc(event="miss")
    return "miss"


@dataclass(frozen=True)
class DeviceCatalog:
    """Catalog tensors resident on device, cached by catalog epoch."""

    alloc: jax.Array      # f32 [T, R]
    price: jax.Array      # f32 [T, Z, C]
    avail: jax.Array      # bool [T, Z, C]
    # f32 [T, Z, R] zone-varying daemonset reservation, or a [1, 1, R]
    # zero dummy when absent (the static zone_ovh flag compiles it out)
    ovh_z: Optional[jax.Array] = None


def device_catalog(cat: CatalogTensors, R: int, mesh=None,
                   resident_key: Optional[tuple] = None) -> DeviceCatalog:
    """mesh: replicate the catalog over the mesh's devices (the sharded
    solve reads it on every chip) instead of committing to device 0.

    resident_key (single-device only): route the four catalog tensors
    through the device-resident state manager (ops/resident.py) — an
    epoch bump then ships only the instance-type rows whose content
    changed (an ICE mark flips a few avail rows, not the catalog), as a
    NON-donated scatter from the previous resident copy: a split shared
    view's predecessor DeviceCatalog may still serve a co-tenant, so
    its buffers must survive the patch."""
    from .encode import align_zone_overhead
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(mesh, P())
        put = lambda name, x: _put_sharded(np.asarray(x), rep)
    else:
        from .resident import RESIDENT
        if resident_key is not None and RESIDENT.armed:
            tok = cat.cache_token
            put = lambda name, x: RESIDENT.upload(
                resident_key + (name,), np.asarray(x), token=tok,
                donate=False, patch_across_tokens=True)
        else:
            put = lambda name, x: _put(x)
    zovh = align_zone_overhead(cat, R)
    sp = (TRACER.span("solve.catalog_put", T=int(cat.T), R=int(R),
                      mesh=mesh is not None)
          if TRACER.enabled else NOOP_SPAN)
    with sp:
        b0 = transfer_bytes()[0]
        with dm.attributed(reason="catalog_put", kind="catalog",
                           token=cat.cache_token) as grp:
            dcat = DeviceCatalog(
                alloc=put("alloc", align_resources(cat.allocatable, R)),
                price=put("price", cat.price),
                avail=put("avail", cat.available),
                ovh_z=put("ovh_z", zovh) if zovh is not None else None,
            )
        # the DeviceCatalog OWNS these tensors: the residency ledger's
        # leak invariant watches for the owner dying while the buffers
        # stay live (something else pinning an evicted view's upload)
        dm.DEVICEMEM.adopt(grp, dcat)
        sp.set(h2d_bytes=transfer_bytes()[0] - b0)
    return dcat


# catalog-epoch device cache for DIRECT solve_device callers (the facade
# keeps its own epoch-keyed cache): keyed on id(cat) with a weakref
# finalizer so a freed CatalogTensors' reused address can never alias a
# stale entry. Without this, every bare solve_device call re-uploads the
# [T,R]+2x[T,Z,C] catalog — 3 tunnel round-trips that made round 4's
# end-to-end numbers regress ~45 ms/solve.
#
# Fleet extension: views minted by the facade's SharedCatalogCache carry
# a CONTENT-authoritative token ("shared", nodeclass-hash, fingerprint),
# and those key here by token instead of id — the per-solve derived
# copies (block gating, daemonset overhead) then share ONE device upload
# across every tenant facade, and — shapes being equal — one compiled
# executable. Only "shared"-rooted tokens qualify: the classic
# (nodeclass-hash, epoch) tokens are unique per provider, not per
# content, and two tenants' epoch counters can collide while their
# availability differs.
_dcat_auto: dict = {}
_DCAT_TOKEN_MAX = 32  # bound for token-keyed entries (no weakref owner)
# evictions observed inside weakref finalizers queue here and flush to
# the metric from caller context: a finalizer runs inside GC, which can
# fire on a thread already holding the metric's (non-reentrant) lock
_dcat_evict_pending: list = []


def _count_dcat_eviction(reason: str) -> None:
    from ..metrics import DCAT_EVICTIONS
    DCAT_EVICTIONS.inc(reason=reason)


def _finalize_dcat(key) -> None:
    """weakref-finalizer eviction of an id-keyed entry (GC context:
    dict ops only, metric deferred)."""
    if _dcat_auto.pop(key, None) is not None:
        _dcat_evict_pending.append("weakref")


def release_shared_views(prefix: tuple) -> int:
    """Drop every token-keyed device-catalog entry whose content token
    starts with `prefix` — the SharedCatalogCache calls this when it
    evicts a view, so a dead shared view can never pin device buffers
    past its own eviction (they would otherwise linger until the FIFO
    bound trimmed them). Returns the number of entries released."""
    victims = [k for k in _dcat_auto
               if isinstance(k[0], tuple) and k[0][:len(prefix)] == prefix]
    for k in victims:
        _dcat_auto.pop(k, None)
        _count_dcat_eviction("view_evicted")
    # the view's device-resident delta state goes with it: resident
    # tensors encoded against the dead view's ("shared", ...) token must
    # never outlive the view — a later tenant resolving the same
    # nodeclass re-seeds cold instead of patching a retired baseline
    from .resident import RESIDENT
    RESIDENT.invalidate_token(prefix)
    return len(victims)


def _auto_dcat(cat: CatalogTensors, R: int, mesh=None) -> DeviceCatalog:
    """Epoch-cached device catalog for callers without their own cache;
    mesh=None caches the single-device replica, a Mesh caches the
    mesh-replicated one (same staleness predicate and weakref lifecycle
    — ONE implementation so the two can't diverge). Every eviction path
    meters dcat_evictions_total{reason} — churn here is re-upload cost,
    and residency WITHOUT evictions is how a pinned dead view would
    present."""
    import weakref
    while _dcat_evict_pending:  # flush GC-deferred weakref evictions
        _count_dcat_eviction(_dcat_evict_pending.pop())
    tok = cat.cache_token
    by_token = tok is not None and len(tok) > 0 and tok[0] == "shared"
    key = (tuple(tok), mesh) if by_token else (id(cat), mesh)
    ent = _dcat_auto.get(key)
    if (ent is not None and ent.alloc.shape[1] >= R
            and (ent.ovh_z is not None) == (cat.zone_overhead is not None)):
        return ent
    if ent is not None:
        # present but unusable (resource axis grew / overhead flipped):
        # the rebuild below replaces it
        _count_dcat_eviction("stale")
    if ent is None and not by_token:
        weakref.finalize(cat, _finalize_dcat, key)
    # shared (content-token) views patch through the resident manager:
    # the key carries the nodeclass root + derived-view structure but
    # NOT the availability fingerprint, so an epoch bump ships only the
    # changed type rows — and since _dcat_auto fronts this per token,
    # a batched pump's co-staged tickets patch the shared catalog once
    # per bump, not once per ticket
    rkey = None
    if by_token and mesh is None:
        # key = nodeclass root + the FULL derived-view suffix (the
        # "noblocks"/"ds" markers AND the daemonset digest) minus the
        # availability fingerprint (tok[2]) — epoch bumps patch, but
        # two distinct daemonset-derived views never collide on (and
        # alternately thrash) one resident entry
        rkey = ("dcat", "shared", tok[1]) + tuple(tok[3:])
    dcat = device_catalog(cat, R, mesh=mesh, resident_key=rkey)
    _dcat_auto[key] = dcat
    if by_token:
        # token-keyed entries deliberately OUTLIVE any one CatalogTensors
        # object (derived per-solve copies die at end of solve; their
        # upload must not) — bound them FIFO instead of by weakref
        tkeys = [k for k in _dcat_auto if isinstance(k[0], tuple)]
        for k in tkeys[:max(0, len(tkeys) - _DCAT_TOKEN_MAX)]:
            _dcat_auto.pop(k, None)
            _count_dcat_eviction("fifo")
    return dcat


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_max", "track_conflicts", "zone_ovh"))
def _solve_kernel(alloc, price, avail, requests, counts, compat, allow_zone,
                  allow_cap, max_per_node, prior_counts, banned, conflict,
                  zovh, node_type, node_cum, node_zmask, node_cmask,
                  node_open, n_used, n_max: int, track_conflicts: bool = False,
                  zone_ovh: bool = False):
    """scan over G groups; returns final node state + per-(g,n) take matrix
    + per-group unschedulable counts.

    banned: bool [G, N] — node n may not take group g (facade-computed
    resident-pod anti-affinity; see VirtualNode.banned_groups).
    conflict + track_conflicts: cross-group anti-affinity. When the static
    flag is False (no group has anti terms — the common case) the per-step
    [N, G] hosted bookkeeping is compiled out entirely; conflict is then a
    [G, 1] dummy.
    zovh + zone_ovh: zone-varying daemonset reservation [T, Z, R] — a node
    charges the elementwise max over its (post-take) zone mask, so zones
    narrowing away from a zone-pinned daemonset restore headroom. When the
    static flag is False (no partial-overlap daemonset — the common case)
    the per-step [N, Z, R] gather is compiled out; zovh is a [1, 1, R]
    dummy."""

    T, Z, C = price.shape
    R = alloc.shape[1]
    Gp = requests.shape[0]
    node_ids = jnp.arange(n_max)
    group_ids = jnp.arange(Gp)

    def step(state, ginput):
        ntype, cum, zmask, cmask, nopen, nused, hosted = state
        (req, count, gcompat, gzone, gcap, cap_per, prior_n, banned_n,
         conf_g, gi) = ginput
        count = count.astype(jnp.int32)
        cap_per = jnp.where(cap_per == 0, BIG, cap_per).astype(jnp.int32)

        # --- 1. fill existing nodes (vectorized first-fit) ---
        zmask2 = zmask & gzone[None, :]                 # [N, Z]
        cmask2 = cmask & gcap[None, :]                  # [N, C]
        talloc = alloc[ntype]                           # [N, R]
        if zone_ovh:
            # post-take zone mask: taking the pod commits the node to
            # zmask2, so the reservation maxes over exactly those zones
            ovh_n = jnp.where(zmask2[:, :, None], zovh[ntype],
                              0.0).max(axis=1)          # [N, R]
            talloc = talloc - ovh_n
        headroom = talloc - cum                         # [N, R]
        # max pods of this group per node by capacity
        with_req = jnp.where(req > 0, req, 1.0)
        k_cap = jnp.where(req > 0,
                          jnp.floor(headroom / with_req + EPS),
                          jnp.asarray(BIG, jnp.float32)).min(axis=1)
        k_cap = jnp.maximum(k_cap, 0.0).astype(jnp.int32)   # [N]
        # eligibility: open, type-compatible, masks intersect an available offering
        off_ok = jnp.einsum("nz,nc,nzc->n", zmask2, cmask2,
                            avail[ntype], preferred_element_type=jnp.float32) > 0
        eligible = nopen & gcompat[ntype] & off_ok & ~banned_n
        if track_conflicts:
            eligible &= ~(hosted & conf_g[None, :]).any(axis=1)
        # per-node cap accounts prior occupancy of this group (anti-affinity
        # across reconciles). k is clamped to count BEFORE the prefix sum:
        # k_cap can be BIG (zero-request pods) and an int32 cumsum over the
        # node axis would wrap. The prefix runs in f32 (x64 is disabled):
        # exact while below 2^24 ≥ any real pod count, and once the prefix
        # passes `count` the take clamps to zero so precision is moot.
        cap_eff = jnp.maximum(cap_per - prior_n, 0)
        k = jnp.where(eligible, jnp.minimum(k_cap, cap_eff), 0)  # [N]
        kf = jnp.minimum(k, count).astype(jnp.float32)
        prefix = jnp.cumsum(kf) - kf
        take = jnp.clip(jnp.minimum(kf, count.astype(jnp.float32) - prefix),
                        0).astype(jnp.int32)                     # [N]
        placed = jnp.minimum(jnp.sum(take), count)
        rem = count - placed

        got = take > 0
        cum = cum + take[:, None].astype(jnp.float32) * req[None, :]
        zmask = jnp.where(got[:, None], zmask2, zmask)
        cmask = jnp.where(got[:, None], cmask2, cmask)

        # --- 2. open new nodes at the cost-per-slot argmin offering ---
        adm = (avail & gcompat[:, None, None] & gzone[None, :, None]
               & gcap[None, None, :])                   # [T, Z, C]
        alloc_eff = alloc
        if zone_ovh:
            # a new node's zone mask becomes gzone & type-available zones;
            # reserve the max over exactly those (host oracle mirrors)
            zm_open = gzone[None, :] & avail.any(axis=2)   # [T, Z]
            alloc_eff = alloc - jnp.where(zm_open[:, :, None], zovh,
                                          0.0).max(axis=1)
        slots_t = jnp.where(req > 0,
                            jnp.floor(alloc_eff / with_req[None, :] + EPS),
                            jnp.asarray(BIG, jnp.float32)).min(axis=1)
        slots_t = jnp.minimum(jnp.maximum(slots_t, 0.0).astype(jnp.int32), cap_per)  # [T]
        feasible = adm & (slots_t[:, None, None] >= 1)
        cps = jnp.where(feasible,
                        price / jnp.maximum(slots_t, 1)[:, None, None].astype(jnp.float32),
                        _F32_MAX)
        flat = jnp.argmin(cps.reshape(-1))
        best_cps = cps.reshape(-1)[flat]
        t_star = (flat // (Z * C)).astype(jnp.int32)
        schedulable = (best_cps < _F32_MAX) & (rem > 0)

        s = jnp.maximum(slots_t[t_star], 1)
        n_new_want = jnp.where(schedulable, -(-rem // s), 0)  # ceil div
        n_new = jnp.minimum(n_new_want, jnp.maximum(n_max - nused, 0))  # hard cap
        clamped = n_new < n_new_want
        # last new node may be partial
        new_pos = node_ids - nused                       # position among new nodes
        is_new = (new_pos >= 0) & (new_pos < n_new)
        pods_on = jnp.clip(rem - new_pos * s, 0, s)      # [N]
        new_take = jnp.where(is_new, pods_on, 0).astype(jnp.int32)
        overflow = jnp.where(schedulable,
                             jnp.maximum(rem - jnp.sum(new_take), 0), 0)

        t_avail_z = avail[t_star].any(axis=1)            # [Z]
        t_avail_c = avail[t_star].any(axis=0)            # [C]
        ntype = jnp.where(is_new, t_star, ntype)
        cum = jnp.where(is_new[:, None],
                        new_take[:, None].astype(jnp.float32) * req[None, :], cum)
        zmask = jnp.where(is_new[:, None], gzone[None, :] & t_avail_z[None, :], zmask)
        cmask = jnp.where(is_new[:, None], gcap[None, :] & t_avail_c[None, :], cmask)
        nopen = nopen | is_new
        nused = nused + n_new

        unsched = jnp.where(schedulable, overflow, rem)
        g_take = take + new_take
        if track_conflicts:
            hosted = hosted | ((g_take > 0)[:, None] & (group_ids == gi)[None, :])
        return (ntype, cum, zmask, cmask, nopen, nused, hosted), (
            g_take, unsched, clamped)

    hosted0 = jnp.zeros((n_max, Gp if track_conflicts else 1), bool)
    init = (node_type, node_cum, node_zmask, node_cmask, node_open, n_used,
            hosted0)
    (ntype, cum, zmask, cmask, nopen, nused, _), (takes, unsched, clamped) = lax.scan(
        step, init, (requests, counts, compat, allow_zone, allow_cap,
                     max_per_node, prior_counts, banned, conflict, group_ids))
    return ntype, cum, zmask, cmask, nopen, nused, takes, unsched, clamped.any()


def _solve_kernel_packed_impl(alloc, price, avail, requests, counts, compat,
                              allow_zone, allow_cap, max_per_node, prior_counts,
                              banned, conflict, zovh, node_type, node_cum,
                              node_zmask, node_cmask, node_open, n_used,
                              n_max: int, k_max: int,
                              track_conflicts: bool = False,
                              zone_ovh: bool = False):
    """Kernel + single-buffer output packing.

    The deployment TPU sits behind a network tunnel where every host read
    costs a full RTT (~70ms measured), so the 9 logical outputs are packed
    into ONE int32 vector; node cum/zone/cap state is recomputed host-side
    from the sparse (group, node, take) triples (exactly — same f32 ops in
    the same order). Layout:
      [0]                  n_used
      [1]                  overflow flag (node budget exhausted)
      [2]                  nnz (actual nonzero takes; > k_max means refetch)
      [3 : 3+G]            unschedulable count per group
      [3+G : 3+G+N]        node type ids
      [.. : ..+k_max]      flat indices (g * n_max + n) of nonzero takes
      [.. : ..+k_max]      take values
    """
    out = _solve_kernel(alloc, price, avail, requests, counts, compat,
                        allow_zone, allow_cap, max_per_node, prior_counts,
                        banned, conflict, zovh, node_type, node_cum,
                        node_zmask, node_cmask, node_open, n_used, n_max=n_max,
                        track_conflicts=track_conflicts, zone_ovh=zone_ovh)
    ntype, _cum, _zm, _cm, _no, nused, takes, unsched, overflow = out
    flat = takes.reshape(-1)
    nnz = jnp.sum(flat > 0)
    (idx,) = jnp.nonzero(flat, size=k_max, fill_value=0)
    vals = flat[idx]
    return jnp.concatenate([
        jnp.stack([nused.astype(jnp.int32), overflow.astype(jnp.int32),
                   nnz.astype(jnp.int32)]),
        unsched.astype(jnp.int32),
        ntype.astype(jnp.int32),
        idx.astype(jnp.int32),
        vals.astype(jnp.int32),
    ])


_solve_kernel_packed = partial(
    jax.jit, static_argnames=("n_max", "k_max", "track_conflicts",
                              "zone_ovh")
)(_solve_kernel_packed_impl)


# ---------------------------------------------------------------------------
# single-upload dispatch: the tunnel-optimal single-device path
# ---------------------------------------------------------------------------
# The deployment TPU sits behind a network tunnel where every independent
# host→device upload costs up to a full RTT. The multi-array call above
# ships ~15 buffers per solve; this path ships ONE:
#   - all per-group inputs pack into a single f32 matrix (gbuf), unpacked
#     by static column slices inside the jit
#   - fresh-solve node state (all zeros) is CREATED inside the jit — no
#     upload at all; resumed solves pack node state into one matrix (nbuf)
#   - the compiled-out dummies (prior/banned/conflict/zovh when their
#     static flags are off) are jnp.zeros inside the trace, never shipped
#   - the resource axis is projected to `cols` (columns some group actually
#     requests) inside the jit: dropped columns can never bind (k_cap and
#     slots_t only scan req>0 columns; cum only grows in requested
#     columns), so the scan does R_k≤R work with identical results.


def _pack_groups(requests, counts, compat, allow_zone, allow_cap,
                 max_per_node, cols) -> np.ndarray:
    """One f32 [Gp, Rk+1+T+Z+C+1] matrix: requests (projected), counts,
    compat, allow_zone, allow_cap, max_per_node. Counts/caps are exact in
    f32 below 2^24 — far above any real pod count."""
    return np.concatenate([
        requests[:, cols].astype(np.float32),
        counts[:, None].astype(np.float32),
        compat.astype(np.float32),
        allow_zone.astype(np.float32),
        allow_cap.astype(np.float32),
        max_per_node[:, None].astype(np.float32),
    ], axis=1)


def _pack_nodes(node_type, node_cum, node_zmask, node_cmask, node_open,
                cols) -> np.ndarray:
    """One f32 [n, 1+Rk+Z+C+1] matrix of resumed-node state."""
    return np.concatenate([
        node_type[:, None].astype(np.float32),
        node_cum[:, cols].astype(np.float32),
        node_zmask.astype(np.float32),
        node_cmask.astype(np.float32),
        node_open[:, None].astype(np.float32),
    ], axis=1)


def _solve_onebuf_impl(alloc, price, avail, gbuf, prior, banned, conflict,
                       zovh, nbuf, n_max: int, k_max: int, cols: tuple,
                       track_conflicts: bool, zone_ovh: bool):
    """Unpack gbuf/nbuf by static offsets, synthesize whatever wasn't
    shipped, run the kernel, pack the output (same layout as
    _solve_kernel_packed_impl's docstring)."""
    T, Z, C = price.shape
    Rk = len(cols)
    Gp = gbuf.shape[0]
    cix = jnp.asarray(np.asarray(cols, np.int32))
    alloc_k = alloc[:, cix]
    requests = gbuf[:, :Rk]
    o = Rk
    counts = gbuf[:, o].astype(jnp.int32); o += 1
    compat = gbuf[:, o:o + T] > 0; o += T
    allow_zone = gbuf[:, o:o + Z] > 0; o += Z
    allow_cap = gbuf[:, o:o + C] > 0; o += C
    max_per_node = gbuf[:, o].astype(jnp.int32)
    prior_ = prior if prior is not None else jnp.zeros((Gp, 1), jnp.int32)
    banned_ = banned if banned is not None else jnp.zeros((Gp, 1), bool)
    conflict_ = (conflict if conflict is not None
                 else jnp.zeros((Gp, 1), bool))
    zovh_ = (zovh[:, :, cix] if zone_ovh
             else jnp.zeros((1, 1, Rk), jnp.float32))
    if nbuf is None:
        node_type = jnp.zeros(n_max, jnp.int32)
        node_cum = jnp.zeros((n_max, Rk), jnp.float32)
        node_zmask = jnp.zeros((n_max, Z), bool)
        node_cmask = jnp.zeros((n_max, C), bool)
        node_open = jnp.zeros(n_max, bool)
        n_used = jnp.asarray(0, jnp.int32)
    else:
        node_type = nbuf[:, 0].astype(jnp.int32)
        node_cum = nbuf[:, 1:1 + Rk]
        node_zmask = nbuf[:, 1 + Rk:1 + Rk + Z] > 0
        node_cmask = nbuf[:, 1 + Rk + Z:1 + Rk + Z + C] > 0
        node_open = nbuf[:, 1 + Rk + Z + C] > 0
        # resumed nodes are exactly the open prefix
        n_used = node_open.sum().astype(jnp.int32)
    out = _solve_kernel(alloc_k, price, avail, requests, counts, compat,
                        allow_zone, allow_cap, max_per_node, prior_, banned_,
                        conflict_, zovh_, node_type, node_cum, node_zmask,
                        node_cmask, node_open, n_used, n_max=n_max,
                        track_conflicts=track_conflicts, zone_ovh=zone_ovh)
    ntype, _cum, _zm, _cm, _no, nused, takes, unsched, overflow = out
    flat = takes.reshape(-1)
    nnz = jnp.sum(flat > 0)
    (idx,) = jnp.nonzero(flat, size=k_max, fill_value=0)
    vals = flat[idx]
    return jnp.concatenate([
        jnp.stack([nused.astype(jnp.int32), overflow.astype(jnp.int32),
                   nnz.astype(jnp.int32)]),
        unsched.astype(jnp.int32),
        ntype.astype(jnp.int32),
        idx.astype(jnp.int32),
        vals.astype(jnp.int32),
    ])


_solve_onebuf = partial(
    jax.jit, static_argnames=("n_max", "k_max", "cols", "track_conflicts",
                              "zone_ovh")
)(_solve_onebuf_impl)


# ---------------------------------------------------------------------------
# batched dispatch: one device call, many solve requests
# ---------------------------------------------------------------------------
# The fleet funnels every tenant's solve through one queue (ROADMAP item
# 2), and the kernel is ~2-3ms inside a ~100ms reconcile — so dispatching
# queued requests ONE AT A TIME leaves the mesh idle between kernels and
# pays the tunnel RTT per request. This engine packs compatible requests
# (same padded shape class: Gp/n_max/k_max/cols/flags + one shared device
# catalog) into a single vmapped kernel call along a new leading request
# axis. Each request keeps its own padding masks (padded groups have
# count 0; padded batch rows have ALL counts zeroed), so results decode
# independently and are byte-identical to serial per-request solves — the
# parity fuzz in tests/test_batch_parity.py is the gate.


def _solve_batched_impl(alloc, price, avail, gbuf, conflict, zovh,
                        n_max: int, k_max: int, cols: tuple,
                        track_conflicts: bool, zone_ovh: bool):
    """vmap of the onebuf kernel over a leading request axis. Catalog
    tensors (and zovh) are closed over — one bucket shares ONE device
    catalog, so they broadcast instead of stacking B copies."""
    def one(gb, cf):
        return _solve_onebuf_impl(alloc, price, avail, gb, None, None, cf,
                                  zovh, None, n_max=n_max, k_max=k_max,
                                  cols=cols, track_conflicts=track_conflicts,
                                  zone_ovh=zone_ovh)
    if track_conflicts:
        return jax.vmap(one)(gbuf, conflict)
    return jax.vmap(lambda gb: one(gb, None))(gbuf)


_solve_batched = partial(
    jax.jit, static_argnames=("n_max", "k_max", "cols", "track_conflicts",
                              "zone_ovh")
)(_solve_batched_impl)

# donate the resident batch buffer (gbuf, arg 3): each batch uploads a
# fresh stacked request matrix and never reads it back, so XLA may
# reuse its device allocation for the packed output instead of growing
# the working set per in-flight batch (SNIPPETS.md [1] donate_argnums).
# CPU backends warn on donation, so the non-donating jit serves there.
_solve_batched_donate = partial(
    jax.jit, static_argnames=("n_max", "k_max", "cols", "track_conflicts",
                              "zone_ovh"), donate_argnums=(3,)
)(_solve_batched_impl)


def _batched_fn():  # graftlint: donates=3
    """Pick the batched kernel for this backend. The returned callable
    CONSUMES argument 3 (the stacked gbuf) when donating — the
    `# graftlint: donates=3` annotation makes the use-after-donate rule
    track call sites, so a read of the donated stack after dispatch
    fails `make lint`."""
    try:
        cpu = jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — backend probing must not crash a solve
        cpu = True
    return _solve_batched if cpu else _solve_batched_donate


# mesh-jitted BATCHED kernels, keyed on the (hashable) Mesh — the same
# bound-cache discipline as _mesh_fn_cache below. One executable per
# mesh serves every shape class (shapes are jit cache keys underneath).
_batched_mesh_cache: dict = {}
_BATCHED_MESH_CACHE_MAX = 16


def _batched_mesh_fn(mesh):
    """jit the batched kernel with the REQUEST axis laid across `mesh`
    (parallel/mesh.make_batch_mesh): input shardings ride in on the
    device_put stack (P(axis) over batch rows), the catalog replicates,
    and out_shardings pins the packed [Bp, L] result to the same layout.
    vmap lanes are independent solves, so GSPMD partitions this with no
    collectives at all — batch capacity scales linearly with mesh.size.
    NEVER donates: the sharded stack may be a resident buffer the server
    patches next round (ops/resident.py sharded puts)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    fn = _batched_mesh_cache.get(mesh)
    if fn is None:
        if len(_batched_mesh_cache) >= _BATCHED_MESH_CACHE_MAX:
            _batched_mesh_cache.clear()
            # dead jit wrappers ⇒ honest recompiles next dispatch
            _compile_seen.difference_update(
                {k for k in _compile_seen if k[0] == "batch_mesh"})
        axis = mesh.axis_names[0]
        fn = partial(
            jax.jit, static_argnames=("n_max", "k_max", "cols",
                                      "track_conflicts", "zone_ovh"),
            out_shardings=NamedSharding(mesh, P(axis)),
        )(_solve_batched_impl)
        _batched_mesh_cache[mesh] = fn
    return fn


@dataclass
class BatchableSolve:
    """One solve request staged for batched dispatch: the encoded
    problem plus the padded shape class that decides which requests may
    share a device call (and hence a compiled executable)."""

    cat: CatalogTensors
    enc: EncodedPods
    dcat: "DeviceCatalog"
    Gp: int
    statics: dict          # n_max / k_max / cols / track_conflicts / zone_ovh
    signature: tuple       # full co-batch key (shape class + device catalog)
    shape_class: str       # "g<Gp>/n<n_max>" — the ledger's signature class
    # upload-redundancy meter key: identifies "the previous upload for
    # this catalog view" — per (facade, view) when staged through a
    # facade, per device catalog otherwise, so co-batched tenants'
    # matrices never hash against each other's history
    meter_key: tuple = ()


def prepare_batchable(cat: CatalogTensors, enc: EncodedPods,
                      dcat: Optional["DeviceCatalog"] = None,
                      meter_key: Optional[tuple] = None,
                      ) -> Optional[BatchableSolve]:
    """Stage a FRESH solve (no existing nodes, no priors/bans — the
    dominant fleet case) for batched dispatch. Returns None when the
    request cannot batch. The shape class mirrors solve_device's prep
    exactly (same _bucket/_auto_node_budget/_request_cols), so a staged
    request and a serial dispatch of the same enc are the same program."""
    assert not enc.spread_zone.any(), "run split_spread_groups before solve"
    if enc.G == 0:
        return None
    R = enc.requests.shape[1]
    if dcat is not None and (
            dcat.alloc.shape[1] < R
            or (dcat.ovh_z is not None) != (cat.zone_overhead is not None)):
        dcat = None
    if dcat is None:
        dcat = _auto_dcat(cat, R)
    Gp = _bucket(enc.G, 8)
    n_max = _auto_node_budget(cat, enc, 0)
    k_max = _bucket(2 * n_max)
    cols = _request_cols(enc, cat)
    track = enc.conflict is not None
    zone_ovh = dcat.ovh_z is not None
    statics = dict(n_max=n_max, k_max=k_max, cols=cols,
                   track_conflicts=track, zone_ovh=zone_ovh)
    # the device catalog is part of the co-batch key (requests in one
    # call share ONE resident catalog); two buckets with equal shapes
    # but different catalogs still share the compiled executable — the
    # catalog is a runtime argument, not a static
    signature = ("batch", Gp, n_max, k_max, cols, track, zone_ovh,
                 tuple(dcat.alloc.shape), tuple(dcat.price.shape),
                 id(dcat))
    return BatchableSolve(cat=cat, enc=enc, dcat=dcat, Gp=Gp,
                          statics=statics, signature=signature,
                          shape_class=f"g{Gp}/n{n_max}",
                          meter_key=(meter_key if meter_key is not None
                                     else ("dcat", id(dcat))))


class InFlightBatch:
    """A dispatched batch whose device work may still be running: the
    async half of the encode→upload→dispatch→decode pipeline. The caller
    overlaps host work with the device by delaying block()/decode() —
    fleet/service.py keeps one of these in flight while staging the
    next bucket."""

    def __init__(self, reqs: List[BatchableSolve], packed,
                 dispatched_at: float):
        self.reqs = reqs
        self._packed = packed       # device int32 [Bp, L]
        self.dispatched_at = dispatched_at
        self._buf: Optional[np.ndarray] = None
        self.wait_s = 0.0           # host time spent blocked on the device
        self.span_s = 0.0           # dispatch-return -> results ready
        self.fallbacks = 0          # rows re-run serially (budget regrow)

    @property
    def size(self) -> int:
        return len(self.reqs)

    @property
    def padded_size(self) -> int:
        return int(self._packed.shape[0]) if self._buf is None \
            else int(self._buf.shape[0])

    def block(self) -> float:
        """Wait for the device and read the packed result back (the ONE
        d2h of the whole batch). Returns the blocked-wait seconds —
        ~zero when host work fully overlapped the device."""
        if self._buf is not None:
            return 0.0
        import time as _time
        t0 = _time.perf_counter()
        self._packed.block_until_ready()
        self.wait_s = _time.perf_counter() - t0
        sp = (TRACER.span("solve.readback", batch=self.size)
              if TRACER.enabled else NOOP_SPAN)
        with sp, dm.attributed(shape_class=self.reqs[0].shape_class):
            self._buf = _read(self._packed)
            sp.set(d2h_bytes=int(self._buf.nbytes))
        self._packed = None
        self.span_s = _time.perf_counter() - self.dispatched_at
        return self.wait_s

    def decode(self, i: int) -> SolveResult:
        """Decode request i's row independently of its batch peers —
        the same host-side reconstruction as the serial path. A row
        whose sparse/node budget proved too small re-runs serially
        (solve_device's regrow loop), exactly what a serial dispatch of
        that request would have done."""
        self.block()
        req = self.reqs[i]
        st = req.statics
        Gp, n_max, k_max = req.Gp, st["n_max"], st["k_max"]
        (nused, overflowed, nnz, unsched, ntype, idx,
         vals) = _parse_packed(self._buf[i], Gp, n_max, k_max)
        total_pods = int(req.enc.counts.sum())
        if nnz > k_max or (overflowed and n_max < total_pods):
            self.fallbacks += 1
            return solve_device(req.cat, req.enc, dcat=req.dcat)
        sp = (TRACER.span("solve.decode", batch_index=i)
              if TRACER.enabled else NOOP_SPAN)
        with sp:
            R = req.enc.requests.shape[1]
            result = _decode_solution(
                req.cat, req.enc, [], np.zeros((0, R), np.float32),
                np.zeros((0, req.cat.Z), bool),
                np.zeros((0, req.cat.C), bool),
                nused, ntype, idx, vals, nnz, unsched, n_max)
            sp.set(nodes=len(result.nodes), nnz=int(nnz))
        return result

    def results(self) -> List[SolveResult]:
        return [self.decode(i) for i in range(self.size)]

    @classmethod
    def from_rows(cls, reqs: List[BatchableSolve], rows: np.ndarray,
                  span_s: float = 0.0) -> "InFlightBatch":
        """Rehydrate a drained batch from already-read packed rows —
        the federation client's path: the device half ran in the server
        process and the [Bp, L] int32 rows arrived as wire bytes.
        decode() then runs HERE against the client's own cat/enc, so a
        federated solve and an in-process solve share one decode path
        (byte-identical results by construction). block() is a no-op
        (_buf already set); the wire latency is the caller's to meter."""
        ifb = cls(reqs, None, 0.0)
        ifb._buf = np.ascontiguousarray(rows, dtype=np.int32)
        ifb.span_s = float(span_s)
        return ifb


# batch-axis padding buckets: {1, 2, 3, 4, 6, 8, 12, 16, ...} so
# executables converge per shape class instead of recompiling per fleet
# occupancy (same {2^k, 3*2^(k-1)} ladder as the node axis)
def _batch_bucket(b: int) -> int:
    return _bucket(b, 1)


def _stage_batch_stack(gstack_np: np.ndarray, conf_np, track: bool,
                       mesh=None, resident_key: Optional[tuple] = None,
                       token=None, shape_class: str = ""):
    """Upload one packed request stack ([Bp, Gp, W] f32, plus the
    optional [Bp, Gp, Gp] conflict stack). Three routes, composable:
    plain _put (classic), _put_sharded over a batch mesh (each device
    receives only ITS batch rows — h2d volume per chip shrinks with
    mesh.size), or the resident manager (resident_key set: an unchanged
    tenant's rows patch instead of re-uploading, sharded when a mesh is
    given — the PR 11 follow-up). Returns (gstack, conf, ledger group,
    donate_ok): resident and mesh stacks must NOT be donated — resident
    buffers serve the next pump, and the mesh jit never donates."""
    sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    with dm.attributed(reason="batch_upload", kind="batch_gbuf",
                       shape_class=shape_class) as grp:
        donate_ok = False
        if resident_key is not None:
            from .resident import RESIDENT
            if RESIDENT.armed:
                gstack = RESIDENT.upload(
                    resident_key + ("batch_gbuf",) + tuple(gstack_np.shape),
                    gstack_np, token=token, shape_class=shape_class,
                    donate=False, sharding=sharding)
            elif sharding is not None:
                gstack = _maybe_corrupt(
                    "gbuf", _put_sharded(gstack_np, sharding))
            else:
                gstack = _maybe_corrupt("gbuf", _put(gstack_np))
        elif sharding is not None:
            gstack = _maybe_corrupt("gbuf", _put_sharded(gstack_np, sharding))
        else:
            gstack = _maybe_corrupt("gbuf", _put(gstack_np))
            donate_ok = True
        conf = None
        if track and conf_np is not None:
            conf = (_put_sharded(conf_np, sharding) if sharding is not None
                    else _put(conf_np))
    return gstack, conf, grp, donate_ok


def _dispatch_stack(gstack, conf, dcat, st: dict, donate_ok: bool,
                    mesh=None):
    """Classify the dispatch hit/miss and run the batched kernel —
    the device half shared by dispatch_batch (in-process buckets) and
    dispatch_packed (federation server: the stack arrived as wire
    bytes). Consumes `gstack` (possibly donated) — callers must not
    touch their handle afterwards."""
    track, zone_ovh = st["track_conflicts"], st["zone_ovh"]
    Bp = int(gstack.shape[0])
    head = ("batch_mesh", mesh) if mesh is not None else ("batch",)
    event = _dispatch_cache_event(
        head + (Bp, tuple(dcat.alloc.shape), tuple(dcat.price.shape),
                tuple(gstack.shape), track, zone_ovh, st["n_max"],
                st["k_max"], tuple(st["cols"])))
    sp = (TRACER.span("solve.compile" if event == "miss"
                      else "solve.dispatch", cache=event, backend="device",
                      batch=Bp, n_max=st["n_max"], mesh=mesh is not None)
          if TRACER.enabled else NOOP_SPAN)
    # NO fault-hook probe here: the fleet's injector routes faults by
    # current_tenant(), and this call serves MANY tenants — the caller
    # probes via probe_dispatch_fault() under each tenant's scope BEFORE
    # dispatching (fleet/service._dispatch_bucket), so a tenant-targeted
    # fault aborts the batch while an unscoped probe can neither miss
    # the target nor fire for a tenant that isn't even in the batch
    # the donating call keeps the factory at the call site (not bound to
    # a local first): the use-after-donate lint resolves donate positions
    # from `_batched_fn()(...)` shapes, and this is the site it guards
    with sp:
        if mesh is not None:
            packed = _batched_mesh_fn(mesh)(
                dcat.alloc, dcat.price, dcat.avail, gstack, conf,
                dcat.ovh_z if zone_ovh else None,
                n_max=st["n_max"], k_max=st["k_max"], cols=st["cols"],
                track_conflicts=track, zone_ovh=zone_ovh)
        elif not donate_ok:
            packed = _solve_batched(
                dcat.alloc, dcat.price, dcat.avail, gstack, conf,
                dcat.ovh_z if zone_ovh else None,
                n_max=st["n_max"], k_max=st["k_max"], cols=st["cols"],
                track_conflicts=track, zone_ovh=zone_ovh)
        else:  # donating branch LAST: no gstack read may follow it
            packed = _batched_fn()(
                dcat.alloc, dcat.price, dcat.avail, gstack, conf,
                dcat.ovh_z if zone_ovh else None,
                n_max=st["n_max"], k_max=st["k_max"], cols=st["cols"],
                track_conflicts=track, zone_ovh=zone_ovh)
    # dispatch donated gstack (off-CPU): XLA may already have reused its
    # bytes for `packed` — drop the host handle so no later edit can
    # read the dead buffer (the use-after-donate lint rule enforces it)
    del gstack
    return packed


def dispatch_batch(reqs: List[BatchableSolve], mesh=None,
                   resident_key: Optional[tuple] = None) -> InFlightBatch:
    """Pack one bucket of same-signature requests into a single device
    call and return without blocking (the device executes while the
    caller stages the next bucket). Padded batch rows replicate request
    0 with every group count zeroed — pure no-ops in the scan.

    mesh: lay the REQUEST axis across a batch mesh
    (parallel/mesh.make_batch_mesh) — Bp rounds up to a mesh.size
    multiple so every chip owns whole rows, the bucket's device catalog
    replicates over the mesh, and batch capacity scales with slice size
    instead of the padding ladder. Results are decoded row-by-row
    exactly like the single-device path (lanes never interact), so
    hashes are identical either way.
    resident_key: route the stacked request matrix through the
    device-resident manager (federation server steady state: tenant
    rows that didn't change between pumps patch instead of re-ship)."""
    import time as _time
    assert reqs, "empty batch"
    first = reqs[0]
    assert all(r.signature == first.signature for r in reqs), \
        "batched requests must share one shape-class signature"
    st = first.statics
    Gp, cols = first.Gp, list(st["cols"])
    track = st["track_conflicts"]
    dcat = first.dcat
    B, Bp = len(reqs), _batch_bucket(len(reqs))
    if mesh is not None:
        ms = int(mesh.size)
        Bp = -(-Bp // ms) * ms  # whole rows per chip: Bp % mesh.size == 0
        # the bucket must read a catalog resident on the SAME mesh —
        # _auto_dcat keys on (token|id, mesh), so this is one replicated
        # upload per (view, mesh), shared by every later bucket
        dcat = _auto_dcat(first.cat, first.enc.requests.shape[1], mesh=mesh)
    sp = (TRACER.span("solve.batch_pack", requests=B, padded=Bp,
                      shape_class=first.shape_class)
          if TRACER.enabled else NOOP_SPAN)
    with sp:
        b0 = transfer_bytes()[0]
        gbufs = [_pack_groups(*_group_inputs(r.enc, Gp), cols)
                 for r in reqs]
        # redundancy metering BEFORE the stack: each request's matrix
        # hashes against the previous upload under ITS OWN meter key
        # (per facade/view), so the identical-byte fraction measures
        # exactly what a per-view delta upload would save
        for r, g in zip(reqs, gbufs):
            dm.UPLOADS.observe(r.meter_key, g)
        if Bp > B:
            pad = gbufs[0].copy()
            pad[:, len(cols)] = 0.0  # zero the counts column: a no-op row
            gbufs.extend([pad] * (Bp - B))
        conf_np = None
        if track:
            confs = [_pad_to(_pad_to(r.enc.conflict, Gp, 0), Gp, 1)
                     if r.enc.conflict is not None
                     else np.zeros((Gp, Gp), bool) for r in reqs]
            confs.extend([np.zeros((Gp, Gp), bool)] * (Bp - B))
            conf_np = np.stack(confs)
        gstack, conf, grp, donate_ok = _stage_batch_stack(
            np.stack(gbufs), conf_np, track, mesh=mesh,
            resident_key=resident_key, token=first.cat.cache_token,
            shape_class=first.shape_class)
        sp.set(h2d_bytes=transfer_bytes()[0] - b0)
    packed = _dispatch_stack(gstack, conf, dcat, st, donate_ok, mesh=mesh)
    del gstack  # consumed by _dispatch_stack (donated off-CPU)
    ifb = InFlightBatch(reqs, packed, _time.perf_counter())
    # the in-flight batch OWNS the staged uploads and the pending packed
    # output: residency drops when it drains (block() frees _packed) or
    # when the batch object itself dies
    dm.DEVICEMEM.adopt(grp, ifb)
    dm.DEVICEMEM.track("packed_result", [packed], owner=ifb,
                       shape_class=first.shape_class)
    return ifb


def dispatch_packed(gstack_np: np.ndarray, conf_np, dcat: "DeviceCatalog",
                    statics: dict, shape_class: str = "", mesh=None,
                    resident_key: Optional[tuple] = None, token=None):
    """Dispatch an ALREADY-PACKED request stack — the federation
    server's entry point: its clients packed the gbufs on their own
    hosts and shipped the bytes, so there are no BatchableSolve objects
    (no cat/enc) on this side. Pads the batch axis to the bucket (and
    mesh multiple), uploads, dispatches, and returns (device packed
    [Bp, L] int32, residency-ledger group) without blocking; the caller
    reads the rows back and ships them to the owning clients, which
    decode with their own catalogs."""
    B = int(gstack_np.shape[0])
    Bp = _batch_bucket(B)
    if mesh is not None:
        ms = int(mesh.size)
        Bp = -(-Bp // ms) * ms
    track = statics["track_conflicts"]
    if Bp > B:
        pad = np.repeat(gstack_np[:1], Bp - B, axis=0).copy()
        pad[:, :, len(statics["cols"])] = 0.0  # zero counts: no-op rows
        gstack_np = np.concatenate([gstack_np, pad], axis=0)
        if track and conf_np is not None:
            conf_np = np.concatenate(
                [conf_np, np.zeros((Bp - B,) + conf_np.shape[1:], bool)],
                axis=0)
    sp = (TRACER.span("solve.batch_pack", requests=B, padded=Bp,
                      shape_class=shape_class)
          if TRACER.enabled else NOOP_SPAN)
    with sp:
        b0 = transfer_bytes()[0]
        gstack, conf, grp, donate_ok = _stage_batch_stack(
            gstack_np, conf_np, track, mesh=mesh,
            resident_key=resident_key, token=token,
            shape_class=shape_class)
        sp.set(h2d_bytes=transfer_bytes()[0] - b0)
    packed = _dispatch_stack(gstack, conf, dcat, statics, donate_ok,
                             mesh=mesh)
    del gstack  # consumed by _dispatch_stack (donated off-CPU)
    return packed, grp


def probe_dispatch_fault(backend: str) -> None:
    """Fire the injected device-fault seam, if armed. The batched
    dispatcher calls this once per distinct tenant in a bucket, each
    under that tenant's metric scope — the same per-tenant probe
    semantics the serial dispatch path has (the hook fires inside the
    ticket's scoped thunk there)."""
    if _dispatch_fault_hook is not None:
        _dispatch_fault_hook(backend)


def solve_device_batched(reqs: List[BatchableSolve]) -> List[SolveResult]:
    """Synchronous convenience: dispatch one bucket and decode every
    row. The pipelined overlap (and the per-tenant fault probing) lives
    in the caller (fleet/service.py); tests and direct callers use
    this."""
    probe_dispatch_fault("device")
    return dispatch_batch(reqs).results()


# monotone union of resource columns ever requested in this process: cols
# is a jit STATIC (its value fixes the projection slices), so a per-solve
# minimal set would recompile the kernel every time the pod mix's resource
# footprint changed. The union only grows — recompiles are bounded by the
# number of distinct resource columns, not by solve count. Column indices
# are process-stable because the resource vocabulary only grows (see the
# existing-node assert in solve_device).
_cols_union: set = {0}


def _request_cols(enc: EncodedPods, cat: CatalogTensors) -> tuple:
    """Resource columns the kernel must carry: the process-lifetime union
    of columns any group has requested, plus any column a zone-overhead
    reservation charges (its subtraction must reach headroom in columns
    pods then request — charged columns nobody requests still can't bind,
    but keeping them keeps the projection reasoning local). Clamped to the
    current resource axis; never empty — the scan needs R≥1."""
    used = enc.requests.any(axis=0)
    if cat.zone_overhead is not None:
        zc = cat.zone_overhead.any(axis=(0, 1))
        used[: zc.shape[0]] |= zc
    _cols_union.update(int(c) for c in np.nonzero(used)[0])
    R = enc.requests.shape[1]
    return tuple(c for c in sorted(_cols_union) if c < R)


# mesh-jitted packed kernels, keyed on the (hashable) Mesh itself — id()
# keys break under address reuse and pin dead meshes; the cap bounds both
# executable count and the meshes the cache keeps alive
_mesh_fn_cache: dict = {}
_MESH_FN_CACHE_MAX = 32


def _mesh_packed_fn(mesh, n_max: int, k_max: int, track: bool,
                    zone_ovh: bool = False):
    """jit the packed kernel for a node-axis-sharded mesh run. Inputs are
    device_put with explicit shardings by the caller; GSPMD propagates them
    through the scan and inserts the ICI collectives (cumsum/argmin/sum
    reductions over the node axis). The packed output replicates — it's a
    small int32 vector read once by the host."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    key = (mesh, n_max, k_max, track, zone_ovh)
    fn = _mesh_fn_cache.get(key)
    if fn is None:
        if len(_mesh_fn_cache) >= _MESH_FN_CACHE_MAX:
            _mesh_fn_cache.clear()
            # the jitted wrappers just died — dispatches with previously
            # seen mesh shapes will recompile, and reporting them as
            # 'hit' would hide exactly the compile stall the counter
            # exists to expose
            _compile_seen.difference_update(
                {k for k in _compile_seen if k[0] == "mesh"})
        fn = jax.jit(
            partial(_solve_kernel_packed_impl, n_max=n_max, k_max=k_max,
                    track_conflicts=track, zone_ovh=zone_ovh),
            out_shardings=NamedSharding(mesh, P()))
        _mesh_fn_cache[key] = fn
    return fn


def _group_inputs(enc: EncodedPods, Gp: int):
    """Pad the per-group arrays to the scan bucket — the ONE prep both
    solve_device and the kernel_args bench seam share, so the published
    kernel timing can't drift from the production shapes."""
    return (_pad_to(enc.requests.astype(np.float32), Gp),
            _pad_to(enc.counts.astype(np.int32), Gp),
            _pad_to(enc.compat, Gp),
            _pad_to(enc.allow_zone, Gp),
            _pad_to(enc.allow_cap, Gp),
            _pad_to(enc.max_per_node.astype(np.int32), Gp))


def _auto_node_budget(cat: CatalogTensors, enc: EncodedPods,
                      n_existing: int) -> int:
    """Node-axis budget: the estimate commits the same cost-per-slot argmin
    type the kernel does and lands within a few % of n_used, so 1.25x
    margin suffices; underestimates are safe — the kernel reports overflow
    and solve_device retries doubled."""
    est = _estimate_nodes(cat, enc)
    return _bucket(n_existing + max(64, est + est // 4 + enc.G))


def _mesh_put(mesh, np_arrays_nodes, np_arrays_rep):
    """device_put node-axis arrays as P('nodes') shards and the rest
    replicated; returns the two lists of device arrays."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    nodes = NamedSharding(mesh, P("nodes"))
    rep = NamedSharding(mesh, P())
    return ([jax.device_put(a, nodes) for a in np_arrays_nodes],
            [jax.device_put(a, rep) for a in np_arrays_rep])


def _pad_to(x: np.ndarray, n: int, axis: int = 0, fill=0):
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, n - x.shape[axis])
    return np.pad(x, pad, constant_values=fill)


def _estimate_nodes(cat: CatalogTensors, enc: EncodedPods) -> int:
    """FFD node-count estimate: ceil(count / slots) per group, at the slots
    of the COST-PER-SLOT-ARGMIN type — the type the kernel actually commits
    when it opens nodes for the group (max-slot types would undercount by
    10x+; first-fit sharing only ever lowers the real total). Chunked over
    groups so the [chunk, T, R] broadcast stays small."""
    alloc = align_resources(cat.allocatable, enc.requests.shape[1])
    # cheapest offering per type given each group's zone/captype masks is
    # approximated by the global min price per type — close enough for a
    # budget (the overflow retry covers the rest)
    min_price = np.where(cat.available, cat.price, np.inf).min(axis=(1, 2))
    est = 0.0
    for lo in range(0, enc.G, 256):
        hi = min(lo + 256, enc.G)
        req = enc.requests[lo:hi].astype(np.float32)            # [g, R]
        with_req = np.where(req > 0, req, np.float32(1.0))
        slots = np.where(req[:, None, :] > 0,
                         np.floor(alloc[None, :, :] / with_req[:, None, :]
                                  + EPS),
                         np.float32(BIG)).min(axis=2)           # [g, T]
        cap = np.where(enc.max_per_node[lo:hi] > 0,
                       enc.max_per_node[lo:hi], BIG)[:, None]
        slots = np.clip(slots, 0.0, cap)
        ok = enc.compat[lo:hi] & (slots >= 1) & np.isfinite(min_price)[None, :]
        cps = np.where(ok, min_price[None, :] / np.maximum(slots, 1.0),
                       np.inf)                                  # [g, T]
        t_star = np.argmin(cps, axis=1)
        g_idx = np.arange(hi - lo)
        s = np.where(np.isfinite(cps[g_idx, t_star]),
                     slots[g_idx, t_star], np.float32(BIG))
        est += float(np.ceil(enc.counts[lo:hi] / np.maximum(s, 1.0)).sum())
    return int(est)


def _bucket(n: int, quantum: int = 64) -> int:
    """Round up to a padding bucket to bound recompilation.

    Buckets are {2^k, 3·2^(k-1)}: the intermediate step keeps worst-case
    padding waste at 33% instead of 100% — the scan's per-step cost is
    O(n_max), so rounding 2.6k nodes up to 8192 rather than 3072 was
    directly visible in kernel time."""
    n = max(n, 1)
    p = int(2 ** math.floor(math.log2(n)))
    for cand in (p, 3 * p // 2, 2 * p):
        if cand >= n:
            return max(quantum, cand)
    return max(quantum, 2 * p)


def kernel_args(cat: CatalogTensors, enc: EncodedPods,
                dcat: Optional[DeviceCatalog] = None):
    """Device-committed kernel inputs for the fresh-solve case (no existing
    nodes) — the benchmarking/profiling seam: bench.py times the raw kernel
    on these to report device time separate from tunnel RTT. Mirrors
    solve_device's input prep; results equivalence is covered by the golden
    tests comparing solve_device to the host oracle.

    Returns (args_tuple, statics_dict) for _solve_onebuf."""
    R = enc.requests.shape[1]
    Gp = _bucket(enc.G, 8)
    if dcat is None or dcat.alloc.shape[1] < R:
        dcat = _auto_dcat(cat, R)
    n_max = _auto_node_budget(cat, enc, 0)
    k_max = _bucket(2 * n_max)
    track = enc.conflict is not None
    zone_ovh = dcat.ovh_z is not None
    cols = _request_cols(enc, cat)
    conflict = (_put(_pad_to(_pad_to(enc.conflict, Gp, 0), Gp, 1)) if track
                else None)
    gbuf = _put(_pack_groups(*_group_inputs(enc, Gp), list(cols)))
    args = (dcat.alloc, dcat.price, dcat.avail, gbuf, None, None, conflict,
            dcat.ovh_z if zone_ovh else None, None)
    statics = dict(n_max=n_max, k_max=k_max, cols=cols,
                   track_conflicts=track, zone_ovh=zone_ovh)
    return args, statics


def slope_time(dispatch, iters: int = 40, n_variants: int = 8) -> float:
    """Per-call device time via the slope method, in seconds.

    Two pipelined loops of N and 2N dispatches, each blocked once; the
    per-call time is (t_2N - t_N) / N, which cancels BOTH the tunnel RTT
    of the blocking read (~70 ms on this rig — amortizing it over one loop
    still inflates the number by RTT/N) and the Python dispatch ramp.
    `dispatch(i)` must return the device output for input variant
    i % n_variants — callers MUST cycle ≥2 materially distinct inputs:
    the tunnel runtime coalesces identical in-flight executions, so timing
    the same buffers 40x reports fantasy numbers. Shared by
    kernel_device_time and consolidate.screen_device_time so the two
    published timings stay methodologically identical."""
    import time

    def loop(n):
        out = None
        t0 = time.perf_counter()
        for i in range(n):
            out = dispatch(i)
        out.block_until_ready()
        return time.perf_counter() - t0

    loop(n_variants)  # warm: compile + device caches
    t1 = min(loop(iters) for _ in range(2))
    t2 = min(loop(2 * iters) for _ in range(2))
    return max((t2 - t1) / iters, 1e-9)


def kernel_device_time(cat: CatalogTensors, enc: EncodedPods,
                       iters: int = 40) -> float:
    """Per-run device time for the solve kernel, in seconds (slope_time
    over 8 variants with perturbed group counts)."""
    args, statics = kernel_args(cat, enc)
    alloc, price, avail, gbuf, prior, banned, conflict, zovh, nbuf = args
    g0 = np.asarray(gbuf)
    Rk = len(statics["cols"])
    variants = []
    for i in range(8):
        g = g0.copy()
        g[:, Rk] += i  # perturb counts: same shapes, distinct work
        variants.append(_put(g))
    return slope_time(
        lambda i: _solve_onebuf(alloc, price, avail, variants[i % 8], prior,
                                banned, conflict, zovh, nbuf, **statics),
        iters=iters)


def solve_device(cat: CatalogTensors, enc: EncodedPods,
                 existing: Optional[List[VirtualNode]] = None,
                 n_max: Optional[int] = None,
                 dcat: Optional[DeviceCatalog] = None,
                 mesh=None,
                 resident_key: Optional[tuple] = None) -> SolveResult:
    """Run the kernel and decode the result to the same SolveResult shape
    solve_host produces. `enc` must be spread-free (split_spread_groups).

    mesh: a jax.sharding.Mesh with a "nodes" axis — the node axis shards
    across the mesh's chips (catalog + group inputs replicated; GSPMD
    inserts the ICI collectives), the production multi-chip path.

    Tracing wrapper: when the process tracer is on, the whole solve runs
    under a `solve.device` span whose children decompose it into
    device-put / compile-or-dispatch / readback / decode stages (see
    docs/observability.md); the per-solve transfer-byte deltas land on
    the two transfer gauges either way, so tunnel-volume growth is
    scrapeable without a bench run."""
    from ..metrics import TRANSFER_BYTES_D2H, TRANSFER_BYTES_H2D
    u0, d0 = transfer_bytes()
    if TRACER.enabled:
        span = TRACER.span(
            "solve.device",
            backend="mesh" if mesh is not None else "device",
            pods=int(enc.counts.sum()), groups=int(enc.G))
    else:
        span = NOOP_SPAN
    with span:
        result = _solve_device_impl(cat, enc, existing, n_max, dcat, mesh,
                                    resident_key=resident_key)
        u1, d1 = transfer_bytes()
        TRANSFER_BYTES_H2D.set(u1 - u0)
        TRANSFER_BYTES_D2H.set(d1 - d0)
        span.set(h2d_bytes=u1 - u0, d2h_bytes=d1 - d0)
    return result


def _solve_device_impl(cat: CatalogTensors, enc: EncodedPods,
                       existing: Optional[List[VirtualNode]] = None,
                       n_max: Optional[int] = None,
                       dcat: Optional[DeviceCatalog] = None,
                       mesh=None,
                       resident_key: Optional[tuple] = None) -> SolveResult:
    assert not enc.spread_zone.any(), "run split_spread_groups before solve"
    prep_sp = (TRACER.span("solve.prep") if TRACER.enabled else NOOP_SPAN)
    with prep_sp:
        R = enc.requests.shape[1]
        existing = existing or []
        n_existing = len(existing)
        total_pods = int(enc.counts.sum())
        G = enc.G
        auto_n = n_max is None
        if auto_n:
            # node budget from per-group best-type slots (the kernel's
            # per-step cost is O(n_max), so a tight guess matters: 100k
            # small pods pack ~100/node, not 4)
            n_max = _auto_node_budget(cat, enc, n_existing)
        if mesh is not None:
            ms = int(mesh.size)
            n_max = -(-n_max // ms) * ms  # shardable node axis
        Gp = _bucket(G, 8)

        if dcat is not None and (
                dcat.alloc.shape[1] < R
                or (dcat.ovh_z is not None) != (cat.zone_overhead is not None)):
            dcat = None
        if dcat is None:
            dcat = (device_catalog(cat, R, mesh=mesh) if mesh is not None
                    else _auto_dcat(cat, R))

        # pad group inputs; padded groups have count 0 → no-ops in the scan
        (requests, counts, compat, allow_zone, allow_cap,
         max_per_node) = _group_inputs(enc, Gp)

        node_type = np.zeros(n_existing, np.int32)
        node_cum = np.zeros((n_existing, R), np.float32)
        node_zmask = np.zeros((n_existing, cat.Z), bool)
        node_cmask = np.zeros((n_existing, cat.C), bool)
        node_open = np.zeros(n_existing, bool)
        for i, n in enumerate(existing):
            assert len(n.cum) <= R, (
                f"existing node cum has {len(n.cum)} resources but the "
                f"current axis is {R} — the resource axis only grows "
                f"within a process")
            node_type[i] = n.type_idx
            node_cum[i, : len(n.cum)] = n.cum
            node_zmask[i] = n.zone_mask
            node_cmask[i] = n.cap_mask
            node_open[i] = True

        track = enc.conflict is not None
        zone_ovh = dcat.ovh_z is not None
        conflict_np = (_pad_to(_pad_to(enc.conflict, Gp, 0), Gp, 1) if track
                       else np.zeros((Gp, 1), bool))
        # prior occupancy / resident bans exist only when existing nodes
        # carry them; otherwise ship [Gp, 1] zero dummies that broadcast
        # over the node axis inside the kernel — saves a [Gp, n_max] int32
        # + bool host→device transfer per solve (the common fresh-solve
        # case)
        has_prior = any(n.prior_by_group for n in existing)
        has_banned = any(n.banned_groups is not None for n in existing)
        # single-device uploads: ONE packed group matrix; node state only
        # when resuming onto existing nodes; dummies synthesized inside
        # the jit
        cols = _request_cols(enc, cat)
        prep_sp.set(n_max=int(n_max), groups_padded=int(Gp))
    shape_class = f"g{Gp}/n{n_max}"
    if mesh is None:
        sp = (TRACER.span("solve.device_put") if TRACER.enabled
              else NOOP_SPAN)
        with sp:
            b0 = transfer_bytes()[0]
            gbuf_np = _pack_groups(requests, counts, compat, allow_zone,
                                   allow_cap, max_per_node, list(cols))
            from .resident import RESIDENT
            if resident_key is not None and RESIDENT.armed:
                # device-resident delta path (ops/resident.py): the
                # request matrix stays on device across reconciles and
                # only CHANGED group rows cross the tunnel, applied as
                # a donated in-place scatter; an unchanged warm solve
                # ships zero upload bytes. Fallbacks (epoch bump,
                # shape-class growth, dense churn) re-upload full —
                # byte-parity with this cold path either way.
                gbuf_dev = RESIDENT.upload(
                    resident_key + ("gbuf", Gp), gbuf_np,
                    token=cat.cache_token, shape_class=shape_class)
                conflict_dev = (RESIDENT.upload(
                    resident_key + ("conflict", Gp), conflict_np,
                    token=cat.cache_token, shape_class=shape_class)
                    if track else None)
            else:
                # redundancy meter: how much of THIS view's request
                # matrix is byte-identical to the previous solve's
                # upload — the measured delta-upload headroom the
                # resident path above spends
                dm.UPLOADS.observe(("serial", id(dcat), Gp), gbuf_np)
                with dm.attributed(shape_class=shape_class):
                    gbuf_dev = _maybe_corrupt("gbuf", _put(gbuf_np))
                    conflict_dev = _put(conflict_np) if track else None
            sp.set(gbuf_shape=str(tuple(gbuf_dev.shape)),
                   h2d_bytes=transfer_bytes()[0] - b0)
    # sparse-take budget: nnz ≈ n_used + cross-node sharing, far below the
    # [Gp·n_max] flat size; regrown + rerun on overflow (rare)
    k_max = _bucket(2 * n_max)
    while True:
        prior = np.zeros((Gp, n_max if has_prior else 1), np.int32)
        banned = np.zeros((Gp, n_max if has_banned else 1), bool)
        for i, n in enumerate(existing):
            if has_prior:
                for g, cnt in n.prior_by_group.items():
                    if g < Gp:
                        prior[g, i] = cnt
            if has_banned and n.banned_groups is not None:
                banned[: len(n.banned_groups), i] = n.banned_groups
        if mesh is not None:
            if dcat.alloc.shape[1] != R:
                dcat = device_catalog(cat, R, mesh=mesh)
            zovh = (dcat.ovh_z if zone_ovh
                    else np.zeros((1, 1, R), np.float32))
            from jax.sharding import NamedSharding, PartitionSpec as P
            nodes_sh = NamedSharding(mesh, P("nodes"))
            rep_sh = NamedSharding(mesh, P())
            gn_sh = NamedSharding(mesh, P(None, "nodes"))
            put = _put_sharded
            event = _dispatch_cache_event(
                ("mesh", mesh, n_max, k_max, track, zone_ovh,
                 requests.shape, prior.shape, banned.shape))
            sp = (TRACER.span("solve.compile" if event == "miss"
                              else "solve.dispatch", cache=event,
                              backend="mesh",
                              note="includes replicated input puts")
                  if TRACER.enabled else NOOP_SPAN)
            if _dispatch_fault_hook is not None:
                _dispatch_fault_hook("mesh")
            with sp, dm.attributed(shape_class=shape_class):
                packed = _mesh_packed_fn(mesh, n_max, k_max, track,
                                         zone_ovh)(
                    dcat.alloc, dcat.price, dcat.avail,
                    put(requests, rep_sh), put(counts, rep_sh),
                    put(compat, rep_sh), put(allow_zone, rep_sh),
                    put(allow_cap, rep_sh), put(max_per_node, rep_sh),
                    put(prior, gn_sh if has_prior else rep_sh),
                    put(banned, gn_sh if has_banned else rep_sh),
                    put(conflict_np, rep_sh),
                    zovh if zone_ovh else put(np.asarray(zovh), rep_sh),
                    put(_pad_to(node_type, n_max), nodes_sh),
                    put(_pad_to(node_cum, n_max), nodes_sh),
                    put(_pad_to(node_zmask, n_max), nodes_sh),
                    put(_pad_to(node_cmask, n_max), nodes_sh),
                    put(_pad_to(node_open, n_max), nodes_sh),
                    put(np.asarray(n_existing, np.int32), rep_sh))
        else:
            sp = (TRACER.span("solve.device_put") if TRACER.enabled
                  else NOOP_SPAN)
            with sp, dm.attributed(shape_class=shape_class):
                b0 = transfer_bytes()[0]
                nbuf = (None if n_existing == 0 else
                        _put(_pack_nodes(_pad_to(node_type, n_max),
                                         _pad_to(node_cum, n_max),
                                         _pad_to(node_zmask, n_max),
                                         _pad_to(node_cmask, n_max),
                                         _pad_to(node_open, n_max),
                                         list(cols))))
                prior_dev = _put(prior) if has_prior else None
                banned_dev = _put(banned) if has_banned else None
                sp.set(h2d_bytes=transfer_bytes()[0] - b0,
                       resumed_nodes=n_existing)
            event = _dispatch_cache_event(
                ("onebuf", dcat.alloc.shape, dcat.price.shape,
                 tuple(gbuf_dev.shape),
                 None if prior_dev is None else tuple(prior_dev.shape),
                 None if banned_dev is None else tuple(banned_dev.shape),
                 nbuf is None, zone_ovh, track, n_max, k_max, cols))
            sp = (TRACER.span("solve.compile" if event == "miss"
                              else "solve.dispatch", cache=event,
                              backend="device", n_max=n_max, k_max=k_max)
                  if TRACER.enabled else NOOP_SPAN)
            if _dispatch_fault_hook is not None:
                _dispatch_fault_hook("device")
            with sp:
                packed = _solve_onebuf(
                    dcat.alloc, dcat.price, dcat.avail, gbuf_dev,
                    prior_dev, banned_dev,
                    conflict_dev, dcat.ovh_z if zone_ovh else None, nbuf,
                    n_max=n_max, k_max=k_max, cols=cols,
                    track_conflicts=track, zone_ovh=zone_ovh)
        dm.DEVICEMEM.track("packed_result", [packed],
                           shape_class=shape_class)
        sp = (TRACER.span("solve.readback") if TRACER.enabled
              else NOOP_SPAN)
        with sp, dm.attributed(shape_class=shape_class):
            buf = _read(packed)  # ONE host read
            sp.set(d2h_bytes=int(buf.nbytes), shape=str(tuple(buf.shape)))
        (nused, overflowed, nnz, unsched, ntype, idx,
         vals) = _parse_packed(buf, Gp, n_max, k_max)
        if nnz > k_max:
            # sparse budget too small: takes were truncated — regrow & rerun
            k_max = _bucket(nnz)
            continue
        if not overflowed or not auto_n or n_max >= n_existing + total_pods:
            break
        n_max = min(_bucket(n_max * 2), _bucket(n_existing + total_pods))
        if mesh is not None:
            ms = int(mesh.size)
            n_max = -(-n_max // ms) * ms
        k_max = _bucket(2 * n_max)

    sp = (TRACER.span("solve.decode") if TRACER.enabled
          else NOOP_SPAN)
    with sp:
        result = _decode_solution(cat, enc, existing, node_cum, node_zmask,
                                  node_cmask, nused, ntype, idx, vals, nnz,
                                  unsched, n_max)
        sp.set(nodes=len(result.nodes), nnz=int(nnz))
        return result


def _parse_packed(buf: np.ndarray, Gp: int, n_max: int, k_max: int):
    """Split one packed int32 result vector by the layout documented on
    _solve_kernel_packed_impl — shared by the serial readback and every
    row of a batched readback."""
    nused, overflowed, nnz = int(buf[0]), bool(buf[1]), int(buf[2])
    o = 3
    unsched = buf[o: o + Gp]; o += Gp
    ntype = buf[o: o + n_max]; o += n_max
    idx = buf[o: o + k_max]; o += k_max
    vals = buf[o: o + k_max]
    return nused, overflowed, nnz, unsched, ntype, idx, vals


def _decode_solution(cat: CatalogTensors, enc: EncodedPods,
                     existing: List[VirtualNode], node_cum: np.ndarray,
                     node_zmask: np.ndarray, node_cmask: np.ndarray,
                     nused: int, ntype: np.ndarray, idx: np.ndarray,
                     vals: np.ndarray, nnz: int, unsched: np.ndarray,
                     n_max: int) -> SolveResult:
    """Host-side reconstruction (vectorized, no device reads) — the ONE
    decode the serial path and every batched row share, so the batched
    results stay byte-identical to serial solves by construction.

    pods_by_group keys refer to THIS enc's group indices; existing nodes'
    prior occupancy is baked into their input cum, so their dict reports
    only placements from this solve (same convention as solve_host)."""
    R = enc.requests.shape[1]
    G = enc.G
    n_existing = len(existing)
    n_total = min(nused, n_max)
    take_g = idx[:nnz] // n_max
    take_n = idx[:nnz] % n_max
    take_v = vals[:nnz]

    # cum: accumulate in ascending group order with the same f32 ops as the
    # kernel so golden tests agree bitwise
    cum = np.zeros((n_total, R), np.float32)
    cum[:n_existing] = node_cum[:n_existing]
    zmask = np.ones((n_total, cat.Z), bool)
    cmask = np.ones((n_total, cat.C), bool)
    zmask[:n_existing] = node_zmask[:n_existing]
    cmask[:n_existing] = node_cmask[:n_existing]
    fresh = np.ones(n_total, bool)
    fresh[:n_existing] = False
    t_avail_z = cat.available.any(axis=2)  # [T, Z]
    t_avail_c = cat.available.any(axis=1)  # [T, C]
    nt = ntype[:n_total]
    zmask[fresh] = t_avail_z[nt[fresh]]
    cmask[fresh] = t_avail_c[nt[fresh]]

    # per-group vectorized accumulation in ascending group order — the same
    # f32 add sequence per node as the kernel's scan, so values agree bitwise
    pods_by_node: List[dict] = [dict() for _ in range(n_total)]
    in_range = take_n < n_total
    for g in range(G):
        sel = (take_g == g) & in_range
        if not sel.any():
            continue
        ns = take_n[sel]
        vs = take_v[sel]
        cum[ns] = cum[ns] + vs[:, None].astype(np.float32) * enc.requests[g][None, :].astype(np.float32)
        zmask[ns] &= enc.allow_zone[g]
        cmask[ns] &= enc.allow_cap[g]
        for n, v in zip(ns.tolist(), vs.tolist()):
            pods_by_node[n][g] = v

    nodes: List[VirtualNode] = []
    for i in range(n_total):
        nodes.append(VirtualNode(
            type_idx=int(nt[i]), zone_mask=zmask[i], cap_mask=cmask[i],
            cum=cum[i], pods_by_group=pods_by_node[i],
            banned_groups=existing[i].banned_groups if i < n_existing else None,
            existing_name=existing[i].existing_name if i < n_existing else None))

    unschedulable = {g: int(unsched[g]) for g in range(G) if unsched[g] > 0}
    result = SolveResult(nodes=nodes, unschedulable=unschedulable)
    # launch decisions straight from the dense arrays already in hand —
    # finalize_offerings would re-stack per-node masks from the objects
    # (several ms at 2k+ nodes, pure Python attribute traffic); the
    # policy itself is the shared cheapest_offerings
    fi = np.nonzero(fresh)[0]
    if fi.size:
        from .binpack import cheapest_offerings
        result.launches = cheapest_offerings(nt[fi], zmask[fi], cmask[fi],
                                             cat)
    return result
