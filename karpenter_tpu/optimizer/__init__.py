"""Global disruption optimizer: combinatorial repack search (ROADMAP 3).

Consolidation used to be "screen + greedy": a dense candidate screen
(ops/consolidate.py) followed by single-node selection and a PREFIX-only
multi-node binary search (controllers/disruption.py) mirroring the
reference's budgeted heuristic. Multi-node savings that require JOINT
eviction of a non-prefix subset were structurally invisible — the
cheapest-to-disrupt candidate being un-repackable blinded the search to
everything behind it.

This package turns consolidation into a global search:

1. **subsets.py** — a seeded candidate-subset generator (exhaustive for
   small pools, slack-guided + hash-sampled past the budget) producing
   a batched [S, N] victim-mask tensor;
2. **tournament.py** — a repack-feasibility + cost-delta tournament
   scoring all S subsets in ONE dispatch, reusing the screen's
   CatalogTensors/EncodedPods encodings, with a device path that shards
   the subset axis across the mesh exactly like the screen shards its
   node axis;
3. **relax.py** — an LP/convex-relaxation scoring pass (fractional
   repack by projected proportional fitting, jitted) that ranks the
   feasible subsets by cross-group contention BEFORE the handful of
   exact `Solver.solve()` verifications — the CvxCluster recipe;
4. integration behind `KARPENTER_TPU_OPTIMIZER` in
   `DisruptionController._multi_node` (=0 restores the greedy path
   byte-for-byte), honoring budgets, PDBs, the spot flexibility floor,
   and the pending-disruption revalidation unchanged. Every EXECUTED
   disruption still passes a real exact solve — the optimizer only
   proposes; `Solver.solve()` disposes.

Observability: `consolidation_savings_total{source}` meters realized
$/hr by decision source, `optimizer_subsets_total{event}` the search
funnel, the `optimizer_search`/`optimizer_verify` phase buckets land the
wall time in the profile ledger, and the watchdog's
`optimizer_divergence` invariant fires when exact verification keeps
rejecting the relaxation's ranked picks (stats.OPTIMIZER reject streak).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .relax import RELAX_ITERS
from .stats import OPTIMIZER
from .subsets import MAX_K, MAX_SUBSETS, evictability, generate_subsets
from .tournament import (repack_inputs, score_subsets_device,
                         score_subsets_host)

OPTIMIZER_ENV = "KARPENTER_TPU_OPTIMIZER"
# relaxation tensor budget (S*N*G elements): the subset batch shrinks
# before the [S, N, G] fractional-repack tensor outgrows memory
RELAX_BUDGET = 8_000_000
# residual (fractional unplaced pods) below this counts as "fractionally
# repackable" for ranking purposes
RESIDUAL_EPS = 1e-3
# exact verifications attempted per pass, independent of subset count
VERIFY_LIMIT = 8


def optimizer_enabled() -> bool:
    """The opt-out gate: KARPENTER_TPU_OPTIMIZER=0 restores the greedy
    multi-node path byte-for-byte (default: armed)."""
    return os.environ.get(OPTIMIZER_ENV, "1") not in ("0", "false", "no")


@dataclass
class RepackPlan:
    """Ranked output of one subset search: `subsets` are view-index
    tuples ordered by expected value (feasible, low relaxation residual,
    high savings first) — the exact-verify queue."""

    subsets: List[Tuple[int, ...]] = field(default_factory=list)
    savings: List[float] = field(default_factory=list)
    residuals: List[float] = field(default_factory=list)
    scored: int = 0
    feasible: int = 0
    exhaustive: bool = True
    backend: str = "host"
    search_s: float = 0.0


def plan_repack(cat, enc, views: Sequence, counts: np.ndarray,
                slack: np.ndarray, candidate_idx: Sequence[int],
                max_k: int = MAX_K, *,
                exclude: Optional[np.ndarray] = None,
                use_device: bool = False, mesh=None,
                max_subsets: int = MAX_SUBSETS,
                iters: int = RELAX_ITERS, seed: int = 0) -> RepackPlan:
    """Run the tournament over subsets of `candidate_idx` (positions in
    `views`) and return the ranked exact-verify queue. Deterministic for
    fixed inputs — the chaos repeat contract."""
    t0 = time.perf_counter()
    from .tournament import group_slot_prices
    N = len(views)
    prices = np.array([float(v.price) for v in views], np.float32)
    G = max(int(enc.G), 1)
    cap = max(16, RELAX_BUDGET // max(N * G, 1))
    max_subsets = min(max_subsets, cap)
    per_slot = group_slot_prices(cat, enc)
    guide = evictability(slack, counts, prices, candidate_idx, per_slot)
    subs, exhaustive = generate_subsets(len(candidate_idx), guide,
                                        max_k=max_k,
                                        max_subsets=max_subsets, seed=seed)
    if not subs:
        return RepackPlan(backend="host")
    cand = np.asarray(list(candidate_idx), np.int64)
    masks = np.zeros((len(subs), N), np.float32)
    for si, s in enumerate(subs):
        masks[si, cand[list(s)]] = 1.0
    if use_device:
        feasible, savings, residual, repl_lb = score_subsets_device(
            cat, enc, views, counts, prices, masks, mesh=mesh,
            iters=iters, exclude=exclude)
        backend = "mesh" if mesh is not None else "device"
        if exclude is not None and exclude.any():
            # supply-side exclusion rode the active bit into the kernel
            # (same as the host path); subsets CONTAINING an excluded
            # node as a victim are struck host-side
            bad = masks[:, exclude].any(axis=1)
            feasible = feasible & ~bad
            savings = np.where(bad, np.float32(0.0), savings)
    else:
        headroom, group_req, _elig, k, _active = repack_inputs(
            cat, enc, views, counts, exclude=exclude)
        feasible, savings, residual, repl_lb = score_subsets_host(
            headroom, group_req, k, counts, prices, masks, per_slot,
            iters=iters)
        backend = "host"
    # two tiers in one ranking: replacement-FREE repacks (per-group
    # feasible AND ~zero fractional residue) by gross savings, then
    # replacement-BACKED subsets (residue priced by the lower bound) by
    # NET savings — the exact solve re-prices both, this only decides
    # who gets a slot in the verify budget
    repack_free = feasible & (residual <= RESIDUAL_EPS)
    net = savings - repl_lb
    value = np.where(repack_free, np.float32(1e6) + savings,
                     np.where(net > 0, net, np.float32(-1.0)))
    order = [i for i in np.argsort(-value, kind="stable")
             if value[i] > 0]
    search_s = time.perf_counter() - t0
    plan = RepackPlan(
        subsets=[tuple(int(c) for c in cand[list(subs[i])])
                 for i in order],
        savings=[float(savings[i]) for i in order],
        residuals=[float(residual[i]) for i in order],
        scored=len(subs), feasible=int(np.count_nonzero(repack_free)),
        exhaustive=exhaustive, backend=backend, search_s=search_s)
    OPTIMIZER.record_scored(len(subs), search_s)
    from ..metrics import OPTIMIZER_SUBSETS
    OPTIMIZER_SUBSETS.inc(len(subs), event="scored")
    return plan


__all__ = ["OPTIMIZER", "OPTIMIZER_ENV", "RepackPlan",
           "MAX_K", "MAX_SUBSETS", "VERIFY_LIMIT", "optimizer_enabled",
           "plan_repack", "repack_inputs", "generate_subsets",
           "evictability"]
