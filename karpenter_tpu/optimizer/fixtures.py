"""Synthetic joint-consolidation fleets — the optimizer's proving ground.

Builds a deterministic underutilized fleet whose savings are INVISIBLE
to the greedy multi-node prefix search but provable by the global
optimizer, used by bench c14, `make disrupt-report`, and the seeded
regression tests. The structure, per 6-node tile (all nodes one pinned
instance type, allocatable cpu = c):

    A, B, C   anchor pods of c-2 cpu (free 2): too big for ANY
              survivor's headroom AND two per fresh node — every greedy
              prefix {A,B,...} needs ≥2 replacement launches and is
              rejected (the >1-launch rule);
    D         two 3-cpu pods (free c-6);
    E, F      one 3-cpu pod each (free c-3).

Greedy multi-node (cost-ordered prefixes always start at the anchors)
finds NOTHING. The joint pair {E, F} repacks replacement-free onto D
(3+3 ≤ c-6 for c ≥ 12) — the 2-node consolidation only a subset search
sees. Deletion costs order the candidates anchors-first, so the miss is
structural, not a tie-break accident.
"""

from __future__ import annotations

from typing import Dict

from ..models import labels as L
from ..models.nodepool import Budget, DisruptionSpec
from ..models.pod import Pod
from ..models.requirements import Operator, Requirement, Requirements
from ..models.resources import Resources

ITYPE = "c5.4xlarge"   # 16 vcpu in the small catalog; allocatable ~15
SQUEEZE_SMALL = "c5.xlarge"   # 4 vcpu — the squeeze fleet's victim type


def _pod(name: str, cpu: float, deletion_cost: int = 0) -> Pod:
    return Pod(name=name,
               requests=Resources.parse({"cpu": f"{cpu:g}",
                                         "memory": "1Gi"}),
               deletion_cost=deletion_cost)


def build_joint_fleet(sim, tiles: int = 1, itype: str = ITYPE,
                      settle_timeout: float = 600.0) -> Dict[str, object]:
    """Provision `tiles` 6-node tiles on `sim` (host backend), then
    rewrite three nodes per tile into the D/E/F shape by direct store
    binds. Returns {"alloc_cpu", "nodes", "pair_savings"} for asserts.

    The pool is pinned to one instance type (uniform arithmetic) and
    given an explicit node budget so the multi-node pass is never
    budget-starved."""
    pool = sim.store.nodepools["default"]
    pool.requirements = Requirements(
        Requirement(L.INSTANCE_TYPE, Operator.IN, (itype,)))
    pool.disruption = DisruptionSpec(budgets=[Budget(nodes="30")])
    cat = sim.solver.tensors(sim.store.nodeclasses["default"])
    t_idx = cat.name_to_idx[itype]
    alloc_cpu = float(cat.allocatable[t_idx, 0])
    assert alloc_cpu >= 12.0, f"{itype} allocatable {alloc_cpu} < 12"
    anchor_cpu = alloc_cpu - 2.0
    n_nodes = 6 * tiles
    for i in range(n_nodes):
        sim.store.add_pod(_pod(f"anchor-{i:03d}", anchor_cpu))
    ok = sim.engine.run_until(
        lambda: all(p.node_name is not None
                    for p in sim.store.pods.values()),
        timeout=settle_timeout)
    assert ok, "anchor fleet failed to settle"
    claims = sorted(sim.store.nodeclaims.values(), key=lambda c: c.name)
    assert len(claims) == n_nodes, (len(claims), n_nodes)
    pair_savings = 0.0
    for tile in range(tiles):
        d, e, f = claims[6 * tile + 3: 6 * tile + 6]
        pair_savings += e.price + f.price
        for role, claim, pods in (
                ("d", d, [("x", 3.0, 800), ("y", 3.0, 800)]),
                ("e", e, [("x", 3.0, 500)]),
                ("f", f, [("x", 3.0, 600)])):
            node = sim.store.node_for_nodeclaim(claim)
            assert node is not None
            for p in list(sim.store.pods_on_node(node.name)):
                sim.store.delete_pod(p.namespace, p.name)
            for suffix, cpu, cost in pods:
                pod = _pod(f"{role}{tile}-{suffix}", cpu,
                           deletion_cost=cost)
                sim.store.add_pod(pod)
                sim.store.bind_pod(pod, node.name)
    return {"alloc_cpu": alloc_cpu, "nodes": n_nodes,
            "pair_savings": pair_savings / max(tiles, 1),
            "claims": [c.name for c in claims]}


def build_squeeze_fleet(sim, tiles: int = 1,
                        settle_timeout: float = 600.0) -> Dict[str, object]:
    """The bench c14 fleet: savings GREEDY REALIZES NOTHING OF. Per
    tile: 3 big anchors (c-2 cpu on the pinned 16-vcpu type — every
    greedy multi-node prefix starts with two of them and needs >=2
    replacement launches, rejected) plus 5 one-pod `c5.xlarge` victims
    (3 cpu each, free ~0.9). Single-node consolidation fails everywhere:
    no survivor holds 3 free cpu, and the cheapest fresh node for one
    3-cpu pod IS another c5.xlarge — `new_price >= victim price` is
    rejected. Replacing k<5 victims fails the same price test (linear
    in-family pricing: k xlarge == one (4k/4)xlarge). ONLY the joint
    5-victim squeeze onto one fresh c5.4xlarge is strictly cheaper
    (5 x 0.17 > 0.68) — a replacement-backed joint eviction no prefix
    search and no single-node pass can represent. The pool pins
    on-demand capacity so the spot flexibility floor is out of frame."""
    pool = sim.store.nodepools["default"]
    cat = sim.solver.tensors(sim.store.nodeclasses["default"])
    alloc_cpu = float(cat.allocatable[cat.name_to_idx[ITYPE], 0])
    anchor_cpu = alloc_cpu - 2.0
    od = Requirement(L.CAPACITY_TYPE, Operator.IN, ("on-demand",))
    # zero budget during construction: the per-phase type pins below
    # would otherwise read as requirements drift on the OTHER phase's
    # nodes and roll them mid-build
    pool.disruption = DisruptionSpec(budgets=[Budget(nodes="0")])

    def settle():
        ok = sim.engine.run_until(
            lambda: all(p.node_name is not None
                        for p in sim.store.pods.values()),
            timeout=settle_timeout)
        assert ok, "squeeze fleet failed to settle"

    # phase 1: victims on the SMALL type (one 3-cpu pod per c5.xlarge —
    # 3+3 exceeds its allocatable, so they cannot share); the pin is
    # what a dedicated small-pool or an arrival-fragmented history
    # produces, which is exactly the shape consolidation exists to fix
    pool.requirements = Requirements(
        Requirement(L.INSTANCE_TYPE, Operator.IN, (SQUEEZE_SMALL,)), od)
    for tile in range(tiles):
        for i in range(5):
            # deletion costs order the victims AFTER the anchors in the
            # greedy cost order — the structural blind spot
            sim.store.add_pod(_pod(f"squeeze-{tile}-{i}", 3.0,
                                   deletion_cost=500 + i))
    settle()
    # phase 2: the big anchors
    pool.requirements = Requirements(
        Requirement(L.INSTANCE_TYPE, Operator.IN, (ITYPE,)), od)
    for tile in range(tiles):
        for i in range(3):
            sim.store.add_pod(_pod(f"anchor-{tile}-{i}", anchor_cpu))
    settle()
    # final shape: both types allowed (no drift — every node's label is
    # in the live set), real disruption budget restored
    pool.requirements = Requirements(
        Requirement(L.INSTANCE_TYPE, Operator.IN, (ITYPE, SQUEEZE_SMALL)),
        od)
    pool.disruption = DisruptionSpec(budgets=[Budget(nodes="30")])
    claims = list(sim.store.nodeclaims.values())
    small = [c for c in claims if c.instance_type == SQUEEZE_SMALL]
    big = [c for c in claims if c.instance_type == ITYPE]
    assert len(small) == 5 * tiles and len(big) == 3 * tiles, (
        sorted(c.instance_type for c in claims))
    od_i = cat.captypes.index("on-demand")
    ti = cat.name_to_idx[ITYPE]
    big_price = float(cat.price[ti, :, od_i][
        cat.available[ti, :, od_i]].min())
    victims_price = sum(c.price for c in small)
    return {"alloc_cpu": alloc_cpu, "nodes": len(claims),
            "victims_price": victims_price,
            "big_price": big_price,
            "squeeze_savings": victims_price - tiles * big_price}


def measure_consolidation(fleet: str = "squeeze", tiles: int = 2,
                          armed: bool = True,
                          run_for: float = 900.0) -> Dict[str, object]:
    """Build one fleet, run it for `run_for` sim seconds with the
    optimizer armed or disarmed, and return what that decision path
    realized — the ONE measurement procedure bench c14 and `make
    disrupt-report` share (identical windows for both modes, so the
    compared savings are measured under identical conditions). Saves
    and restores KARPENTER_TPU_OPTIMIZER."""
    import os
    import time

    from ..metrics import CONSOLIDATION_SAVINGS
    from ..sim import make_sim
    from . import OPTIMIZER_ENV
    from .stats import OPTIMIZER
    build = build_squeeze_fleet if fleet == "squeeze" else build_joint_fleet
    source = "optimizer" if armed else "greedy"
    prev = os.environ.get(OPTIMIZER_ENV)
    os.environ[OPTIMIZER_ENV] = "1" if armed else "0"
    try:
        base = CONSOLIDATION_SAVINGS.sum(source=source)
        tot0 = OPTIMIZER.totals()
        sim = make_sim(backend="host")
        build(sim, tiles=tiles)
        n0 = len(sim.store.nodeclaims)
        t0 = time.perf_counter()
        sim.engine.run_for(run_for, step=5)
        wall = time.perf_counter() - t0
        tot1 = OPTIMIZER.totals()
    finally:
        if prev is None:
            os.environ.pop(OPTIMIZER_ENV, None)
        else:
            os.environ[OPTIMIZER_ENV] = prev
    st = sim.disruption.stats
    return {
        "mode": source,
        "nodes_before": n0,
        "nodes_after": len(sim.store.nodeclaims),
        "savings": round(CONSOLIDATION_SAVINGS.sum(source=source) - base,
                         4),
        "multi_consolidated": int(st.get("multi_consolidated", 0)),
        "single_consolidated": int(st.get("consolidated", 0)),
        "joint_consolidations": int(st.get("optimizer_consolidated", 0)),
        "subsets_scored": int(tot1["scored"] - tot0["scored"]),
        "exact_verifies": int(tot1["verified"] - tot0["verified"]),
        "verify_accepts": int(tot1["accepted"] - tot0["accepted"]),
        "search_s": round(tot1["search_s"] - tot0["search_s"], 4),
        "screen_cache_hits": int(st.get("screen_cache_hits", 0)),
        "wall_s": round(wall, 2),
        "all_bound": all(p.node_name is not None
                         for p in sim.store.pods.values()),
    }
