"""Convex-relaxation scoring: fractional repack of victim subsets.

The per-group tournament screen (tournament.py) is an over-approximation
— it checks each pod group against the survivors' headroom SEPARATELY,
so two groups that individually fit but jointly exceed a node's capacity
still screen feasible, and the exact `Solver.solve()` verification then
wastes a full re-solve rejecting them. This module scores each subset
with the natural LP relaxation of the repack instead: place FRACTIONAL
pods of each victim group onto surviving nodes, subject to per-node
multi-resource capacity and per-(node, group) eligibility caps, and
report the unplaceable fractional residue.

The relaxation is solved by projected proportional fitting (a damped
Sinkhorn-style alternation between the group-demand constraints and the
node-capacity simplex), a fixed small number of iterations so it jits to
one fused kernel — the CvxCluster recipe of trading an exact
combinatorial solve for a convex surrogate that ranks candidates in
microseconds (PAPERS.md), with `Solver.solve()` retained as the exact
arbiter for the handful of winners.

residual == 0  ⇒ the subset is fractionally repackable (cross-group
                 contention included) — verify it first;
residual >> 0  ⇒ the per-group screen was fooled; rank it last (and
                 usually never spend an exact solve on it).

The same function body serves NumPy (host path, tier-1) and jax.numpy
(device path) via the `xp` module parameter — one implementation, two
backends, no drift.
"""

from __future__ import annotations

import numpy as np

RELAX_ITERS = 6
_BIG = np.float32(1e9)
_EPS = np.float32(1e-6)


def relax_residuals(xp, headroom, group_req, k, masks, need,
                    iters: int = RELAX_ITERS):
    """Fractional-repack residue per subset.

    headroom  [N, R]  survivors' free capacity (victims' rows are dead
                      weight — their columns are zeroed via `masks`)
    group_req [G, R]  per-pod resource vector per group
    k         [N, G]  per-(node, group) placement cap (eligibility +
                      single-resource fit, the screen's k)
    masks     [S, N]  victim masks (1.0 = evicted)
    need      [S, G]  pods of each group the subset must rehome

    Returns residual [S, G] — fractional pods of each group with no
    feasible home (all-zero row = fractionally repackable). All
    float32, no in-place ops, safe under jit."""
    headroom = xp.maximum(headroom, 0.0)
    surv = 1.0 - masks                                    # [S, N]
    cap = surv[:, :, None] * k[None, :, :]                # [S, N, G]
    denom = cap.sum(axis=1) + _EPS                        # [S, G]
    x = cap * (need / denom)[:, None, :]                  # proportional seed
    for _ in range(int(iters)):
        load = xp.einsum("sng,gr->snr", x, group_req)     # [S, N, R]
        ratio = xp.where(load > _EPS,
                         headroom[None, :, :] / xp.maximum(load, _EPS),
                         _BIG)
        scale = xp.clip(ratio.min(axis=2), 0.0, 1.0)      # [S, N]
        x = x * scale[:, :, None]                         # capacity proj
        deficit = xp.maximum(need - x.sum(axis=1), 0.0)   # [S, G]
        slack = xp.maximum(cap - x, 0.0)                  # [S, N, G]
        sden = slack.sum(axis=1) + _EPS
        x = x + slack * (deficit / sden)[:, None, :]      # demand proj
    # one last capacity projection, then measure what never found a home
    load = xp.einsum("sng,gr->snr", x, group_req)
    ratio = xp.where(load > _EPS,
                     headroom[None, :, :] / xp.maximum(load, _EPS), _BIG)
    scale = xp.clip(ratio.min(axis=2), 0.0, 1.0)
    x = x * scale[:, :, None]
    return xp.maximum(need - x.sum(axis=1), 0.0)       # [S, G]


def replacement_lower_bound(xp, residual, per_slot):
    """$/hr estimate of the NEW capacity a subset's fractionally
    unplaceable residue would force open: residual pods per group
    priced at that group's best price-per-slot — the SAME
    price-per-pod-slot metric the exact solver opens nodes with
    (ops/binpack solve_host step 2), so the ranking and the verdict
    share one cost model. Exact pricing belongs to `Solver.solve()` —
    this only decides who gets a slot in the verify budget.

    residual [S, G] (relax_residuals), per_slot [G] ($/pod-slot/hr,
    BIG where no type can host the group). Returns [S]."""
    return residual @ per_slot                          # [S]
