"""Process-global optimizer meter — the observability face of the search.

One meter per process (like `obs.profile.LEDGER` / `obs.devicemem`
ledgers): the disruption controllers of every tenant shard record their
subset-search and exact-verify outcomes here under the live tenant scope
(metrics/tenant.py), and the watchdog's `optimizer_divergence` invariant
reads the per-tenant reject streaks — a relaxation ranking that keeps
proposing subsets the exact solver rejects has diverged from solve
semantics and must be visible the moment it happens, not after a bench
run.
"""

from __future__ import annotations

import threading
from typing import Dict


class OptimizerMeter:
    """Per-tenant counters for the global disruption optimizer:

    - ``scored``      subsets scored by the tournament kernel
    - ``verified``    exact `Solver.solve()` verifications attempted
    - ``accepted``    verifications that confirmed the subset (executed)
    - ``rejected``    verifications the exact solver refused
    - ``reject_streak`` consecutive rejects since the last accept — the
      watchdog's divergence signal (an accept resets it to zero)
    - ``fallbacks``   searches that degraded to the greedy path
    - ``search_s``    cumulative wall seconds spent in subset search
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: Dict[str, Dict[str, float]] = {}

    def _row(self, tenant: str) -> Dict[str, float]:
        return self._tenants.setdefault(tenant, {
            "scored": 0, "verified": 0, "accepted": 0, "rejected": 0,
            "reject_streak": 0, "fallbacks": 0, "search_s": 0.0})

    @staticmethod
    def _tenant() -> str:
        from ..metrics.tenant import current_tenant
        return current_tenant()

    def record_scored(self, n: int, search_s: float = 0.0,
                      tenant: str = "") -> None:
        with self._lock:
            row = self._row(tenant or self._tenant())
            row["scored"] += int(n)
            row["search_s"] += float(search_s)

    def record_verify(self, accepted: bool, tenant: str = "") -> None:
        with self._lock:
            row = self._row(tenant or self._tenant())
            row["verified"] += 1
            if accepted:
                row["accepted"] += 1
                row["reject_streak"] = 0
            else:
                row["rejected"] += 1
                row["reject_streak"] += 1

    def record_fallback(self, tenant: str = "") -> None:
        with self._lock:
            self._row(tenant or self._tenant())["fallbacks"] += 1

    # --- read side (watchdog + reports) -----------------------------------
    def reject_streaks(self) -> Dict[str, int]:
        """tenant -> consecutive exact-verify rejects since the last
        accept — the `optimizer_divergence` observable."""
        with self._lock:
            return {t: int(r["reject_streak"])
                    for t, r in self._tenants.items()}

    def verify_hit_rate(self, tenant: str = "") -> float:
        with self._lock:
            row = self._tenants.get(tenant or self._tenant())
            if not row or not row["verified"]:
                return 0.0
            return row["accepted"] / row["verified"]

    def snapshot(self) -> dict:
        with self._lock:
            return {t: dict(r) for t, r in sorted(self._tenants.items())}

    def totals(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {
                "scored": 0, "verified": 0, "accepted": 0, "rejected": 0,
                "fallbacks": 0, "search_s": 0.0}
            for row in self._tenants.values():
                for key in out:
                    out[key] += row[key]
        return out


OPTIMIZER = OptimizerMeter()
