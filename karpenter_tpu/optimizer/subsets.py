"""Seeded candidate-subset generation for the repack tournament.

The greedy multi-node pass only ever considers PREFIXES of the
cost-ordered candidate list; the search space of the global optimizer
is arbitrary size-2..K subsets. Exhaustive enumeration is exact for
small candidate pools; past the subset budget the generator goes
guided + sampled:

- **guided**: candidates are ranked by a screen-slack evictability
  score (how much per-group headroom the OTHER nodes hold for this
  node's pods, from the consolidation screen's slack output) broken by
  price (bigger savings first), and the densest region of the ranking
  is enumerated exhaustively;
- **sampled**: the remaining budget is filled with subsets drawn by a
  keyed blake2b hash of (seed, draw index) — deterministic by
  construction, no RNG stream is consumed, so the chaos repeat
  contract (`--repeat 2` identical hashes with the optimizer armed)
  holds without coordinating with the FaultPlan's generator.

Everything returns subsets as tuples of CANDIDATE positions in a fixed
deterministic order; the caller scatters them into [S, N] victim masks
over the full node-view axis.
"""

from __future__ import annotations

import hashlib
from itertools import combinations
from math import comb
from typing import List, Sequence, Tuple

import numpy as np

MAX_SUBSETS = 256      # default tournament batch bound
MAX_K = 5              # largest joint eviction considered by default
_GUIDED_PAIR_POOL = 24  # top-of-ranking pool enumerated pairwise
_GUIDED_TRIPLE_POOL = 10
_GUIDED_DEEP_POOL = 8   # top pool enumerated at sizes 4..max_k


def evictability(slack: np.ndarray, counts: np.ndarray,
                 prices: np.ndarray, cand_idx: Sequence[int],
                 per_slot: np.ndarray) -> np.ndarray:
    """Guide score per candidate (higher = more promising victim): the
    node's standalone NET-savings upper bound — its price minus the
    per-slot replacement cost of its own resident pods (the same rate
    card the subset ranking prices residues with). A cheap node full of
    expensive-to-rehome pods guides low; an expensive node whose pods
    rehome for pennies guides high. The screen's slack margin (`others
    - need`) breaks ties toward nodes the cluster can absorb
    replacement-free."""
    out = np.zeros(len(cand_idx), np.float32)
    pmax = float(prices.max()) if len(prices) else 1.0
    for j, i in enumerate(cand_idx):
        resident = counts[i] > 0
        rehome = float((counts[i] * np.minimum(per_slot, 1e6)).sum())
        margin = float(slack[i][resident].min()) if resident.any() else 0.0
        out[j] = (float(prices[i]) - rehome
                  + 1e-3 * np.tanh(margin) * max(pmax, 1e-9))
    return out


def _hash_draw(seed: int, draw: int, size: int, pool: int) -> Tuple[int, ...]:
    """Deterministic subset of `size` distinct indices out of `pool`,
    keyed by (seed, draw) — a keyed hash, never a shared RNG stream."""
    members: List[int] = []
    salt = 0
    while len(members) < size:
        h = hashlib.blake2b(f"{seed}|{draw}|{salt}".encode(),
                            digest_size=8).digest()
        idx = int.from_bytes(h, "big") % pool
        if idx not in members:
            members.append(idx)
        salt += 1
        if salt > 16 * size:   # degenerate pool; bail deterministically
            break
    return tuple(sorted(members))


def generate_subsets(n_candidates: int, guide: np.ndarray,
                     max_k: int = MAX_K,
                     max_subsets: int = MAX_SUBSETS,
                     seed: int = 0) -> Tuple[List[Tuple[int, ...]], bool]:
    """Size-2..max_k subsets of candidate positions, at most
    `max_subsets`, in a deterministic order. Returns (subsets,
    exhaustive) — exhaustive=True means every subset in range was
    enumerated, so a miss is a true negative of the tournament, not a
    sampling artifact."""
    C = int(n_candidates)
    max_k = max(2, min(int(max_k), C))
    if C < 2:
        return [], True
    total = sum(comb(C, k) for k in range(2, max_k + 1))
    if total <= max_subsets:
        out = [s for k in range(2, max_k + 1)
               for s in combinations(range(C), k)]
        return out, True
    # guided region: stable descending-evictability order, with the
    # subset budget SLICED per size — pairs must not starve the deep
    # joint evictions (a 5-victim squeeze is exactly the shape the
    # search exists for)
    order = [int(i) for i in np.argsort(-guide, kind="stable")]
    seen = set()
    out: List[Tuple[int, ...]] = []
    n_sizes = max_k - 1
    per_size = max(8, max_subsets // n_sizes)

    def push(subset: Tuple[int, ...]) -> bool:
        if subset in seen:
            return False
        seen.add(subset)
        out.append(subset)
        return len(out) >= max_subsets

    pools = {2: _GUIDED_PAIR_POOL, 3: _GUIDED_TRIPLE_POOL}
    for k in range(2, max_k + 1):
        pool = order[:min(C, pools.get(k, _GUIDED_DEEP_POOL))]
        taken = 0
        for combo in combinations(range(len(pool)), k):
            if taken >= per_size:
                break
            if push(tuple(sorted(pool[t] for t in combo))):
                return out, False
            taken += 1
    # sampled tail: deterministic keyed draws over the WHOLE candidate
    # pool (diversity past the guided region)
    draw = 0
    misses = 0
    while len(out) < max_subsets and misses < 4 * max_subsets:
        size = 2 + (draw % (max_k - 1)) if max_k > 2 else 2
        s = _hash_draw(seed, draw, size, C)
        draw += 1
        if len(s) != size or s in seen:
            misses += 1
            continue
        seen.add(s)
        out.append(s)
    return out, False
