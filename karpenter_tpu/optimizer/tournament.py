"""Batched repack tournament: score S victim subsets in one dispatch.

The consolidation screen (ops/consolidate.py) answers "could node n's
pods re-schedule onto the OTHERS" for every node at once; the tournament
generalizes it to SUBSETS: for each candidate subset s with victim mask
m_s ∈ {0,1}^N,

    need_s[g]   = Σ_{n∈s} counts[n, g]          pods to rehome
    supply_s[g] = Σ_{n∉s} k[n, g]               survivors' per-group caps
    feasible_s  = ∀g: need_s[g] ≤ supply_s[g]
    savings_s   = Σ_{n∈s} price[n]              (replacement-free repack)

where k[n, g] is the screen's per-(node, group) placement cap — computed
from the SAME CatalogTensors / EncodedPods encodings, so the tournament
and the screen can never disagree about headroom. The subset axis turns
the screen's [N, G] computation into [S, N]·[N, G] matmuls: all S
subsets score in one kernel call, and the convex-relaxation pass
(relax.py) rides the same dispatch to rank the feasible ones by
cross-group contention.

Two backends, byte-compatible by construction:

- **host** (numpy): tier-1 and the small-cluster path — the math above
  verbatim;
- **device** (jit): the packed-buffer idiom of `_screen_onebuf` — node-
  side and group-side inputs ship as two matrices (shared packing code
  with the screen), masks+prices as one [S+1, N] matrix, ONE packed
  [S, 3] readback. With a mesh, the SUBSET axis shards across the chips
  exactly like the screen's node axis (parallel/mesh.py recipe): each
  chip scores its slice of the tournament, the output replicates for
  the single host read.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

from ..ops.binpack import BIG, EPS
from .relax import RELAX_ITERS, relax_residuals, replacement_lower_bound


def group_slot_prices(cat, enc) -> np.ndarray:
    """[G] best $/pod-slot/hr per group — the replacement bound's rate
    card, matching the host solver's node-opening metric: over types
    compatible with the group, (cheapest offering surviving the group's
    zone/captype masks) / (pods of the group the type holds). BIG where
    no compatible available type can host the group."""
    from ..ops.encode import align_resources
    R = enc.requests.shape[1]
    alloc = align_resources(cat.allocatable, R)             # [T, R]
    req = enc.requests.astype(np.float32)                   # [G, R]
    with_req = np.where(req > 0, req, np.float32(1.0))
    slots = np.where(req[:, None, :] > 0,
                     np.floor(alloc[None, :, :] / with_req[:, None, :]
                              + EPS),
                     np.float32(BIG)).min(axis=2)           # [G, T]
    slots = np.where(enc.compat, np.maximum(slots, 0.0), 0.0)
    # cheapest offering per (group, type) surviving the group's masks
    mask = (cat.available[None, :, :, :]
            & enc.allow_zone[:, None, :, None]
            & enc.allow_cap[:, None, None, :])              # [G, T, Z, C]
    price = np.where(mask, cat.price[None], np.inf)
    price_gt = price.reshape(enc.G, cat.T, -1).min(axis=2)  # [G, T]
    per_slot = np.where(slots > 0, price_gt / np.maximum(slots, 1.0),
                        np.inf).min(axis=1)                 # [G]
    return np.where(np.isfinite(per_slot), per_slot,
                    np.float32(BIG)).astype(np.float32)


def repack_inputs(cat, enc, views, group_counts: np.ndarray,
                  exclude: Optional[np.ndarray] = None):
    """Host-side tournament inputs, shared with the screen's
    construction (`_screen_args`) so the two headroom views are
    identical: (headroom [N, R], group_req [G, R], elig [N, G],
    k [N, G], active [N])."""
    from ..ops.consolidate import _screen_args
    (alloc, avail, node_type, node_cum, node_zmask, node_cmask, active,
     req, compat, allow_zone, allow_cap, _counts) = _screen_args(
        cat, enc, views, group_counts)
    active = active.copy()
    if exclude is not None:
        active &= ~exclude
    talloc = alloc[node_type]                               # [N, R]
    headroom = (talloc - node_cum).astype(np.float32)
    ok_t = compat[:, node_type].T                           # [N, G]
    a = avail[node_type]                                    # [N, Z, C]
    off = np.einsum("nz,gz,nc,gc,nzc->ng",
                    node_zmask.astype(np.float32),
                    allow_zone.astype(np.float32),
                    node_cmask.astype(np.float32),
                    allow_cap.astype(np.float32),
                    a.astype(np.float32)) > 0               # [N, G]
    elig = ok_t & off & active[:, None]
    req = req.astype(np.float32)
    with_req = np.where(req > 0, req, np.float32(1.0))
    ratios = np.where(req[None, :, :] > 0,
                      np.floor(headroom[:, None, :] / with_req[None, :, :]
                               + EPS),
                      np.float32(BIG))                      # [N, G, R]
    k = np.where(elig, np.maximum(ratios.min(axis=2), 0.0),
                 np.float32(0.0)).astype(np.float32)
    return headroom, req, elig, k, active


def score_subsets_host(headroom: np.ndarray, group_req: np.ndarray,
                       k: np.ndarray, counts: np.ndarray,
                       prices: np.ndarray, masks: np.ndarray,
                       per_slot: np.ndarray,
                       iters: int = RELAX_ITERS,
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray]:
    """NumPy tournament: (feasible [S] bool — per-group replacement-free
    screen, savings [S] f32, residual [S] f32 — fractionally unplaced
    pods, repl_lb [S] f32 — replacement-cost lower bound for the
    residue)."""
    counts = counts.astype(np.float32)
    need = masks @ counts                                   # [S, G]
    supply = k.sum(axis=0)[None, :] - masks @ k             # [S, G]
    feasible = ((need <= supply + EPS) | (need == 0)).all(axis=1)
    savings = masks @ prices.astype(np.float32)             # [S]
    residual_g = relax_residuals(np, headroom, group_req, k, masks, need,
                                 iters=iters)               # [S, G]
    repl_lb = replacement_lower_bound(np, residual_g, per_slot)
    return (feasible, savings.astype(np.float32),
            np.asarray(residual_g.sum(axis=1), np.float32),
            np.asarray(repl_lb, np.float32))


# --- device path -----------------------------------------------------------
# Packed single-dispatch tournament, mirroring ops/consolidate's onebuf
# screen: nbuf/gbuf reuse the screen's packing helpers verbatim; mbuf
# packs the [S, N] victim masks with the price row appended so the whole
# subset side is ONE upload. Output is one packed [S, 3] buffer
# (feasible, savings, residual) — one blocking read.


def _tournament_impl(alloc, avail, nbuf, gbuf, mbuf, pslot, cols: tuple,
                     iters: int = RELAX_ITERS):
    import jax.numpy as jnp
    T, Z, C = avail.shape
    Rk = len(cols)
    G = gbuf.shape[0]
    cix = jnp.asarray(np.asarray(cols, np.int32))
    alloc_k = alloc[:, cix]
    req = gbuf[:, :Rk]
    o = Rk
    compat = gbuf[:, o:o + T] > 0; o += T
    allow_zone = gbuf[:, o:o + Z] > 0; o += Z
    allow_cap = gbuf[:, o:o + C] > 0
    node_type = nbuf[:, 0].astype(jnp.int32)
    o = 1
    node_cum = nbuf[:, o:o + Rk]; o += Rk
    node_zmask = nbuf[:, o:o + Z] > 0; o += Z
    node_cmask = nbuf[:, o:o + C] > 0; o += C
    active = nbuf[:, o] > 0; o += 1
    counts = nbuf[:, o:o + G]
    masks = mbuf[:-1]                                     # [S, N]
    prices = mbuf[-1]                                     # [N]
    talloc = alloc_k[node_type]
    headroom = talloc - node_cum
    ok_t = compat[:, node_type].T
    a = avail[node_type]
    off = jnp.einsum("nz,gz,nc,gc,nzc->ng",
                     node_zmask.astype(jnp.float32),
                     allow_zone.astype(jnp.float32),
                     node_cmask.astype(jnp.float32),
                     allow_cap.astype(jnp.float32),
                     a.astype(jnp.float32)) > 0
    elig = ok_t & off & active[:, None]
    with_req = jnp.where(req > 0, req, 1.0)
    ratios = jnp.where(req[None, :, :] > 0,
                       jnp.floor(headroom[:, None, :] / with_req[None, :, :]
                                 + EPS),
                       jnp.asarray(BIG, jnp.float32))
    k = jnp.where(elig, jnp.maximum(ratios.min(axis=2), 0.0), 0.0)
    need = masks @ counts
    supply = k.sum(axis=0)[None, :] - masks @ k
    feasible = ((need <= supply + EPS) | (need == 0)).all(axis=1)
    savings = masks @ prices
    residual_g = relax_residuals(jnp, headroom, req, k, masks, need,
                                 iters=iters)             # [S, G]
    repl_lb = replacement_lower_bound(jnp, residual_g, pslot)
    return jnp.stack([feasible.astype(jnp.float32), savings,
                      residual_g.sum(axis=1), repl_lb],
                     axis=1).reshape(-1)                  # packed [S*4]


_jit_tournament = None


def _tournament_fn():
    global _jit_tournament
    if _jit_tournament is None:
        import jax
        _jit_tournament = jax.jit(_tournament_impl,
                                  static_argnames=("cols", "iters"))
    return _jit_tournament


# mesh-jitted tournaments, keyed on the (hashable) Mesh + cols — the
# same bound-cache discipline as consolidate._mesh_screen_fn
_mesh_cache: dict = {}
_MESH_CACHE_MAX = 16


def _mesh_tournament_fn(mesh, cols: tuple, iters: int):
    """Subset-axis-sharded tournament: the [S+1, N] mask matrix shards
    its subset rows over the mesh (each chip scores its slice; the
    price row rides the last shard's padding), node/group inputs
    replicate, output replicates for the single host read."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax
    key = (mesh, cols, iters)
    fn = _mesh_cache.get(key)
    if fn is None:
        if len(_mesh_cache) >= _MESH_CACHE_MAX:
            _mesh_cache.clear()
        fn = jax.jit(partial(_tournament_impl, cols=cols, iters=iters),
                     out_shardings=NamedSharding(mesh, P()))
        _mesh_cache[key] = fn
    return fn


def score_subsets_device(cat, enc, views, group_counts: np.ndarray,
                         prices: np.ndarray, masks: np.ndarray,
                         mesh=None, iters: int = RELAX_ITERS,
                         exclude: Optional[np.ndarray] = None,
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray]:
    """Device tournament: same results as score_subsets_host, one packed
    dispatch (optionally subset-sharded over `mesh`). `exclude` [N]
    strikes nodes (pending victims, deleting claims) from the SUPPLY
    side by clearing their active bit — the same `active &= ~exclude`
    the host path applies, so the two backends agree about who may
    absorb a repack. Probes the chaos device-fault seam like every
    other kernel dispatch."""
    from ..obs import devicemem as _dm
    from ..ops import solver as _solver_mod
    from ..ops.consolidate import (_pack_screen_groups, _pack_screen_nodes,
                                   _screen_args)
    from ..ops.solver import _auto_dcat, _put, _put_sharded, _read, \
        _request_cols
    if _solver_mod._dispatch_fault_hook is not None:
        _solver_mod._dispatch_fault_hook("optimizer")
    S = masks.shape[0]
    R = enc.requests.shape[1]
    cols = _request_cols(enc, cat)
    (_, _, node_type, node_cum, node_zmask, node_cmask, active,
     req, compat, allow_zone, allow_cap, counts) = _screen_args(
        cat, enc, views, group_counts)
    if exclude is not None:
        active = active & ~exclude
    nbuf_np = _pack_screen_nodes(node_type, node_cum, node_zmask,
                                 node_cmask, active, counts, list(cols))
    gbuf_np = _pack_screen_groups(req, compat, allow_zone, allow_cap,
                                  list(cols))
    pslot_np = group_slot_prices(cat, enc)
    # masks + price row in ONE upload; pad the subset axis with zero
    # masks (inert: need == 0 ⇒ feasible, savings 0) so the TOTAL row
    # count Sp+1 — the price row shards with the masks — divides the
    # mesh
    Sp = S if mesh is None else \
        -(-(S + 1) // int(mesh.size)) * int(mesh.size) - 1
    mbuf_np = np.zeros((Sp + 1, len(views)), np.float32)
    mbuf_np[:S] = masks
    mbuf_np[-1] = prices.astype(np.float32)
    dcat = _auto_dcat(cat, R, mesh=mesh)
    with _dm.attributed(reason="screen_upload"):
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            nbuf = _put_sharded(nbuf_np, NamedSharding(mesh, P()))
            gbuf = _put_sharded(gbuf_np, NamedSharding(mesh, P()))
            pslot = _put_sharded(pslot_np, NamedSharding(mesh, P()))
            mbuf = _put_sharded(mbuf_np,
                                NamedSharding(mesh, P("nodes", None)))
            buf = _read(_mesh_tournament_fn(mesh, cols, iters)(
                dcat.alloc, dcat.avail, nbuf, gbuf, mbuf, pslot))
        else:
            nbuf = _put(nbuf_np)
            gbuf = _put(gbuf_np)
            mbuf = _put(mbuf_np)
            pslot = _put(pslot_np)
            buf = _read(_tournament_fn()(dcat.alloc, dcat.avail, nbuf,
                                         gbuf, mbuf, pslot, cols=cols,
                                         iters=iters))
    out = buf.reshape(Sp, 4)[:S]
    return (out[:, 0] > 0.5, out[:, 1].astype(np.float32),
            out[:, 2].astype(np.float32), out[:, 3].astype(np.float32))
