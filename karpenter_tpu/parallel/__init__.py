"""Mesh + sharding: multi-chip distribution of the solver."""

from .mesh import make_mesh, run_sharded_solve, sharded_solve_fn

__all__ = ["make_mesh", "run_sharded_solve", "sharded_solve_fn"]
