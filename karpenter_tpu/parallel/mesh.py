"""Multi-chip distribution of the solver.

The scaling-book recipe: pick a mesh, annotate shardings, let XLA's SPMD
partitioner insert the collectives. The solve kernel's per-step work is
O(N·T·Z·C) masked arithmetic over the node axis N — that axis shards
cleanly across chips ("data parallel" over nodes): k/take computed
shard-local, the prefix-cumsum and argmin reductions become ICI
collectives GSPMD inserts automatically. The catalog tensors (alloc,
price, avail — a few MB) are replicated; group inputs are replicated
(they're the scan carrier).

This is the honest multi-chip story for a scheduler: pods interact through
shared node state, so the group scan stays sequential, but each step's
node-axis work — the part that grows with cluster size — spreads across
the slice. For 100k-node clusters at G≈256 groups, per-step work dominates
and scales ~linearly with chips.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.solver import _solve_kernel


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    mesh_devices = mesh_utils.create_device_mesh((n,), devices=devices[:n])
    return Mesh(mesh_devices, ("nodes",))


def make_batch_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the batched dispatcher's REQUEST axis (axis name
    "batch") — the other way to spend a slice. make_mesh shards one big
    solve's node axis; this shards a bucket of independent solves, one
    whole request per chip (vmap lanes never interact, so GSPMD inserts
    ZERO collectives — embarrassingly parallel). Batch capacity then
    scales with slice size instead of the padding ladder: a bucket of
    B requests costs ceil(B / n_devices) sequential kernel latencies.
    On a 1-device host (tier-1 CPU runs) this degenerates to the plain
    batched path byte-for-byte."""
    devices = jax.devices()
    n = n_devices or len(devices)
    mesh_devices = mesh_utils.create_device_mesh((n,), devices=devices[:n])
    return Mesh(mesh_devices, ("batch",))


# jitted sharded-solve wrappers, keyed on the (hashable) Mesh + n_max —
# the bound-cache discipline every other mesh-jit factory in the tree
# follows (consolidate._mesh_screen_fn, solver._mesh_fn_cache): without
# it each call built a FRESH jit wrapper, so jax's executable cache
# missed and every sharded solve retraced (graftlint jit-in-hot-path)
_sharded_fn_cache: dict = {}
_SHARDED_FN_CACHE_MAX = 16


def sharded_solve_fn(mesh: Mesh, n_max: int):
    """jit the kernel with node-axis sharding over `mesh`; XLA partitions
    the scan body and inserts ICI collectives for cumsum/argmin."""
    key = (mesh, n_max)
    fn = _sharded_fn_cache.get(key)
    if fn is not None:
        return fn
    if len(_sharded_fn_cache) >= _SHARDED_FN_CACHE_MAX:
        _sharded_fn_cache.clear()
    rep = NamedSharding(mesh, P())
    nodes = NamedSharding(mesh, P("nodes"))

    prior = NamedSharding(mesh, P(None, "nodes"))

    kernel = partial(_solve_kernel, n_max=n_max)
    fn = jax.jit(
        kernel,
        in_shardings=(
            rep, rep, rep,            # alloc, price, avail (catalog, replicated)
            rep, rep, rep, rep, rep, rep,  # group inputs (scan carrier)
            prior,                    # prior_counts [G, N]
            prior,                    # banned [G, N]
            rep,                      # conflict [G, G] (replicated like groups)
            rep,                      # zovh [T, Z, R] (catalog, replicated)
            nodes,                    # node_type
            nodes,                    # node_cum
            nodes,                    # node_zmask
            nodes,                    # node_cmask
            nodes,                    # node_open
            rep,                      # n_used
        ),
        out_shardings=(nodes, nodes, nodes, nodes, nodes, rep, rep, rep, rep),
    )
    _sharded_fn_cache[key] = fn
    return fn


def run_sharded_solve(mesh: Mesh, alloc, price, avail, requests, counts,
                      compat, allow_zone, allow_cap, max_per_node,
                      n_max: int, n_existing: int = 0):
    """Convenience wrapper: zero node state, device placement, one solve."""
    R = alloc.shape[1]
    Z, C = price.shape[1], price.shape[2]
    Gp = requests.shape[0]
    fn = sharded_solve_fn(mesh, n_max)
    out = fn(jnp.asarray(alloc), jnp.asarray(price), jnp.asarray(avail),
             jnp.asarray(requests), jnp.asarray(counts), jnp.asarray(compat),
             jnp.asarray(allow_zone), jnp.asarray(allow_cap),
             jnp.asarray(max_per_node),
             jnp.zeros((Gp, n_max), jnp.int32),
             jnp.zeros((Gp, n_max), bool), jnp.zeros((Gp, 1), bool),
             jnp.zeros((1, 1, R), jnp.float32),
             jnp.zeros(n_max, jnp.int32), jnp.zeros((n_max, R), jnp.float32),
             jnp.zeros((n_max, Z), bool), jnp.zeros((n_max, C), bool),
             jnp.zeros(n_max, bool), jnp.asarray(n_existing, jnp.int32))
    return out
