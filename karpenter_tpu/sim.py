"""SimEnvironment: the full stack wired against the fake cloud.

The pkg/test.Environment analog (reference environment.go:56-233): every
real controller + provider runs against in-memory fakes with an injectable
clock, so scale/flow tests run with zero cloud spend — and it doubles as
the kwok-style simulation backend for benchmarks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .catalog.generator import GeneratorConfig, generate_catalog, small_catalog
from .catalog.provider import CatalogProvider
from .cloud.fake import FakeCloud, FakeCloudConfig
from .controllers.disruption import DisruptionController
from .controllers.engine import Engine
from .controllers.gc import GarbageCollectionController
from .controllers.interruption import InterruptionController
from .controllers.lifecycle import BindingController, LifecycleController
from .controllers.provisioner import Provisioner
from .controllers.termination import TerminationController
from .models.instancetype import InstanceType
from .models.nodepool import NodeClassSpec, NodePool
from .ops.facade import Solver
from .state.store import Store
from .utils.clock import FakeClock


@dataclass
class SimEnvironment:
    clock: FakeClock
    store: Store
    cloud: FakeCloud
    catalog: CatalogProvider
    solver: Solver
    engine: Engine
    provisioner: Provisioner
    lifecycle: LifecycleController
    binding: BindingController
    termination: TerminationController
    disruption: DisruptionController
    interruption: InterruptionController
    gc: GarbageCollectionController
    # armed faults.FaultPlan when the stack was built with fault injection
    # (make_sim(fault_plan=...)); None in a healthy sim
    fault_plan: Optional[object] = None
    # warmpath.WarmPathEngine when built with make_sim(warmpath=True):
    # arrival-only reconciles admit against the standing headroom ledger
    # instead of paying a full solve; None = every reconcile is cold
    warmpath: Optional[object] = None
    # state.journal.IntentJournal: the provisioning write-ahead log.
    # Always present; pass the previous stack's journal to make_sim
    # (with its cloud) to simulate a crash-restart — open intents replay
    # during rehydration
    journal: Optional[object] = None
    # obs.watchdog.Watchdog: the online invariant monitor, armed on this
    # stack's store/cloud/journal/warmpath and ticked by the engine.
    # Read-only over everything it watches, so end-state hashes and
    # fault fingerprints are identical with it armed
    watchdog: Optional[object] = None

    def start_chaos(self, interval: float = 60.0, seed: int = 0) -> None:
        """kwok kill-node-thread analog (kwok/ec2/ec2.go:253-282): kill a
        random running instance every `interval` sim-seconds; the state-
        change interruption event + GC/liveness recover the cluster.
        stop_chaos() disarms it (tests quiesce before final invariants)."""
        import random
        rng = random.Random(seed)
        state = {"last": self.clock.now()}
        self._chaos_on = True

        def hook(now: float) -> None:
            if not getattr(self, "_chaos_on", False):
                return
            if now - state["last"] >= interval:
                state["last"] = now
                running = [i for i in self.cloud.instances.values()
                           if i.state == "running"]
                if running:
                    self.cloud.kill_instance(rng.choice(running).id,
                                             reason="chaos")
        self.engine.add_hook(hook)

    def stop_chaos(self) -> None:
        self._chaos_on = False


def make_sim(types: Optional[List[InstanceType]] = None,
             backend: str = "host",
             cloud_config: Optional[FakeCloudConfig] = None,
             nodepool: Optional[NodePool] = None,
             cloud: Optional[FakeCloud] = None,
             clock: Optional[FakeClock] = None,
             fault_plan: Optional[object] = None,
             warmpath: bool = False,
             warm_audit_every: int = 1,
             journal: Optional[object] = None,
             solver_factory: Optional[object] = None,
             watchdog: bool = True) -> SimEnvironment:
    """Passing an existing `cloud` (+ its clock) simulates an operator
    restart: the new stack rehydrates its fresh Store from the cloud's
    durable state instead of starting empty-world. Passing the previous
    stack's intent `journal` alongside replays its open launch intents
    (adopt-or-reap) during that rehydration — the crash-window recovery
    path (state/journal.py).

    fault_plan: an armed faults.FaultPlan — every controller then speaks
    to the cloud through a faults.injector.FaultyCloud decorator (injected
    throttles/server errors), the fake cloud honors the plan's ICE
    windows, the clock carries its skew jumps, and its interruption bursts
    are delivered by an engine hook. The raw FakeCloud stays on
    `sim.cloud` (the environment-model seam — node materialization and
    test introspection are not subject to API faults)."""
    if cloud is not None and (types is not None or cloud_config is not None):
        raise ValueError("types/cloud_config are ignored when an existing "
                         "cloud is passed — configure the cloud directly")
    # a passed cloud keeps its own clock: driving it from a fresh clock
    # would freeze its time (register delays never elapse, buckets never
    # refill), so default to the cloud's
    clock = clock or (cloud.clock if cloud is not None else FakeClock())
    store = Store()
    types = types if types is not None else small_catalog()
    cloud = cloud or FakeCloud(types, clock=clock, config=cloud_config)
    # api_cloud is what controllers hold; identical to `cloud` unless a
    # fault plan interposes the injection decorator
    api_cloud = cloud
    from .state.journal import IntentJournal
    journal = journal if journal is not None else IntentJournal()
    if fault_plan is not None:
        from .faults.injector import FaultyCloud
        # first install on this clock stamps the origin and schedules the
        # skew jumps; a RE-install (the restart harness rebuilding a
        # stack on the surviving clock) must do neither — rule times stay
        # relative to the ORIGINAL run start, and jumps already consumed
        # or scheduled must not double-apply
        first_install = fault_plan.clock is not clock
        fault_plan.clock = clock
        if first_install:
            fault_plan.origin = clock.now()    # rule times are run-relative
            for j in fault_plan.clock_jumps:   # skew
                clock.schedule_jump(fault_plan.origin + j.at, j.delta,
                                    fault_plan.on_jump)
        cloud.fault_plan = fault_plan          # ICE windows
        api_cloud = FaultyCloud(cloud, fault_plan, clock)
    # the catalog's backend listing goes through the gated view too, so
    # an ApiFault on "describe_types" really browns out catalog refresh
    # (rules targeting it should start at t0 > 0 — make_sim's sync
    # hydrate below runs at t=0 and does not absorb cloud errors)
    catalog = CatalogProvider(lambda: api_cloud.describe_types(),
                              clock=clock)
    # solver_factory(catalog) -> a Solver-compatible object: the fleet
    # seam (karpenter_tpu/fleet/) — each tenant shard's controllers then
    # speak to the shared SolverService through its queue-fronted client
    # instead of owning a private facade. `backend` is the factory's
    # concern in that case.
    solver = (solver_factory(catalog) if solver_factory is not None
              else Solver(catalog, backend=backend))
    # warm-path incremental admission (warmpath/): audit_every=1 means the
    # auditor replays EVERY warm admission through a full solve — the
    # always-on mode tier-1 tests and chaos scenarios run with
    warm_engine = None
    if warmpath:
        from .warmpath import WarmPathEngine
        warm_engine = WarmPathEngine(store, solver, catalog,
                                     audit_every=warm_audit_every)
    provisioner = Provisioner(store=store, solver=solver, cloud=api_cloud,
                              catalog=catalog, warmpath=warm_engine,
                              journal=journal)
    lifecycle = LifecycleController(store=store, cloud=api_cloud)
    binding = BindingController(store=store)
    termination = TerminationController(store=store, cloud=api_cloud,
                                        catalog=catalog)
    disruption = DisruptionController(store=store, solver=solver,
                                      catalog=catalog, provisioner=provisioner,
                                      termination=termination)
    interruption = InterruptionController(store=store, cloud=api_cloud,
                                          catalog=catalog,
                                          termination=termination)
    gc = GarbageCollectionController(store=store, cloud=api_cloud,
                                     journal=journal)
    from .cloud.image import ImageProvider
    from .controllers.auxiliary import (CatalogRefreshController,
                                        DiscoveredCapacityController,
                                        ReservationExpirationController,
                                        SpotPricingController,
                                        TaggingController)
    from .controllers.metrics_controller import CloudProviderMetricsController
    from .controllers.nodeclass import NodeClassController
    from .controllers.repair import NodeRepairController
    metrics_c = CloudProviderMetricsController(catalog=catalog, store=store)
    images = ImageProvider(lister=cloud.describe_images, clock=clock)
    nodeclass_c = NodeClassController(store=store, cloud=api_cloud,
                                      images=images)
    repair = NodeRepairController(store=store, termination=termination)
    tagging = TaggingController(store=store, cloud=api_cloud)
    discovered = DiscoveredCapacityController(store=store, catalog=catalog)
    refresh = CatalogRefreshController(catalog=catalog, store=store,
                                       images=images)
    res_exp = ReservationExpirationController(store=store, cloud=api_cloud,
                                              catalog=catalog,
                                              termination=termination)
    spot_pricing = SpotPricingController(catalog=catalog, cloud=api_cloud)
    engine = Engine(clock=clock).add(nodeclass_c, provisioner, lifecycle,
                                     binding, termination, disruption,
                                     interruption, gc, metrics_c, repair,
                                     tagging, discovered, refresh, res_exp,
                                     spot_pricing)
    # the verification plane's online monitor: armed BEFORE the workload
    # so the store watch feed sees every claim from birth; the engine
    # ticks it outside the traced window. Arming is read-only over the
    # whole stack — chaos end-state hashes and fault fingerprints are
    # byte-identical with it on (tests/test_watchdog.py asserts so)
    wd = None
    if watchdog:
        from .obs.watchdog import Watchdog
        wd = Watchdog(clock, store=store, cloud=cloud, journal=journal,
                      warmpath=warm_engine).arm(clock.now())
        engine.watchdog = wd

    # cloud → store node materialization (kubelet joining the cluster).
    # The in-process fake pushes node events through a callback; a cloud
    # without that hook (RemoteCloud — another process, no shared memory)
    # is synced by POLLING its node/instance views each tick, the
    # watch-fallback analog.
    is_local = hasattr(cloud, "on_node_created")
    if is_local:
        cloud.on_node_created.append(store.add_node)

    def _tick(now: float) -> None:
        from .cloud.provider import CloudError
        try:
            cloud.tick()
            if is_local:
                insts = cloud.instances
            else:
                for node in cloud.describe_nodes():
                    cur = store.nodes.get(node.name)
                    if cur is None:
                        store.add_node(node)
                    else:
                        # sync kubelet-owned fields only — locally applied
                        # taints (cordons) must survive the poll
                        cur.ready = node.ready
                        cur.conditions.update(node.conditions)
                insts = {i.id: i for i in cloud.describe()}
        except CloudError as e:
            if e.retryable:
                return  # transient (throttle/transport): sync next tick
            raise
        # terminated instances drop their nodes (cloud-side node deletion).
        # The polled view (describe) omits terminated instances entirely,
        # so remotely the signal is ABSENCE; the local fast path sees the
        # fake's full instance map and checks state.
        for node in list(store.nodes.values()):
            iid = node.provider_id.rsplit("/", 1)[-1]
            inst = insts.get(iid)
            if inst is None:
                if not is_local:
                    store.delete_node(node.name)
            elif inst.state == "terminated":
                store.delete_node(node.name)
    engine.add_hook(_tick)
    if fault_plan is not None:
        from .faults.injector import install_bursts
        install_bursts(engine, cloud, fault_plan, store)

    store.add_nodeclass(NodeClassSpec(name="default"))
    store.add_nodepool(nodepool or NodePool(name="default"))
    nodeclass_c.reconcile(clock.now())  # sync hydrate (operator.go:151 analog)
    from .state.rehydrate import rehydrate
    rh = rehydrate(store, cloud, catalog, clock.now(),
                   journal=journal)  # adopt any pre-existing fleet
    if warm_engine is not None and (rh["claims_adopted"]
                                    or rh["intents_adopted"]
                                    or rh["intents_aborted"]
                                    or rh["intents_reaped"]):
        # this stack took over a live fleet: the warm window must open
        # cold (no predecessor ledger is trustworthy across a restart)
        warm_engine.on_restart()
    return SimEnvironment(clock=clock, store=store, cloud=cloud,
                          catalog=catalog, solver=solver, engine=engine,
                          provisioner=provisioner, lifecycle=lifecycle,
                          binding=binding, termination=termination,
                          disruption=disruption, interruption=interruption,
                          gc=gc, fault_plan=fault_plan,
                          warmpath=warm_engine, journal=journal,
                          watchdog=wd)
