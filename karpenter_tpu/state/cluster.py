"""ClusterState: the solver-facing view of live nodes.

The reference keeps an in-memory cluster mirror (`state.NewCluster`,
cmd/controller/main.go:43) that the scheduler and disruption controllers
simulate against. Ours projects the Store into VirtualNodes (committed
type + occupancy) so provisioning fills real headroom and consolidation
re-solves against it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import labels as L
from ..models.nodeclaim import Node, NodeClaim, Phase
from ..models.pod import Pod
from ..models.resources import Resources
from ..ops.binpack import VirtualNode
from ..ops.encode import CatalogTensors
from ..state.store import Store


@dataclass
class NodeView:
    claim: NodeClaim
    node: Optional[Node]
    pods: List[Pod]
    virtual: VirtualNode
    price: float

    @property
    def name(self) -> str:
        return self.claim.name

    def disruption_cost(self) -> float:
        """Candidate ordering (reference consolidation orders candidates by
        pod count / deletion cost / priority / remaining lifetime —
        designs/consolidation.md): cheaper-to-disrupt first."""
        cost = 0.0
        for p in self.pods:
            cost += 1.0 + p.deletion_cost / 1000.0 + p.priority / 1e6
        return cost

    def has_do_not_disrupt(self) -> bool:
        """Voluntary-disruption block: any resident pod carries the
        annotation, or the NODE/claim itself does (reference node-level
        controls, disruption.md:385-396 — karpenter.sh/do-not-disrupt on
        the Node object blocks all voluntary disruption)."""
        from ..models.pod import DO_NOT_DISRUPT
        if self.node is not None and \
                self.node.annotations.get(DO_NOT_DISRUPT) == "true":
            return True
        if self.claim.annotations.get(DO_NOT_DISRUPT) == "true":
            return True
        return any(p.do_not_disrupt() for p in self.pods)


def build_node_views(store: Store, cat: CatalogTensors,
                     clock_now: float) -> List[NodeView]:
    views: List[NodeView] = []
    for claim in store.nodeclaims.values():
        if claim.is_deleting() or claim.phase not in (Phase.LAUNCHED,
                                                      Phase.REGISTERED,
                                                      Phase.INITIALIZED):
            continue
        t_idx = cat.name_to_idx.get(claim.instance_type or "")
        if t_idx is None:
            continue
        node = store.node_for_nodeclaim(claim)
        pods = store.pods_on_node(node.name) if node else []
        # nominated-but-unbound pods also occupy the claim
        from ..controllers.provisioner import NOMINATED
        for p in store.pods.values():
            if p.annotations.get(NOMINATED) == claim.name and p.node_name is None:
                pods.append(p)
        cum_res = Resources()
        for p in pods:
            cum_res = cum_res.add(p.requests)
        vec = cum_res.to_vector()
        cum = np.zeros(len(cat.resources), np.float32)
        cum[: len(vec)] = vec[: len(cum)]
        zone_mask = np.array([z == claim.zone for z in cat.zones], bool) \
            if claim.zone else np.ones(cat.Z, bool)
        cap_mask = np.array([c == claim.capacity_type for c in cat.captypes], bool) \
            if claim.capacity_type else np.ones(cat.C, bool)
        views.append(NodeView(
            claim=claim, node=node, pods=pods,
            virtual=VirtualNode(type_idx=t_idx, zone_mask=zone_mask,
                                cap_mask=cap_mask, cum=cum,
                                existing_name=claim.name),
            price=claim.price))
    return views
