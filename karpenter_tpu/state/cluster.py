"""ClusterState: the solver-facing view of live nodes.

The reference keeps an in-memory cluster mirror (`state.NewCluster`,
cmd/controller/main.go:43) that the scheduler and disruption controllers
simulate against. Ours projects the Store into VirtualNodes (committed
type + occupancy) so provisioning fills real headroom and consolidation
re-solves against it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import labels as L
from ..models.nodeclaim import Node, NodeClaim, Phase
from ..models.pod import Pod
from ..models.resources import Resources
from ..ops.binpack import VirtualNode
from ..ops.encode import CatalogTensors
from ..state.store import Store


@dataclass
class NodeView:
    claim: NodeClaim
    node: Optional[Node]
    pods: List[Pod]
    virtual: VirtualNode
    price: float

    @property
    def name(self) -> str:
        return self.claim.name

    def disruption_cost(self) -> float:
        """Candidate ordering (reference consolidation orders candidates by
        pod count / deletion cost / priority / remaining lifetime —
        designs/consolidation.md): cheaper-to-disrupt first."""
        cost = 0.0
        for p in self.pods:
            cost += 1.0 + p.deletion_cost / 1000.0 + p.priority / 1e6
        return cost

    def has_do_not_disrupt(self) -> bool:
        """Voluntary-disruption block: any resident pod carries the
        annotation, or the NODE/claim itself does (reference node-level
        controls, disruption.md:385-396 — karpenter.sh/do-not-disrupt on
        the Node object blocks all voluntary disruption)."""
        from ..models.pod import DO_NOT_DISRUPT
        if self.node is not None and \
                self.node.annotations.get(DO_NOT_DISRUPT) == "true":
            return True
        if self.claim.annotations.get(DO_NOT_DISRUPT) == "true":
            return True
        return any(p.do_not_disrupt() for p in self.pods)


def copy_virtual_node(vn: VirtualNode) -> VirtualNode:
    """Independent copy of a VirtualNode (masks/cum/placement maps are
    fresh objects): the one copy both the facade's colocation branch and
    the warm-path ledger/audit snapshots use, so a new VirtualNode field
    has a single place to be added to."""
    return VirtualNode(
        type_idx=vn.type_idx, zone_mask=vn.zone_mask.copy(),
        cap_mask=vn.cap_mask.copy(), cum=vn.cum.copy(),
        pods_by_group=dict(vn.pods_by_group),
        prior_by_group=dict(vn.prior_by_group),
        banned_groups=vn.banned_groups,
        existing_name=vn.existing_name)


def pool_node_views(store: Store, cat: CatalogTensors, clock_now: float,
                    pool_name: str) -> List[NodeView]:
    """The node views ONE NodePool's solve may fill: live + in-flight
    claims of the pool, minus nodes cordoned for disruption (reusing a
    disrupted node's headroom would rot the validated disruption while
    its replacement boots). The single filter the provisioner's cold
    path and the warm-path ledger share — the two headroom views must
    be identical or the warm auditor meters false divergence."""
    out = []
    for view in build_node_views(store, cat, clock_now):
        if view.claim.nodepool != pool_name:
            continue
        if view.node is not None and any(
                t.key == L.DISRUPTED_TAINT_KEY for t in view.node.taints):
            continue
        out.append(view)
    return out


def cluster_occupancy(store: Store,
                      by_claim: Optional[Dict[str, List[Pod]]] = None,
                      ) -> List[Tuple[Optional[str], List[Pod]]]:
    """Cluster-wide (zone, pods) per node — every pool's claims plus
    unmanaged nodes — for topology-spread domain counting (k8s counts
    matching pods wherever they run, not per NodePool). Moved here from
    the provisioner so the warm-path commit snapshots the same view the
    cold solve seeds spread constraints with.

    by_claim: optional out-param mapping claim name → its (shared) pods
    list in the returned view, so the warm path can append placements to
    a claim's entry in place instead of rebuilding the whole view."""
    out: List[Tuple[Optional[str], List[Pod]]] = []
    claim_node_names = set()
    # one pass over all pods: nominated-but-unbound pods per claim
    nominated: Dict[str, List[Pod]] = {}
    for p in store.pods.values():
        c = p.annotations.get(L.NOMINATED)
        if c is not None and p.node_name is None:
            nominated.setdefault(c, []).append(p)
    for claim in store.nodeclaims.values():
        if claim.node_name:
            # claim its node even when deleting, so the drained node's
            # pods aren't double-counted through the unmanaged loop
            claim_node_names.add(claim.node_name)
        if claim.is_deleting():
            continue
        pods = list(nominated.get(claim.name, []))
        if claim.node_name:
            pods.extend(store.pods_on_node(claim.node_name))
        if by_claim is not None:
            by_claim[claim.name] = pods
        out.append((claim.zone, pods))
    for node in store.nodes.values():
        if node.name in claim_node_names:
            continue
        out.append((node.labels.get(L.ZONE),
                    store.pods_on_node(node.name)))
    return out


def build_node_views(store: Store, cat: CatalogTensors,
                     clock_now: float) -> List[NodeView]:
    views: List[NodeView] = []
    for claim in store.nodeclaims.values():
        if claim.is_deleting() or claim.phase not in (Phase.LAUNCHED,
                                                      Phase.REGISTERED,
                                                      Phase.INITIALIZED):
            continue
        t_idx = cat.name_to_idx.get(claim.instance_type or "")
        if t_idx is None:
            continue
        node = store.node_for_nodeclaim(claim)
        pods = store.pods_on_node(node.name) if node else []
        # nominated-but-unbound pods also occupy the claim
        from ..controllers.provisioner import NOMINATED
        for p in store.pods.values():
            if p.annotations.get(NOMINATED) == claim.name and p.node_name is None:
                pods.append(p)
        cum_res = Resources()
        for p in pods:
            cum_res = cum_res.add(p.requests)
        vec = cum_res.to_vector()
        cum = np.zeros(len(cat.resources), np.float32)
        cum[: len(vec)] = vec[: len(cum)]
        zone_mask = np.array([z == claim.zone for z in cat.zones], bool) \
            if claim.zone else np.ones(cat.Z, bool)
        cap_mask = np.array([c == claim.capacity_type for c in cat.captypes], bool) \
            if claim.capacity_type else np.ones(cat.C, bool)
        views.append(NodeView(
            claim=claim, node=node, pods=pods,
            virtual=VirtualNode(type_idx=t_idx, zone_mask=zone_mask,
                                cap_mask=cap_mask, cum=cum,
                                existing_name=claim.name),
            price=claim.price))
    return views
