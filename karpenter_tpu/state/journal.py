"""Provisioning intent journal: the write-ahead log of the launch path.

The reference survives operator crashes because its durable state is the
Kubernetes API — NodeClaims are written *before* CreateFleet, so a crash
between the cloud call and the status commit leaves a durable record the
GC can reconcile against (reference pkg/controllers/nodeclaim/
garbagecollection/controller.go). Our Store is process-local, so the
same discipline needs an explicit intent log: `Provisioner._launch`
opens one `LaunchIntent` per request BEFORE the CreateFleet wire call
and resolves it after the result commits. The journal is the Borg/Omega
intent-log idiom (PAPERS.md): every state the process can die in is
recoverable from (journal, cloud) alone —

- intent open + no instance carrying its token  → the crash landed
  before the wire call; nothing launched; the intent aborts and the
  re-listed pods simply re-solve.
- intent open + a live token-tagged instance    → the crash landed
  after the wire call but before the commit; restart ADOPTS the
  instance (`state/rehydrate.replay_intents`) and marks the intent
  committed — no double launch (the idempotency token dedupes any
  replayed CreateFleet as well).
- intent open + claim unrecoverable             → the instance is
  reaped immediately instead of waiting out the GC sweep.

While an intent is open, the GC sweep MUST NOT reap its instance (the
launch may still be in flight in a batcher window, or the commit may
simply not have happened yet): `controllers/gc.py` gates on
`open_tokens()`/`open_claim_names()`.

The journal is append-only: opens and resolutions are appended to
`records` (and, when a path is given, fsync'd as JSON lines BEFORE the
wire call they protect), never rewritten. `IntentJournal(path=...)`
replays an existing file on construction, so a restarted operator
resumes with its predecessor's open intents — the sim passes the
journal OBJECT across restarts instead (faults/runner.RestartRunner).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Dict, FrozenSet, List, Optional

OPEN = "open"
COMMITTED = "committed"
ABORTED = "aborted"   # the launch never produced an instance
REAPED = "reaped"     # restart replay terminated an unadoptable instance


def launch_token(claim_name: str, pool_fingerprint: str,
                 attempt: int) -> str:
    """Deterministic idempotency token for one launch attempt: a replay
    of the SAME (claim, pool config, attempt) — e.g. a crash-restart
    re-sending a journaled request — maps to the same token and dedupes
    cloud-side; a genuinely new attempt (new claim name, or a bumped
    attempt counter) mints a new one."""
    h = hashlib.sha256(
        f"{claim_name}|{pool_fingerprint}|{attempt}".encode())
    return h.hexdigest()[:32]


@dataclass
class LaunchIntent:
    seq: int
    claim_name: str
    nodepool: str
    node_class: str
    token: str
    attempt: int
    created_at: float
    status: str = OPEN
    provider_id: str = ""
    resolved_at: Optional[float] = None


class IntentJournal:
    """Append-only provisioning intent log. One journal per operator
    process lineage: it must survive the process (file backing in the
    real runtime, object handoff in the sim) to be worth anything."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.records: List[dict] = []      # append-only ledger
        self._open: Dict[int, LaunchIntent] = {}   # seq -> intent
        self._attempts: Dict[str, int] = {}        # claim -> opens so far
        self._seq = 0
        self.stats = {"opened": 0, "committed": 0, "aborted": 0,
                      "reaped": 0}
        if path and os.path.exists(path):
            self._replay_file(path)

    # --- write side -------------------------------------------------------
    def next_attempt(self, claim_name: str) -> int:
        """1-based attempt number the NEXT open for this claim gets —
        part of the token preimage, so a deliberate relaunch of the same
        claim (attempt bump) is distinguishable from a crash replay."""
        return self._attempts.get(claim_name, 0) + 1

    def open_launch(self, claim_name: str, nodepool: str, node_class: str,
                    token: str, now: float,
                    attempt: Optional[int] = None) -> LaunchIntent:
        return self.open_batch([{
            "claim_name": claim_name, "nodepool": nodepool,
            "node_class": node_class, "token": token,
            "attempt": attempt}], now)[0]

    def open_batch(self, specs: List[dict], now: float) -> List[LaunchIntent]:
        """Open one intent per spec ({claim_name, nodepool, node_class,
        token, attempt?}) with a SINGLE durability boundary: the whole
        batch's records land in one write+fsync. The boundary that
        matters is the one CreateFleet wire call AFTER all opens —
        per-record fsyncs would buy nothing but N× the latency on the
        launch hot path."""
        intents: List[LaunchIntent] = []
        records: List[dict] = []
        for spec in specs:
            attempt = spec.get("attempt")
            if attempt is None:
                attempt = self.next_attempt(spec["claim_name"])
            self._seq += 1
            intent = LaunchIntent(seq=self._seq,
                                  claim_name=spec["claim_name"],
                                  nodepool=spec["nodepool"],
                                  node_class=spec["node_class"],
                                  token=spec["token"], attempt=attempt,
                                  created_at=now)
            self._attempts[intent.claim_name] = attempt
            self._open[intent.seq] = intent
            self.stats["opened"] += 1
            intents.append(intent)
            records.append({"op": "open", **asdict(intent)})
        self._append_many(records)
        self._publish()
        return intents

    def resolve(self, intent: LaunchIntent, status: str,
                provider_id: str = "", now: float = 0.0) -> None:
        intent.status = status
        intent.provider_id = provider_id or intent.provider_id
        intent.resolved_at = now
        self._open.pop(intent.seq, None)
        self.stats[status] = self.stats.get(status, 0) + 1
        # resolutions are written but NOT fsync'd: losing one in a crash
        # merely leaves the intent open for restart replay, which
        # re-resolves it idempotently (a committed instance re-adopts) —
        # whereas a lost OPEN record would leave a launch unprotected,
        # so only opens pay the fsync
        self._append_many([{"op": "resolve", "seq": intent.seq,
                            "status": status,
                            "provider_id": intent.provider_id,
                            "resolved_at": now}], sync=False)
        self._publish()

    # --- read side --------------------------------------------------------
    def open_intents(self) -> List[LaunchIntent]:
        return list(self._open.values())

    def open_tokens(self) -> FrozenSet[str]:
        return frozenset(i.token for i in self._open.values())

    def open_claim_names(self) -> FrozenSet[str]:
        return frozenset(i.claim_name for i in self._open.values())

    # --- persistence ------------------------------------------------------
    def _append_many(self, records: List[dict], sync: bool = True) -> None:
        self.records.extend(records)
        if self.path and records:
            # opens are written + flushed + fsync'd BEFORE the wire call
            # they protect: an intent that only lived in a page cache
            # when the process died protects nothing. One fsync covers
            # the whole batch; resolutions pass sync=False (see resolve).
            # The span feeds the phase ledger's journal_fsync bucket —
            # fsync latency on the launch hot path is exactly the kind
            # of host-side cost the profiler exists to attribute.
            from ..obs.tracer import NOOP_SPAN, TRACER
            sp = (TRACER.span("journal.fsync", records=len(records),
                              sync=sync)
                  if TRACER.enabled else NOOP_SPAN)
            with sp:
                with open(self.path, "a", encoding="utf-8") as f:
                    for record in records:
                        f.write(json.dumps(record, sort_keys=True) + "\n")
                    f.flush()
                    if sync:
                        os.fsync(f.fileno())

    def _replay_file(self, path: str) -> None:
        """Rebuild the open set from an existing journal file (operator
        restart in the real runtime). Truncated trailing lines — the
        process died mid-append — are skipped: an unreadable OPEN is a
        launch whose request never shipped."""
        by_seq: Dict[int, LaunchIntent] = {}
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                # the restored journal carries its predecessor's full
                # ledger and stats, not just the open set — consumers of
                # `records`/`stats` see one continuous history
                self.records.append(rec)
                if rec.get("op") == "open":
                    intent = LaunchIntent(
                        **{k: v for k, v in rec.items() if k != "op"})
                    by_seq[intent.seq] = intent
                    self._seq = max(self._seq, intent.seq)
                    self._attempts[intent.claim_name] = max(
                        self._attempts.get(intent.claim_name, 0),
                        intent.attempt)
                    self.stats["opened"] += 1
                elif rec.get("op") == "resolve":
                    by_seq.pop(rec.get("seq"), None)
                    status = rec.get("status", "")
                    if status in self.stats:
                        self.stats[status] += 1
        self._open = {seq: i for seq, i in by_seq.items()}
        self._publish()

    def _publish(self) -> None:
        from ..metrics import INTENT_JOURNAL_OPEN
        INTENT_JOURNAL_OPEN.set(float(len(self._open)))
