"""Restart-safe state rehydration.

The reference's durable state is the Kubernetes API: on operator restart
everything rebuilds from watches, and the GC only reaps instances whose
NodeClaim is verifiably gone in that durable store
(reference pkg/controllers/nodeclaim/garbagecollection/controller.go:55-112,
cmd/controller/main.go:43 state.NewCluster). Our durable stores are the
cloud itself — instances carry adoption tags stamped at launch — and the
cluster's node objects (kubelet/API-server side). This module rebuilds
`Store` from both, so a restarted operator adopts its fleet instead of
reaping it, and `Store.hydrated` gates the GC sweep until adoption ran.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..models import labels as L
from ..models.nodeclaim import NodeClaim, Phase
from ..models.requirements import Requirements
from ..models.resources import Resources
from .store import Store

from ..models.labels import (TAG_NODECLAIM, TAG_NODECLASS, TAG_NODECLASS_HASH,
                             TAG_NODECLASS_HASH_VERSION, TAG_NODEPOOL,
                             TAG_NODEPOOL_HASH, TAG_NODEPOOL_HASH_VERSION)


def rehydrate(store: Store, cloud, catalog=None, now: float = 0.0,
              journal=None) -> Dict[str, int]:
    """Rebuild Store from the cloud's durable state; marks the store hydrated.

    Idempotent: instances already backed by a NodeClaim (matched on
    provider_id) and nodes already present are skipped, so calling this on
    a warm store is a no-op. Untagged instances are not ours — they are
    left alone (the reference GC likewise only considers instances carrying
    the cluster's ownership tags).

    journal: the predecessor process's provisioning intent journal
    (state/journal.IntentJournal). Open intents — launches the dead
    process recorded but never resolved — are replayed AFTER tag
    adoption: each either adopts the instance its token actually minted,
    aborts (the crash landed before the wire call), or reaps a live
    instance whose claim could not be rebuilt. Replaying twice is a
    no-op (resolved intents leave the open set).
    """
    stats = {"nodes_adopted": 0, "claims_adopted": 0,
             "intents_adopted": 0, "intents_aborted": 0,
             "intents_reaped": 0}
    # 1. nodes: node objects live with the cluster and survive operator
    #    restarts (in k8s they sit in the API server; our fake cloud plays
    #    the kubelet/API-server side and exposes them via describe_nodes)
    instances = _describe_with_retry(cloud)
    for node in cloud.describe_nodes():
        if node.name not in store.nodes:
            store.add_node(node)
            stats["nodes_adopted"] += 1
    nodes_by_pid = {n.provider_id: n for n in store.nodes.values()}
    # capacity must come from the instance's NODECLASS view of the
    # catalog, not the raw catalog: per-NodeClass overrides (block-device
    # ephemeral storage) would otherwise vanish on every restart and the
    # adopted node would appear over-committed
    types_by_nc: Dict[str, Dict[str, object]] = {}

    def types_for(nc_name: str) -> Dict[str, object]:
        hit = types_by_nc.get(nc_name)
        if hit is None:
            if catalog is None:
                hit = {}
            else:
                nc = store.nodeclasses.get(nc_name)
                src = (catalog.list(nc) if nc is not None
                       else catalog.raw_types())
                hit = {t.name: t for t in src}
            types_by_nc[nc_name] = hit
        return hit
    claimed_pids = {c.provider_id for c in store.nodeclaims.values()
                    if c.provider_id}
    # 2. instances → NodeClaims via adoption tags (untagged = not ours)
    max_suffix = -1
    for inst in instances:
        if inst.state == "terminated" or inst.provider_id in claimed_pids:
            continue
        name = inst.tags.get(TAG_NODECLAIM)
        if not name:
            continue
        claim = _adopt(store, inst, name, nodes_by_pid.get(inst.provider_id),
                       types_for(inst.tags.get(TAG_NODECLASS, "default")),
                       now)
        store.add_nodeclaim(claim)
        store.record_event("nodeclaim", claim.name, "Adopted",
                           f"rehydrated from instance {inst.id}")
        stats["claims_adopted"] += 1
        tail = name.rsplit("-", 1)[-1]
        if tail.isdigit():
            max_suffix = max(max_suffix, int(tail))
    if max_suffix >= 0:
        # a restarted process's name sequence restarts at 0; advance it past
        # every adopted name so fresh launches can't mint a colliding name
        # (which would overwrite the adopted claim and expose its live
        # instance to GC)
        from ..models.nodeclaim import advance_name_sequence
        advance_name_sequence(max_suffix)
    if journal is not None and journal.open_intents():
        replay_intents(store, cloud, journal, instances, now, stats)
    store.hydrated = True
    if stats["claims_adopted"]:
        # disruption honors a settle window after adoption so workloads can
        # re-list before the empty pass sees pod-less adopted nodes (the
        # reference's analog: disruption waits for cluster-state sync)
        store.adopted_at = now
    return stats


def replay_intents(store: Store, cloud, journal, instances, now: float,
                   stats: Dict[str, int]) -> None:
    """Resolve the dead process's open launch intents deterministically:

    - a live instance carrying the intent's token tag + a rebuilt claim
      tracking it → the crash landed between the wire call and the
      commit; the tag adoption above already rebuilt the claim, so the
      intent simply commits (``adopted``);
    - a live token-tagged instance with NO rebuilt claim (adoption tags
      stripped, nodepool gone) → reap it NOW instead of leaking it until
      the GC sweep (``reaped``);
    - no instance for the token → the crash landed before the wire call
      (or the launch failed); nothing exists, the intent closes
      (``aborted``) and the re-listed pods re-solve normally.

    Metered per outcome (`karpenter_tpu_restart_adoptions_total`) and
    trace-visible as a `restart.adopt` span."""
    from ..cloud.provider import CloudError
    from ..metrics import RESTART_ADOPTIONS
    from ..obs.tracer import NOOP_SPAN, TRACER
    open_intents = journal.open_intents()
    sp = (TRACER.span("restart.adopt", intents=len(open_intents))
          if TRACER.enabled else NOOP_SPAN)
    with sp:
        by_token = {}
        for inst in instances:
            tok = inst.tags.get(L.TAG_LAUNCH_TOKEN)
            if tok and inst.state != "terminated":
                by_token[tok] = inst
        for intent in open_intents:
            inst = by_token.get(intent.token)
            if inst is None:
                journal.resolve(intent, "aborted", now=now)
                stats["intents_aborted"] += 1
                RESTART_ADOPTIONS.inc(outcome="aborted")
                continue
            claim = store.nodeclaims.get(intent.claim_name)
            if claim is not None and claim.provider_id == inst.provider_id:
                journal.resolve(intent, "committed",
                                provider_id=inst.provider_id, now=now)
                stats["intents_adopted"] += 1
                RESTART_ADOPTIONS.inc(outcome="adopted")
                store.record_event("nodeclaim", intent.claim_name,
                                   "IntentAdopted",
                                   f"open intent resolved to {inst.id}")
            else:
                try:
                    cloud.terminate([inst.id])
                except CloudError:
                    pass  # intent closes either way; GC backstops the reap
                journal.resolve(intent, "reaped", now=now)
                stats["intents_reaped"] += 1
                RESTART_ADOPTIONS.inc(outcome="reaped")
                store.record_event("instance", inst.id, "IntentReaped",
                                   f"unadoptable launch of {intent.claim_name}")
        sp.set(adopted=stats["intents_adopted"],
               aborted=stats["intents_aborted"],
               reaped=stats["intents_reaped"])


def _describe_with_retry(cloud, attempts: int = 6):
    """Boot-path DescribeInstances with backoff: a restart that lands in a
    throttling window must not crash-loop the operator (controllers get
    engine-level retry for RateLimitedError; this one-shot path needs its
    own)."""
    import time

    from ..cloud.provider import RateLimitedError, ServerError
    delay = 0.5
    clk = getattr(cloud, "clock", None)
    for i in range(attempts):
        try:
            return cloud.describe()
        except (RateLimitedError, ServerError):
            if i == attempts - 1:
                raise
            if clk is not None and hasattr(clk, "step"):
                # injected fake clock: the throttle bucket refills on IT,
                # not on wall time — stepping it is the only useful wait
                clk.step(delay)
            else:
                time.sleep(delay)
            delay = min(delay * 2, 8.0)


def _adopt(store: Store, inst, name: str, node, types: Dict[str, object],
           now: float) -> NodeClaim:
    pool = store.nodepools.get(inst.tags.get(TAG_NODEPOOL, ""))
    claim = NodeClaim(
        name=name,
        nodepool=inst.tags.get(TAG_NODEPOOL, ""),
        requirements=pool.requirements.copy() if pool else Requirements(),
        taints=list(pool.taints) if pool else [],
        startup_taints=list(pool.startup_taints) if pool else [],
        node_class=inst.tags.get(TAG_NODECLASS, "default"),
        expire_after=pool.expire_after if pool else None,
        termination_grace_period=pool.termination_grace_period if pool else None,
        created_at=inst.launch_time)
    claim.provider_id = inst.provider_id
    claim.instance_type = inst.instance_type
    claim.zone = inst.zone
    claim.capacity_type = inst.capacity_type
    claim.price = inst.price
    claim.image_id = inst.image_id
    claim.network_groups = list(inst.network_groups)
    claim.profile = inst.profile
    claim.launched_at = inst.launch_time
    claim.phase = Phase.LAUNCHED
    if inst.reservation_id:
        claim.annotations["karpenter.tpu/reservation-id"] = inst.reservation_id
    for tag, anno in ((TAG_NODECLASS_HASH, TAG_NODECLASS_HASH),
                      (TAG_NODECLASS_HASH_VERSION, TAG_NODECLASS_HASH_VERSION),
                      (TAG_NODEPOOL_HASH, TAG_NODEPOOL_HASH),
                      (TAG_NODEPOOL_HASH_VERSION, TAG_NODEPOOL_HASH_VERSION)):
        if tag in inst.tags:
            claim.annotations[anno] = inst.tags[tag]
    it = types.get(inst.instance_type)
    if it is not None:
        claim.capacity = Resources(it.capacity)
        claim.allocatable = it.allocatable()
        claim.labels.update(it.node_labels(inst.zone, inst.capacity_type))
    claim.labels[L.ZONE] = inst.zone
    claim.labels[L.CAPACITY_TYPE] = inst.capacity_type
    claim.labels[L.INSTANCE_TYPE] = inst.instance_type
    if pool is not None:
        claim.labels[L.NODEPOOL] = pool.name
    if node is not None:
        node.nodeclaim = claim.name
        claim.node_name = node.name
        claim.registered_at = inst.launch_time
        if node.labels.get(L.NODE_INITIALIZED) == "true":
            claim.phase = Phase.INITIALIZED
            claim.initialized_at = inst.launch_time
            claim.set_condition("Initialized", True, now=now)
        else:
            claim.phase = Phase.REGISTERED
        claim.set_condition("Registered", True, now=now)
    return claim
