"""In-memory cluster state store — the sim's API server.

Plays the role the Kubernetes API server plays for the reference (its
coordination bus; SURVEY.md §5 'distributed communication backend'):
controllers watch it via event hooks. Unlike the real API server the
store is process-local, so restart recovery rebuilds it from the cloud's
durable state (`state/rehydrate.py`: instance adoption tags + cluster
node objects); the `hydrated` flag gates destructive sweeps (GC) until
that adoption ran.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Callable, Dict, Iterable, List, Optional

from ..models.nodeclaim import Node, NodeClaim
from ..models.nodepool import NodeClassSpec, NodePool
from ..models.pod import Pod


class Store:
    def __init__(self) -> None:
        self.pods: Dict[str, Pod] = {}
        self.nodepools: Dict[str, NodePool] = {}
        self.nodeclasses: Dict[str, NodeClassSpec] = {}
        self.nodeclaims: Dict[str, NodeClaim] = {}
        self.nodes: Dict[str, Node] = {}
        self._watchers: Dict[str, List[Callable]] = defaultdict(list)
        self.events: List[tuple] = []  # (kind, object-name, reason, message)
        # set by state.rehydrate.rehydrate(); until then the store may be a
        # cold restart and GC must not reap (see controllers/gc.py)
        self.hydrated: bool = False
        # when rehydration adopted a live fleet, the time it did so —
        # disruption waits out a settle window from here so re-listing
        # workloads aren't raced by the empty-node pass
        self.adopted_at: Optional[float] = None

    # --- watch / events ---
    def watch(self, kind: str, fn: Callable) -> None:
        self._watchers[kind].append(fn)

    def _notify(self, kind: str, action: str, obj) -> None:
        for fn in self._watchers[kind]:
            fn(action, obj)

    def record_event(self, kind: str, name: str, reason: str, message: str = "") -> None:
        self.events.append((kind, name, reason, message))

    # --- pods ---
    def add_pod(self, pod: Pod) -> Pod:
        key = f"{pod.namespace}/{pod.name}"
        self.pods[key] = pod
        # amortize constraint-signature interning to admission time: the
        # solve-time encode then groups 100k pods by one int read per pod
        # instead of re-walking Python constraint objects every reconcile
        pod.group_key()
        self._notify("pod", "add", pod)
        return pod

    def delete_pod(self, namespace: str, name: str) -> None:
        pod = self.pods.pop(f"{namespace}/{name}", None)
        if pod:
            self._notify("pod", "delete", pod)

    def pending_pods(self) -> List[Pod]:
        return [p for p in self.pods.values()
                if p.phase == "Pending" and p.node_name is None]

    def pods_on_node(self, node_name: str) -> List[Pod]:
        return [p for p in self.pods.values() if p.node_name == node_name]

    def bind_pod(self, pod: Pod, node_name: str) -> None:
        pod.node_name = node_name
        pod.phase = "Running"
        self._notify("pod", "bind", pod)

    # --- nodepools / nodeclasses (validated at admission, like the
    # reference's CEL rules on the CRDs) ---
    def add_nodepool(self, np_: NodePool) -> NodePool:
        from ..models.validation import validate_nodepool
        validate_nodepool(np_)
        self.nodepools[np_.name] = np_
        self._notify("nodepool", "add", np_)
        return np_

    def add_nodeclass(self, nc: NodeClassSpec) -> NodeClassSpec:
        from ..models.validation import validate_nodeclass
        validate_nodeclass(nc)
        self.nodeclasses[nc.name] = nc
        self._notify("nodeclass", "add", nc)
        return nc

    def delete_nodeclass(self, name: str) -> None:
        nc = self.nodeclasses.pop(name, None)
        if nc is not None:
            self._notify("nodeclass", "delete", nc)

    def nodepools_by_weight(self) -> List[NodePool]:
        """Descending weight — provisioning tries heavier pools first
        (reference NodePool weight, karpenter.sh_nodepools.yaml:427-432)."""
        return sorted(self.nodepools.values(), key=lambda p: -p.weight)

    # --- nodeclaims ---
    def add_nodeclaim(self, nc: NodeClaim) -> NodeClaim:
        self.nodeclaims[nc.name] = nc
        self._notify("nodeclaim", "add", nc)
        return nc

    def delete_nodeclaim(self, name: str) -> None:
        nc = self.nodeclaims.pop(name, None)
        if nc:
            self._notify("nodeclaim", "delete", nc)

    def nodeclaims_for_pool(self, pool: str) -> List[NodeClaim]:
        return [c for c in self.nodeclaims.values() if c.nodepool == pool]

    def nodeclaim_by_provider_id(self, provider_id: str) -> Optional[NodeClaim]:
        """The instance-id field index (reference operator.go:298-319)."""
        for c in self.nodeclaims.values():
            if c.provider_id == provider_id:
                return c
        return None

    # --- nodes ---
    def add_node(self, node: Node) -> Node:
        self.nodes[node.name] = node
        self._notify("node", "add", node)
        return node

    def delete_node(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node:
            self._notify("node", "delete", node)

    def node_for_nodeclaim(self, claim: NodeClaim) -> Optional[Node]:
        for n in self.nodes.values():
            if n.provider_id == claim.provider_id:
                return n
        return None
