"""In-memory cluster state store — the sim's API server.

Plays the role the Kubernetes API server plays for the reference (its
coordination bus; SURVEY.md §5 'distributed communication backend'):
controllers watch it via event hooks. Unlike the real API server the
store is process-local, so restart recovery rebuilds it from the cloud's
durable state (`state/rehydrate.py`: instance adoption tags + cluster
node objects); the `hydrated` flag gates destructive sweeps (GC) until
that adoption ran.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Callable, Dict, Iterable, List, Optional

from ..models import labels as L
from ..models.nodeclaim import Node, NodeClaim
from ..models.nodepool import NodeClassSpec, NodePool
from ..models.pod import Pod


class Store:
    def __init__(self) -> None:
        self.pods: Dict[str, Pod] = {}
        # admission-time pending-group index: gid -> {key -> pod} holding
        # exactly the provisioner's input set (Pending, unbound,
        # un-nominated). Maintained on every pod state transition so the
        # solve-time encode never walks O(pods) Python objects — the
        # delta-encode analogue of the reference caching resolved
        # instance types by hash (instancetype.go:219-229). All pod
        # state transitions MUST go through store methods (add/bind/
        # unbind/nominate/unnominate/delete) or the index goes stale.
        self._pending_groups: Dict[int, Dict[str, Pod]] = {}
        self.nodepools: Dict[str, NodePool] = {}
        self.nodeclasses: Dict[str, NodeClassSpec] = {}
        self.nodeclaims: Dict[str, NodeClaim] = {}
        # instance id (provider-id tail) -> claim name; maintained by
        # add/delete_nodeclaim + index_nodeclaim_instance so interruption
        # storms resolve claims O(1), not O(claims) per message
        self._claims_by_iid: Dict[str, str] = {}
        self.nodes: Dict[str, Node] = {}
        self.daemonsets: Dict[str, object] = {}
        self.pdbs: Dict[str, object] = {}
        self.pvcs: Dict[str, object] = {}  # PersistentVolumeClaims by key
        # pvc key -> referencing pod keys: add_pvc re-decoration must not
        # scan 100k pods per claim event
        self._pods_by_pvc: Dict[str, set] = {}
        self._watchers: Dict[str, List[Callable]] = defaultdict(list)
        self.events: List[tuple] = []  # (kind, object-name, reason, message)
        # set by state.rehydrate.rehydrate(); until then the store may be a
        # cold restart and GC must not reap (see controllers/gc.py)
        self.hydrated: bool = False
        # when rehydration adopted a live fleet, the time it did so —
        # disruption waits out a settle window from here so re-listing
        # workloads aren't raced by the empty-node pass
        self.adopted_at: Optional[float] = None

    # --- watch / events ---
    def watch(self, kind: str, fn: Callable) -> None:
        self._watchers[kind].append(fn)

    def _notify(self, kind: str, action: str, obj) -> None:
        for fn in self._watchers[kind]:
            fn(action, obj)

    def record_event(self, kind: str, name: str, reason: str, message: str = "") -> None:
        self.events.append((kind, name, reason, message))

    # --- pods ---
    def add_pod(self, pod: Pod) -> Pod:
        key = f"{pod.namespace}/{pod.name}"
        old = self.pods.get(key)
        if old is not None and old is not pod:
            # same-key replacement: evict the old OBJECT from the index
            # (its gid may differ — a stranded entry would be re-solved
            # as a ghost pod every reconcile, forever); its PVC refs go
            # too, or add_pvc events re-decorate a ghost forever
            self._index_discard(old, key)
            for pname in set(old.pvc_names):
                refs = self._pods_by_pvc.get(f"{old.namespace}/{pname}")
                if refs is not None:
                    refs.discard(key)
                    if not refs:
                        del self._pods_by_pvc[f"{old.namespace}/{pname}"]
        self.pods[key] = pod
        if old is not None and old is not pod:
            # a same-key replacement is a MUTATION of cluster state, not a
            # plain arrival — the warm-path delta tracker (and any other
            # watcher) must be able to tell the two apart
            self._notify("pod", "replace", pod)
        for name in set(pod.pvc_names):
            self._pods_by_pvc.setdefault(
                f"{pod.namespace}/{name}", set()).add(key)
        # volume constraints resolve BEFORE interning: the injected zone
        # affinity and attach-count request are part of the signature
        self._apply_volume_constraints(pod)
        # amortize constraint-signature interning to admission time: the
        # solve-time encode then groups 100k pods by one int read per pod
        # instead of re-walking Python constraint objects every reconcile
        pod.group_key()
        self._index_update(pod, key)
        self._notify("pod", "add", pod)
        return pod

    # --- persistent volume claims (volume topology + attach limits) ---
    def add_pvc(self, pvc) -> None:
        """Register/update a claim; pending pods referencing it are
        re-decorated via the pvc→pods index (a PV binding after pod
        admission must still pin the pod's zone before it schedules —
        core volume-topology behavior). A nominated pod whose nominated
        claim no longer satisfies the new pin is un-nominated so the
        provisioner re-solves with the constraint."""
        self.pvcs[pvc.key] = pvc
        self._notify("pvc", "add", pvc)
        for key in list(self._pods_by_pvc.get(pvc.key, ())):
            pod = self.pods.get(key)
            if pod is None or pod.node_name is not None:
                continue
            if pvc.bound_zone() is None and not pod.node_affinity:
                continue  # zoneless claim, nothing to re-derive
            self._index_discard(pod, key)
            self._apply_volume_constraints(pod)
            pod.invalidate_group_key()
            pod.group_key()
            self._index_update(pod, key)
            nominated = pod.annotations.get(L.NOMINATED)
            if nominated:
                claim = self.nodeclaims.get(nominated)
                want = pod.scheduling_requirements().get(L.ZONE)
                if (claim is None
                        or (want is not None
                            and (not claim.zone
                                 or not want.contains(claim.zone)))):
                    # the pre-binding nomination no longer satisfies the
                    # volume's zone — return the pod to pending. A claim
                    # whose zone is still UNKNOWN (launch in flight, the
                    # override list may span zones) is treated as not
                    # satisfying: keeping the nomination would gamble that
                    # the launch lands in the volume's zone, and a miss
                    # permanently separates the pod from its volume.
                    self.unnominate_pod(pod)

    def _apply_volume_constraints(self, pod: Pod) -> None:
        """Lower PVC effects onto existing scheduling machinery
        (models/volume.py docstring): each bound zonal claim contributes a
        required node-affinity IN term — the Requirements set-algebra then
        INTERSECTS it with user selectors and other claims, so conflicting
        zones make the pod unschedulable instead of silently landing where
        one of its volumes isn't. Unique claims each consume one
        attachable-volume resource unit (RWX claims shared across pods
        still charge per pod — the resource model is per-pod; noted
        limitation)."""
        if not pod.pvc_names:
            return
        from ..models import labels as L
        from ..models.volume import VOLUME_ATTACH_RESOURCE
        unique = sorted(set(pod.pvc_names))
        pod.requests[VOLUME_ATTACH_RESOURCE] = float(len(unique))
        # volume-injected terms are tagged so re-binding replaces, never
        # accumulates, stale pins (signature ignores the marker key)
        pod.node_affinity = [t for t in pod.node_affinity
                             if "_volume" not in t]
        for name in unique:
            pvc = self.pvcs.get(f"{pod.namespace}/{name}")
            if pvc is None:
                # referenced claim doesn't exist (informer-order race):
                # the pod must NOT schedule — if the claim later arrives
                # bound to some zone, a pod already running elsewhere is
                # permanently separated from its volume. An empty In()
                # is a requirements conflict: matches nothing, so the
                # pod stays pending until add_pvc re-decorates it.
                pod.node_affinity.append(
                    {"key": L.ZONE, "operator": "In", "values": (),
                     "_volume": f"{pod.namespace}/{name}"})
                continue
            zone = pvc.bound_zone()
            if zone is not None:
                pod.node_affinity.append(
                    {"key": L.ZONE, "operator": "In", "values": (zone,),
                     "_volume": f"{pod.namespace}/{name}"})

    def _index_update(self, pod: Pod, key: str) -> None:
        """Insert/remove a pod from the pending-group index according to
        its CURRENT state — the one reconciliation point every pod state
        transition funnels through."""
        if (pod.phase == "Pending" and pod.node_name is None
                and L.NOMINATED not in pod.annotations):
            self._pending_groups.setdefault(pod._gid, {})[key] = pod
        else:
            self._index_discard(pod, key)

    def _index_discard(self, pod: Pod, key: str) -> None:
        g = self._pending_groups.get(pod._gid)
        if g is not None:
            g.pop(key, None)
            if not g:
                del self._pending_groups[pod._gid]

    def delete_pod(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        pod = self.pods.pop(key, None)
        if pod:
            for pname in set(pod.pvc_names):
                refs = self._pods_by_pvc.get(f"{namespace}/{pname}")
                if refs is not None:
                    refs.discard(key)
                    if not refs:
                        del self._pods_by_pvc[f"{namespace}/{pname}"]
            self._index_discard(pod, key)
            self._notify("pod", "delete", pod)

    def pending_pods(self) -> List[Pod]:
        return [p for p in self.pods.values()
                if p.phase == "Pending" and p.node_name is None]

    def pending_unnominated_groups(self) -> List[List[Pod]]:
        """The provisioner's input, pre-grouped by constraint signature
        (gid) straight from the admission-time index — no per-pod pass.
        Returns fresh lists; callers may consume/mutate them freely."""
        return [list(g.values()) for g in self._pending_groups.values() if g]

    def pods_on_node(self, node_name: str) -> List[Pod]:
        return [p for p in self.pods.values() if p.node_name == node_name]

    def bind_pod(self, pod: Pod, node_name: str) -> None:
        pod.node_name = node_name
        pod.phase = "Running"
        self._index_update(pod, f"{pod.namespace}/{pod.name}")
        self._notify("pod", "bind", pod)

    def unbind_pod(self, pod: Pod) -> None:
        """Eviction: the pod returns to the pending pool (and the
        pending-group index, unless still nominated elsewhere)."""
        pod.node_name = None
        pod.phase = "Pending"
        self._index_update(pod, f"{pod.namespace}/{pod.name}")
        self._notify("pod", "unbind", pod)

    def nominate_pod(self, pod: Pod, claim_name: str) -> None:
        pod.annotations[L.NOMINATED] = claim_name
        self._index_update(pod, f"{pod.namespace}/{pod.name}")
        self._notify("pod", "nominate", pod)

    def unnominate_pod(self, pod: Pod) -> None:
        pod.annotations.pop(L.NOMINATED, None)
        self._index_update(pod, f"{pod.namespace}/{pod.name}")
        self._notify("pod", "unnominate", pod)

    # --- daemonsets (namespaced, like the pod index — name-only keys
    # would let team-b's "agent" silently replace team-a's) ---
    def add_daemonset(self, ds) -> object:
        self.daemonsets[f"{ds.namespace}/{ds.name}"] = ds
        self._notify("daemonset", "add", ds)
        return ds

    def delete_daemonset(self, name: str,
                         namespace: str = "default") -> None:
        ds = self.daemonsets.pop(f"{namespace}/{name}", None)
        if ds is not None:
            self._notify("daemonset", "delete", ds)

    # --- pod disruption budgets (namespaced, same rationale) ---
    def add_pdb(self, pdb) -> object:
        self.pdbs[f"{pdb.namespace}/{pdb.name}"] = pdb
        self._notify("pdb", "add", pdb)
        return pdb

    def delete_pdb(self, name: str, namespace: str = "default") -> None:
        pdb = self.pdbs.pop(f"{namespace}/{name}", None)
        if pdb is not None:
            self._notify("pdb", "delete", pdb)

    def pdb_disruptions_allowed(self, pdb) -> int:
        """Live disruptionsAllowed for one PDB: matching pods across the
        cluster, healthy = bound + Running."""
        total = healthy = 0
        for p in self.pods.values():
            if pdb.matches(p):
                total += 1
                if p.node_name is not None and p.phase == "Running":
                    healthy += 1
        return pdb.disruptions_allowed(total, healthy)

    # --- nodepools / nodeclasses (validated at admission, like the
    # reference's CEL rules on the CRDs) ---
    def add_nodepool(self, np_: NodePool) -> NodePool:
        from ..models.validation import validate_nodepool
        validate_nodepool(np_)
        self.nodepools[np_.name] = np_
        self._notify("nodepool", "add", np_)
        return np_

    def add_nodeclass(self, nc: NodeClassSpec) -> NodeClassSpec:
        from ..models.validation import validate_nodeclass
        validate_nodeclass(nc)
        self.nodeclasses[nc.name] = nc
        self._notify("nodeclass", "add", nc)
        return nc

    def delete_nodeclass(self, name: str) -> None:
        nc = self.nodeclasses.pop(name, None)
        if nc is not None:
            self._notify("nodeclass", "delete", nc)

    def nodepools_by_weight(self) -> List[NodePool]:
        """Descending weight — provisioning tries heavier pools first
        (reference NodePool weight, karpenter.sh_nodepools.yaml:427-432)."""
        return sorted(self.nodepools.values(), key=lambda p: -p.weight)

    # --- nodeclaims ---
    def add_nodeclaim(self, nc: NodeClaim) -> NodeClaim:
        self.nodeclaims[nc.name] = nc
        self.index_nodeclaim_instance(nc)
        self._notify("nodeclaim", "add", nc)
        return nc

    def delete_nodeclaim(self, name: str) -> None:
        nc = self.nodeclaims.pop(name, None)
        if nc:
            if nc.provider_id:
                iid = nc.provider_id.rsplit("/", 1)[-1]
                if self._claims_by_iid.get(iid) == name:
                    del self._claims_by_iid[iid]
            self._notify("nodeclaim", "delete", nc)

    def touch_nodeclaim(self, nc: NodeClaim, action: str = "update") -> None:
        """Broadcast an IN-PLACE NodeClaim mutation to watchers. Claim
        state largely mutates on the object (phase, deletion timestamp),
        which no watcher can see — controllers making a mutation that
        changes what a solve may do (marking for deletion, cordoning)
        must call this so the warm-path delta feed observes it."""
        self._notify("nodeclaim", action, nc)

    def touch_node(self, node: Node, action: str = "update") -> None:
        """Broadcast an in-place Node mutation (e.g. a cordon taint) —
        same rationale as touch_nodeclaim."""
        self._notify("node", action, node)

    def index_nodeclaim_instance(self, nc: NodeClaim) -> None:
        """Register the claim's instance id in the lookup index — called
        when provider_id is assigned post-launch (the claim is added to the
        store before the cloud answers, so add-time indexing misses it)."""
        if nc.provider_id:
            self._claims_by_iid[nc.provider_id.rsplit("/", 1)[-1]] = nc.name

    def nodeclaims_for_pool(self, pool: str) -> List[NodeClaim]:
        return [c for c in self.nodeclaims.values() if c.nodepool == pool]

    def nodeclaim_by_provider_id(self, provider_id: str) -> Optional[NodeClaim]:
        """The provider-id index (reference operator.go:298-319)."""
        if not provider_id:
            return None
        c = self.nodeclaim_by_instance_id(provider_id.rsplit("/", 1)[-1])
        return c if c is not None and c.provider_id == provider_id else None

    def nodeclaims_by_instance_ids(self, instance_ids: Iterable[str],
                                   ) -> Dict[str, NodeClaim]:
        """Batch instance-id → NodeClaim resolution for the interruption
        drain: one pass over the maintained index for the whole batch,
        and AT MOST ONE fallback scan shared by every index miss (the
        per-message path paid a full-claims scan per unknown instance —
        at 15k-message storms that scan dominated the drain). Unknown
        ids are simply absent from the result."""
        out: Dict[str, NodeClaim] = {}
        misses: List[str] = []
        for iid in instance_ids:
            if iid in out:
                continue
            name = self._claims_by_iid.get(iid)
            if name is not None:
                c = self.nodeclaims.get(name)
                if (c is not None
                        and (c.provider_id or "").rsplit("/", 1)[-1] == iid):
                    out[iid] = c
                    continue
            misses.append(iid)
        if misses:
            want = set(misses)
            for c in self.nodeclaims.values():
                pid = c.provider_id or ""
                if not pid:
                    continue
                iid = pid.rsplit("/", 1)[-1]
                if iid in want:
                    self._claims_by_iid[iid] = c.name
                    out[iid] = c
                    want.discard(iid)
                    if not want:
                        break
        return out

    def nodeclaim_by_instance_id(self, instance_id: str) -> Optional[NodeClaim]:
        """Instance-id lookup: provider ids end in the instance id
        (tpu:///zone/i-xxx), mirroring the reference's id-from-provider-id
        parse (utils.ParseInstanceID). O(1) via the maintained index; the
        scan fallback covers claims whose provider_id was set without
        index_nodeclaim_instance (tests mutating claims directly)."""
        name = self._claims_by_iid.get(instance_id)
        if name is not None:
            c = self.nodeclaims.get(name)
            if (c is not None
                    and (c.provider_id or "").rsplit("/", 1)[-1] == instance_id):
                return c
        for c in self.nodeclaims.values():
            pid = c.provider_id or ""
            if pid and pid.rsplit("/", 1)[-1] == instance_id:
                self._claims_by_iid[instance_id] = c.name
                return c
        return None

    # --- nodes ---
    def add_node(self, node: Node) -> Node:
        self.nodes[node.name] = node
        self._notify("node", "add", node)
        return node

    def delete_node(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node:
            self._notify("node", "delete", node)

    def node_for_nodeclaim(self, claim: NodeClaim) -> Optional[Node]:
        for n in self.nodes.values():
            if n.provider_id == claim.provider_id:
                return n
        return None
