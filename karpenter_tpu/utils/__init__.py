from .cache import TTLCache
from .clock import Clock, FakeClock, RealClock

__all__ = ["TTLCache", "Clock", "FakeClock", "RealClock"]
