"""TTL cache.

Reference parity: pkg/cache/cache.go:19-65 defines per-provider TTLs
(instance types 5m, offerings 5m, SSM 24h, discovered capacity 60d, ...).
Ours takes an injectable clock so tests can step time.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from .clock import Clock, RealClock

# TTL constants (seconds) — mirrors pkg/cache/cache.go
INSTANCE_TYPES_TTL = 5 * 60
OFFERINGS_TTL = 5 * 60
UNAVAILABLE_OFFERINGS_TTL = 3 * 60
PRICING_REFRESH = 12 * 3600
IMAGE_RESOLUTION_TTL = 24 * 3600
DISCOVERED_CAPACITY_TTL = 60 * 24 * 3600


class TTLCache:
    def __init__(self, ttl: float, clock: Optional[Clock] = None):
        self.ttl = ttl
        self.clock = clock or RealClock()
        self._store: Dict[Any, Tuple[float, Any]] = {}

    def get(self, key: Any) -> Optional[Any]:
        ent = self._store.get(key)
        if ent is None:
            return None
        exp, val = ent
        if self.clock.now() >= exp:
            del self._store[key]
            return None
        return val

    def set(self, key: Any, value: Any, ttl: Optional[float] = None) -> None:
        self._store[key] = (self.clock.now() + (ttl if ttl is not None else self.ttl), value)

    def get_or_set(self, key: Any, fn: Callable[[], Any]) -> Any:
        v = self.get(key)
        if v is None:
            v = fn()
            self.set(key, v)
        return v

    def delete(self, key: Any) -> None:
        self._store.pop(key, None)

    def prune(self) -> int:
        """Evict expired entries; returns how many were removed (callers
        that version their contents — the ICE cache — bump on expiry)."""
        now = self.clock.now()
        expired = [k for k, (exp, _) in self._store.items() if now >= exp]
        for k in expired:
            del self._store[k]
        return len(expired)

    def flush(self) -> None:
        self._store.clear()

    def items(self):
        now = self.clock.now()
        return [(k, v) for k, (exp, v) in self._store.items() if now < exp]

    def __len__(self) -> int:
        return len(self.items())
