"""ChangeMonitor: log-once-per-change dedupe.

Reference: `pretty.ChangeMonitor` (used at instancetype.go:261-266,305-321)
— noisy periodic reconciles log "discovered X" only when X actually
changed, with a TTL so steady-state re-logs occasionally.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Tuple

from .clock import Clock, RealClock


class ChangeMonitor:
    def __init__(self, ttl: float = 24 * 3600, clock: Optional[Clock] = None):
        self.ttl = ttl
        self.clock = clock or RealClock()
        self._seen: Dict[str, Tuple[str, float]] = {}

    def has_changed(self, key: str, value: Any) -> bool:
        """True (and remembers) if value differs from last call or the TTL
        lapsed — callers log only on True."""
        digest = hashlib.sha256(
            json.dumps(value, sort_keys=True, default=str).encode()).hexdigest()
        now = self.clock.now()
        prev = self._seen.get(key)
        if prev is not None and prev[0] == digest and now - prev[1] < self.ttl:
            return False
        self._seen[key] = (digest, now)
        return True
