"""Injectable clock (reference uses k8s.io/utils/clock the same way; the
fake clock drives TTL/expiry behavior in tests deterministically)."""

from __future__ import annotations

import time
from typing import Protocol


class Clock(Protocol):
    def now(self) -> float: ...


class RealClock:
    def now(self) -> float:
        return time.time()


class FakeClock:
    """Steppable clock. Besides step/set it supports SCHEDULED JUMPS — the
    fault-injection seam for clock skew (faults/plan.ClockJump: "clock
    jumps +90s at t=200"): once simulated time reaches `at`, now() applies
    the delta exactly once, so TTL caches, batcher windows, lease renewals
    and boot delays all see the same discontinuity a real clock step (NTP
    correction, VM migration) produces. Zero overhead with no jumps armed
    (one empty-list check)."""

    def __init__(self, start: float = 1_000_000.0):
        self._t = start
        # sorted [(at, delta, callback-or-None)], applied by now()
        self._jumps: list = []

    def schedule_jump(self, at: float, delta: float,
                      on_jump=None) -> None:
        """Arm a one-shot jump: when now() first observes t >= at, time
        becomes t + delta. on_jump(new_now, delta) fires as it applies."""
        import bisect
        bisect.insort(self._jumps, (at, delta, on_jump),
                      key=lambda j: j[0])

    def now(self) -> float:
        if self._jumps and self._t >= self._jumps[0][0]:
            # a jump can carry time past the next jump's `at` — drain all
            while self._jumps and self._t >= self._jumps[0][0]:
                _, delta, cb = self._jumps.pop(0)
                self._t += delta
                if cb is not None:
                    cb(self._t, delta)
        return self._t

    def step(self, seconds: float) -> None:
        self._t += seconds

    def set(self, t: float) -> None:
        self._t = t
