"""Injectable clock (reference uses k8s.io/utils/clock the same way; the
fake clock drives TTL/expiry behavior in tests deterministically)."""

from __future__ import annotations

import time
from typing import Protocol


class Clock(Protocol):
    def now(self) -> float: ...


class RealClock:
    def now(self) -> float:
        return time.time()


class FakeClock:
    def __init__(self, start: float = 1_000_000.0):
        self._t = start

    def now(self) -> float:
        return self._t

    def step(self, seconds: float) -> None:
        self._t += seconds

    def set(self, t: float) -> None:
        self._t = t
