"""Crash-point seams: named cut points on the provisioning commit path.

Borg/Omega-lineage controllers prove failover by dying at the worst
possible instants — between the intent write and the wire call, between
the wire call and the commit, mid-drain, mid-audit — and asserting the
rebuilt process converges without leaking or double-provisioning
(PAPERS.md: Borg §3.3 "Checkpointing and failover"). This module is the
seam those deaths flow through: production code calls `fire(point)` at
each cut point, and the call is a no-op (one `is None` check) unless a
restart chaos harness armed the hook (`faults/injector.crash_point_hook`
→ `FaultPlan.on_crash_point`, which raises `CrashInjected` when a
`CrashPoint` rule covers the firing).

The cut-point catalog (docs/robustness.md "Restart & crash recovery"):

- ``mid_launch_batch``  — Provisioner._launch, AFTER the intent journal
  records the batch, BEFORE the CreateFleet wire call (intents open,
  nothing launched).
- ``post_launch``       — Provisioner._launch, AFTER CreateFleet
  returned, BEFORE any result is committed to the store (instances
  exist, no claim knows about them).
- ``mid_drain``         — TerminationController._terminate_one,
  immediately before the instance terminate call (node gone from the
  store, instance still running).
- ``mid_warm_audit``    — WarmPathEngine._run_audit, before the warm
  window's accumulated admissions replay through the full solver.

Same nil-guarded shape as ops.solver's device-dispatch fault hook: an
un-armed process pays one attribute check per seam.
"""

from __future__ import annotations

from typing import Callable, Optional

CUT_POINTS = ("mid_launch_batch", "post_launch", "mid_drain",
              "mid_warm_audit")


class CrashInjected(RuntimeError):
    """The simulated operator process died at a cut point. Deliberately
    NOT a CloudError: the engine's retry machinery must not absorb it —
    it unwinds the whole engine, exactly like a real crash."""


_hook: Optional[Callable[[str], None]] = None


def set_crash_hook(fn: Optional[Callable[[str], None]]) -> None:
    """Arm/disarm the process-global crash hook (faults/injector scopes
    this with a context manager so a failed scenario can't leak it)."""
    global _hook
    _hook = fn


def fire(point: str) -> None:
    """Production seams call this at each cut point; armed plans may
    raise CrashInjected from inside the hook."""
    if _hook is not None:
        _hook(point)
