"""Minimal 5-field cron matcher (UTC) for disruption-budget windows.

Reference: NodePool disruption budgets carry `schedule` (crontab) +
`duration`; the budget constrains disruption only while inside an open
window (karpenter.sh_nodepools.yaml:126-141 — 'schedule must be set
with duration'). Supported syntax: `*`, numbers, ranges `a-b`, lists
`a,b,c`, steps `*/n` and `a-b/n`, plus the standard dom/dow OR rule
(when BOTH day fields are restricted, either matching suffices). No
external cron library exists in this image; windows are minutes-grained
so the matcher only ever needs per-minute checks.
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import FrozenSet, Tuple

_BOUNDS = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))


class CronError(ValueError):
    pass


def _parse_field(spec: str, lo: int, hi: int) -> FrozenSet[int]:
    vals = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, s = part.split("/", 1)
            if not s.isdigit() or int(s) < 1:
                raise CronError(f"bad step {s!r}")
            step = int(s)
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            if not (a.isdigit() and b.isdigit()):
                raise CronError(f"bad range {part!r}")
            start, end = int(a), int(b)
        elif part.isdigit():
            start = end = int(part)
        else:
            raise CronError(f"bad field {part!r}")
        if not (lo <= start <= end <= hi):
            raise CronError(f"{part!r} out of [{lo}, {hi}]")
        vals.update(v for v in range(start, end + 1)
                    if (v - start) % step == 0)
    return frozenset(vals)


@lru_cache(maxsize=256)
def parse(expr: str) -> Tuple[FrozenSet[int], ...]:
    fields = expr.split()
    if len(fields) != 5:
        raise CronError(f"cron needs 5 fields, got {len(fields)}: {expr!r}")
    return tuple(_parse_field(f, lo, hi)
                 for f, (lo, hi) in zip(fields, _BOUNDS))


def matches(expr: str, t: float) -> bool:
    """Does minute t (epoch seconds, UTC) match the expression?"""
    minute, hour, dom, month, dow = parse(expr)
    g = time.gmtime(t)
    if g.tm_min not in minute or g.tm_hour not in hour:
        return False
    if g.tm_mon not in month:
        return False
    # dom/dow OR rule: when both are restricted, either matching passes
    cron_dow = (g.tm_wday + 1) % 7  # cron: 0 = Sunday; tm_wday: 0 = Monday
    dom_restricted = dom != frozenset(range(1, 32))
    dow_restricted = dow != frozenset(range(0, 7))
    dom_ok = g.tm_mday in dom
    dow_ok = cron_dow in dow
    if dom_restricted and dow_restricted:
        return dom_ok or dow_ok
    return dom_ok and dow_ok


@lru_cache(maxsize=4096)
def _in_window_bucket(expr: str, duration: float, minute: int) -> bool:
    start_minute = minute * 60
    for i in range(int(duration // 60) + 1):
        t = start_minute - i * 60
        if t + duration <= start_minute:
            break
        if matches(expr, t):
            return True
    return False


def in_window(expr: str, duration: float, now: float) -> bool:
    """Is `now` inside a window opened by the most recent matching
    minute? (A window opens at every matching minute and stays open for
    `duration` seconds.) Memoized per minute: the scan is linear in
    duration, and disruption evaluates budgets once per candidate per
    pass — a month-long freeze must not cost 43k gmtime calls per
    candidate. Minute granularity: a non-minute-aligned duration's
    close rounds up to the end of its minute (cron windows are
    minute-grained; erring open is the conservative side for a
    freeze)."""
    return _in_window_bucket(expr, float(duration), int(now) // 60)
