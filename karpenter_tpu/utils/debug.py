"""Debug monitor: stream cluster state transitions during runs.

Reference: test/pkg/debug/monitor.go:31-71 — the e2e suites attach
observers that stream node/nodeclaim/pod/event changes while a scenario
runs, so a wedged run shows WHERE it wedged instead of a silent timeout.
Ours hooks the store's watch seams plus an engine hook for in-place
mutations the watches can't see (claim phases, node readiness, events).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class DebugMonitor:
    """Attach with `DebugMonitor.attach(sim)`; every transition goes to
    `sink` as one line. `lines` keeps the trace for assertions."""

    store: object
    clock: object
    sink: Callable[[str], None]
    lines: List[str] = field(default_factory=list)
    _phases: Dict[str, str] = field(default_factory=dict)
    _ready: Dict[str, bool] = field(default_factory=dict)
    _events_seen: int = 0

    @classmethod
    def attach(cls, sim, sink: Optional[Callable[[str], None]] = None
               ) -> "DebugMonitor":
        mon = cls(store=sim.store, clock=sim.clock,
                  sink=sink or (lambda s: print(s, file=sys.stderr)))
        sim.store.watch("nodeclaim", lambda a, o: mon._emit(
            f"nodeclaim/{o.name}", a, getattr(o.phase, "value", o.phase)))
        sim.store.watch("node", lambda a, o: mon._emit(
            f"node/{o.name}", a, "ready" if o.ready else "not-ready"))
        sim.store.watch("pod", lambda a, o: mon._emit(
            f"pod/{o.namespace}/{o.name}", a, o.node_name or "pending"))
        sim.engine.add_hook(mon._tick)
        return mon

    def _emit(self, obj: str, action: str, detail) -> None:
        line = f"[{self.clock.now():10.1f}] {action:6s} {obj} ({detail})"
        self.lines.append(line)
        self.sink(line)

    def _tick(self, now: float) -> None:
        """Diff in-place mutations the watch seams don't fire for."""
        for c in self.store.nodeclaims.values():
            phase = getattr(c.phase, "value", str(c.phase))
            if c.is_deleting():
                phase = "Terminating"
            if self._phases.get(c.name) != phase:
                self._phases[c.name] = phase
                self._emit(f"nodeclaim/{c.name}", "phase", phase)
        for n in self.store.nodes.values():
            if self._ready.get(n.name) != n.ready:
                self._ready[n.name] = n.ready
                self._emit(f"node/{n.name}", "cond",
                           "Ready" if n.ready else "NotReady")
        if len(self.store.events) > self._events_seen:
            for kind, name, reason, msg in self.store.events[self._events_seen:]:
                self._emit(f"{kind}/{name}", "event",
                           f"{reason}: {msg}" if msg else reason)
            self._events_seen = len(self.store.events)
