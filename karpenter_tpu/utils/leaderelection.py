"""Lease-based leader election — the HA story for multi-replica deploys.

Reference behavior: the controller-runtime manager's leader election
(cmd/controller/main.go wires `operator.NewOperator()` whose manager runs
client-go's leaderelection over a coordination.k8s.io Lease; the Helm chart
ships 2 replicas so one warm standby waits on the lease). This module
implements the same algorithm — client-go's tryAcquireOrRenew — over a
compare-and-swap'd lease record:

  - the lease names a holder with acquire/renew timestamps; writers CAS on
    a version counter (the resourceVersion analog);
  - expiry is judged from when THIS observer last saw the record CHANGE,
    never from the holder's timestamps directly (holders' clocks may skew —
    client-go's observedTime rule);
  - a holder renews every retry_period; failing to renew for renew_deadline
    steps it down locally (it stops reconciling before the lease expires,
    so two leaders never overlap: renew_deadline < lease_duration);
  - a non-holder acquires only after the observed record has not changed
    for lease_duration; transitions count leadership changes;
  - release() on clean shutdown hands the lease over immediately.

Backends: InMemoryLeaseBackend (sim/tests — deterministic with FakeClock),
FileLeaseBackend (flock'd JSON file: real mutual exclusion for replicas
sharing a volume; a Kubernetes backend would CAS a Lease object through the
API server the same way).

Timing defaults match client-go/controller-runtime: 15s lease, 10s renew
deadline, 2s retry.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field, replace
from typing import Callable, List, Optional, Protocol, Tuple


@dataclass(frozen=True)
class Lease:
    holder: str
    acquire_time: float
    renew_time: float
    lease_duration: float
    transitions: int = 0
    version: int = 0  # CAS token, assigned by the backend on every write


class LeaseBackend(Protocol):
    def get(self) -> Optional[Lease]:
        ...

    def update(self, lease: Lease, expected_version: Optional[int]) -> bool:
        """Write iff the stored version matches (None = create iff absent).
        Returns success; the backend assigns the new version itself."""
        ...


class InMemoryLeaseBackend:
    """Thread-safe CAS lease for tests and the single-process sim."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._lease: Optional[Lease] = None
        self._next_version = 1
        self.fail_writes = False  # fault injection: partition the backend

    def get(self) -> Optional[Lease]:
        with self._lock:
            return self._lease

    def update(self, lease: Lease, expected_version: Optional[int]) -> bool:
        with self._lock:
            if self.fail_writes:
                return False
            cur = self._lease.version if self._lease is not None else None
            if cur != expected_version:
                return False
            self._lease = replace(lease, version=self._next_version)
            self._next_version += 1
            return True


class FileLeaseBackend:
    """flock'd JSON lease file: real cross-process mutual exclusion for
    replicas sharing a volume (the k8s Lease-object analog)."""

    def __init__(self, path: str) -> None:
        self.path = path

    def _locked(self):
        import contextlib
        import fcntl

        @contextlib.contextmanager
        def cm():
            fd = os.open(self.path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                yield
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
                os.close(fd)
        return cm()

    def _read(self) -> Optional[Lease]:
        try:
            with open(self.path) as f:
                return Lease(**json.load(f))
        except (FileNotFoundError, json.JSONDecodeError, TypeError):
            return None

    def get(self) -> Optional[Lease]:
        with self._locked():
            return self._read()

    def update(self, lease: Lease, expected_version: Optional[int]) -> bool:
        with self._locked():
            cur = self._read()
            cur_ver = cur.version if cur is not None else None
            if cur_ver != expected_version:
                return False
            out = replace(lease, version=(cur_ver or 0) + 1)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(out.__dict__, f)
            os.replace(tmp, self.path)  # atomic publish
            return True


class HTTPLeaseBackend:
    """CAS lease through the remote cloud server's /lease endpoint — the
    coordination.k8s.io Lease-through-API-server analog. Replicas elect
    over the network instead of a shared RWX volume (the FileLeaseBackend
    caveat in deploy/karpenter-tpu.yaml). Transport failures read as
    'can't reach the lease': get() → None-safe False paths and update() →
    False, so a partitioned leader steps down within renew_deadline, the
    same way losing the API server does in client-go."""

    def __init__(self, host: str, port: int, timeout: float = 2.0) -> None:
        self.host, self.port, self.timeout = host, port, timeout

    def _request(self, method: str, body: Optional[dict] = None):
        import http.client
        import json as _json
        try:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout)
            try:
                conn.request(method, "/lease",
                             body=_json.dumps(body) if body else None,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return _json.loads(resp.read())
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            # HTTPException covers a connection dropped mid-response
            # (IncompleteRead/BadStatusLine) — same "can't reach the
            # lease" semantics as a refused connection
            return None

    def get(self) -> Optional[Lease]:
        out = self._request("GET")
        if not out or out.get("lease") is None:
            return None
        return Lease(**out["lease"])

    def update(self, lease: Lease, expected_version: Optional[int]) -> bool:
        out = self._request("POST", {"lease": lease.__dict__,
                                     "expected_version": expected_version})
        return bool(out and out.get("ok"))


@dataclass
class Elector:
    backend: LeaseBackend
    identity: str
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    on_started_leading: List[Callable[[], None]] = field(default_factory=list)
    on_stopped_leading: List[Callable[[], None]] = field(default_factory=list)

    _leading: bool = False
    _renewed_at: float = 0.0
    # (version, first-seen-at) of the last observed record — expiry is
    # judged from OUR clock at the moment the record last changed
    _observed: Optional[Tuple[int, float]] = None

    name = "leader-election"  # lets the Engine drive it as a controller

    def is_leader(self) -> bool:
        return self._leading

    def reconcile(self, now: float) -> float:
        self.tick(now)
        return self.retry_period

    def tick(self, now: float) -> bool:
        """One tryAcquireOrRenew pass; returns leadership after the pass."""
        acquired = self._try_acquire_or_renew(now)
        if acquired:
            self._renewed_at = now
            if not self._leading:
                self._leading = True
                for fn in self.on_started_leading:
                    fn()
        elif self._leading and now - self._renewed_at >= self.renew_deadline:
            # can't reach/CAS the lease: step down BEFORE it expires so a
            # new leader elected elsewhere never overlaps with us
            self._step_down()
        return self._leading

    def _step_down(self) -> None:
        self._leading = False
        for fn in self.on_stopped_leading:
            fn()

    def _observe(self, lease: Optional[Lease], now: float) -> None:
        if lease is None:
            self._observed = None
        elif self._observed is None or self._observed[0] != lease.version:
            self._observed = (lease.version, now)

    def _try_acquire_or_renew(self, now: float) -> bool:
        lease = self.backend.get()
        self._observe(lease, now)
        if lease is None or not lease.holder:
            return self.backend.update(
                Lease(holder=self.identity, acquire_time=now, renew_time=now,
                      lease_duration=self.lease_duration,
                      transitions=(lease.transitions + 1) if lease else 0),
                lease.version if lease else None)
        if lease.holder != self.identity:
            seen_at = self._observed[1] if self._observed else now
            if now - seen_at < lease.lease_duration:
                return False  # current holder still within its lease
            return self.backend.update(
                Lease(holder=self.identity, acquire_time=now, renew_time=now,
                      lease_duration=self.lease_duration,
                      transitions=lease.transitions + 1),
                lease.version)
        return self.backend.update(
            replace(lease, renew_time=now,
                    lease_duration=self.lease_duration),
            lease.version)

    def release(self, now: float) -> None:
        """Clean handover on shutdown (client-go's ReleaseOnCancel): clear
        the holder so the standby acquires on its next retry, not after a
        full lease_duration."""
        if not self._leading:
            return
        lease = self.backend.get()
        if lease is not None and lease.holder == self.identity:
            self.backend.update(
                replace(lease, holder="", renew_time=now), lease.version)
        self._step_down()
