"""Layered configuration: flags < env vars < explicit overrides.

Reference pattern: pkg/operator/options/options.go:30-58 — provider
options layered onto core options via an Injectable interface, every flag
mirrored by an env var, plus feature gates (Makefile:21-24: NodeRepair,
ReservedCapacity, SpotToSpotConsolidation, ...).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field, fields
from typing import Dict, Optional


def _env_name(flag: str) -> str:
    return flag.upper().replace("-", "_")


# Process-environment knobs that live OUTSIDE the Options dataclass —
# subsystem gates and artifact sinks read directly from os.environ at
# their use sites (module import order and subprocess scenarios make
# flag plumbing the wrong seam for these). This registry is the single
# documentation source: tools/gen_docs.py renders it into
# docs/reference/settings.md, and graftlint's `undocumented-env` rule
# fails the build when a KARPENTER_TPU_* literal appears in the package
# without a row here (docs/static-analysis.md).
# Rows: (name, default, description).
ENV_KNOBS: tuple = (
    ("KARPENTER_TPU_DELTA", "1",
     "delta-plane master gate (ops/delta.py) — 0 disarms every "
     "serve-and-verify memo (solve/affinity/spread/optimizer) and the "
     "steady state recomputes from scratch, byte-for-byte identical"),
    ("KARPENTER_TPU_DELTA_AUDIT", "16",
     "delta-memo audit cadence: every this-many serves of a key is "
     "refused and recomputed fresh for a confirm/diverge verdict "
     "(0 audits every pass, i.e. the memo never serves)"),
    ("KARPENTER_TPU_DURATIONS", "<repo>/scale_durations.jsonl",
     "duration-event JSONL sink for the scale suite "
     "(metrics/durations.py, the Timestream analog)"),
    ("KARPENTER_TPU_FED_TIMEOUT", "10",
     "federation per-RPC wire deadline in seconds (federation/"
     "transport.py) — every HTTP transport call and handshake respects "
     "it; a timed-out RPC surfaces as a retryable ServerError and "
     "feeds the client's retry/breaker ladder"),
    ("KARPENTER_TPU_INTEGRITY", "1",
     "solution-integrity plane master gate — 0 restores the unverified "
     "solve path byte-for-byte (integrity/)"),
    ("KARPENTER_TPU_INTEGRITY_AUDIT", "16",
     "resident-state digest-audit cadence: one readback audit per this "
     "many verified solves (0 disables the audit)"),
    ("KARPENTER_TPU_INTEGRITY_CANARY", "64",
     "canary dual-path cadence: one host re-solve per this many device "
     "solves per facade (0 disables the canary)"),
    ("KARPENTER_TPU_OPTIMIZER", "1",
     "global disruption optimizer gate — 0 restores greedy "
     "consolidation byte-for-byte (optimizer/)"),
    ("KARPENTER_TPU_PALLAS", "0",
     "opt-in Pallas screen kernel — 1 enables when a TPU backend is "
     "attached and the probe compiles (ops/pallas_screen.py)"),
    ("KARPENTER_TPU_PERF_ARCHIVE", "<repo>/perf_archive.jsonl",
     "cross-run perf archive path the bench appends to and "
     "`make perf-gate` reads (obs/perfarchive.py)"),
    ("KARPENTER_TPU_RESIDENT", "1",
     "device-resident cluster state — 0 disarms the manager and every "
     "upload falls back to the classic full-upload path (ops/resident.py)"),
    ("KARPENTER_TPU_TRACE_DIR", "",
     "when set, the tracer auto-enables and writes traces.jsonl here "
     "(obs/tracer.py)"),
    ("KARPENTER_TPU_TRACE_RING", "16",
     "flight-recorder ring capacity (traces kept in memory for "
     "post-mortem dumps)"),
)


@dataclass
class Options:
    cluster_name: str = "karpenter-tpu"
    region: str = "region-1"
    # reference default vmMemoryOverheadPercent=0.075 (options.go)
    vm_memory_overhead_percent: float = 0.075
    interruption_queue: str = ""          # empty = interruption handling off
    # auto | hybrid | device | native | host — auto resolves to the
    # size-adaptive hybrid on accelerator hosts (small solves native/host,
    # large on the device kernel)
    solver_backend: str = "auto"
    # non-empty: every Solve() runs under jax.profiler.trace(dir) —
    # TensorBoard-viewable XLA device traces (utils/profiling.py)
    profile_dir: str = ""
    batch_idle_seconds: float = 1.0
    batch_max_seconds: float = 10.0
    max_instance_types: int = 60
    isolated: bool = False                # static pricing only (isolated-vpc)
    # last-good price book persisted here (the reference's generated
    # static price table analog); empty disables persistence
    pricing_snapshot_file: str = ""
    metrics_port: int = 8080
    log_level: str = "info"
    # HA: lease-based leader election (reference: controller-runtime
    # manager election, 2-replica chart). The file backend gives replicas
    # sharing a volume real mutual exclusion; empty path disables election.
    leader_elect: bool = False
    leader_elect_lease_file: str = "/var/run/karpenter-tpu/leader.lease"
    # host:port of a cloud endpoint serving the CAS'd /lease (the
    # Lease-through-API-server analog); non-empty overrides the file
    # backend and removes the shared-RWX-volume requirement
    leader_elect_endpoint: str = ""
    leader_elect_identity: str = ""       # default: hostname-pid
    # provisioning intent journal (state/journal.py): the write-ahead
    # log of launches, fsync'd before every CreateFleet call. Set a path
    # in production — a restarted operator replays the file's open
    # intents (adopt-or-reap) during rehydration; empty keeps the
    # journal in-memory (crash recovery then rests on adoption tags +
    # idempotency tokens alone)
    intent_journal_file: str = ""
    # warm-path audit cadence: every K-th warm admission window is
    # replayed through a full solve (docs/warmpath.md; tier-1 tests and
    # chaos scenarios run at 1 = always-on). Only read when the
    # WarmPathAdmission gate is on.
    warmpath_audit_every: int = 16
    # fleet mode (docs/fleet.md): >0 runs N simulated tenant control
    # planes through one process and ONE shared SolverService instead of
    # the single-cluster operator (`make fleet` drives 50). Each tenant
    # gets its own store/cloud/journal/warm path; per-tenant WAL files
    # derive from the --intent-journal-file DIRECTORY when set.
    fleet_tenants: int = 0
    # per-tenant solve-dispatch cap per fleet scheduling window — the
    # noisy-neighbor backpressure knob (fleet/service.py); only read in
    # fleet mode
    fleet_inflight_cap: int = 16
    # arm the shared SolverService's batched + pipelined dispatch engine
    # (fleet/service.py): compatible tenants' solves pack into one
    # vmapped device call, encode/decode for batch k+1 overlaps device
    # work for batch k. Results, hashes, and fault fingerprints are
    # identical either way; only read in fleet mode
    fleet_batch: bool = False
    # federation mode (docs/federation.md): route the fleet's batched
    # buckets through the federation plane (karpenter_tpu/federation) —
    # the device half of every solve runs in a SolverServer process
    # reached over the cloud/remote.py wire, catalogs cross once per
    # cluster via content tokens, and wire failures degrade buckets to
    # the local host-solve path under the watchdog's
    # federation_degraded invariant. Implies --fleet-batch and a device
    # backend; only read in fleet mode
    federate: bool = False
    # host:port of a running federation solver server (python -m
    # karpenter_tpu.federation.server); empty with --federate embeds a
    # SolverServer behind an in-memory transport (full wire fidelity —
    # every payload round-trips the codec — without a socket); only
    # read with --federate
    server_addr: str = ""
    # long-soak serving mode (loadgen/, docs/loadgen.md): --soak drives
    # a tenant fleet OPEN-LOOP — seeded arrival processes fire on the
    # sim clock without waiting for drain, admission control sheds or
    # defers load past saturation, and the run is judged by the SLO
    # burn rates + the watchdog's overload_unbounded invariant
    soak: bool = False
    # soak scenario from loadgen.SOAK_SCENARIOS (soak_smoke |
    # soak_overload | soak_diurnal); only read with --soak
    soak_scenario: str = "soak_smoke"
    # arrival-rate override in batches/sec per tenant (0 = the
    # scenario's default); only read with --soak
    arrival_rate: float = 0.0
    # open-loop drive window in sim seconds (0 = scenario default; a
    # shorter value never truncates scheduled arrivals — the window
    # only ever extends); only read with --soak
    soak_duration: float = 0.0
    # disarm the admission controller's shed/defer verdicts (the
    # negative harness — overload then degrades unboundedly and the
    # watchdog must page); only read with --soak
    soak_no_admission: bool = False
    # feature gates (reference Makefile:21-24 + settings.md)
    feature_gates: Dict[str, bool] = field(default_factory=lambda: {
        "SpotToSpotConsolidation": True,
        "ReservedCapacity": True,
        "NodeRepair": True,
        "NodeOverlay": False,
        # arrival-only reconciles admit against the standing headroom
        # ledger instead of paying a full solve (karpenter_tpu/warmpath/)
        "WarmPathAdmission": False,
    })

    def gate(self, name: str) -> bool:
        return self.feature_gates.get(name, False)

    @classmethod
    def parse(cls, argv: Optional[list] = None,
              env: Optional[Dict[str, str]] = None) -> "Options":
        env = dict(os.environ if env is None else env)
        parser = argparse.ArgumentParser("karpenter-tpu")
        defaults = cls()
        for f in fields(cls):
            if f.name == "feature_gates":
                parser.add_argument("--feature-gates", type=str, default=None,
                                    help="Gate=true,Gate2=false")
                continue
            flag = "--" + f.name.replace("_", "-")
            default = getattr(defaults, f.name)
            if f.type in ("bool", bool):
                # bare `--soak` arms the flag; `--soak false` still
                # disarms (there are no positionals, so nargs="?" is
                # unambiguous)
                parser.add_argument(flag, nargs="?", const=True,
                                    type=lambda s: s.lower() in ("1", "true", "yes"),
                                    default=None)
            elif f.type in ("float", float):
                parser.add_argument(flag, type=float, default=None)
            elif f.type in ("int", int):
                parser.add_argument(flag, type=int, default=None)
            else:
                parser.add_argument(flag, type=str, default=None)
        args = parser.parse_args(argv or [])

        out = cls()
        for f in fields(cls):
            if f.name == "feature_gates":
                continue
            # precedence: explicit flag > env var > default
            val = getattr(args, f.name, None)
            if val is None:
                ev = env.get(_env_name(f.name))
                if ev is not None:
                    cur = getattr(out, f.name)
                    if isinstance(cur, bool):
                        val = ev.lower() in ("1", "true", "yes")
                    elif isinstance(cur, float):
                        val = float(ev)
                    elif isinstance(cur, int):
                        val = int(ev)
                    else:
                        val = ev
            if val is not None:
                setattr(out, f.name, val)
        gates_str = args.feature_gates or env.get("FEATURE_GATES")
        if gates_str:
            for part in gates_str.split(","):
                if "=" in part:
                    k, v = part.split("=", 1)
                    out.feature_gates[k.strip()] = v.strip().lower() in ("1", "true", "yes")
        return out
