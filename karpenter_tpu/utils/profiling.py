"""Solve() profiling — the JAX/XLA device-trace hook.

Reference observability is Prometheus metrics + structured logs (SURVEY §5:
every AWS SDK call timed through a middleware). The TPU-side analog this
framework adds: when `Options.profile_dir` is set, each device solve runs
under `jax.profiler.trace`, producing TensorBoard-viewable traces with
per-op device time (MXU/VPU occupancy, transfer gaps, scan step cost) —
the tool used to find the node-axis oversizing this repo's bench history
records. Wall-clock timing is always on via the SOLVE_DURATION histogram
(`metrics/registry`), measured around `block_until_ready`-equivalent
boundaries (the facade's host read blocks on the device result).
"""

from __future__ import annotations

import contextlib


_warned = False


@contextlib.contextmanager
def maybe_trace(profile_dir: str = ""):
    """Wrap a block in a JAX profiler trace when profile_dir is set;
    zero-cost no-op otherwise. Tracing is best-effort: on a jax-less host
    (where the native/host backends still run) the hook degrades to a
    one-time warning instead of killing every solve."""
    if not profile_dir:
        yield
        return
    try:
        import jax
    except ImportError:
        global _warned
        if not _warned:
            _warned = True
            import warnings
            warnings.warn("profile_dir set but jax is not importable; "
                          "solve tracing disabled")
        yield
        return
    with jax.profiler.trace(profile_dir):
        yield
