"""Warm-path incremental admission engine with full-solve audit.

The north-star headline is the cold case — 100k pending pods against the
full catalog in one kernel solve — but production steady state is the
opposite shape: a few pods arrive per engine tick against a standing
fleet, and a full [G, N, T, Z, C, R] solve per trickle pays the whole
encode + node-view rebuild + solve cost for a placement a first-fit into
known headroom decides in microseconds. This subsystem splits the two
regimes (the CvxCluster structure-reuse insight, PAPERS.md; the
Tesserae incremental-vs-periodic-global split):

- `DeltaTracker` (delta.py) watches the store's event feed and
  classifies each reconcile: *warm* when only pod arrivals happened
  since the last committed solve, *cold* when anything else changed
  (claims, nodes, daemonsets, catalog epoch, ICE marks, config hashes).
- `WarmAdmitter` (admitter.py) places warm arrivals against the
  standing per-pool headroom ledger using the SAME first-fit policy and
  offering masks as the full solver's existing-node pass
  (ops/binpack.first_fit_group — shared code, not a reimplementation).
  Colocation bundles and any non-fitting remainder escalate to the full
  solver; the warm path never approximates.
- `Auditor` (auditor.py) replays accumulated warm admissions through a
  fresh full `Solver.solve()` every K batches (always, in tier-1 tests)
  and meters divergence; divergence forces the path cold and
  flight-records a trace. The auditor is what makes the warm path a
  correctness tool instead of a fast-path gamble.
- `WarmPathEngine` (engine.py) orchestrates: classify → admit →
  audit → commit, wired into the provisioner (controllers/provisioner).

See docs/warmpath.md for the decision table and escalation rules.
"""

from .admitter import PoolLedger, WarmAdmitter, build_pool_ledger
from .auditor import Auditor
from .delta import DeltaTracker
from .engine import WarmPathEngine

__all__ = ["DeltaTracker", "WarmAdmitter", "PoolLedger",
           "build_pool_ledger", "Auditor", "WarmPathEngine"]
