"""WarmAdmitter: first-fit arrival pods into the standing headroom ledger.

The ledger is a per-pool snapshot of exactly the solve inputs the cold
path would rebuild from scratch every reconcile: the pool's
availability-masked catalog tensors (capacity-block gate + daemonset
overhead already applied — `Solver.warm_catalog`), the standing virtual
nodes with resident occupancy (`state.cluster.pool_node_views` — the
same filter the provisioner's cold pass uses), and the residents per
claim. Between commits the ledger is advanced in place by each warm
admission, so admitting a 32-pod burst costs one small encode plus a
first-fit walk — no O(claims × pods) node-view rebuild, no full solve.

Placement semantics are the full solver's by construction: the encode
pipeline is `Solver.prepare_warm` (the same calls, in the same order,
as `Solver.solve`'s plain path) and the node-filling loop is
`ops.binpack.first_fit_group` — the code `solve_host` itself runs
before opening new nodes. What the warm path does NOT do is open nodes:
colocation bundles and any pods the standing fleet cannot absorb
escalate to the full solver untouched.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..models.nodepool import NodeClassSpec, NodePool
from ..models.pod import Pod
from ..ops.binpack import (SolveResult, VirtualNode, clone_nodes,
                           first_fit_group)
from ..ops.encode import (CatalogTensors, align_resources,
                          align_zone_overhead)


def _key(p: Pod) -> str:
    return f"{p.namespace}/{p.name}"


def pool_fingerprint(pool: NodePool) -> tuple:
    """Every solve-relevant NodePool field. Broader than pool.hash() —
    the drift hash deliberately covers only node-template fields (labels,
    taints, node_class), but the warm/cold decision must also notice
    requirements, limits, and weight changes: any of them changes what a
    solve would do."""
    reqs = pool.requirements
    req_sig = tuple(sorted(
        (k, tuple(sorted(vs.values)), vs.complement, vs.gt, vs.lt,
         vs.dne, reqs.min_values(k))
        for k, vs in ((k, reqs.get(k)) for k in reqs.keys())))
    limits = tuple(sorted(pool.limits.items())) if pool.limits else ()
    return (pool.hash(), req_sig, limits, pool.weight)


@dataclass
class PoolLedger:
    """One pool's standing headroom: everything a warm admission needs
    that a cold solve would otherwise recompute."""

    pool: NodePool
    node_class: NodeClassSpec
    pool_fp: tuple                    # pool_fingerprint(pool) at build
    nodeclass_hash: str
    ready: bool
    epoch: tuple                      # catalog availability version at build
    cat: Optional[CatalogTensors]     # gated + daemonset-reduced (None if not ready)
    nodes: List[VirtualNode] = field(default_factory=list)
    existing_pods: Dict[str, List[Pod]] = field(default_factory=dict)
    daemonsets: list = field(default_factory=list)


def build_pool_ledger(store, solver, pool: NodePool, now: float) -> PoolLedger:
    """Snapshot one pool's headroom from live cluster state — called at
    commit time (end of every cold solve). Uses the same view builder as
    the cold path (`pool_node_views`), so ledger and solve headroom
    cannot diverge."""
    from ..state.cluster import pool_node_views
    node_class = store.nodeclasses.get(pool.node_class) or NodeClassSpec()
    daemonsets = list(store.daemonsets.values())
    if not node_class.ready:
        # the cold path skips not-ready pools too; an empty ledger makes
        # the admitter pass every group through to the next pool
        return PoolLedger(pool=pool, node_class=node_class,
                          pool_fp=pool_fingerprint(pool),
                          nodeclass_hash=node_class.hash(), ready=False,
                          epoch=tuple(solver.catalog.epoch), cat=None,
                          daemonsets=daemonsets)
    cat = solver.warm_catalog(pool, node_class, daemonsets)
    views = pool_node_views(store, cat, now, pool.name)
    return PoolLedger(pool=pool, node_class=node_class,
                      pool_fp=pool_fingerprint(pool),
                      nodeclass_hash=node_class.hash(), ready=True,
                      epoch=tuple(solver.catalog.epoch), cat=cat,
                      nodes=[v.virtual for v in views],
                      existing_pods={v.claim.name: list(v.pods)
                                     for v in views},
                      daemonsets=daemonsets)


@dataclass
class WarmAdmission:
    """One pool's warm admission result."""

    placements: Dict[str, List[Pod]]   # claim name -> pods placed on it
    want: Dict[str, str]               # pod key -> claim name (audit record)
    passthrough: List[List[Pod]]       # taint-dropped groups -> next pool
    escalated: List[List[Pod]]         # bundles / non-fitting -> full solver
    # solution-integrity oracle findings on this admission's first-fit
    # result: >0 means NOTHING was placed (the whole batch escalated to
    # the full solver) and the engine must force the window cold
    integrity_violations: int = 0


class WarmAdmitter:
    def admit(self, solver, ledger: PoolLedger, pool: NodePool,
              groups: List[List[Pod]],
              occupancy: List[Tuple[Optional[str], List[Pod]]],
              ) -> WarmAdmission:
        """Place arrival `groups` (signature-grouped pod lists) onto the
        ledger's standing nodes. Mutates the ledger with successful
        placements. Escalation rules (never approximate):

        - a group carrying required positive hostname affinity (a
          colocation bundle) escalates whole — the bundle planner owns it;
        - a group the pool's taints drop passes through to the next pool
          (identical to the cold path's fall-through);
        - pods the standing fleet cannot absorb escalate to the full
          solver, which may open nodes for them."""
        from ..ops.colocate import has_colocation
        escalated: List[List[Pod]] = []
        plain: List[List[Pod]] = []
        for g in groups:
            (escalated if has_colocation([g[0]]) else plain).append(list(g))
        if not ledger.ready:
            # the cold path skips not-ready pools (pods fall through to
            # the next pool untouched) — mirror it
            return WarmAdmission({}, {}, plain, escalated)
        if not plain:
            return WarmAdmission({}, {}, [], escalated)
        if not ledger.nodes:
            # no standing fleet: every placement would need a new node
            escalated.extend(plain)
            return WarmAdmission({}, {}, [], escalated)
        cat = ledger.cat
        enc = solver.prepare_warm(plain, pool, cat, occupancy,
                                  ledger.nodes, ledger.existing_pods)
        passthrough: List[List[Pod]] = []
        if enc.dropped_keys:
            dropped = set(enc.dropped_keys)
            kept = []
            for g in plain:
                (passthrough if _key(g[0]) in dropped else kept).append(g)
            plain = kept
        if enc.G == 0 or not plain:
            escalated.extend(plain)
            return WarmAdmission({}, {}, passthrough, escalated)

        R = enc.requests.shape[1]
        alloc = align_resources(cat.allocatable, R)
        zovh = align_zone_overhead(cat, R)
        nodes = clone_nodes(ledger.nodes, R)
        unsched: Dict[int, int] = {}
        for g in range(enc.G):
            rem = first_fit_group(nodes, g, enc, cat, alloc, zovh,
                                  int(enc.counts[g]))
            if rem:
                unsched[g] = rem
        result = SolveResult(nodes=nodes, unschedulable=unsched)
        # solution-integrity oracle on the warm first-fit, BEFORE any
        # nomination commits — the same validation finish_solve applies
        # to cold results (karpenter_tpu/integrity/). A violation here
        # means the ledger's standing view produced an infeasible
        # placement: place nothing, escalate the whole batch to the
        # full solver, and let the engine force the window cold.
        from ..integrity import integrity_enabled
        if integrity_enabled():
            from ..integrity import INTEGRITY, verify_warm_result
            violations = verify_warm_result(cat, enc, result)
            INTEGRITY.record_warm(len(violations))
            # a warm commit advances the facade's resident-audit cadence
            # too: steady-state fleets are warm-dominated, and device-
            # resident rot must surface within one audit period, not at
            # the next (possibly hours-away) cold solve
            solver.warm_integrity_tick()
            if violations:
                INTEGRITY.record_breach_event()
                for vio in violations:
                    INTEGRITY.record_violation(vio.check, vio.detail)
                import logging
                logging.getLogger("karpenter_tpu.integrity").warning(
                    "warm-admit integrity violation (%s) — escalating "
                    "the batch to the full solver",
                    "; ".join(str(v) for v in violations[:4]))
                escalated.extend(plain)
                return WarmAdmission({}, {}, passthrough, escalated,
                                     integrity_violations=len(violations))
        out = solver._decode(cat, enc, result, pool, [])

        by_key = {_key(p): p for g in plain for p in g}
        placements = {c: [by_key[k] for k in keys]
                      for c, keys in out.existing_placements.items()}
        want = {k: c for c, keys in out.existing_placements.items()
                for k in keys}
        un = set(out.unschedulable)
        for g in plain:
            rest = [p for p in g if _key(p) in un]
            if rest:
                escalated.append(rest)
        if want:
            # fold the batch into the standing ledger: the first-fit's
            # node copies (cum advanced, masks narrowed) become the new
            # standing nodes; placements become residents. prior_by_group
            # and bans are recomputed per batch from existing_pods, so
            # clearing pods_by_group loses nothing.
            for n in nodes:
                n.pods_by_group = {}
            ledger.nodes = nodes
            for c, pods in placements.items():
                ledger.existing_pods.setdefault(c, []).extend(pods)
            # solve work the standing ledger answered without a gbuf
            # dispatch — the delta-served outcome the c16 regime's
            # warm-admit floor measures
            from ..obs.recompute import RECOMPUTE
            RECOMPUTE.classify("solve", served=True, units=len(want))
        return WarmAdmission(placements, want, passthrough, escalated)
