"""Auditor: the warm path's full-solve correctness check.

At every ledger commit the auditor snapshots the per-pool baseline —
standing nodes, residents, and the cluster occupancy exactly as the
ledger saw them. Each warm admission is recorded as (pods, intended
placement map). Every K recorded batches (K=1, i.e. after every warm
admission, in tier-1 tests and chaos scenarios) it replays ALL
admissions accumulated since the commit through a fresh, full
`Solver.solve()` against the baseline and compares:

- every audited pod must land on the SAME existing node the warm path
  chose (`existing_placements` equality),
- the full solver must open no new nodes for them (`launches` empty —
  the warm path only admits what the standing fleet absorbs),
- none may be unschedulable.

Any difference is divergence: metered (`warmpath_divergence_total`),
flight-recorded as a `warmpath.divergence` trace when tracing is on,
and reported to the engine, which forces the path cold. The audit costs
one solve against snapshots — it never touches live cluster state.

After a clean audit the engine rebases the baseline to the CURRENT
ledger state (on_commit again), so every audit window replays exactly
the batches admitted since the window opened against the headroom they
were admitted into. With K=1 each window holds one batch and the
comparison is exact semantics parity; with K>1 the replay solves the
window's batches as ONE pod set, so the solver's global FFD ordering
can legitimately disagree with the order the batches arrived in — a
real (if rare) quality divergence of incremental admission, exactly
what the meter exists to surface, repaired by the forced cold solve.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..models.pod import Pod
from ..obs.tracer import TRACER
from .admitter import PoolLedger


@dataclass
class _Baseline:
    ledger: PoolLedger           # pool/node_class/daemonset refs
    nodes: list                  # VirtualNode copies at commit
    pods: Dict[str, List[Pod]]   # residents per claim at commit
    occupancy: List[Tuple[Optional[str], List[Pod]]]


@dataclass
class _Batches:
    pods: List[Pod] = field(default_factory=list)
    want: Dict[str, str] = field(default_factory=dict)


class Auditor:
    def __init__(self, solver, audit_every: int = 1):
        self.solver = solver
        self.audit_every = max(1, int(audit_every))
        self._baselines: Dict[str, _Baseline] = {}
        self._batches: Dict[str, _Batches] = {}
        self._since_audit = 0
        # sim time of the OLDEST recorded-but-unaudited admission — the
        # audit-lag observable the invariant watchdog monitors (warm
        # coverage silently drifting behind is a finding, not a log line)
        self.pending_since: Optional[float] = None
        self.stats = {"audits": 0, "divergences": 0, "audited_pods": 0}

    def reset(self) -> None:
        """Restart: recorded-but-unaudited warm batches died with the
        old process, and the baselines they were admitted against
        describe a store that no longer exists — replaying them against
        a rebuilt baseline would manufacture false divergences. Drop
        everything; the forced-cold commit that follows a restart
        (WarmPathEngine.on_restart) re-establishes audit coverage."""
        self._baselines = {}
        self._batches = {}
        self._since_audit = 0
        self.pending_since = None

    # --- commit-time snapshot ---
    def on_commit(self, ledgers: Dict[str, PoolLedger],
                  occupancy: List[Tuple[Optional[str], List[Pod]]]) -> None:
        from ..state.cluster import copy_virtual_node
        self._baselines = {
            name: _Baseline(
                ledger=led,
                nodes=[copy_virtual_node(n) for n in led.nodes],
                pods={k: list(v) for k, v in led.existing_pods.items()},
                occupancy=[(z, list(ps)) for z, ps in occupancy])
            for name, led in ledgers.items() if led.ready}
        self._batches = {}
        self._since_audit = 0
        self.pending_since = None

    # --- per-admission record ---
    def record(self, pool_name: str, pods: List[Pod],
               want: Dict[str, str],
               now: Optional[float] = None) -> None:
        b = self._batches.setdefault(pool_name, _Batches())
        b.pods.extend(pods)
        b.want.update(want)
        if self.pending_since is None and now is not None:
            self.pending_since = now

    def close_window(self) -> None:
        """One warm RECONCILE recorded admissions (possibly across
        several pools) — the engine calls this once per reconcile, so
        audit_every counts admission windows, not per-pool batches."""
        self._since_audit += 1

    def has_pending(self) -> bool:
        return bool(self._batches)

    def due(self) -> bool:
        return bool(self._batches) and self._since_audit >= self.audit_every

    # --- the replay ---
    def audit(self) -> List[str]:
        """Replay the window's accumulated admissions through the full
        solver; returns human-readable divergences (empty = parity).
        Batches are consumed; the engine rebases the baseline after a
        clean audit and forces cold (which recommits) on divergence."""
        self._since_audit = 0
        self.pending_since = None
        batches, self._batches = self._batches, {}
        divergences: List[str] = []
        for pool_name, b in batches.items():
            base = self._baselines.get(pool_name)
            if base is None:
                divergences.append(f"{pool_name}: no baseline for batch")
                continue
            self.stats["audits"] += 1
            self.stats["audited_pods"] += len(b.pods)
            from ..state.cluster import copy_virtual_node
            led = base.ledger
            out = self.solver.solve(
                b.pods, led.pool, led.node_class,
                existing=[copy_virtual_node(n) for n in base.nodes],
                existing_pods={k: list(v) for k, v in base.pods.items()},
                spread_occupancy=[(z, list(ps))
                                  for z, ps in base.occupancy],
                daemonsets=list(led.daemonsets))
            got = {k: c for c, keys in out.existing_placements.items()
                   for k in keys}
            if out.launches:
                divergences.append(
                    f"{pool_name}: full solve opened {len(out.launches)} "
                    f"node(s) for warm-admitted pods")
            if out.unschedulable:
                divergences.append(
                    f"{pool_name}: full solve found "
                    f"{len(out.unschedulable)} warm-admitted pod(s) "
                    f"unschedulable: {sorted(out.unschedulable)[:3]}")
            if got != b.want:
                moved = sorted(k for k in set(got) | set(b.want)
                               if got.get(k) != b.want.get(k))
                divergences.append(
                    f"{pool_name}: {len(moved)} placement(s) differ "
                    f"(e.g. {moved[:3]})")
        if divergences:
            self.stats["divergences"] += len(divergences)
            self._flight_record(divergences)
        return divergences

    def _flight_record(self, divergences: List[str]) -> None:
        """Put the divergence into the flight recorder (a dedicated trace
        when the tracer is on — zero-cost otherwise) so /debug/traces can
        attribute the forced cold solve that follows."""
        if not TRACER.enabled:
            return
        with TRACER.trace("warmpath.divergence", count=len(divergences)):
            for d in divergences:
                with TRACER.span("warmpath.divergence.detail", detail=d):
                    pass
