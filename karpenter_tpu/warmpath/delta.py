"""DeltaTracker: classify cluster change since the last committed solve.

Subscribes to the store's watch feed (state/store.py `_notify`) and
folds every event into one of two buckets:

- *warm-compatible*: a plain pod arrival (Pending, unbound,
  un-nominated), a pending never-nominated pod deleted before it was
  placed, or a nominated pod binding onto its nominated claim's node
  (the BindingController's steady-state work — it moves a pod from
  "nominated" to "bound" on the same node, so no headroom changes).
- *dirty*: everything else — claim/node lifecycle, un-nominations,
  unbinds, daemonset/PDB/NodePool/NodeClass/PVC changes. The FIRST
  dirty reason is kept (it names what broke the warm window).

Catalog-side change (ICE marks + expiry, pricing, reservations,
overlays) is deliberately NOT event-fed: the WarmPathEngine compares
`catalog.epoch` against the committed epoch at classify time, which
also prunes expired ICE marks — a TTL lapse bumps the epoch exactly
like a fresh mark does.

The tracker starts dirty ("uncommitted"): until a cold solve commits a
ledger there is nothing to admit against.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from ..models import labels as L
from ..state.store import Store

WATCHED_KINDS = ("pod", "nodeclaim", "node", "daemonset", "pdb",
                 "nodepool", "nodeclass", "pvc")


class DeltaTracker:
    def __init__(self, store: Store):
        self.store = store
        self._dirty: Optional[str] = "uncommitted"
        self._ignore = 0
        self.stats = {"events": 0, "dirty_marks": 0}
        for kind in WATCHED_KINDS:
            store.watch(kind, self._handler(kind))

    # --- classification state ---
    @property
    def dirty(self) -> Optional[str]:
        """The first dirty reason since the last clear(), or None."""
        return self._dirty

    def mark_dirty(self, reason: str) -> None:
        self.stats["dirty_marks"] += 1
        if self._dirty is None:
            self._dirty = reason

    def clear(self) -> None:
        """A cold solve just committed a fresh ledger — the baseline."""
        self._dirty = None

    @contextmanager
    def ignoring(self):
        """Suppress events for the warm path's OWN store mutations
        (nominations of warm-admitted pods) — they are part of the
        ledger, not drift from it."""
        self._ignore += 1
        try:
            yield
        finally:
            self._ignore -= 1

    # --- event feed ---
    def _handler(self, kind: str):
        def on_event(action: str, obj) -> None:
            if self._ignore:
                return
            self.stats["events"] += 1
            if kind == "pod":
                self._on_pod(action, obj)
            else:
                # claims/nodes appearing or vanishing, daemonset/PDB/
                # NodePool/NodeClass/PVC updates: all change the headroom
                # or constraint picture — cold
                self.mark_dirty(f"{kind}-{action}")
        return on_event

    def _on_pod(self, action: str, pod) -> None:
        if action == "add":
            if (pod.phase == "Pending" and pod.node_name is None
                    and L.NOMINATED not in pod.annotations):
                return  # a plain arrival — exactly what the warm path is for
            self.mark_dirty("pod-add-nonpending")
        elif action == "bind":
            # a nominated pod landing on its claim's node: the claim
            # already accounted for it (NodeView counts nominated pods),
            # so the ledger's headroom is unchanged
            if pod.annotations.get(L.NOMINATED):
                return
            self.mark_dirty("pod-bind")
        elif action == "delete":
            if pod.node_name is None and L.NOMINATED not in pod.annotations:
                return  # a pending arrival withdrawn before placement
            self.mark_dirty("pod-delete")
        else:
            # unbind (eviction returns capacity), unnominate (ledger
            # resident vanishes), replace (mutation), nominate (someone
            # other than the warm path placed a pod), future actions
            self.mark_dirty(f"pod-{action}")
