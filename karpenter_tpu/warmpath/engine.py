"""WarmPathEngine: classify → admit → audit → commit.

The provisioner's entry points:

- `try_admit(groups, now)` at the top of every reconcile with pending
  pods: classifies the reconcile warm or cold and, when warm, places
  what the standing fleet absorbs (nominating pods to claims exactly
  the way the cold path's existing-placement branch does). Returns the
  groups the FULL solver must still handle — empty means the whole
  burst was admitted warm and the reconcile is done.
- `commit(now)` at the end of every cold pass: rebuilds the per-pool
  headroom ledgers and the cluster occupancy snapshot from post-solve
  state, clears the delta tracker, and hands the auditor its baseline.

The decision table, escalation rules, and auditor semantics are
documented in docs/warmpath.md.
"""

from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Tuple

from ..catalog.provider import CatalogProvider
from ..metrics import (PODS_SCHEDULED, WARMPATH_ADMIT_DURATION,
                       WARMPATH_AUDITS, WARMPATH_DECISIONS,
                       WARMPATH_DIVERGENCE, WARMPATH_HIT_RATE)
from ..models.nodepool import NodeClassSpec
from ..models.pod import Pod
from ..obs.tracer import NOOP_SPAN, TRACER
from ..ops.facade import Solver
from ..state.store import Store
from ..utils import crashpoints
from .admitter import PoolLedger, WarmAdmitter, build_pool_ledger
from .auditor import Auditor
from .delta import DeltaTracker


class WarmPathEngine:
    def __init__(self, store: Store, solver: Solver,
                 catalog: CatalogProvider, audit_every: int = 1):
        self.store = store
        self.solver = solver
        self.catalog = catalog
        self.tracker = DeltaTracker(store)
        self.admitter = WarmAdmitter()
        self.auditor = Auditor(solver, audit_every=audit_every)
        self.ledgers: Dict[str, PoolLedger] = {}
        self._occupancy: List[Tuple[Optional[str], List[Pod]]] = []
        self._occ_by_claim: Dict[str, List[Pod]] = {}
        self.stats = {"warm_reconciles": 0, "cold_reconciles": 0,
                      "warm_pods": 0, "cold_pods": 0, "escalated_pods": 0,
                      "commits": 0, "divergences": 0}

    # --- classification ---
    def force_cold(self, reason: str) -> None:
        self.tracker.mark_dirty(reason)

    def classify(self) -> Optional[str]:
        """None = warm; otherwise the cold reason. Checks the delta
        tracker first, then everything events cannot carry: the catalog
        availability epoch (whose read also prunes expired ICE marks —
        a mark lapsing moves the epoch like a fresh mark does) and the
        NodePool/NodeClass config hashes."""
        if self.tracker.dirty:
            return self.tracker.dirty
        pools = self.store.nodepools_by_weight()
        if {p.name for p in pools} != set(self.ledgers):
            return "pool-set-changed"
        self.catalog.raw_types()  # TTL'd re-list: a changed backend catalog
        epoch = tuple(self.catalog.epoch)  # bumps the epoch checked here
        for pool in pools:
            led = self.ledgers[pool.name]
            if led.epoch != epoch:
                return "catalog-epoch"
            node_class = (self.store.nodeclasses.get(pool.node_class)
                          or NodeClassSpec())
            from .admitter import pool_fingerprint
            if (led.pool_fp != pool_fingerprint(pool)
                    or led.nodeclass_hash != node_class.hash()
                    or led.ready != node_class.ready):
                return "pool-config"
        return None

    # --- the warm pass ---
    def try_admit(self, groups: List[List[Pod]], now: float,
                  ) -> Tuple[bool, List[List[Pod]]]:
        """(admitted_any, leftover_groups). Leftover groups — escalated
        bundles, non-fitting remainders, or everything on a cold
        classification — go through the full solver."""
        total = sum(len(g) for g in groups)
        reason = self.classify()
        if reason is not None:
            self.stats["cold_reconciles"] += 1
            self.stats["cold_pods"] += total
            WARMPATH_DECISIONS.inc(path="cold", reason=reason)
            self._publish()
            return False, groups
        t0 = _time.perf_counter()
        sp = (TRACER.span("warmpath.admit", pods=total, groups=len(groups))
              if TRACER.enabled else NOOP_SPAN)
        admitted = 0
        escalated: List[List[Pod]] = []
        with sp, self.tracker.ignoring():
            remaining = groups
            for pool in self.store.nodepools_by_weight():
                if not remaining:
                    break
                led = self.ledgers[pool.name]
                adm = self.admitter.admit(self.solver, led, pool,
                                          remaining, self._occupancy)
                for claim_name, pods in adm.placements.items():
                    claim = self.store.nodeclaims.get(claim_name)
                    if claim is None or claim.is_deleting():
                        # the ledger named a claim the store no longer
                        # holds (or one now draining) — stale beyond
                        # what events explained; never place blind.
                        # Belt-and-braces: controllers broadcast these
                        # mutations (store.touch_nodeclaim), so the
                        # classifier should have gone cold already.
                        self.force_cold("ledger-claim-stale")
                        escalated.append(pods)
                        continue
                    for p in pods:
                        self.store.nominate_pod(p, claim.name)
                        claim.resource_requests = (
                            claim.resource_requests.add(p.requests))
                        self._occ_by_claim.setdefault(
                            claim.name, []).append(p)
                    admitted += len(pods)
                if adm.integrity_violations:
                    # the ledger produced a provably infeasible warm
                    # placement: never-wrong-twice — the window goes
                    # cold until the next full solve rebuilds it
                    self.force_cold("integrity-violation")
                if adm.want:
                    self.auditor.record(
                        pool.name,
                        [p for ps in adm.placements.values() for p in ps],
                        adm.want, now=now)
                escalated.extend(adm.escalated)
                remaining = adm.passthrough
            # groups every pool's taint filter dropped end up exactly
            # where the cold path sends them: the full pass, which
            # records FailedScheduling
            escalated.extend(remaining)
            sp.set(admitted=admitted,
                   escalated=sum(len(g) for g in escalated))
        WARMPATH_ADMIT_DURATION.observe(_time.perf_counter() - t0)
        n_esc = sum(len(g) for g in escalated)
        self.stats["warm_pods"] += admitted
        self.stats["escalated_pods"] += n_esc
        # path reflects what actually happened, matching the reconcile
        # span's attribute: "warm" = fully served from standing headroom,
        # "mixed" = partially, "escalated" = classified warm but nothing
        # fit (the full solver serves it all)
        if admitted:
            self.stats["warm_reconciles"] += 1
            path = "warm" if not n_esc else "mixed"
        else:
            path = "escalated"
        WARMPATH_DECISIONS.inc(path=path, reason="arrivals-only")
        if admitted:
            PODS_SCHEDULED.inc(admitted)  # nominations count as scheduled
            self.auditor.close_window()
            if self.auditor.due():
                self._run_audit()
        self._publish()
        return admitted > 0, escalated

    def on_restart(self, reason: str = "restart") -> None:
        """A rebuilt operator may NOT trust a warm window: the ledgers,
        baselines, and recorded-but-unaudited batches all described the
        dead process's view of the cluster. Drop them and force the next
        reconcile cold — the full solve + commit rebuilds coverage from
        the adopted fleet (called by make_sim/rehydrate after a restart
        adoption)."""
        self.auditor.reset()
        self.ledgers = {}
        self._occupancy = []
        self._occ_by_claim = {}
        self.force_cold(reason)

    def _run_audit(self) -> None:
        crashpoints.fire("mid_warm_audit")  # cut point: admissions
        divergences = self.auditor.audit()  # nominated, audit unproven
        if divergences:
            self.stats["divergences"] += len(divergences)
            WARMPATH_DIVERGENCE.inc(len(divergences))
            WARMPATH_AUDITS.inc(outcome="divergent")
            for d in divergences:
                self.store.record_event("warmpath", "auditor",
                                        "WarmPathDivergence", d)
            import logging
            logging.getLogger("karpenter_tpu.warmpath").warning(
                "warm-path audit diverged from the full solver — forcing "
                "cold: %s", "; ".join(divergences))
            # never wrong twice: the path goes cold until the next
            # committed full solve rebuilds the ledger — and no
            # incremental DEVICE state may be trusted either: drop the
            # solver's resident delta buffers so the repair solve
            # re-seeds them from a clean cold upload
            inval = getattr(self.solver, "invalidate_resident", None)
            if inval is not None:
                inval("invalidated")
            self.force_cold("audit-divergence")
        else:
            WARMPATH_AUDITS.inc(outcome="clean")
            # rebase: the next audit window replays against the ledger
            # state its batches were actually admitted into
            self.auditor.on_commit(self.ledgers, self._occupancy)

    # --- commit (end of every cold solve) ---
    def commit(self, now: float) -> None:
        """Rebuild the standing ledgers from post-solve cluster state.
        This is the warm path's ONE expensive step — the same node-view
        walk a cold solve pays every reconcile — amortized over every
        warm tick that follows."""
        from ..state.cluster import cluster_occupancy
        sp = (TRACER.span("warmpath.commit") if TRACER.enabled
              else NOOP_SPAN)
        with sp:
            if self.auditor.has_pending():
                # a mixed reconcile reached its cold pass with recorded
                # warm batches still unaudited (audit_every > 1): replay
                # them NOW — resetting the baseline below would silently
                # drop them from audit coverage. Divergence here still
                # meters/flight-records; the rebuild below IS the forced
                # cold repair.
                self._run_audit()
            self.ledgers = {
                pool.name: build_pool_ledger(self.store, self.solver,
                                             pool, now)
                for pool in self.store.nodepools_by_weight()}
            self._occ_by_claim = {}
            self._occupancy = cluster_occupancy(self.store,
                                                by_claim=self._occ_by_claim)
            self.tracker.clear()
            self.auditor.on_commit(self.ledgers, self._occupancy)
            self.stats["commits"] += 1
            sp.set(pools=len(self.ledgers),
                   nodes=sum(len(l.nodes) for l in self.ledgers.values()))

    # --- observability ---
    def _publish(self) -> None:
        placed = self.stats["warm_pods"]
        seen = placed + self.stats["cold_pods"] + self.stats["escalated_pods"]
        if seen:
            WARMPATH_HIT_RATE.set(placed / seen)

    @property
    def hit_rate(self) -> float:
        placed = self.stats["warm_pods"]
        seen = placed + self.stats["cold_pods"] + self.stats["escalated_pods"]
        return placed / seen if seen else 0.0
