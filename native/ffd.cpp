// Native group-FFD solver — the compiled in-process Solve() implementation.
//
// Role: (a) the apples-to-apples baseline for the TPU kernel (the reference
// implements Solve() as a tight compiled first-fit-decreasing loop in Go;
// this is the same algorithm in C++), and (b) the production host fallback
// when no accelerator is attached.
//
// Semantics are identical to karpenter_tpu/ops/binpack.solve_host — same
// f32 arithmetic (EPS slack), same flat-argmin tie-breaks — so the golden
// agreement tests cover all three backends.
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {

constexpr float EPS = 1e-4f;
constexpr int64_t BIG = 1000000000;
constexpr float F32_MAX = std::numeric_limits<float>::max();

struct Dims {
  int64_t G, T, Z, C, R, Nmax, Ne;  // groups, types, zones, captypes, resources, node cap, existing
};

// per-node state (struct-of-arrays for cache friendliness)
struct NodeState {
  std::vector<int32_t> type;
  std::vector<float> cum;        // [N * R]
  std::vector<uint8_t> zmask;    // [N * Z]
  std::vector<uint8_t> cmask;    // [N * C]
  int64_t used = 0;
};

inline int64_t fit_count(const float* alloc_t, const float* cum,
                         const float* req, int64_t R) {
  float k = static_cast<float>(BIG);
  for (int64_t r = 0; r < R; ++r) {
    if (req[r] > 0.0f) {
      float v = std::floor((alloc_t[r] - cum[r]) / req[r] + EPS);
      if (v < k) k = v;
    }
  }
  if (k < 0.0f) return 0;
  return static_cast<int64_t>(k);
}

}  // namespace

extern "C" {

// Returns 0 on success, 1 if Nmax overflowed (some pods dropped to
// unschedulable that a larger Nmax would place).
//
// Inputs are row-major flat arrays. Existing nodes occupy the first Ne
// rows of the node-state output arrays and must be pre-filled by the
// caller (type, cum, zmask, cmask); prior_counts is [G * Nmax].
// Outputs: node_type/cum/zmask/cmask [Nmax...], takes [G * Nmax],
// unsched [G], n_used.
int32_t ffd_solve(
    const float* alloc,        // [T * R]
    const float* price,        // [T * Z * C]
    const uint8_t* avail,      // [T * Z * C]
    const float* requests,     // [G * R]
    const int32_t* counts,     // [G]
    const uint8_t* compat,     // [G * T]
    const uint8_t* allow_zone, // [G * Z]
    const uint8_t* allow_cap,  // [G * C]
    const int32_t* max_per_node,  // [G]
    const int32_t* prior_counts,  // [G * Nmax] (may be null)
    const uint8_t* banned,        // [G * Nmax] resident-pod anti-affinity (may be null)
    const uint8_t* conflict,      // [G * G] cross-group anti-affinity (may be null)
    int64_t G, int64_t T, int64_t Z, int64_t C, int64_t R,
    int64_t Nmax, int64_t Ne,
    int32_t* node_type,        // [Nmax] in/out
    float* node_cum,           // [Nmax * R] in/out
    uint8_t* node_zmask,       // [Nmax * Z] in/out
    uint8_t* node_cmask,       // [Nmax * C] in/out
    int32_t* takes,            // [G * Nmax] out
    int32_t* unsched,          // [G] out
    int64_t* n_used_out) {
  int64_t used = Ne;
  int32_t overflowed = 0;
  std::memset(takes, 0, sizeof(int32_t) * G * Nmax);
  std::memset(unsched, 0, sizeof(int32_t) * G);

  std::vector<int64_t> slots_t(T);
  // hosted[n * G + g]: node n took pods of group g in THIS solve (for
  // cross-group anti-affinity; residents enter via `banned`)
  std::vector<uint8_t> hosted;
  if (conflict) hosted.assign(Nmax * G, 0);

  for (int64_t g = 0; g < G; ++g) {
    const float* req = requests + g * R;
    int64_t cap_per = max_per_node[g] == 0 ? BIG : max_per_node[g];
    int64_t rem = counts[g];
    if (rem == 0) continue;

    // 1. fill open nodes in index order (first-fit)
    for (int64_t n = 0; n < used && rem > 0; ++n) {
      int32_t t = node_type[n];
      if (!compat[g * T + t]) continue;
      if (banned && banned[g * Nmax + n]) continue;
      if (conflict) {
        bool conf = false;
        const uint8_t* host_n = hosted.data() + n * G;
        const uint8_t* conf_g = conflict + g * G;
        for (int64_t h = 0; h < G && !conf; ++h)
          conf = host_n[h] && conf_g[h];
        if (conf) continue;
      }
      // zone/captype mask intersection must keep >=1 available offering
      bool off_ok = false;
      for (int64_t z = 0; z < Z && !off_ok; ++z) {
        if (!(node_zmask[n * Z + z] && allow_zone[g * Z + z])) continue;
        for (int64_t c = 0; c < C; ++c) {
          if (node_cmask[n * C + c] && allow_cap[g * C + c] &&
              avail[(t * Z + z) * C + c]) {
            off_ok = true;
            break;
          }
        }
      }
      if (!off_ok) continue;
      int64_t cap_eff = cap_per;
      if (prior_counts) cap_eff -= prior_counts[g * Nmax + n];
      if (cap_eff <= 0) continue;
      int64_t take = fit_count(alloc + t * R, node_cum + n * R, req, R);
      if (take > cap_eff) take = cap_eff;
      if (take > rem) take = rem;
      if (take < 1) continue;
      for (int64_t r = 0; r < R; ++r)
        node_cum[n * R + r] += static_cast<float>(take) * req[r];
      for (int64_t z = 0; z < Z; ++z)
        node_zmask[n * Z + z] &= allow_zone[g * Z + z];
      for (int64_t c = 0; c < C; ++c)
        node_cmask[n * C + c] &= allow_cap[g * C + c];
      takes[g * Nmax + n] += static_cast<int32_t>(take);
      if (conflict) hosted[n * G + g] = 1;
      rem -= take;
    }
    if (rem == 0) continue;

    // 2. cost-per-slot argmin over admissible offerings (flat tie-break:
    //    lowest (t, z, c) index among equal minima, matching the kernel)
    for (int64_t t = 0; t < T; ++t) {
      float k = static_cast<float>(BIG);
      bool any_req = false;
      for (int64_t r = 0; r < R; ++r) {
        if (req[r] > 0.0f) {
          any_req = true;
          float v = std::floor(alloc[t * R + r] / req[r] + EPS);
          if (v < k) k = v;
        }
      }
      int64_t s = any_req ? static_cast<int64_t>(std::fmax(k, 0.0f)) : BIG;
      slots_t[t] = s < cap_per ? s : cap_per;
    }
    float best = F32_MAX;
    int64_t best_t = -1;
    for (int64_t t = 0; t < T; ++t) {
      if (!compat[g * T + t] || slots_t[t] < 1) continue;
      float denom = static_cast<float>(slots_t[t] < 1 ? 1 : slots_t[t]);
      for (int64_t z = 0; z < Z; ++z) {
        if (!allow_zone[g * Z + z]) continue;
        for (int64_t c = 0; c < C; ++c) {
          if (!allow_cap[g * C + c]) continue;
          if (!avail[(t * Z + z) * C + c]) continue;
          float cps = price[(t * Z + z) * C + c] / denom;
          if (cps < best) {  // strict <: first flat index wins ties
            best = cps;
            best_t = t;
          }
        }
      }
    }
    if (best_t < 0) {
      unsched[g] += static_cast<int32_t>(rem);
      continue;
    }
    int64_t s = slots_t[best_t] < 1 ? 1 : slots_t[best_t];
    while (rem > 0) {
      if (used >= Nmax) {
        overflowed = 1;
        unsched[g] += static_cast<int32_t>(rem);
        break;
      }
      int64_t take = rem < s ? rem : s;
      int64_t n = used++;
      node_type[n] = static_cast<int32_t>(best_t);
      for (int64_t r = 0; r < R; ++r)
        node_cum[n * R + r] = static_cast<float>(take) * req[r];
      for (int64_t z = 0; z < Z; ++z) {
        uint8_t az = 0;
        for (int64_t c = 0; c < C; ++c)
          az |= avail[(best_t * Z + z) * C + c];
        node_zmask[n * Z + z] = allow_zone[g * Z + z] && az;
      }
      for (int64_t c = 0; c < C; ++c) {
        uint8_t ac = 0;
        for (int64_t z = 0; z < Z; ++z)
          ac |= avail[(best_t * Z + z) * C + c];
        node_cmask[n * C + c] = allow_cap[g * C + c] && ac;
      }
      takes[g * Nmax + n] = static_cast<int32_t>(take);
      if (conflict) hosted[n * G + g] = 1;
      rem -= take;
    }
  }
  *n_used_out = used;
  return overflowed;
}

}  // extern "C"
