"""Test harness: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware isn't available in CI; sharding correctness is
validated on host devices (same XLA partitioner). The environment's
sitecustomize imports jax at interpreter start with JAX_PLATFORMS=axon
(a tunneled remote TPU with ~70ms/transfer RTT — far too slow for a test
suite), so plain env vars are too late; jax.config.update still works
because no backend has been initialized yet when conftest runs.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
