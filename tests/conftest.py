"""Test harness: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware isn't available in CI; sharding correctness is
validated on host devices (same XLA partitioner). Must run before jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
