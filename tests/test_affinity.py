"""Zone-level required pod (anti-)affinity — the allow_zone pre-pass.

Reference behavior: the core scheduler's inter-pod affinity handling
(scheduling.md); hostname-level terms are covered in test_solver.py's
cross-group anti-affinity suites.
"""

import numpy as np

from karpenter_tpu.catalog import CatalogProvider, small_catalog
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod, PodAffinityTerm
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.ops.affinity import apply_zone_affinity
from karpenter_tpu.ops.binpack import solve_host, validate_solution
from karpenter_tpu.ops.encode import encode_catalog, encode_pods
from karpenter_tpu.ops.facade import Solver


def pod(name, labels=None, terms=(), cpu="1", mem="1Gi"):
    return Pod(name=name, labels=labels or {},
               requests=Resources.parse({"cpu": cpu, "memory": mem}),
               affinity_terms=list(terms))


def zone_term(selector, anti=False, required=True):
    return PodAffinityTerm(topology_key=L.ZONE, label_selector=selector,
                           anti=anti, required=required)


class TestZoneAntiAffinity:
    def setup_method(self):
        self.cat = encode_catalog(small_catalog())

    def test_cross_group_disjoint_zones(self):
        a = [pod(f"a{i}", {"app": "a"}, [zone_term({"app": "b"}, anti=True)])
             for i in range(4)]
        b = [pod(f"b{i}", {"app": "b"}) for i in range(4)]
        enc = apply_zone_affinity(encode_pods(a + b, self.cat), self.cat)
        res = solve_host(self.cat, enc)
        assert not validate_solution(self.cat, enc, res)
        assert not res.unschedulable
        za = set()
        zb = set()
        for n in res.nodes:
            zs = set(np.flatnonzero(n.zone_mask).tolist())
            for g in n.pods_by_group:
                if enc.groups[g].representative.labels["app"] == "a":
                    za |= zs
                else:
                    zb |= zs
        assert not (za & zb), (za, zb)

    def test_self_zone_anti_splits_one_per_zone(self):
        pods = [pod(f"p{i}", {"app": "solo"},
                    [zone_term({"app": "solo"}, anti=True)])
                for i in range(3)]
        enc = apply_zone_affinity(encode_pods(pods, self.cat), self.cat)
        assert enc.G == 3
        assert all(enc.counts[i] == 1 for i in range(3))
        # each pinned to a distinct zone
        zs = [tuple(np.flatnonzero(enc.allow_zone[i]).tolist())
              for i in range(3)]
        assert len(set(zs)) == 3 and all(len(z) == 1 for z in zs)
        res = solve_host(self.cat, enc)
        assert not res.unschedulable

    def test_self_zone_anti_excess_unschedulable(self):
        pods = [pod(f"p{i}", {"app": "solo"},
                    [zone_term({"app": "solo"}, anti=True)])
                for i in range(5)]  # only 3 zones in small_catalog
        enc = apply_zone_affinity(encode_pods(pods, self.cat), self.cat)
        res = solve_host(self.cat, enc)
        assert sum(res.unschedulable.values()) == 2

    def test_resident_zone_banned_both_directions(self):
        # group's own anti term vs a resident
        mine = [pod("m0", {"app": "x"}, [zone_term({"app": "y"}, anti=True)])]
        occupancy = [("zone-a", [Pod(name="r", labels={"app": "y"})])]
        enc = apply_zone_affinity(encode_pods(mine, self.cat), self.cat,
                                  occupancy)
        assert not enc.allow_zone[0][0] and enc.allow_zone[0][1:].all()
        # resident's anti term repels the incoming group symmetrically
        resident = Pod(name="r", labels={"app": "y"},
                       affinity_terms=[zone_term({"app": "x"}, anti=True)])
        mine2 = [pod("m1", {"app": "x"})]
        enc2 = apply_zone_affinity(encode_pods(mine2, self.cat), self.cat,
                                   [("zone-b", [resident])])
        assert not enc2.allow_zone[0][1]
        assert enc2.allow_zone[0][0] and enc2.allow_zone[0][2]

    def test_namespace_scoping(self):
        a = [pod("a0", {"app": "a"}, [zone_term({"app": "b"}, anti=True)])]
        b = [Pod(name="b0", namespace="other", labels={"app": "b"},
                 requests=Resources.parse({"cpu": "1"}))]
        enc = apply_zone_affinity(encode_pods(a + b, self.cat), self.cat)
        # different namespace → no conflict → no pinning
        assert enc.allow_zone.all()


class TestZonePositiveAffinity:
    def setup_method(self):
        self.cat = encode_catalog(small_catalog())

    def test_resident_match_restricts_zone(self):
        web = [pod("w0", {"app": "cache"}, [zone_term({"app": "db"})])]
        occupancy = [("zone-b", [Pod(name="db", labels={"app": "db"})])]
        enc = apply_zone_affinity(encode_pods(web, self.cat), self.cat,
                                  occupancy)
        assert np.flatnonzero(enc.allow_zone[0]).tolist() == [1]

    def test_incoming_groups_co_pinned(self):
        a = [pod(f"a{i}", {"app": "front"}, [zone_term({"app": "back"})],
                 cpu="2") for i in range(3)]
        b = [pod(f"b{i}", {"app": "back"}) for i in range(3)]
        enc = apply_zone_affinity(encode_pods(a + b, self.cat), self.cat)
        res = solve_host(self.cat, enc)
        assert not res.unschedulable
        zones = set()
        for n in res.nodes:
            zones |= set(np.flatnonzero(n.zone_mask).tolist())
        assert len(zones) == 1  # everything in one common zone

    def test_self_match_bootstrap_single_zone(self):
        pods = [pod(f"p{i}", {"app": "ring"}, [zone_term({"app": "ring"})])
                for i in range(4)]
        enc = apply_zone_affinity(encode_pods(pods, self.cat), self.cat)
        assert enc.allow_zone[0].sum() == 1
        res = solve_host(self.cat, enc)
        assert not res.unschedulable

    def test_no_match_anywhere_unschedulable(self):
        pods = [pod("p0", {"app": "x"}, [zone_term({"app": "nothing"})])]
        enc = apply_zone_affinity(encode_pods(pods, self.cat), self.cat)
        assert not enc.allow_zone[0].any()
        res = solve_host(self.cat, enc)
        assert sum(res.unschedulable.values()) == 1

    def test_facade_end_to_end_disjoint_nomination(self):
        solver = Solver(CatalogProvider(lambda: small_catalog()),
                        backend="host")
        a = [pod(f"a{i}", {"app": "a"}, [zone_term({"app": "b"}, anti=True)])
             for i in range(2)]
        b = [pod(f"b{i}", {"app": "b"}) for i in range(2)]
        out = solver.solve(a + b, NodePool(name="np"))
        assert not out.unschedulable
        za = {l.zone for l in out.launches
              if any(k.endswith(("a0", "a1")) for k in l.pod_keys)}
        zb = {l.zone for l in out.launches
              if any(k.endswith(("b0", "b1")) for k in l.pod_keys)}
        assert za and zb and not (za & zb)
        keys = [k for l in out.launches for k in l.pod_keys]
        assert len(keys) == len(set(keys)) == 4

    def test_no_terms_fast_path_returns_same_enc(self):
        enc = encode_pods([pod("p0"), pod("p1", {"x": "y"})], self.cat)
        assert apply_zone_affinity(enc, self.cat) is enc


class TestAffinityInteractions:
    def test_anti_greedy_respects_positive_pins(self):
        """Groups a (anti b) processed before b must not steal the zone b
        was co-pinned to by a positive-affinity cluster (e2e-found bug)."""
        cat = encode_catalog(small_catalog())
        a = [pod(f"a{i}", {"app": "a"}, [zone_term({"app": "b"}, anti=True)])
             for i in range(2)]
        b = [pod(f"b{i}", {"app": "b"}) for i in range(2)]
        c = [pod(f"c{i}", {"app": "c"}, [zone_term({"app": "b"})])
             for i in range(2)]
        enc = apply_zone_affinity(encode_pods(a + b + c, cat), cat)
        res = solve_host(cat, enc)
        assert not res.unschedulable
        zone_of = {}
        for n in res.nodes:
            zs = frozenset(np.flatnonzero(n.zone_mask).tolist())
            for g in n.pods_by_group:
                app = enc.groups[g].representative.labels["app"]
                zone_of.setdefault(app, set()).update(zs)
        assert not (zone_of["a"] & zone_of["b"])
        assert zone_of["c"] <= zone_of["b"]

    def test_both_pre_pinned_same_zone_one_unschedulable(self):
        """Review finding: two groups node-selected to the same single zone
        with required zone anti-affinity between them must not silently
        colocate — the later group goes unschedulable."""
        cat = encode_catalog(small_catalog())
        sel = {L.ZONE: "zone-a"}
        a = [Pod(name="a0", labels={"app": "a"}, node_selector=dict(sel),
                 requests=Resources.parse({"cpu": "1"}),
                 affinity_terms=[zone_term({"app": "b"}, anti=True)])]
        b = [Pod(name="b0", labels={"app": "b"}, node_selector=dict(sel),
                 requests=Resources.parse({"cpu": "1"}))]
        enc = apply_zone_affinity(encode_pods(a + b, cat), cat)
        res = solve_host(cat, enc)
        assert not validate_solution(cat, enc, res)
        assert sum(res.unschedulable.values()) == 1

    def test_validate_solution_flags_zone_conflict(self):
        cat = encode_catalog(small_catalog())
        a = [pod("a0", {"app": "a"}, [zone_term({"app": "b"}, anti=True)])]
        b = [pod("b0", {"app": "b"})]
        enc = apply_zone_affinity(encode_pods(a + b, cat), cat)
        assert enc.zone_conflict is not None
        res = solve_host(cat, enc)
        assert not validate_solution(cat, enc, res)
        # force both groups' nodes into overlapping zones → audit must flag
        for n in res.nodes:
            n.zone_mask = np.ones(cat.Z, bool)
        errs = validate_solution(cat, enc, res)
        assert any("zone-conflicting" in e for e in errs), errs

    def test_soft_zone_pref_not_treated_as_hard_pin(self):
        """Review finding: a soft zone preference narrowing a group to one
        zone must not pre-pin it — the conflicting hard-pinned group keeps
        the zone and the soft group relaxes elsewhere."""
        cat = encode_catalog(small_catalog())
        a = [Pod(name="a0", labels={"app": "a"},
                 node_selector={L.ZONE: "zone-a"},
                 requests=Resources.parse({"cpu": "1"}),
                 affinity_terms=[zone_term({"app": "b"}, anti=True)])]
        b = [Pod(name="b0", labels={"app": "b"},
                 requests=Resources.parse({"cpu": "1"}),
                 preferred_node_affinity=[{
                     "key": L.ZONE, "operator": "In",
                     "values": ["zone-a"], "weight": 1}])]
        for order in (a + b, b + a):
            enc = apply_zone_affinity(encode_pods(order, cat), cat)
            res = solve_host(cat, enc)
            assert not res.unschedulable, order[0].name
            zone_of = {}
            for n in res.nodes:
                zs = set(np.flatnonzero(n.zone_mask).tolist())
                for g in n.pods_by_group:
                    app = enc.groups[g].representative.labels["app"]
                    zone_of.setdefault(app, set()).update(zs)
            assert zone_of["a"] == {0}
            assert 0 not in zone_of["b"], zone_of

    def test_soft_preference_never_blocks_zone_anti(self):
        """Review finding: a preferred family only available in the banned
        zone must be dropped, not make the pod unschedulable."""
        from karpenter_tpu.catalog import CatalogProvider
        types = small_catalog()
        prov = CatalogProvider(lambda: types)
        solver = Solver(prov, backend="host")
        cat0 = solver.tensors()
        # m5 family available only in zone-a
        for n in cat0.names:
            if n.startswith("m5."):
                for z in cat0.zones[1:]:
                    for c in cat0.captypes:
                        prov.unavailable.mark_unavailable(n, z, c,
                                                          reason="test")
        p = Pod(name="w0", labels={"app": "w"},
                requests=Resources.parse({"cpu": "1", "memory": "1Gi"}),
                preferred_node_affinity=[{
                    "key": L.INSTANCE_FAMILY, "operator": "In",
                    "values": ["m5"], "weight": 1}],
                affinity_terms=[zone_term({"app": "resident"}, anti=True)])
        resident = Pod(name="r", labels={"app": "resident"})
        out = solver.solve([p], NodePool(name="np"),
                           spread_occupancy=[("zone-a", [resident])])
        assert not out.unschedulable
        assert out.launches[0].zone != "zone-a"
        assert not out.launches[0].instance_type.startswith("m5.")


class TestOfferingAxisPreferences:
    def setup_method(self):
        self.cat = encode_catalog(small_catalog())

    def test_zone_preference_narrows(self):
        p = Pod(name="p0", requests=Resources.parse({"cpu": "1"}),
                preferred_node_affinity=[{
                    "key": L.ZONE, "operator": "In",
                    "values": ["zone-b"], "weight": 1}])
        enc = encode_pods([p], self.cat)
        assert np.flatnonzero(enc.allow_zone[0]).tolist() == [1]
        assert enc.zone_hard is not None and enc.zone_hard[0].all()

    def test_captype_preference_narrows(self):
        p = Pod(name="p0", requests=Resources.parse({"cpu": "1"}),
                preferred_node_affinity=[{
                    "key": L.CAPACITY_TYPE, "operator": "In",
                    "values": ["spot"], "weight": 1}])
        enc = encode_pods([p], self.cat)
        assert enc.allow_cap[0].sum() == 1
        assert enc.cap_hard is not None and enc.cap_hard[0].all()
        res = solve_host(self.cat, enc)
        assert not res.unschedulable
        assert res.launches[0][2] == list(self.cat.captypes).index("spot")

    def test_zone_preference_skipped_under_zone_spread(self):
        from karpenter_tpu.models.pod import TopologySpreadConstraint
        p = [Pod(name=f"p{i}", labels={"app": "s"},
                 requests=Resources.parse({"cpu": "1"}),
                 topology_spread=[TopologySpreadConstraint(
                     topology_key=L.ZONE)],
                 preferred_node_affinity=[{
                     "key": L.ZONE, "operator": "In",
                     "values": ["zone-a"], "weight": 1}])
             for i in range(3)]
        enc = encode_pods(p, self.cat)
        # spread wins: the preference must not narrow the domain set
        assert enc.allow_zone[0].all()

    def test_infeasible_zone_preference_dropped(self):
        p = Pod(name="p0", requests=Resources.parse({"cpu": "1"}),
                preferred_node_affinity=[{
                    "key": L.ZONE, "operator": "In",
                    "values": ["zone-nope"], "weight": 1}])
        enc = encode_pods([p], self.cat)
        assert enc.allow_zone[0].all()
        res = solve_host(self.cat, enc)
        assert not res.unschedulable


class TestCrossPoolSpreadOccupancy:
    def test_spread_counts_see_earlier_pool_placements(self):
        """Review finding: occupancy computed once per reconcile made a
        later pool blind to claims the earlier pool just created — skew
        could exceed maxSkew across pools."""
        from karpenter_tpu.models.pod import TopologySpreadConstraint
        from karpenter_tpu.sim import make_sim
        from karpenter_tpu.models.nodepool import NodePool
        from collections import Counter

        heavy = NodePool(name="heavy", weight=10,
                         limits=Resources.parse({"cpu": "20"}))
        sim = make_sim(nodepool=heavy)
        sim.store.add_nodepool(NodePool(name="light", weight=1))
        pods = [Pod(name=f"s{i}", labels={"app": "s"},
                    requests=Resources.parse({"cpu": "4", "memory": "1Gi"}),
                    topology_spread=[TopologySpreadConstraint(
                        topology_key=L.ZONE, max_skew=1,
                        label_selector={"app": "s"})])
                for i in range(9)]
        for p in pods:
            sim.store.add_pod(p)
        sim.engine.run_until(
            lambda: all(p.node_name for p in sim.store.pods.values()),
            timeout=900)
        zones = Counter(
            sim.store.nodes[p.node_name].labels.get(L.ZONE)
            for p in sim.store.pods.values())
        assert max(zones.values()) - min(zones.values()) <= 1, zones


class TestSoftSpreadHardMasks:
    def test_preferred_captype_does_not_collapse_soft_spread(self):
        """Review finding: preferred capacity-type=reserved (only in one
        zone) must not pin a ScheduleAnyway spread entirely to that zone."""
        from karpenter_tpu.catalog import CatalogProvider
        from karpenter_tpu.models.instancetype import Offering
        from karpenter_tpu.models.pod import TopologySpreadConstraint
        types = small_catalog()
        # reserved offerings exist only in zone-a
        for t in types:
            t.offerings = [o for o in t.offerings
                           if o.capacity_type != "reserved"]
        types[0].offerings.append(Offering(
            zone="zone-a", capacity_type="reserved", price=0.0,
            available=True, reservation_capacity=10, reservation_id="r-1"))
        solver = Solver(CatalogProvider(lambda: types), backend="host")
        pods = [Pod(name=f"p{i}", labels={"app": "w"},
                    requests=Resources.parse({"cpu": "1", "memory": "1Gi"}),
                    topology_spread=[TopologySpreadConstraint(
                        topology_key=L.ZONE, max_skew=1,
                        when_unsatisfiable="ScheduleAnyway",
                        label_selector={"app": "w"})],
                    preferred_node_affinity=[{
                        "key": L.CAPACITY_TYPE, "operator": "In",
                        "values": ["reserved"], "weight": 1}])
                for i in range(4)]
        out = solver.solve(pods, NodePool(name="np"))
        assert not out.unschedulable
        zones = {l.zone for l in out.launches}
        assert len(zones) >= 2, zones  # spread survives the preference

    def test_soft_spread_skips_zone_where_nothing_fits(self):
        """Review finding: a zone whose compatible types are too small must
        be excluded from a ScheduleAnyway split (fits test)."""
        from karpenter_tpu.catalog import CatalogProvider
        from karpenter_tpu.models.pod import TopologySpreadConstraint
        types = small_catalog()
        biggest = max(float(t.capacity.get("cpu", 0)) for t in types)
        # zone-b keeps only small types: drop every offering of big types
        for t in types:
            if float(t.capacity.get("cpu", 0)) > 4:
                t.offerings = [o for o in t.offerings if o.zone != "zone-b"]
        solver = Solver(CatalogProvider(lambda: types), backend="host")
        pods = [Pod(name=f"p{i}", labels={"app": "big"},
                    requests=Resources.parse({"cpu": "6", "memory": "1Gi"}),
                    topology_spread=[TopologySpreadConstraint(
                        topology_key=L.ZONE, max_skew=1,
                        when_unsatisfiable="ScheduleAnyway",
                        label_selector={"app": "big"})])
                for i in range(4)]
        out = solver.solve(pods, NodePool(name="np"))
        assert not out.unschedulable, out.unschedulable
        assert all(l.zone != "zone-b" for l in out.launches)
