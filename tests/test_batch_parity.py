"""Batched-vs-serial dispatch parity fuzz (ISSUE 9 acceptance gate).

The batched dispatch engine (ops/solver.dispatch_batch + the fleet
service's batched pump) packs many tenants' solves into one vmapped
device call. Its contract is BYTE-IDENTITY: every request's SolveOutput
must equal what a serial per-ticket dispatch produces — same launches
(type/zone/captype/price/overrides/pod keys), same placements, same
unschedulable set — across randomized shape classes, batch-padding
remainders, and mid-batch tenant catalog divergence (an ICE mark that
splits one tenant off the shared device catalog). Same gate style as
the encode-cache cold/cached fuzz: sweep the space the golden tests
can't reach, fail by seed.

Everything runs the device path on whatever backend jax resolved (CPU
in tier-1) — the kernel is identical math either way.
"""

from __future__ import annotations

import random

import pytest

from karpenter_tpu.catalog import CatalogProvider
from karpenter_tpu.catalog.generator import small_catalog
from karpenter_tpu.fleet.service import SolverService
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod, PodAffinityTerm
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.utils.clock import FakeClock

POOL = NodePool(name="default")

_CPUS = ["100m", "250m", "500m", "1", "2"]
_MEMS = ["128Mi", "512Mi", "1Gi", "2Gi"]


def _tenant_pods(rng: random.Random, tenant: str, n: int,
                 manifests: int, anti: bool):
    """n pods drawn from `manifests` distinct constraint signatures —
    more manifests => more groups => a different padded shape class;
    `anti` adds hostname anti-affinity (the conflict-tracking kernel
    variant)."""
    pods = []
    for i in range(n):
        s = i % manifests
        kw = dict(requests=Resources.parse(
            {"cpu": _CPUS[s % len(_CPUS)], "memory": _MEMS[s % len(_MEMS)]}),
            labels={"app": f"{tenant}-m{s}"})
        if s % 3 == 0:
            kw["node_selector"] = {
                L.ZONE: rng.choice(["zone-a", "zone-b"])}
        if anti and s % 4 == 1:
            kw["affinity_terms"] = [PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                label_selector={"app": f"{tenant}-m{s}"}, anti=True)]
        pods.append(Pod(name=f"{tenant}-p{i}", **kw))
    return pods


def _mk_fleet(rng: random.Random, n_tenants: int):
    """(tenant name, pods, ice?) rows — a randomized mix of shape
    classes; one tenant may take an ICE mark (catalog divergence)."""
    rows = []
    ice_at = rng.randrange(n_tenants) if rng.random() < 0.7 else -1
    for t in range(n_tenants):
        name = f"t{t:02d}"
        manifests = rng.choice([3, 5, 8, 12])
        n = rng.randrange(4, 28)
        anti = rng.random() < 0.3
        rows.append((name, _tenant_pods(rng, name, n, manifests, anti),
                     t == ice_at))
    return rows


def _run_serial(rows, types):
    svc = SolverService(FakeClock(), backend="device")
    outs = {}
    for name, pods, ice in rows:
        client = svc.register(name, CatalogProvider(lambda: types))
        if ice:
            client.catalog.unavailable.mark_unavailable(
                types[0].name, "zone-a", "spot", reason="fuzz")
        outs[name] = client.solve(pods, POOL)
    return outs


def _run_batched(rows, types):
    svc = SolverService(FakeClock(), backend="device", batch=True)
    clients = {}
    for name, pods, ice in rows:
        clients[name] = svc.register(name, CatalogProvider(lambda: types))
        if ice:
            clients[name].catalog.unavailable.mark_unavailable(
                types[0].name, "zone-a", "spot", reason="fuzz")
    tickets = {name: clients[name].solve_async(pods, POOL)
               for name, pods, _ in rows}
    svc.pump()
    return {name: tk.result() for name, tk in tickets.items()}, svc


def _assert_identical(serial, batched, seed):
    assert serial.keys() == batched.keys()
    for name in serial:
        s, b = serial[name], batched[name]
        assert s.launches == b.launches, (
            f"seed {seed} tenant {name}: launches diverged")
        assert s.existing_placements == b.existing_placements, (
            f"seed {seed} tenant {name}: placements diverged")
        assert s.unschedulable == b.unschedulable, (
            f"seed {seed} tenant {name}: unschedulable diverged")


@pytest.mark.parametrize("seed", range(4))
def test_batched_dispatch_byte_identical_to_serial(seed):
    rng = random.Random(seed * 7919 + 13)
    types = small_catalog()
    rows = _mk_fleet(rng, n_tenants=rng.randrange(3, 7))
    serial = _run_serial(rows, types)
    batched, svc = _run_batched(rows, types)
    _assert_identical(serial, batched, seed)
    # the fleet actually co-batched something whenever >=2 tenants
    # shared a shape class AND a catalog view this round
    assert svc.stats["dispatched"] == len(rows)


def test_padding_remainder_rows_are_inert():
    """A 5-request bucket pads its request axis to 6: the padded row
    must place nothing, and every real row decodes as if dispatched
    alone."""
    types = small_catalog()
    rows = [(f"t{i:02d}",
             _tenant_pods(random.Random(i), f"t{i:02d}", 6 + i, 3, False),
             False)
            for i in range(5)]
    serial = _run_serial(rows, types)
    batched, svc = _run_batched(rows, types)
    _assert_identical(serial, batched, "pad")
    assert svc.stats["batches"] == 1
    assert svc.stats["batched_tickets"] == 5
    assert svc.stats["padded_slots"] == 6  # {1,2,3,4,6,8,...} ladder


def test_mid_batch_ice_divergence_splits_the_bucket():
    """One tenant's ICE mark re-fingerprints its catalog view: it may
    no longer share the batch's device catalog, so it dispatches in its
    own bucket — and its result reflects the mark while its neighbors'
    do not (isolation by content, exactly like the shared-catalog
    cache)."""
    types = small_catalog()
    rng = random.Random(99)
    rows = [("t00", _tenant_pods(rng, "t00", 8, 3, False), False),
            ("t01", _tenant_pods(rng, "t01", 8, 3, False), True),
            ("t02", _tenant_pods(rng, "t02", 8, 3, False), False)]
    serial = _run_serial(rows, types)
    batched, svc = _run_batched(rows, types)
    _assert_identical(serial, batched, "ice")
    # the diverged tenant could not ride the shared bucket: >= 2 device
    # calls served the round (shape classes agree, catalogs do not)
    assert svc.stats["batches"] >= 2


def test_two_staged_encodes_of_one_tenant_do_not_alias():
    """Regression: a staged EncodedPods holds views into its facade's
    staging arena, valid only until the next encode leases it — and the
    batched pump interleaves MANY encodes before any dispatch. Two
    same-tenant tickets in one pump must decode to what two serial
    solves produce (the pump pre-leases the arena so each staged encode
    owns its memory)."""
    types = small_catalog()
    rng = random.Random(21)
    # the SECOND encode is the smaller one, so the arena's capacity-
    # doubling buffers would be REUSED (not regrown) — without the
    # pump's pre-lease, ticket b's stage overwrites ticket a's rows
    pods_a = _tenant_pods(rng, "x", 14, 6, True)
    pods_b = _tenant_pods(rng, "y", 9, 4, False)

    serial_svc = SolverService(FakeClock(), backend="device")
    sc = serial_svc.register("t", CatalogProvider(lambda: types))
    ser_a, ser_b = sc.solve(pods_a, POOL), sc.solve(pods_b, POOL)

    svc = SolverService(FakeClock(), backend="device", batch=True)
    client = svc.register("t", CatalogProvider(lambda: types))
    ta = client.solve_async(pods_a, POOL)
    tb = client.solve_async(pods_b, POOL)
    svc.pump()
    assert ta.result().launches == ser_a.launches
    assert ta.result().unschedulable == ser_a.unschedulable
    assert tb.result().launches == ser_b.launches
    assert tb.result().unschedulable == ser_b.unschedulable
    # the arena lease is released once the pump drains: the NEXT solve
    # takes the zero-copy fast path again and still agrees
    assert not client.facade._arena._leased
    assert client.solve(pods_a, POOL).launches == ser_a.launches


def test_solve_async_counts_against_the_inflight_cap():
    """The window cap must gate SUBMISSION, not just dispatch: queued-
    but-unpumped async tickets count, or a tenant could park an
    unbounded storm between pumps."""
    from karpenter_tpu.fleet.service import SolverServiceBusy
    types = small_catalog()
    svc = SolverService(FakeClock(), backend="device", batch=True,
                        inflight_cap=2)
    client = svc.register("a", CatalogProvider(lambda: types))
    pods = _tenant_pods(random.Random(1), "a", 4, 2, False)
    t1 = client.solve_async(pods, POOL)
    t2 = client.solve_async(pods, POOL)
    with pytest.raises(SolverServiceBusy):
        client.solve_async(pods, POOL)
    svc.pump()
    assert t1.result().launches and t2.result().launches
    # dispatched tickets still occupy the window until it rolls
    with pytest.raises(SolverServiceBusy):
        client.solve_async(pods, POOL)
    svc.clock.step(svc.window + 1)
    assert client.solve(pods, POOL).launches


def test_block_failure_degrades_only_that_batch(monkeypatch):
    """Real device errors surface at block()/readback, not at dispatch
    — the containment contract must hold there too: the batch's tickets
    re-run through their facades, nothing escapes pump()."""
    from karpenter_tpu.metrics import FLEET_SHAPE_CLASS
    from karpenter_tpu.ops.solver import InFlightBatch

    def boom(self):
        raise RuntimeError("device lost at readback")

    monkeypatch.setattr(InFlightBatch, "block", boom)
    types = small_catalog()
    svc = SolverService(FakeClock(), backend="device", batch=True)
    a = svc.register("a", CatalogProvider(lambda: types))
    b = svc.register("b", CatalogProvider(lambda: types))
    ta = a.solve_async(_tenant_pods(random.Random(1), "a", 5, 2, False),
                       POOL)
    tb = b.solve_async(_tenant_pods(random.Random(2), "b", 5, 2, False),
                       POOL)
    svc.pump()  # must not raise
    assert ta.result().launches and tb.result().launches
    assert FLEET_SHAPE_CLASS.value(event="fault_fallback", tenant="a") >= 1
    assert FLEET_SHAPE_CLASS.value(event="fault_fallback", tenant="b") >= 1


def test_tenant_targeted_fault_spares_cobatched_neighbors():
    """The device-fault seam is probed under EACH bucket tenant's scope:
    a fault targeting tenant b aborts the shared call, but only b's
    facade degrades — a's serial re-run keeps the device path."""
    from karpenter_tpu.metrics.tenant import current_tenant
    from karpenter_tpu.ops import solver as ops_solver
    types = small_catalog()
    svc = SolverService(FakeClock(), backend="device", batch=True)
    a = svc.register("a", CatalogProvider(lambda: types))
    b = svc.register("b", CatalogProvider(lambda: types))

    def hook(backend):
        if current_tenant() == "b":
            raise RuntimeError("injected: tenant b's device is gone")

    ops_solver.set_dispatch_fault_hook(hook)
    try:
        ta = a.solve_async(_tenant_pods(random.Random(3), "a", 5, 2,
                                        False), POOL)
        tb = b.solve_async(_tenant_pods(random.Random(4), "b", 5, 2,
                                        False), POOL)
        svc.pump()
        assert ta.result().launches and tb.result().launches
        assert a.facade.stats["device_fallbacks"] == 0  # stayed on device
        assert b.facade.stats["device_fallbacks"] == 1  # degraded alone
    finally:
        ops_solver.set_dispatch_fault_hook(None)


def test_catalog_divergence_never_trips_pipeline_stall():
    """Two tenants with EQUAL shape classes but diverged catalog views
    legitimately never co-batch — co-pending is counted on the full
    signature, so the watchdog's pipeline_stall cannot false-positive
    on them (the PR 8 zero-false-positive contract)."""
    from karpenter_tpu.obs.watchdog import Watchdog
    types = small_catalog()
    svc = SolverService(FakeClock(), backend="device", batch=True)
    a = svc.register("a", CatalogProvider(lambda: types))
    b = svc.register("b", CatalogProvider(lambda: types))
    b.catalog.unavailable.mark_unavailable(types[0].name, "zone-a",
                                           "spot", reason="split")
    wd = Watchdog(svc.clock, service=svc).arm()
    pods = _tenant_pods(random.Random(6), "p", 6, 3, False)
    for _ in range(wd.COBATCH_MIN_PUMPS + 1):
        ta, tb = a.solve_async(pods, POOL), b.solve_async(pods, POOL)
        svc.pump()
        assert ta.result().launches and tb.result().launches
        svc.clock.step(6.0)
        wd.tick(force=True)
    assert wd.fired("pipeline_stall") == 0


def test_ledger_attributes_batching_overhead_with_full_coverage(
        monkeypatch):
    """ISSUE 9 profile satellite: a traced batched pump lands
    `batch_pack` and `pipeline_wait` in the phase ledger, and the >=99%
    coverage invariant stays green — `fleet.pump` roots the trace and is
    itself mapped, so the pump's own glue attributes to queue_wait.

    Delta memos disarmed: the traced second round repeats the warm
    round's content, and a facade-level serve would skip the pump whose
    phases this test asserts."""
    from karpenter_tpu.obs import TRACER
    from karpenter_tpu.ops.delta import DELTA
    monkeypatch.setenv("KARPENTER_TPU_DELTA", "0")
    DELTA.reset()
    from karpenter_tpu.obs.profile import LEDGER
    types = small_catalog()
    svc = SolverService(FakeClock(), backend="device", batch=True)
    clients = [svc.register(f"t{i}", CatalogProvider(lambda: types))
               for i in range(3)]
    warm = [c.solve_async(_tenant_pods(random.Random(i), f"w{i}", 5, 3,
                                       False), POOL)
            for i, c in enumerate(clients)]
    svc.pump()
    for t in warm:
        t.result()
    LEDGER.reset()
    TRACER.configure(enabled=True)
    try:
        tickets = [c.solve_async(_tenant_pods(random.Random(i), f"x{i}",
                                              5, 3, False), POOL)
                   for i, c in enumerate(clients)]
        svc.pump()
        for t in tickets:
            t.result()
    finally:
        TRACER.configure(enabled=False)
    snap = LEDGER.snapshot()
    buckets = {b for tenant in snap["phases"].values()
               for kind in tenant.values() for b in kind}
    assert "batch_pack" in buckets, buckets
    assert "pipeline_wait" in buckets, buckets
    assert LEDGER.coverage(kind="reconcile") >= 0.99
    # per-TENANT attribution inside the shared trace: each co-batched
    # tenant's stage/decode phases land on ITS series (the per-ticket
    # spans carry tenant attrs; children inherit), while the shared
    # machinery (batch_pack, pipeline_wait) stays on the ambient tenant
    for t in ("t0", "t1", "t2"):
        t_buckets = {b for kind in snap["phases"].get(t, {}).values()
                     for b in kind}
        assert "decode" in t_buckets, (t, sorted(t_buckets))
    LEDGER.reset()


def test_sync_solve_through_batched_pump_matches_serial_pump():
    """client.solve() (submit+pump+result) must behave identically on
    both engines — the fleet runner path."""
    types = small_catalog()
    pods = _tenant_pods(random.Random(5), "x", 10, 5, True)
    serial = SolverService(FakeClock(), backend="device") \
        .register("x", CatalogProvider(lambda: types)).solve(pods, POOL)
    batched = SolverService(FakeClock(), backend="device", batch=True) \
        .register("x", CatalogProvider(lambda: types)).solve(pods, POOL)
    assert serial.launches == batched.launches
    assert serial.unschedulable == batched.unschedulable
