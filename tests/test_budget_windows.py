"""Scheduled disruption-budget windows (reference NodePool budgets with
schedule + duration, karpenter.sh_nodepools.yaml:78-160): a budget
constrains disruption only while its cron window is open."""

import time

import pytest

from karpenter_tpu.models.nodepool import (Budget, DisruptionSpec,
                                           NodePool)
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.models.validation import (ValidationError,
                                             validate_nodepool)
from karpenter_tpu.sim import make_sim
from karpenter_tpu.utils.cron import CronError, in_window, matches, parse


def _epoch(y, mo, d, h, mi):
    return time.mktime((y, mo, d, h, mi, 0, 0, 0, 0)) - time.timezone


class TestCronMatcher:
    def test_basic_fields(self):
        t = _epoch(2026, 7, 29, 9, 30)  # a Wednesday
        assert matches("30 9 * * *", t)
        assert matches("*/15 * * * *", t)
        assert not matches("0 9 * * *", t)
        assert matches("30 9 29 7 *", t)
        assert matches("30 9 * * 3", t)       # Wednesday = 3
        assert not matches("30 9 * * 0", t)   # not Sunday

    def test_ranges_lists_steps(self):
        t = _epoch(2026, 7, 29, 14, 45)
        assert matches("40-50 9-17 * * 1-5", t)
        assert matches("45 8,14,20 * * *", t)
        assert matches("15-55/10 * * * *", t)  # 15,25,35,45,55
        assert not matches("0-40/10 * * * *", t)

    def test_dom_dow_or_rule(self):
        # July 29 2026 is a Wednesday; both fields restricted: OR
        t = _epoch(2026, 7, 29, 0, 0)
        assert matches("0 0 1 * 3", t)   # dom=1 misses, dow=Wed hits
        assert matches("0 0 29 * 0", t)  # dom hits, dow misses
        assert not matches("0 0 1 * 0", t)

    def test_rejects_garbage(self):
        for bad in ("* * * *", "61 * * * *", "a * * * *", "*/0 * * * *"):
            with pytest.raises(CronError):
                parse(bad)

    def test_window(self):
        start = _epoch(2026, 7, 29, 9, 0)
        assert in_window("0 9 * * *", 3600, start + 1800)
        assert in_window("0 9 * * *", 3600, start)
        assert not in_window("0 9 * * *", 3600, start + 3600)
        assert not in_window("0 9 * * *", 3600, start - 60)


class TestBudgetWindows:
    def test_scheduled_zero_budget_blocks_only_in_window(self):
        """nodes:'0' during a daily window freezes drift inside it and
        releases it outside (the reference's maintenance-freeze
        pattern)."""
        pool = NodePool(name="default")
        pool.disruption = DisruptionSpec(budgets=[
            Budget(nodes="0", schedule="0 0 * * *", duration=3600.0),
            Budget(nodes="10")])
        sim = make_sim(nodepool=pool)
        pods = [sim.store.add_pod(Pod(
            name=f"p{i}", requests=Resources.parse({"cpu": "7"})))
            for i in range(4)]
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=120)
        old = set(sim.store.nodeclaims)

        # jump the fake clock INTO the freeze window (fake epoch ~1e6;
        # align to the next 00:00 UTC after now)
        now = sim.clock.now()
        next_midnight = (int(now) // 86400 + 1) * 86400
        sim.clock.step(next_midnight - now + 60)  # 00:01, inside freeze
        sim.store.nodeclasses["default"].user_data = "v2"  # drift all
        sim.engine.run_for(1800, step=30)  # stays within the 1h window
        assert set(sim.store.nodeclaims) & old == old, \
            "drift rolled nodes inside the frozen window"
        # leave the window: the roll proceeds under the 10-node budget
        sim.engine.run_for(3600, step=30)
        sim.engine.run_for(1200, step=10)
        assert not (set(sim.store.nodeclaims) & old)
        assert all(p.node_name for p in pods)

    def test_validation(self):
        bad = NodePool(name="x")
        bad.disruption = DisruptionSpec(budgets=[
            Budget(nodes="1", schedule="0 0 * * *")])  # no duration
        with pytest.raises(ValidationError):
            validate_nodepool(bad)
        bad2 = NodePool(name="x")
        bad2.disruption = DisruptionSpec(budgets=[
            Budget(nodes="1", schedule="not cron", duration=60.0)])
        with pytest.raises(ValidationError):
            validate_nodepool(bad2)
