"""Capacity-block reservations + the capacity-reservation drift reason.

Reference parity: CapacityReservationType partition and capacity-block
selection (pkg/providers/instance/filter/filter.go:73-228), block expiry
semantics (capacityreservation controllers), and the fifth drift reason
(pkg/cloudprovider/drift.go:35-41).
"""

from karpenter_tpu.catalog import small_catalog
from karpenter_tpu.cloud.provider import LaunchOverride
from karpenter_tpu.controllers.auxiliary import BLOCK_DRAIN_LEAD
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.requirements import Operator, Requirement
from karpenter_tpu.models.resources import NVIDIA_GPU, Resources
from karpenter_tpu.sim import make_sim

BLOCK_TYPE, BLOCK_ZONE = "g5.4xlarge", "zone-b"
BLOCK_ID = f"cb-{BLOCK_TYPE}-{BLOCK_ZONE}"


def gpu_pods(sim, n, prefix="g"):
    pods = [Pod(name=f"{prefix}-{i}",
                requests=Resources.parse({"cpu": "2", "memory": "4Gi",
                                          NVIDIA_GPU: 1}))
            for i in range(n)]
    for p in pods:
        sim.store.add_pod(p)
    return pods


def block_sim(**kw):
    sim = make_sim(types=small_catalog(8), **kw)
    return sim


class TestPartitionFilter:
    def _ov(self, price, rid=None, rtype="default"):
        return LaunchOverride("t", "z", "reserved" if rid else "on-demand",
                              price, reservation_id=rid,
                              reservation_type=rtype)

    def test_block_primary_targets_single_cheapest_block(self):
        from karpenter_tpu.controllers.provisioner import Provisioner
        rows = [self._ov(0.001, "cb-1", "capacity-block"),
                self._ov(0.002, "cb-2", "capacity-block"),
                self._ov(0.003, "cb-1", "capacity-block"),
                self._ov(1.0),
                self._ov(0.5, "cr-1")]
        out = Provisioner._partition_reservation_overrides(rows)
        assert all(o.reservation_id == "cb-1" for o in out)
        assert len(out) == 2

    def test_nonblock_primary_drops_block_rows(self):
        from karpenter_tpu.controllers.provisioner import Provisioner
        rows = [self._ov(0.5, "cr-1"),
                self._ov(0.7, "cb-1", "capacity-block"),
                self._ov(1.0)]
        out = Provisioner._partition_reservation_overrides(rows)
        assert [o.reservation_id for o in out] == ["cr-1", None]

    def test_no_blocks_is_passthrough(self):
        from karpenter_tpu.controllers.provisioner import Provisioner
        rows = [self._ov(0.5, "cr-1"), self._ov(1.0)]
        assert Provisioner._partition_reservation_overrides(rows) == rows


class TestSolveTimeGate:
    def test_untargeted_pool_never_lands_on_block(self):
        """The solve-time gate (reference filter.go:163-228): a pool that
        does not explicitly name reserved capacity must not commit a
        capacity block even though block prices round to zero — its gpu
        pods land on spot/on-demand and no launch override cites a block."""
        pool = NodePool(name="gpu")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, (BLOCK_ZONE,)))
        sim = block_sim(nodepool=pool)
        launches = []
        orig = sim.cloud.create_fleet

        def spy(requests):
            launches.extend(requests)
            return orig(requests)
        sim.cloud.create_fleet = spy
        pods = gpu_pods(sim, 2)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        assert launches
        for req in launches:
            for o in req.overrides:
                assert o.reservation_type != "capacity-block"
        for c in sim.store.nodeclaims.values():
            assert "karpenter.tpu/reservation-id" not in c.annotations or \
                not c.annotations["karpenter.tpu/reservation-id"].startswith("cb-")

    def test_pod_level_reserved_selector_opens_gate(self):
        """A pod that ITSELF selects reserved capacity under an
        untargeted pool still reaches the block: the reference gate
        evaluates merged nodeclaim requirements (filter.go shouldFilter),
        so pod-level intent opens it — via the facade's ungated re-solve
        of reserved-targeting unschedulable pods."""
        pool = NodePool(name="gpu")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, (BLOCK_ZONE,)))
        sim = block_sim(nodepool=pool)
        pods = [Pod(name=f"r-{i}",
                    requests=Resources.parse({"cpu": "2", "memory": "4Gi",
                                              NVIDIA_GPU: 1}),
                    node_selector={L.CAPACITY_TYPE: L.CAPACITY_RESERVED})
                for i in range(2)]
        for p in pods:
            sim.store.add_pod(p)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        assert any(c.annotations.get("karpenter.tpu/reservation-id")
                   == BLOCK_ID for c in sim.store.nodeclaims.values())

    def test_explicit_reserved_pool_uses_block(self):
        """The same pods under a pool that names reserved capacity DO
        land on the prepaid block — the gate opens on explicit intent."""
        pool = NodePool(name="gpu")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, (BLOCK_ZONE,)))
        pool.requirements.add(Requirement(
            L.CAPACITY_TYPE, Operator.IN, (L.CAPACITY_RESERVED,)))
        sim = block_sim(nodepool=pool)
        pods = gpu_pods(sim, 2)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        assert any(c.annotations.get("karpenter.tpu/reservation-id")
                   == BLOCK_ID for c in sim.store.nodeclaims.values())


class TestCapacityTypePreference:
    def test_expensive_reserved_still_preferred(self):
        """Explicit reserved→spot→OD preference (reference
        getCapacityType, instance.go:530-546): even when a reserved
        offering's price is DISTORTED above on-demand (overlay), a pool
        targeting reserved capacity still lands on the reservation —
        the preference is structural, not a near-zero-price artifact."""
        sim = block_sim()
        # repaint the block as a default ODCR priced ABOVE on-demand
        for t in sim.cloud.types.values():
            for o in t.offerings:
                if o.reservation_id == BLOCK_ID:
                    o.reservation_type = "default"
                    o.price = 99.0
        sim.catalog.refresh()
        pool = NodePool(name="gpu")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, (BLOCK_ZONE,)))
        pool.requirements.add(Requirement(
            L.CAPACITY_TYPE, Operator.IN,
            (L.CAPACITY_RESERVED, L.CAPACITY_ON_DEMAND)))
        sim.store.add_nodepool(pool)
        sim.store.nodepools.pop("default", None)
        pods = gpu_pods(sim, 2)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        reserved = [c for c in sim.store.nodeclaims.values()
                    if c.capacity_type == L.CAPACITY_RESERVED]
        assert reserved, "distorted-price reservation was not preferred"

    def test_prioritize_stage_leads_with_reserved(self):
        from karpenter_tpu.controllers.provisioner import Provisioner
        rows = [LaunchOverride("a", "z", "on-demand", 0.5),
                LaunchOverride("b", "z", "spot", 0.2),
                LaunchOverride("c", "z", "reserved", 42.0,
                               reservation_id="cr-1"),
                LaunchOverride("d", "z", "spot", 0.1),
                LaunchOverride("e", "z", "on-demand", 0.3)]
        out = Provisioner._prioritize_capacity_type(rows)
        # reserved first even at a distorted price; the rest keep their
        # solver-chosen (committed-first, then price) order — spot vs OD
        # stays a cost decision, not a market preference
        assert [o.instance_type for o in out] == ["c", "a", "b", "d", "e"]

    def test_ice_fallback_takes_global_cheapest(self):
        """Review finding: with in-order allocation, the wire list must
        hold global price order after the leading committed row — an
        exhausted committed pick falls back to the cheapest viable row
        of ANY type, never a pricier sibling of the committed type."""
        from karpenter_tpu.cloud.provider import LaunchRequest
        sim = block_sim()
        sim.cloud.capacity_pools[("m5.large", "zone-a", "spot")] = 0
        req = LaunchRequest(
            nodeclaim_name="x",
            overrides=[  # facade contract: committed row, then price order
                LaunchOverride("m5.large", "zone-a", "spot", 0.5),
                LaunchOverride("c5.large", "zone-a", "spot", 0.1),
                LaunchOverride("m5.large", "zone-a", "on-demand", 2.0)])
        (inst,) = sim.cloud.create_fleet([req])
        assert inst.instance_type == "c5.large" and inst.price == 0.1

    def test_launch_overrides_price_ordered_after_primary(self):
        """The facade's wire list: one committed row first, then global
        price order (the cloud walks in order)."""
        sim = block_sim()
        seen = []
        orig = sim.cloud.create_fleet
        sim.cloud.create_fleet = lambda r: (seen.extend(r), orig(r))[1]
        pods = [Pod(name=f"o-{i}",
                    requests=Resources.parse({"cpu": "1", "memory": "2Gi"}))
                for i in range(2)]
        for p in pods:
            sim.store.add_pod(p)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        for req in seen:
            tail = [o.price for o in req.overrides[1:]]
            assert tail == sorted(tail)


class TestBlockLifecycle:
    def test_gpu_pods_land_on_block_and_drain_before_end(self):
        """A pool explicitly targeting reserved capacity lands on the
        near-zero-priced block; the expiration controller drains its
        claims BLOCK_DRAIN_LEAD before end and the cloud rejects
        launches into the ended block."""
        pool = NodePool(name="gpu")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, (BLOCK_ZONE,)))
        pool.requirements.add(Requirement(
            L.CAPACITY_TYPE, Operator.IN, (L.CAPACITY_RESERVED,)))
        sim = block_sim(nodepool=pool)
        pods = gpu_pods(sim, 2)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        block_claims = [c for c in sim.store.nodeclaims.values()
                       if c.annotations.get("karpenter.tpu/reservation-id")
                       == BLOCK_ID]
        assert block_claims, "solver did not commit the capacity block"
        assert all(c.capacity_type == L.CAPACITY_RESERVED
                   for c in block_claims)
        # schedule the block's end
        ends = sim.clock.now() + BLOCK_DRAIN_LEAD + 120
        for t in sim.cloud.types.values():
            for o in t.offerings:
                if o.reservation_id == BLOCK_ID:
                    o.reservation_ends = ends
        sim.catalog.refresh()
        # inside the lead window the claims drain
        sim.engine.run_for(200, step=10)
        res_exp = next(c for c in sim.engine.controllers
                       if c.name == "capacityreservation.expiration")
        assert res_exp.stats["blocks_drained"] >= 1
        assert all(c.annotations.get("karpenter.tpu/reservation-id")
                   != BLOCK_ID or c.is_deleting()
                   for c in sim.store.nodeclaims.values())
        # at the end time the block expires cloud-side
        sim.engine.run_for(600, step=10)
        assert BLOCK_ID in sim.cloud.expired_reservations

    def test_expired_block_offering_unavailable_in_catalog(self):
        sim = block_sim()
        for t in sim.cloud.types.values():
            for o in t.offerings:
                if o.reservation_id == BLOCK_ID:
                    o.reservation_ends = sim.clock.now() - 1
        sim.catalog.refresh()
        offs = [o for t in sim.catalog.list() for o in t.offerings
                if o.reservation_id == BLOCK_ID]
        assert offs and all(not o.available for o in offs)


class TestReservationDrift:
    def test_vanished_reservation_drifts_the_node(self):
        """Fifth drift reason: a reserved node whose reservation left the
        catalog is replaced (drift.go:35-41)."""
        pool = NodePool(name="gpu")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, (BLOCK_ZONE,)))
        # reserved named explicitly (opens the block gate) + on-demand so
        # the drift replacement has somewhere to land once the block dies
        pool.requirements.add(Requirement(
            L.CAPACITY_TYPE, Operator.IN,
            (L.CAPACITY_RESERVED, L.CAPACITY_ON_DEMAND)))
        sim = block_sim(nodepool=pool)
        pods = gpu_pods(sim, 2)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        reserved = [c for c in sim.store.nodeclaims.values()
                    if c.capacity_type == L.CAPACITY_RESERVED]
        assert reserved
        # the reservation disappears from the cloud's catalog entirely
        for t in sim.cloud.types.values():
            t.offerings = [o for o in t.offerings
                           if o.reservation_id != BLOCK_ID]
        sim.catalog.refresh()
        sim.engine.run_for(120, step=5)
        assert sim.disruption.stats["drift"] >= 1
        # drifted claims were replaced; survivors don't cite the dead block
        for c in sim.store.nodeclaims.values():
            if not c.is_deleting():
                assert c.annotations.get(
                    "karpenter.tpu/reservation-id") != BLOCK_ID

    def test_demoted_claim_does_not_drift(self):
        """Default-reservation expiry demotes to on-demand and clears the
        annotation — the drift pass must NOT then roll the node."""
        pool = NodePool(name="gpu")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, (BLOCK_ZONE,)))
        sim = block_sim(nodepool=pool)
        # repaint the block as a DEFAULT reservation for this test
        for t in sim.cloud.types.values():
            for o in t.offerings:
                if o.reservation_id == BLOCK_ID:
                    o.reservation_type = "default"
        sim.catalog.refresh()
        pods = gpu_pods(sim, 2)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=60)
        names = {c.name for c in sim.store.nodeclaims.values()
                 if c.capacity_type == L.CAPACITY_RESERVED}
        assert names
        sim.cloud.expire_reservation(BLOCK_ID)
        sim.engine.run_for(300, step=10)
        for name in names:
            c = sim.store.nodeclaims.get(name)
            assert c is not None and not c.is_deleting()
            assert c.capacity_type == L.CAPACITY_ON_DEMAND
            assert "karpenter.tpu/reservation-id" not in c.annotations
        assert sim.disruption.stats["drift"] == 0
