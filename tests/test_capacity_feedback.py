"""Capacity-feedback loops: zone IP exhaustion, capacity-type droughts,
in-flight address accounting, and the live spot-price feed.

Reference parity: subnet free-address modeling + in-flight IP accounting
(pkg/providers/subnet/subnet.go:135,183-230), InsufficientFreeAddresses →
AZ-wide unavailability and UnfulfillableCapacity → capacity-type-wide
marks (pkg/errors/errors.go:172-185, instance.go:469-512), and the spot
price poller (pkg/providers/pricing/pricing.go:379).
"""

import math

from karpenter_tpu.cloud.fake import FakeCloudConfig
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.requirements import Operator, Requirement
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.sim import make_sim


def add_pods(sim, n, cpu="2", mem="4Gi", prefix="p", one_per_node=False,
             app=None):
    kw = {}
    if one_per_node:
        from karpenter_tpu.models.pod import PodAffinityTerm
        app = app or prefix
        kw = dict(labels={"app": app},
                  affinity_terms=[PodAffinityTerm(
                      topology_key=L.HOSTNAME,
                      label_selector={"app": app}, anti=True)])
    pods = [Pod(name=f"{prefix}-{i}",
                requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)
            for i in range(n)]
    for p in pods:
        sim.store.add_pod(p)
    return pods


def all_bound(sim):
    return all(p.node_name is not None for p in sim.store.pods.values())


class TestZoneExhaustion:
    def test_exhaustion_marks_zone_and_recovers(self):
        """All candidate zones out of addresses → ZoneExhaustedError →
        zone-wide marks; freed addresses + TTL expiry let pods schedule."""
        pool = NodePool(name="pinned")
        pool.requirements.add(Requirement(L.ZONE, Operator.IN, ("zone-a",)))
        sim = make_sim(cloud_config=FakeCloudConfig(zone_ip_capacity={
            "zone-a": 2, "zone-b": 2, "zone-c": 2}), nodepool=pool)
        add_pods(sim, 2, cpu="4", mem="8Gi", one_per_node=True)
        assert sim.engine.run_until(lambda: all_bound(sim), timeout=60)
        assert sim.cloud.zone_ips["zone-a"] == 0
        # next burst cannot launch anywhere (pool pinned to zone-a)
        extra = add_pods(sim, 2, cpu="4", mem="8Gi", prefix="x",
                         one_per_node=True, app="p")
        sim.engine.run_for(30)
        assert all(p.node_name is None for p in extra)
        assert any(e[0] == "zone" and e[2] == "Exhausted"
                   for e in sim.store.events)
        # the catalog now reports every zone-a offering unavailable
        assert all(not o.available
                   for t in sim.catalog.list() for o in t.offerings
                   if o.zone == "zone-a")
        # free an address: remove one original workload pod and drain its
        # node, then wait out the 3m zone mark
        victim_pod = sim.store.pods["default/p-0"]
        node_name = victim_pod.node_name
        sim.store.delete_pod("default", "p-0")
        victim = next(c for c in sim.store.nodeclaims.values()
                      if c.node_name == node_name)
        sim.termination.delete_nodeclaim(victim, sim.clock.now(), "test")
        sim.engine.run_for(4 * 60, step=10)
        sim.engine.run_until(lambda: any(p.node_name for p in extra),
                             timeout=120)
        assert any(p.node_name for p in extra)

    def test_cloud_fails_over_to_zones_with_addresses(self):
        """With free zones still available, the launch lands there —
        no error, no marks (the override list spans zones)."""
        sim = make_sim(cloud_config=FakeCloudConfig(zone_ip_capacity={
            "zone-a": 1, "zone-b": 50, "zone-c": 50}))
        add_pods(sim, 10, cpu="4", mem="8Gi")
        assert sim.engine.run_until(lambda: all_bound(sim), timeout=60)
        by_zone = {}
        for c in sim.store.nodeclaims.values():
            by_zone[c.zone] = by_zone.get(c.zone, 0) + 1
        assert by_zone.get("zone-a", 0) <= 1
        assert sim.provisioner.stats["ice_errors"] == 0


class TestInflightAccounting:
    def test_batch_spreads_before_exhausting_a_zone(self):
        """The accounting pre-pass drops a zone's overrides once earlier
        requests in the SAME batch consumed its budget (subnet.go:183)."""
        from karpenter_tpu.cloud.provider import LaunchOverride, LaunchRequest
        sim = make_sim(cloud_config=FakeCloudConfig(zone_ip_capacity={
            "zone-a": 2, "zone-b": 50, "zone-c": 50}))
        reqs = []
        for i in range(6):
            reqs.append(LaunchRequest(
                nodeclaim_name=f"c{i}",
                overrides=[  # zone-a cheapest for everyone
                    LaunchOverride("m5.large", "zone-a", "on-demand", 0.010),
                    LaunchOverride("m5.large", "zone-b", "on-demand", 0.020),
                    LaunchOverride("m5.large", "zone-c", "on-demand", 0.030)]))
        sim.provisioner._apply_inflight_ip_accounting(reqs)
        # first two keep zone-a; the rest had it dropped client-side
        assert all(any(o.zone == "zone-a" for o in r.overrides)
                   for r in reqs[:2])
        assert all(all(o.zone != "zone-a" for o in r.overrides)
                   for r in reqs[2:])
        # and every request still has somewhere to go
        assert all(r.overrides for r in reqs)


class TestCapacityTypeDrought:
    def test_spot_drought_marks_capacity_type_and_reroutes(self):
        """A spot-only pool hits fleet-wide UnfulfillableCapacity → the
        capacity type is marked; a flexible pool's next solve routes
        straight to on-demand without touching the drought."""
        pool = NodePool(name="spot-only")
        pool.requirements.add(Requirement(L.CAPACITY_TYPE, Operator.IN,
                                          ("spot",)))
        sim = make_sim(nodepool=pool)
        sim.cloud.set_capacity_type_outage("spot")
        stranded = add_pods(sim, 3)
        sim.engine.run_for(20)
        assert all(p.node_name is None for p in stranded)
        assert any(e[0] == "capacity-type" and e[2] == "Unfulfillable"
                   for e in sim.store.events)
        assert sim.catalog.unavailable.is_unavailable(
            "m5.large", "zone-a", "spot")
        # a flexible pool now solves directly to on-demand — one launch
        # call, no new ICE errors
        flexible = NodePool(name="flexible", weight=10)
        sim.store.add_nodepool(flexible)
        errors_before = sim.provisioner.stats["ice_errors"]
        ok = add_pods(sim, 3, prefix="flex")
        sim.engine.run_until(lambda: all(p.node_name for p in ok),
                             timeout=60)
        assert all(p.node_name for p in ok)
        assert sim.provisioner.stats["ice_errors"] == errors_before
        for p in ok:
            claim = sim.store.nodeclaims[p.annotations.get(
                "karpenter.tpu/nominated-nodeclaim")]
            assert claim.capacity_type == "on-demand"


class TestSpotPriceFeed:
    def test_consolidation_reacts_to_spot_price_drop(self):
        """Spot starts expensive → fleet lands on-demand; the market drops,
        the pricing poller ingests it, and consolidation replaces nodes
        with the now-cheaper spot capacity (reference pricing.go:379 +
        SpotToSpotConsolidation=n/a: victims are on-demand)."""
        sim = make_sim()
        # spot drought pricing: 10x on-demand
        for (t, z), p in list(sim.cloud.spot_prices.items()):
            sim.cloud.set_spot_price(t, z, p * 20)
        sim.engine.run_for(2)  # spot poller ingests the expensive book
        add_pods(sim, 6, cpu="4", mem="8Gi")
        assert sim.engine.run_until(lambda: all_bound(sim), timeout=60)
        assert all(c.capacity_type == "on-demand"
                   for c in sim.store.nodeclaims.values())
        # market recovers: spot at 10% of on-demand
        for t in sim.cloud.types.values():
            for o in t.offerings:
                if o.capacity_type == "spot":
                    od = next((x.price for x in t.offerings
                               if x.capacity_type == "on-demand"
                               and x.zone == o.zone), None)
                    if od:
                        sim.cloud.set_spot_price(t.name, o.zone, od * 0.1)
        # poller runs every 300s; give consolidation room to act
        sim.engine.run_for(15 * 60, step=5)
        assert any(c.capacity_type == "spot"
                   for c in sim.store.nodeclaims.values())
        assert sim.disruption.stats["consolidated"] >= 1
