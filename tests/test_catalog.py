import pytest
import numpy as np

from karpenter_tpu.catalog import (CatalogProvider, GeneratorConfig,
                                   UnavailableOfferings, generate_catalog,
                                   small_catalog)
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodeClassSpec
from karpenter_tpu.models.resources import CPU, MEMORY, PODS
from karpenter_tpu.utils.clock import FakeClock


def test_catalog_scale():
    cat = generate_catalog()
    # EC2-scale: the reference paginates ~850 types (instancetype.go:239-252)
    assert 700 <= len(cat) <= 1000
    names = {t.name for t in cat}
    assert len(names) == len(cat)  # unique names


def test_catalog_shapes():
    cat = generate_catalog()
    by_name = {t.name: t for t in cat}
    m = by_name["m5.xlarge"]
    assert m.capacity[CPU] == 4.0
    # memory: 16 GiB minus 7.5% VM overhead
    assert abs(m.capacity[MEMORY] - 16 * 2**30 * 0.925) < 1e6
    assert m.capacity[PODS] == 58
    alloc = m.allocatable()
    assert alloc[CPU] < 4.0  # kube-reserved subtracted
    assert alloc[MEMORY] < m.capacity[MEMORY]
    # requirements carry the label surface
    assert m.requirements.get(L.INSTANCE_FAMILY).contains("m5")
    assert m.requirements.get(L.INSTANCE_CPU).contains("4")
    # offerings exist with spot cheaper than on-demand per zone
    for z in m.zones():
        od = [o for o in m.offerings if o.zone == z and o.capacity_type == "on-demand"]
        sp = [o for o in m.offerings if o.zone == z and o.capacity_type == "spot"]
        if od and sp:
            assert sp[0].price < od[0].price


def test_catalog_deterministic():
    a = generate_catalog()
    b = generate_catalog()
    assert [t.name for t in a] == [t.name for t in b]
    assert all(ta.offerings[0].price == tb.offerings[0].price for ta, tb in zip(a, b))


def test_gpu_and_accelerator_families():
    cat = generate_catalog()
    gpus = [t for t in cat if t.requirements.has(L.INSTANCE_GPU_COUNT)]
    accels = [t for t in cat if t.requirements.has(L.INSTANCE_ACCELERATOR_COUNT)]
    assert gpus and accels
    reserved = [o for t in cat for o in t.offerings if o.capacity_type == "reserved"]
    assert reserved  # ODCR-style offerings exist
    assert all(o.reservation_capacity > 0 for o in reserved)


def test_small_catalog():
    cat = small_catalog()
    assert 10 <= len(cat) <= 40


def test_provider_ice_invalidation():
    clock = FakeClock()
    ice = UnavailableOfferings(clock=clock)
    provider = CatalogProvider(lambda: small_catalog(), unavailable=ice, clock=clock)
    types = provider.list()
    t0 = types[0]
    zone = t0.offerings[0].zone
    ct = t0.offerings[0].capacity_type
    assert t0.offerings[0].available
    epoch0 = provider.epoch

    ice.mark_unavailable(t0.name, zone, ct, reason="ICE")
    types2 = provider.list()
    assert provider.epoch != epoch0
    o2 = [o for o in types2[provider_idx(types2, t0.name)].offerings
          if o.zone == zone and o.capacity_type == ct]
    assert o2 and not o2[0].available

    # TTL expiry restores availability. Staleness bound: the ICE entry
    # expires at 3m but the resolved view refreshes on its own 5m TTL
    # (matching the reference's cache.go SLOs), so step past both.
    clock.step(400)
    types3 = provider.list()
    o3 = [o for o in types3[provider_idx(types3, t0.name)].offerings
          if o.zone == zone and o.capacity_type == ct]
    assert o3 and o3[0].available


def provider_idx(types, name):
    return next(i for i, t in enumerate(types) if t.name == name)


def test_nodeclass_zone_filter():
    provider = CatalogProvider(lambda: small_catalog())
    nc = NodeClassSpec(name="one-zone", zones=["zone-a"])
    types = provider.list(nc)
    assert types
    for t in types:
        assert all(o.zone == "zone-a" for o in t.offerings)


class TestNodeOverlay:
    def test_price_and_capacity_overrides(self):
        from karpenter_tpu.models.overlay import NodeOverlay
        from karpenter_tpu.models.requirements import Operator, Requirement, Requirements
        from karpenter_tpu.models.resources import Resources
        from karpenter_tpu.catalog import CatalogProvider, small_catalog

        prov = CatalogProvider(lambda: small_catalog())
        base = {t.name: t for t in prov.list()}
        m5l = base["m5.large"]
        base_price = m5l.offerings[0].price
        e0 = prov.epoch

        prov.set_overlays([
            NodeOverlay(name="surcharge",
                        requirements=Requirements(Requirement(
                            L.INSTANCE_FAMILY, Operator.IN, ("m5",))),
                        price_adjustment="+50%"),
            NodeOverlay(name="device-plugin",
                        requirements=Requirements(Requirement(
                            L.INSTANCE_FAMILY, Operator.IN, ("m5",))),
                        capacity=Resources({"vendor.io/widget": 4.0})),
        ])
        assert prov.epoch != e0  # overlay version invalidates caches
        after = {t.name: t for t in prov.list()}
        assert after["m5.large"].offerings[0].price == pytest.approx(base_price * 1.5)
        assert after["m5.large"].capacity["vendor.io/widget"] == 4.0
        # non-matching types untouched
        assert after["c5.large"].offerings[0].price == base["c5.large"].offerings[0].price

    def test_absolute_price_and_weight(self):
        from karpenter_tpu.models.overlay import NodeOverlay, apply_overlays
        from karpenter_tpu.models.requirements import Operator, Requirement, Requirements
        from karpenter_tpu.catalog import small_catalog
        types = small_catalog()
        heavy = NodeOverlay(name="pin", weight=10,
                            requirements=Requirements(Requirement(
                                L.INSTANCE_FAMILY, Operator.IN, ("m5",))),
                            price_adjustment="0.01")
        light = NodeOverlay(name="discount", weight=1,
                            requirements=Requirements(Requirement(
                                L.INSTANCE_FAMILY, Operator.IN, ("m5",))),
                            price_adjustment="-50%")
        out = {t.name: t for t in apply_overlays(types, [light, heavy])}
        assert all(o.price == 0.01 for o in out["m5.large"].offerings)
