"""Chaos scenario catalog: convergence, reproducibility, degraded-mode
observability, and the colocated-bundle interruption wave."""

import pytest

from karpenter_tpu.faults import (FaultPlan, InterruptionBurst,
                                  RestartRunner, ScenarioRunner, SCENARIOS)
from karpenter_tpu.obs.tracer import TRACER


@pytest.fixture
def tracer():
    """Enable the process tracer for a test, restoring it after (same
    idiom as tests/test_obs.py)."""
    from karpenter_tpu.obs import FlightRecorder
    saved = (TRACER.enabled, TRACER.clock, TRACER.recorder,
             TRACER.trace_dir, TRACER.drop_empty)
    TRACER.configure(enabled=True, ring_size=64)
    TRACER.trace_dir = ""
    yield TRACER
    (TRACER.enabled, TRACER.clock, TRACER.recorder,
     TRACER.trace_dir, TRACER.drop_empty) = saved


# restart scenarios tear the engine down mid-run — only RestartRunner
# (which rebuilds the stack on the surviving durable state) can drive
# them; they get their own class below
FAST = sorted(n for n, sc in SCENARIOS.items()
              if not sc.slow and not sc.restart)
SLOW = sorted(n for n, sc in SCENARIOS.items()
              if sc.slow and not sc.restart)
RESTART = sorted(n for n, sc in SCENARIOS.items() if sc.restart)


class TestScenarioCatalog:
    @pytest.mark.parametrize("name", FAST)
    def test_every_fast_scenario_converges(self, name):
        """Acceptance: every catalog scenario converges — all pods bound,
        no leaked NodeClaims, store/cloud consistent — and actually
        injected faults."""
        rep = ScenarioRunner(name, seed=0).run()
        assert rep.converged, rep.summary()
        assert not rep.violations, rep.summary()
        assert rep.faults_injected > 0, (
            f"{name} converged without a single injected fault — the "
            f"scenario's weather never arrived")

    @pytest.mark.slow
    @pytest.mark.parametrize("name", SLOW)
    def test_soak_scenarios_converge(self, name):
        rep = ScenarioRunner(name, seed=0).run()
        assert rep.ok, rep.summary()
        assert rep.faults_injected > 10

    def test_same_seed_reproduces_timeline_and_end_state(self):
        """Acceptance: same FaultPlan seed ⇒ identical fault timeline and
        identical end-of-run cluster-state hash across two runs."""
        a = ScenarioRunner("smoke", seed=3).run()
        b = ScenarioRunner("smoke", seed=3).run()
        assert a.ok and b.ok
        assert a.fault_fingerprint == b.fault_fingerprint
        assert a.end_hash == b.end_hash
        assert a.faults_injected == b.faults_injected

    def test_brownout_reproduces_probabilistic_draws(self):
        """p<1 rules draw from the plan RNG — the draw sequence (hence the
        timeline) must still replay from the seed."""
        a = ScenarioRunner("api_brownout", seed=11).run()
        b = ScenarioRunner("api_brownout", seed=11).run()
        assert a.ok and b.ok
        assert a.fault_fingerprint == b.fault_fingerprint
        assert a.end_hash == b.end_hash


class TestRestartScenarios:
    """Crash-restart resilience (docs/robustness.md 'Restart & crash
    recovery'): the engine is torn down at seeded cut points and rebuilt
    from durable state (cloud + intent journal); the run must end with
    zero leaked instances, zero duplicate launches, all pods bound, and
    a fully resolved journal."""

    @pytest.mark.parametrize("name", RESTART)
    def test_restart_scenarios_converge(self, name):
        """Acceptance: every restart scenario converges with clean
        invariants (check_invariants + restart_invariants — the latter
        adds journal-resolved and no-duplicate-launch) and actually
        crashed at least once."""
        rep = RestartRunner(name, seed=0).run()
        assert rep.converged, rep.summary()
        assert not rep.violations, rep.summary()
        assert rep.stats["restarts"] >= 1, (
            f"{name} converged without a single injected crash — the "
            f"scenario's deaths never happened")
        assert rep.stats["intents_opened"] > 0
        # every opened intent resolved one way or another
        assert (rep.stats["intents_committed"]
                + rep.stats["intents_aborted"]
                + rep.stats["intents_reaped"]
                == rep.stats["intents_opened"])

    def test_restart_smoke_reproducible(self):
        """restart_smoke: same seed ⇒ identical fault timeline (crash
        firings included) and identical end-state hash, across the
        teardown/rebuild cycles."""
        a = RestartRunner("restart_smoke", seed=5).run()
        b = RestartRunner("restart_smoke", seed=5).run()
        assert a.ok and b.ok, (a.summary(), b.summary())
        assert a.fault_fingerprint == b.fault_fingerprint
        assert a.end_hash == b.end_hash
        assert a.stats["restarts"] == b.stats["restarts"] >= 1

    def test_crash_storm_warm_path_forced_cold_and_divergence_free(self):
        """The warm path may never survive a restart: the rebuilt engine
        opens cold, and every post-restart warm audit must be
        divergence-free."""
        runner = RestartRunner("crash_launch_storm", seed=0)
        rep = runner.run()
        assert rep.ok, rep.summary()
        assert rep.stats["warm_divergences"] == 0
        sim = runner.last_sim
        assert sim.warmpath is not None


class TestIceStormObservability:
    def test_degraded_mode_and_fault_spans_surface(self, tracer):
        """Acceptance: during an ICE-storm run the degraded-mode gauge,
        the fault counter, and at least one fault-attributed trace span
        are all visible through /metrics and /debug/traces."""
        from karpenter_tpu.metrics import DEGRADED_MODE
        from karpenter_tpu.obs.exposition import render
        rep = ScenarioRunner("ice_storm", seed=0).run()
        assert rep.ok, rep.summary()
        assert rep.stats["ice_marks"] > 0
        assert rep.stats["provisioner_ice_errors"] > 0

        status, _, body = render("/metrics")
        text = body.decode()
        assert status == 200
        assert 'karpenter_tpu_degraded_mode{component="capacity"' in text
        assert "karpenter_tpu_faults_injected_total" in text
        assert 'kind="ice"' in text

        status, _, body = render("/debug/traces")
        assert status == 200
        assert b'"fault.' in body  # fault-attributed span in the recorder

    def test_capacity_degraded_gauge_tracks_live_marks_and_clears(self):
        """The gauge mirrors the live ICE-mark count — non-zero while the
        storm's marks last, back to 0 once they expire."""
        from karpenter_tpu.metrics import DEGRADED_MODE
        runner = ScenarioRunner("ice_storm", seed=0)
        rep = runner.run()
        assert rep.ok
        sim = runner.last_sim
        # the gauge publishes on mark/prune; a prune-read syncs it with
        # the live mark count
        sim.catalog.unavailable.seqnum
        assert DEGRADED_MODE.value(component="capacity") == float(
            sim.catalog.unavailable.active())
        # marks were placed during the storm…
        assert rep.stats["ice_marks"] > 0
        # …and expiring the remainder clears the gauge
        sim.clock.step(181.0)  # past UNAVAILABLE_OFFERINGS_TTL
        sim.catalog.unavailable.seqnum  # prune-on-read publishes
        assert sim.catalog.unavailable.active() == 0
        assert DEGRADED_MODE.value(component="capacity") == 0.0


class TestDeviceLossScenario:
    def test_fallback_metered_and_converges(self):
        from karpenter_tpu.metrics import SOLVER_FALLBACKS
        before = SOLVER_FALLBACKS.value(from_backend="device",
                                        to_backend="host") + \
            SOLVER_FALLBACKS.value(from_backend="device",
                                   to_backend="native")
        rep = ScenarioRunner("device_loss", seed=0).run()
        assert rep.ok, rep.summary()
        assert rep.stats["solver_device_fallbacks"] == 1
        after = SOLVER_FALLBACKS.value(from_backend="device",
                                       to_backend="host") + \
            SOLVER_FALLBACKS.value(from_backend="device",
                                   to_backend="native")
        assert after == before + 1


class TestInterruptionWaveBundle:
    def _sim_with_bundle(self, burst_at=30.0):
        """Colocated bundle + background pods on a pool restricted to
        market capacity (no reservations — keeps the catalog epoch free
        of reservation-version noise so the re-upload count is exact)."""
        from karpenter_tpu.models import labels as L
        from karpenter_tpu.models.pod import Pod, PodAffinityTerm
        from karpenter_tpu.models.requirements import Operator, Requirement
        from karpenter_tpu.models.resources import Resources
        from karpenter_tpu.sim import make_sim
        plan = FaultPlan(seed=0, rules=[
            InterruptionBurst(at=burst_at, count=1, kind="spot",
                              target_pods=("bundle-",))])
        sim = make_sim(fault_plan=plan)
        pool = sim.store.nodepools["default"]
        pool.requirements.add(Requirement(
            L.CAPACITY_TYPE, Operator.IN,
            (L.CAPACITY_SPOT, L.CAPACITY_ON_DEMAND)))
        sim.store.add_pod(Pod(
            name="bundle-cache-0", labels={"app": "bundle-cache"},
            requests=Resources.parse({"cpu": "1", "memory": "2Gi"})))
        for i in range(3):
            sim.store.add_pod(Pod(
                name=f"bundle-w-{i}", labels={"app": "bundle-w"},
                requests=Resources.parse({"cpu": "1", "memory": "1Gi"}),
                affinity_terms=[PodAffinityTerm(
                    topology_key=L.HOSTNAME,
                    label_selector={"app": "bundle-cache"})]))
        for i in range(10):
            sim.store.add_pod(Pod(
                name=f"bg-{i}",
                requests=Resources.parse({"cpu": "500m", "memory": "1Gi"})))
        return sim, plan

    @staticmethod
    def _bundle_nodes(sim):
        return {p.node_name for p in sim.store.pods.values()
                if p.name.startswith("bundle-")}

    def test_bundle_replanned_atomically_and_tensor_reuploaded_once(self):
        """Satellite: an interruption hitting ONE node of a colocated
        bundle forces replanning of the WHOLE bundle (all four pods land
        together on a fresh node), and the resulting UnavailableOfferings
        mark re-keys the availability tensor exactly once."""
        sim, plan = self._sim_with_bundle(burst_at=30.0)

        def all_bound():
            return all(p.node_name is not None
                       for p in sim.store.pods.values())
        assert sim.engine.run_until(all_bound, timeout=25.0), \
            "initial placement did not settle before the wave"
        (node0,) = self._bundle_nodes(sim)  # colocated on ONE node
        marks0 = sim.catalog.unavailable.stats["marks"]
        rebuilds0 = sim.solver.stats["catalog_rebuilds"]
        epoch0 = sim.catalog.epoch
        assert not plan.timeline  # wave not fired yet

        def replanned():
            nodes = self._bundle_nodes(sim)
            return (all_bound() and len(nodes) == 1
                    and node0 not in nodes)
        assert sim.engine.run_until(replanned, timeout=120.0), \
            f"bundle never replanned off {node0}: {self._bundle_nodes(sim)}"
        # the wave hit the bundle's node, and only it
        assert [k for _, k, _ in plan.timeline] == ["interruption"]
        # whole-bundle atomicity: all four pods share ONE fresh node
        (node1,) = self._bundle_nodes(sim)
        assert node1 != node0
        # the spot interruption marked the reclaimed offering once, and
        # that ONE ICE-cache bump is the only availability-epoch change —
        # the epoch keys the (device-)tensor caches, so the availability
        # tensor re-uploads exactly once for the wave
        assert sim.catalog.unavailable.stats["marks"] == marks0 + 1
        epoch1 = sim.catalog.epoch
        assert epoch1[1] == epoch0[1] + 1  # ICE seqnum: exactly one bump
        assert (epoch1[0],) + epoch1[2:] == (epoch0[0],) + epoch0[2:], (
            "a non-ICE component also rolled the epoch — the re-upload "
            "count would over-state the ICE cache's effect")
        assert sim.solver.stats["catalog_rebuilds"] > rebuilds0
        # and the next solve avoided the reclaimed offering: the new
        # bundle node is not on the marked (type, zone, captype)
        claim = next(c for c in sim.store.nodeclaims.values()
                     if sim.store.node_for_nodeclaim(c) is not None
                     and sim.store.node_for_nodeclaim(c).name == node1)
        assert not sim.catalog.unavailable.is_unavailable(
            claim.instance_type, claim.zone, claim.capacity_type)

    def test_full_scenario_converges(self):
        rep = ScenarioRunner("interruption_wave", seed=0).run()
        assert rep.ok, rep.summary()
        assert rep.stats["ice_marks"] >= 1  # the spot reclaim marked


class TestZeroOverheadWhenDisabled:
    def test_plain_sim_has_no_armed_hooks(self):
        from karpenter_tpu.ops import solver as solver_mod
        from karpenter_tpu.sim import make_sim
        from karpenter_tpu.utils import crashpoints
        sim = make_sim()
        assert sim.fault_plan is None
        assert sim.cloud.fault_plan is None
        assert sim.clock._jumps == []
        assert solver_mod._dispatch_fault_hook is None
        # the crash-point seams are disarmed too (one None check each)
        assert crashpoints._hook is None
        # controllers hold the raw cloud — no decorator in the path
        assert sim.provisioner.cloud is sim.cloud
