"""Hostname-level required positive pod affinity — the co-location planner.

Reference behavior: the core scheduler's required podAffinity handling at
topology_key=hostname (scheduling.md), including the first-pod bootstrap.
Zone-level terms are covered in test_affinity.py.
"""

import numpy as np

from karpenter_tpu.catalog import CatalogProvider, small_catalog
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod, PodAffinityTerm
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.ops.binpack import VirtualNode
from karpenter_tpu.ops.colocate import has_colocation, plan_colocation
from karpenter_tpu.ops.encode import encode_catalog
from karpenter_tpu.ops.facade import Solver


def pod(name, labels=None, terms=(), cpu="1", mem="1Gi", ns="default"):
    return Pod(name=name, namespace=ns, labels=labels or {},
               requests=Resources.parse({"cpu": cpu, "memory": mem}),
               affinity_terms=list(terms))


def host_term(selector):
    return PodAffinityTerm(topology_key=L.HOSTNAME, label_selector=selector)


def solver():
    return Solver(CatalogProvider(lambda: small_catalog()), backend="host")


def all_keys(out):
    keys = [k for l in out.launches for k in l.pod_keys]
    keys += [k for ks in out.existing_placements.values() for k in ks]
    keys += out.unschedulable
    return keys


class TestSelfColocation:
    def test_self_match_packs_one_node(self):
        s = solver()
        pods = [pod(f"p{i}", {"app": "ring"}, [host_term({"app": "ring"})])
                for i in range(4)]
        out = s.solve(pods, NodePool(name="np"))
        assert not out.unschedulable
        assert len(out.launches) == 1
        assert len(out.launches[0].pod_keys) == 4

    def test_self_match_excess_unschedulable(self):
        # more pods than any single type can hold → one full node, rest pend
        s = solver()
        cat = s.tensors()
        max_cpu = int(cat.allocatable[:, 0].max())
        pods = [pod(f"p{i}", {"app": "ring"}, [host_term({"app": "ring"})])
                for i in range(max_cpu + 5)]
        out = s.solve(pods, NodePool(name="np"))
        assert len(out.launches) == 1
        fit = len(out.launches[0].pod_keys)
        assert fit >= 1
        assert len(out.unschedulable) == max_cpu + 5 - fit
        # the one-shot node prefers the max-slot type
        assert fit == max(
            int(cat.allocatable[i, 0]) for i in range(cat.T))


class TestCrossGroupColocation:
    def test_initiator_rides_with_target(self):
        s = solver()
        web = [pod(f"w{i}", {"app": "web"}, [host_term({"app": "cache"})])
               for i in range(3)]
        cache = [pod(f"c{i}", {"app": "cache"}) for i in range(2)]
        out = s.solve(web + cache, NodePool(name="np"))
        assert not out.unschedulable
        # every node hosting a web pod also hosts a cache pod
        for l in out.launches:
            if any(k.endswith(("w0", "w1", "w2")) for k in l.pod_keys):
                assert any(k.endswith(("c0", "c1")) for k in l.pod_keys), l.pod_keys
        keys = all_keys(out)
        assert len(keys) == len(set(keys)) == 5

    def test_targets_exhausted_excess_unschedulable(self):
        # each bundle node needs one cache pod; only one exists and the node
        # can't hold every web pod → leftovers have no matching node
        s = solver()
        cat = s.tensors()
        max_cpu = int(cat.allocatable[:, 0].max())
        web = [pod(f"w{i}", {"app": "web"}, [host_term({"app": "cache"})])
               for i in range(max_cpu + 4)]
        cache = [pod("c0", {"app": "cache"})]
        out = s.solve(web + cache, NodePool(name="np"))
        assert len(out.launches) == 1
        assert out.unschedulable  # web pods beyond the single bundle node

    def test_no_match_anywhere_unschedulable(self):
        s = solver()
        pods = [pod("p0", {"app": "x"}, [host_term({"app": "missing"})])]
        out = s.solve(pods, NodePool(name="np"))
        assert out.unschedulable == ["default/p0"]
        assert not out.launches

    def test_namespace_scoped_matching(self):
        s = solver()
        web = [pod("w0", {"app": "web"}, [host_term({"app": "cache"})])]
        cache = [pod("c0", {"app": "cache"}, ns="other")]
        out = s.solve(web + cache, NodePool(name="np"))
        # cross-namespace labels don't match → web unschedulable, cache fine
        assert out.unschedulable == ["default/w0"]
        placed = [k for l in out.launches for k in l.pod_keys]
        assert placed == ["other/c0"]

    def test_two_terms_need_both_targets(self):
        s = solver()
        app = [pod("a0", {"app": "app"},
                   [host_term({"app": "db"}), host_term({"app": "cache"})])]
        db = [pod("d0", {"app": "db"})]
        cache = [pod("c0", {"app": "cache"})]
        out = s.solve(app + db + cache, NodePool(name="np"))
        assert not out.unschedulable
        bundle = next(l for l in out.launches
                      if "default/a0" in l.pod_keys)
        assert "default/d0" in bundle.pod_keys
        assert "default/c0" in bundle.pod_keys


class TestResidentColocation:
    def _existing(self, s, n_pods_cpu=2):
        cat = s.tensors()
        # commit a roomy existing node
        t = int(np.argmax(cat.allocatable[:, 0]))
        vn = VirtualNode(type_idx=t, zone_mask=np.ones(cat.Z, bool),
                         cap_mask=np.ones(cat.C, bool),
                         cum=np.zeros(len(cat.resources), np.float32),
                         existing_name="node-1")
        return cat, vn

    def test_resident_match_places_on_node(self):
        s = solver()
        cat, vn = self._existing(s)
        resident = Pod(name="db0", labels={"app": "db"})
        web = [pod(f"w{i}", {"app": "web"}, [host_term({"app": "db"})])
               for i in range(2)]
        out = s.solve(web, NodePool(name="np"), existing=[vn],
                      existing_pods={"node-1": [resident]})
        assert not out.unschedulable
        assert not out.launches
        assert sorted(out.existing_placements["node-1"]) == [
            "default/w0", "default/w1"]

    def test_resident_full_no_target_unschedulable(self):
        s = solver()
        cat = s.tensors()
        # tiny committed node: full after cum is set to its capacity
        t = int(np.argmin(np.where(cat.allocatable[:, 0] > 0,
                                   cat.allocatable[:, 0], np.inf)))
        cum = cat.allocatable[t].copy()
        vn = VirtualNode(type_idx=t, zone_mask=np.ones(cat.Z, bool),
                         cap_mask=np.ones(cat.C, bool), cum=cum,
                         existing_name="node-1")
        resident = Pod(name="db0", labels={"app": "db"})
        web = [pod("w0", {"app": "web"}, [host_term({"app": "db"})])]
        out = s.solve(web, NodePool(name="np"), existing=[vn],
                      existing_pods={"node-1": [resident]})
        # the only matching node is full and no pending target exists
        assert out.unschedulable == ["default/w0"]

    def test_plan_mutates_existing_cum(self):
        cat = encode_catalog(small_catalog())
        t = int(np.argmax(cat.allocatable[:, 0]))
        vn = VirtualNode(type_idx=t, zone_mask=np.ones(cat.Z, bool),
                         cap_mask=np.ones(cat.C, bool),
                         cum=np.zeros(len(cat.resources), np.float32),
                         existing_name="node-1")
        resident = Pod(name="db0", labels={"app": "db"})
        web = [pod("w0", {"app": "web"}, [host_term({"app": "db"})],
                   cpu="2", mem="2Gi")]
        plan = plan_colocation(web, cat, existing=[vn],
                               existing_pods={"node-1": [resident]})
        assert plan.existing_placements["node-1"][0].name == "w0"
        assert vn.cum[0] == 2.0  # the main solve sees the consumed capacity


class TestPlannerUnit:
    def test_fast_path_no_terms(self):
        cat = encode_catalog(small_catalog())
        pods = [pod("p0"), pod("p1")]
        assert not has_colocation(pods)
        plan = plan_colocation(pods, cat)
        assert plan.remaining == pods
        assert not plan.bundles and not plan.unschedulable

    def test_uncoupled_pods_stay_on_tensor_path(self):
        s = solver()
        ring = [pod(f"r{i}", {"app": "ring"}, [host_term({"app": "ring"})])
                for i in range(2)]
        plain = [pod(f"q{i}", {"app": "plain"}, cpu="2") for i in range(5)]
        out = s.solve(ring + plain, NodePool(name="np"))
        assert not out.unschedulable
        keys = all_keys(out)
        assert len(keys) == len(set(keys)) == 7

    def test_bundle_respects_target_only_resources(self):
        """Review finding: a target pod's request in a resource dim the
        initiator doesn't touch must still gate the bundle's type choice."""
        from karpenter_tpu.catalog import GeneratorConfig, generate_catalog
        types = [t for t in generate_catalog(GeneratorConfig(
            zones=("zone-a",), families=["c5", "g5"]))]
        s = Solver(CatalogProvider(lambda: types), backend="host")
        web = [pod("w0", {"app": "web"}, [host_term({"app": "gpu"})])]
        gpu = Pod(name="g0", labels={"app": "gpu"},
                  requests=Resources.parse({"cpu": "1", "memory": "1Gi",
                                            "accel/tpu": "1"}))
        out = s.solve([gpu] + web, NodePool(name="np"))
        if out.launches:
            bundle = next((l for l in out.launches
                           if "default/w0" in l.pod_keys), None)
            if bundle is not None and "default/g0" in bundle.pod_keys:
                t = next(t for t in types if t.name == bundle.instance_type)
                assert t.allocatable().get("accel/tpu") >= 1, bundle.instance_type

    def test_self_anti_caps_bundle_at_one_per_node(self):
        """Review finding: positive affinity to a target plus required
        self-anti-affinity (one-per-node sidecar) must not pack several
        initiator pods onto one bundle node."""
        from karpenter_tpu.models.pod import PodAffinityTerm
        anti = PodAffinityTerm(topology_key=L.HOSTNAME,
                               label_selector={"app": "sidecar"}, anti=True)
        s = solver()
        side = [pod(f"s{i}", {"app": "sidecar"},
                    [host_term({"app": "db"}), anti]) for i in range(3)]
        db = [pod(f"d{i}", {"app": "db"}) for i in range(3)]
        out = s.solve(side + db, NodePool(name="np"))
        for l in out.launches:
            n_side = sum(1 for k in l.pod_keys if "/s" in k)
            assert n_side <= 1, l.pod_keys

    def test_resident_anti_repels_despite_match(self):
        """Review finding: a node hosting the affinity match AND a pod the
        group's anti-affinity selects must be skipped, not filled."""
        from karpenter_tpu.models.pod import PodAffinityTerm
        s = solver()
        cat = s.tensors()
        t = int(np.argmax(cat.allocatable[:, 0]))
        vn = VirtualNode(type_idx=t, zone_mask=np.ones(cat.Z, bool),
                         cap_mask=np.ones(cat.C, bool),
                         cum=np.zeros(len(cat.resources), np.float32),
                         existing_name="node-1")
        residents = [Pod(name="db0", labels={"app": "db"}),
                     Pod(name="noisy", labels={"app": "noisy"})]
        anti = PodAffinityTerm(topology_key=L.HOSTNAME,
                               label_selector={"app": "noisy"}, anti=True)
        web = [pod("w0", {"app": "web"}, [host_term({"app": "db"}), anti])]
        out = s.solve(web, NodePool(name="np"), existing=[vn],
                      existing_pods={"node-1": residents})
        assert "node-1" not in out.existing_placements
        assert out.unschedulable == ["default/w0"]  # only match is repelled

    def test_consumed_target_own_terms_validated(self):
        """Review finding: a target with its OWN required positive term must
        not be consumed into a bundle that doesn't satisfy it."""
        s = solver()
        # a requires b; b requires c (a resident nowhere) → b unusable as
        # a's target unless c rides along; c is absent → both unschedulable
        a = [pod("a0", {"app": "a"}, [host_term({"app": "b"})])]
        b = [pod("b0", {"app": "b"}, [host_term({"app": "c"})])]
        out = s.solve(a + b, NodePool(name="np"))
        assert sorted(out.unschedulable) == ["default/a0", "default/b0"]
        # chain closes when c exists: one bundle hosts all three
        c = [pod("c0", {"app": "c"})]
        out2 = s.solve(a + b + c, NodePool(name="np"))
        assert not out2.unschedulable

    def test_later_initiator_joins_opened_bundle(self):
        """A bigger group b (processed first, FFD) bundles with c; a's
        target b is then fully consumed — a must join b's node, not pend."""
        s = solver()
        b = [pod("b0", {"app": "b"}, [host_term({"app": "c"})],
                 cpu="4", mem="4Gi")]
        c = [pod("c0", {"app": "c"})]
        a = [pod("a0", {"app": "a"}, [host_term({"app": "b"})])]
        out = s.solve(a + b + c, NodePool(name="np"))
        assert not out.unschedulable
        bundle = next(l for l in out.launches if "default/b0" in l.pod_keys)
        assert "default/a0" in bundle.pod_keys

    def test_bundle_visible_to_zone_anti_affinity(self):
        """Review finding: a required zone anti-affinity term against pods
        the planner consumed into a bundle must still hold — bundle zones
        pin early and feed the zone pre-pass as occupancy."""
        from karpenter_tpu.models.pod import PodAffinityTerm
        zone_anti = PodAffinityTerm(topology_key=L.ZONE,
                                    label_selector={"app": "b"}, anti=True)
        s = solver()
        b = [pod("b0", {"app": "b"}, [host_term({"app": "c"})])]
        c = [pod("c0", {"app": "c"})]
        a = [pod("a0", {"app": "a"}, [zone_anti])]
        out = s.solve(a + b + c, NodePool(name="np"))
        assert not out.unschedulable
        bundle = next(l for l in out.launches if "default/b0" in l.pod_keys)
        a_launch = next(l for l in out.launches if "default/a0" in l.pod_keys)
        assert a_launch.zone != bundle.zone, (a_launch.zone, bundle.zone)

    def test_solve_does_not_mutate_caller_nodes(self):
        """Review finding: the planner's resident placements must not leak
        into the caller's VirtualNodes (disruption reuses them per solve)."""
        s = solver()
        cat = s.tensors()
        t = int(np.argmax(cat.allocatable[:, 0]))
        vn = VirtualNode(type_idx=t, zone_mask=np.ones(cat.Z, bool),
                         cap_mask=np.ones(cat.C, bool),
                         cum=np.zeros(len(cat.resources), np.float32),
                         existing_name="node-1")
        resident = Pod(name="db0", labels={"app": "db"})
        web = [pod("w0", {"app": "web"}, [host_term({"app": "db"})])]
        out = s.solve(web, NodePool(name="np"), existing=[vn],
                      existing_pods={"node-1": [resident]})
        assert out.existing_placements["node-1"] == ["default/w0"]
        assert vn.cum.sum() == 0.0, vn.cum
        assert vn.zone_mask.all() and vn.cap_mask.all()

    def test_mixed_backends_agree(self):
        import karpenter_tpu.ops.native as native
        if not native.available():
            return
        web = [pod(f"w{i}", {"app": "web"}, [host_term({"app": "cache"})])
               for i in range(3)]
        cache = [pod(f"c{i}", {"app": "cache"}) for i in range(2)]
        plain = [pod(f"q{i}", cpu="2") for i in range(4)]
        outs = {}
        for backend in ("host", "native"):
            s = Solver(CatalogProvider(lambda: small_catalog()),
                       backend=backend)
            out = s.solve(web + cache + plain, NodePool(name="np"))
            outs[backend] = sorted(
                (l.instance_type, tuple(sorted(l.pod_keys)))
                for l in out.launches)
        assert outs["host"] == outs["native"]
