"""DaemonSet overhead: per-node resources reserved before workload
placement (reference core: the scheduler adds daemonset pods to every
virtual node in the simulation; the scale suite's GetDaemonSetCount
adjusts density expectations accordingly)."""

import numpy as np

from karpenter_tpu.catalog import small_catalog
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import DaemonSet, Pod, Taint, Toleration
from karpenter_tpu.models.resources import NVIDIA_GPU, PODS, Resources
from karpenter_tpu.ops.encode import encode_catalog
from karpenter_tpu.ops.facade import daemonset_overhead
from karpenter_tpu.sim import make_sim


def small_pods(sim, n, cpu="900m"):
    pods = [Pod(name=f"p{i}",
                requests=Resources.parse({"cpu": cpu, "memory": "512Mi"}))
            for i in range(n)]
    for p in pods:
        sim.store.add_pod(p)
    return pods


class TestOverheadMatrix:
    def setup_method(self):
        self.cat = encode_catalog(small_catalog(8))
        self.pool = NodePool(name="default")

    def test_plain_daemonset_reserves_on_every_type(self):
        ovh = daemonset_overhead(
            self.cat, [DaemonSet(name="logging",
                                 requests=Resources.parse({"cpu": "500m"}))],
            self.pool, self.pool.template_labels())
        assert ovh is not None and (ovh > 0).any()
        cpu_col = self.cat.resources.index("cpu")
        pods_col = self.cat.resources.index(PODS)
        assert np.allclose(ovh[:, cpu_col], 0.5)
        assert np.allclose(ovh[:, pods_col], 1.0)  # one pod slot each

    def test_gpu_selector_daemonset_reserves_only_on_gpu_types(self):
        ovh = daemonset_overhead(
            self.cat, [DaemonSet(
                name="gpu-agent",
                requests=Resources.parse({"cpu": "1"}),
                node_selector={L.INSTANCE_GPU_MANUFACTURER: "nvidia"})],
            self.pool, self.pool.template_labels())
        assert ovh is not None
        gpu_types = self.cat.allocatable[
            :, self.cat.resources.index(NVIDIA_GPU)] > 0
        cpu_col = self.cat.resources.index("cpu")
        assert (ovh[gpu_types, cpu_col] == 1.0).all()
        assert (ovh[~gpu_types, cpu_col] == 0.0).all()

    def test_intolerant_daemonset_skipped_on_tainted_pool(self):
        pool = NodePool(name="tainted", taints=[
            Taint(key="team", value="x", effect="NoSchedule")])
        ds = DaemonSet(name="plain",
                       requests=Resources.parse({"cpu": "1"}))
        assert daemonset_overhead(self.cat, [ds], pool,
                                  pool.template_labels()) is None
        tol = DaemonSet(name="tolerant",
                        requests=Resources.parse({"cpu": "1"}),
                        tolerations=[Toleration(key="team", value="x",
                                                effect="NoSchedule")])
        assert daemonset_overhead(self.cat, [tol], pool,
                                  pool.template_labels()) is not None


class TestZoneVaryingOverhead:
    """Zone-pinned daemonsets with PARTIAL pool-zone overlap reserve per
    (type, zone) — a node charges the max over its remaining zone mask,
    so zones narrowing away from the daemonset restore headroom. This is
    tighter than the reference (which charges any template-compatible
    daemonset on every virtual node) at equal safety."""

    def setup_method(self):
        self.cat = encode_catalog(small_catalog(8))
        self.pool = NodePool(name="default")
        self.ds = DaemonSet(name="zonal-agent",
                            requests=Resources.parse({"cpu": "1",
                                                      "memory": "1Gi"}),
                            node_selector={L.ZONE: "zone-a"})

    def test_partial_overlap_goes_to_zone_tensor(self):
        from karpenter_tpu.ops.facade import (_daemonset_overhead_parts,
                                              apply_daemonset_overhead)
        base, zvar = _daemonset_overhead_parts(
            self.cat, [self.ds], self.pool, self.pool.template_labels())
        assert base is None and zvar is not None
        za = self.cat.zones.index("zone-a")
        cpu = self.cat.resources.index("cpu")
        assert (zvar[:, za, cpu] == 1.0).all()
        assert (zvar[:, [i for i in range(self.cat.Z) if i != za]] == 0).all()
        out = apply_daemonset_overhead(self.cat, [self.ds], self.pool,
                                       self.pool.template_labels())
        assert np.array_equal(out.allocatable, self.cat.allocatable)
        assert out.zone_overhead is not None

    def test_full_overlap_stays_baked(self):
        """A zone selector covering ALL pool zones is zone-invariant:
        baked into allocatable, no zone tensor."""
        from karpenter_tpu.models.nodepool import NodePool as NP
        from karpenter_tpu.models.requirements import (Operator, Requirement,
                                                       Requirements)
        from karpenter_tpu.ops.facade import apply_daemonset_overhead
        pool = NP(name="pinned", requirements=Requirements(
            Requirement(L.ZONE, Operator.IN, ("zone-a",))))
        out = apply_daemonset_overhead(self.cat, [self.ds], pool,
                                       pool.template_labels())
        assert out.zone_overhead is None
        assert (out.allocatable < self.cat.allocatable).any()

    def test_zone_narrowed_nodes_regain_headroom(self):
        """Pods pinned AWAY from the daemonset's zone pack at full
        density; pods pinned INTO it pack at reduced density — on both
        backends, node-for-node identical."""
        from karpenter_tpu.models.nodepool import NodePool as NP
        from karpenter_tpu.models.requirements import (Operator, Requirement,
                                                       Requirements)
        from karpenter_tpu.ops.binpack import solve_host, validate_solution
        from karpenter_tpu.ops.encode import encode_pods
        from karpenter_tpu.ops.facade import apply_daemonset_overhead
        from karpenter_tpu.ops.solver import solve_device
        # pin the type so density is deterministic
        pin = Requirements(Requirement(L.INSTANCE_TYPE, Operator.IN,
                                       ("c5.xlarge",)))  # 3.92 cpu
        pool = NP(name="default", requirements=pin)
        cat = apply_daemonset_overhead(self.cat, [self.ds], pool,
                                       pool.template_labels())
        assert cat.zone_overhead is not None

        def pods(zone, n):
            return [Pod(name=f"{zone}-{i}",
                        requests=Resources.parse({"cpu": "900m"}),
                        node_selector={L.ZONE: zone}) for i in range(n)]

        for pset, per_node in ((pods("zone-b", 8), 4),   # full 3.92 cpu
                               (pods("zone-a", 8), 3)):  # 2.92 after ds
            enc = encode_pods(pset, cat, extra_requirements=pool.requirements)
            h = solve_host(cat, enc)
            d = solve_device(cat, enc)
            assert not h.unschedulable and not d.unschedulable
            assert len(h.nodes) == len(d.nodes) == -(-8 // per_node), (
                f"{pset[0].name}: {len(h.nodes)} host / {len(d.nodes)} "
                f"device nodes, expected {-(-8 // per_node)}")
            for a, b in zip(h.nodes, d.nodes):
                assert (a.type_idx == b.type_idx
                        and a.pods_by_group == b.pods_by_group)
            assert not validate_solution(cat, enc, h)
            assert not validate_solution(cat, enc, d)

    def test_validate_catches_zone_overcommit(self):
        """validate_solution charges the zone reservation: a node whose
        zone mask includes the daemonset's zone and whose cum fits only
        WITHOUT the reservation must be flagged."""
        from karpenter_tpu.models.nodepool import NodePool as NP
        from karpenter_tpu.ops.binpack import (VirtualNode, solve_host,
                                               validate_solution)
        from karpenter_tpu.ops.encode import encode_pods
        from karpenter_tpu.ops.facade import apply_daemonset_overhead
        pool = NP(name="default")
        cat = apply_daemonset_overhead(self.cat, [self.ds], pool,
                                       pool.template_labels())
        t = self.cat.names.index("c5.xlarge")
        pods = [Pod(name=f"p{i}", requests=Resources.parse({"cpu": "900m"}),
                    node_selector={L.ZONE: "zone-a"}) for i in range(4)]
        enc = encode_pods(pods, cat)
        res = solve_host(cat, enc)
        # forge an overcommitted node: 4 × 0.9 cpu on a zone-a c5.xlarge
        # (3.92 raw, 2.92 after the zonal daemonset)
        zmask = np.zeros(cat.Z, bool)
        zmask[cat.zones.index("zone-a")] = True
        bad = VirtualNode(type_idx=t, zone_mask=zmask,
                          cap_mask=np.ones(cat.C, bool),
                          cum=res.nodes[0].cum * 0)
        bad.cum = np.zeros_like(res.nodes[0].cum)
        bad.cum[cat.resources.index("cpu")] = 3.6
        bad.pods_by_group = {0: 4}
        res.nodes = [bad]
        res.unschedulable = {0: 0}
        errs = validate_solution(cat, enc, res)
        assert any("over capacity" in e for e in errs), errs

    def test_screen_charges_zone_overhead(self):
        """The consolidation screen sees a zone-a node's headroom shrunk
        by the zonal daemonset but a zone-b node's untouched."""
        from karpenter_tpu.models.nodeclaim import NodeClaim
        from karpenter_tpu.models.nodepool import NodePool as NP
        from karpenter_tpu.ops.binpack import VirtualNode
        from karpenter_tpu.ops.consolidate import consolidation_screen
        from karpenter_tpu.ops.encode import encode_pods
        from karpenter_tpu.ops.facade import apply_daemonset_overhead
        from karpenter_tpu.state.cluster import NodeView
        pool = NP(name="default")
        cat = apply_daemonset_overhead(self.cat, [self.ds], pool,
                                       pool.template_labels())
        t = self.cat.names.index("c5.xlarge")  # 3.92 cpu
        # candidate 0 hosts 3 pods x 1.2 cpu; its pods fit a zone-b
        # twin (3.92 free) but NOT a zone-a twin (2.92 after the ds)
        pods = [Pod(name=f"p{i}", requests=Resources.parse({"cpu": "1200m"}))
                for i in range(3)]
        enc = encode_pods(pods, cat)

        def view(name, zone, cum_cpu):
            zmask = np.zeros(cat.Z, bool)
            zmask[cat.zones.index(zone)] = True
            cum = np.zeros(len(cat.resources), np.float32)
            cum[cat.resources.index("cpu")] = cum_cpu
            return NodeView(claim=NodeClaim(name=name, nodepool="default"),
                            node=None, pods=[],
                            virtual=VirtualNode(type_idx=t, zone_mask=zmask,
                                                cap_mask=np.ones(cat.C, bool),
                                                cum=cum, existing_name=name),
                            price=0.1)

        counts = np.zeros((2, enc.G), np.int32)
        counts[0, 0] = 3
        cand = view("cand", "zone-b", 3.6)
        screen_b, _ = consolidation_screen(
            cat, enc, [cand, view("tgt-b", "zone-b", 0.0)], counts)
        assert screen_b[0], "empty zone-b twin has 3.92 cpu free — fits"
        screen_a, _ = consolidation_screen(
            cat, enc, [cand, view("tgt-a", "zone-a", 0.0)], counts)
        assert not screen_a[0], (
            "zone-a twin has only 2.92 cpu after the zonal daemonset — "
            "3 x 1.2 cpu cannot fit")


class TestEndToEnd:
    def test_density_drops_under_daemonset_overhead(self):
        """The same workload needs MORE nodes once a fat daemonset
        reserves per-node capacity — and never overcommits: real pod
        usage + overhead fits every node's allocatable."""
        from karpenter_tpu.models.requirements import (Operator,
                                                       Requirement,
                                                       Requirements)
        # pin the type so density is deterministic (the solver would
        # otherwise absorb the overhead by sizing up)
        pin = Requirements(Requirement(L.INSTANCE_TYPE, Operator.IN,
                                       ("m5.xlarge",)))
        base = make_sim(nodepool=NodePool(name="default",
                                          requirements=pin.copy()))
        small_pods(base, 24)
        assert base.engine.run_until(
            lambda: all(p.node_name for p in base.store.pods.values()),
            timeout=120)
        n_without = len(base.store.nodes)

        sim = make_sim(nodepool=NodePool(name="default",
                                         requirements=pin.copy()))
        ds = DaemonSet(name="fat-agent",
                       requests=Resources.parse({"cpu": "2",
                                                 "memory": "2Gi"}))
        sim.store.add_daemonset(ds)
        small_pods(sim, 24)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in sim.store.pods.values()),
            timeout=120)
        assert len(sim.store.nodes) > n_without
        # no node overcommitted once overhead is charged
        for claim in sim.store.nodeclaims.values():
            if not claim.node_name:
                continue
            used = Resources()
            for p in sim.store.pods_on_node(claim.node_name):
                used = used.add(p.requests)
            used = used.add(ds.requests)
            assert used.fits(claim.allocatable), (
                f"{claim.name} overcommitted: {used} vs {claim.allocatable}")

    def test_consolidation_respects_overhead(self):
        """The consolidation re-solve must also charge daemonset
        overhead — replacements sized without it would overcommit."""
        sim = make_sim()
        ds = DaemonSet(name="agent",
                       requests=Resources.parse({"cpu": "2",
                                                 "memory": "2Gi"}))
        sim.store.add_daemonset(ds)
        pods = small_pods(sim, 12)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=120)
        # free up half the load; consolidation repacks
        for p in pods[6:]:
            sim.store.delete_pod(p.namespace, p.name)
        sim.engine.run_for(900, step=10)
        for claim in sim.store.nodeclaims.values():
            if claim.is_deleting() or not claim.node_name:
                continue
            used = Resources()
            for p in sim.store.pods_on_node(claim.node_name):
                used = used.add(p.requests)
            used = used.add(ds.requests)
            assert used.fits(claim.allocatable)
