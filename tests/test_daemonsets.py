"""DaemonSet overhead: per-node resources reserved before workload
placement (reference core: the scheduler adds daemonset pods to every
virtual node in the simulation; the scale suite's GetDaemonSetCount
adjusts density expectations accordingly)."""

import numpy as np

from karpenter_tpu.catalog import small_catalog
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import DaemonSet, Pod, Taint, Toleration
from karpenter_tpu.models.resources import NVIDIA_GPU, PODS, Resources
from karpenter_tpu.ops.encode import encode_catalog
from karpenter_tpu.ops.facade import daemonset_overhead
from karpenter_tpu.sim import make_sim


def small_pods(sim, n, cpu="900m"):
    pods = [Pod(name=f"p{i}",
                requests=Resources.parse({"cpu": cpu, "memory": "512Mi"}))
            for i in range(n)]
    for p in pods:
        sim.store.add_pod(p)
    return pods


class TestOverheadMatrix:
    def setup_method(self):
        self.cat = encode_catalog(small_catalog(8))
        self.pool = NodePool(name="default")

    def test_plain_daemonset_reserves_on_every_type(self):
        ovh = daemonset_overhead(
            self.cat, [DaemonSet(name="logging",
                                 requests=Resources.parse({"cpu": "500m"}))],
            self.pool, self.pool.template_labels())
        assert ovh is not None and (ovh > 0).any()
        cpu_col = self.cat.resources.index("cpu")
        pods_col = self.cat.resources.index(PODS)
        assert np.allclose(ovh[:, cpu_col], 0.5)
        assert np.allclose(ovh[:, pods_col], 1.0)  # one pod slot each

    def test_gpu_selector_daemonset_reserves_only_on_gpu_types(self):
        ovh = daemonset_overhead(
            self.cat, [DaemonSet(
                name="gpu-agent",
                requests=Resources.parse({"cpu": "1"}),
                node_selector={L.INSTANCE_GPU_MANUFACTURER: "nvidia"})],
            self.pool, self.pool.template_labels())
        assert ovh is not None
        gpu_types = self.cat.allocatable[
            :, self.cat.resources.index(NVIDIA_GPU)] > 0
        cpu_col = self.cat.resources.index("cpu")
        assert (ovh[gpu_types, cpu_col] == 1.0).all()
        assert (ovh[~gpu_types, cpu_col] == 0.0).all()

    def test_intolerant_daemonset_skipped_on_tainted_pool(self):
        pool = NodePool(name="tainted", taints=[
            Taint(key="team", value="x", effect="NoSchedule")])
        ds = DaemonSet(name="plain",
                       requests=Resources.parse({"cpu": "1"}))
        assert daemonset_overhead(self.cat, [ds], pool,
                                  pool.template_labels()) is None
        tol = DaemonSet(name="tolerant",
                        requests=Resources.parse({"cpu": "1"}),
                        tolerations=[Toleration(key="team", value="x",
                                                effect="NoSchedule")])
        assert daemonset_overhead(self.cat, [tol], pool,
                                  pool.template_labels()) is not None


class TestEndToEnd:
    def test_density_drops_under_daemonset_overhead(self):
        """The same workload needs MORE nodes once a fat daemonset
        reserves per-node capacity — and never overcommits: real pod
        usage + overhead fits every node's allocatable."""
        from karpenter_tpu.models.requirements import (Operator,
                                                       Requirement,
                                                       Requirements)
        # pin the type so density is deterministic (the solver would
        # otherwise absorb the overhead by sizing up)
        pin = Requirements(Requirement(L.INSTANCE_TYPE, Operator.IN,
                                       ("m5.xlarge",)))
        base = make_sim(nodepool=NodePool(name="default",
                                          requirements=pin.copy()))
        small_pods(base, 24)
        assert base.engine.run_until(
            lambda: all(p.node_name for p in base.store.pods.values()),
            timeout=120)
        n_without = len(base.store.nodes)

        sim = make_sim(nodepool=NodePool(name="default",
                                         requirements=pin.copy()))
        ds = DaemonSet(name="fat-agent",
                       requests=Resources.parse({"cpu": "2",
                                                 "memory": "2Gi"}))
        sim.store.add_daemonset(ds)
        small_pods(sim, 24)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in sim.store.pods.values()),
            timeout=120)
        assert len(sim.store.nodes) > n_without
        # no node overcommitted once overhead is charged
        for claim in sim.store.nodeclaims.values():
            if not claim.node_name:
                continue
            used = Resources()
            for p in sim.store.pods_on_node(claim.node_name):
                used = used.add(p.requests)
            used = used.add(ds.requests)
            assert used.fits(claim.allocatable), (
                f"{claim.name} overcommitted: {used} vs {claim.allocatable}")

    def test_consolidation_respects_overhead(self):
        """The consolidation re-solve must also charge daemonset
        overhead — replacements sized without it would overcommit."""
        sim = make_sim()
        ds = DaemonSet(name="agent",
                       requests=Resources.parse({"cpu": "2",
                                                 "memory": "2Gi"}))
        sim.store.add_daemonset(ds)
        pods = small_pods(sim, 12)
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in pods), timeout=120)
        # free up half the load; consolidation repacks
        for p in pods[6:]:
            sim.store.delete_pod(p.namespace, p.name)
        sim.engine.run_for(900, step=10)
        for claim in sim.store.nodeclaims.values():
            if claim.is_deleting() or not claim.node_name:
                continue
            used = Resources()
            for p in sim.store.pods_on_node(claim.node_name):
                used = used.add(p.requests)
            used = used.add(ds.requests)
            assert used.fits(claim.allocatable)
