"""Delta plane (ops/delta.py): serve-and-verify memos for the
steady-state reconcile — the protocol (serve/store/confirm/diverge),
the audit cadence, the never-wrong-twice cooldown, the invalidation
ladder, and the byte-parity contract: a delta-served pipeline must
produce EXACTLY the output a forced-cold recompute produces, across
seeds, churn, and audit cadences.

The INVALIDATION_CASES table is the canonical test coverage of the
invalidation-reason ladder — `make obs-audit` requires every
ops/delta.INVALIDATION_REASONS name to appear in this file as a string
constant constructed by a test, so a new rung without a test here
fails the audit (the same contract as the recompute taxonomy)."""

import random

import pytest

from karpenter_tpu.catalog import CatalogProvider
from karpenter_tpu.catalog.generator import small_catalog
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import (Pod, PodAffinityTerm,
                                      TopologySpreadConstraint)
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.ops.delta import (DELTA, DOMAINS, INVALIDATION_REASONS,
                                     DeltaPlane)
from karpenter_tpu.ops.facade import Solver

POOL = NodePool(name="default")


@pytest.fixture(autouse=True)
def _fresh_plane():
    """The plane is process-global: isolate every test's memo set."""
    DELTA.reset()
    yield
    DELTA.reset()


# --- the serve/verify protocol ---------------------------------------------


class TestProtocol:
    def test_miss_store_serve_roundtrip(self):
        p = DeltaPlane()
        assert p.serve("solve", ("k",), 1) is None          # cold miss
        assert p.store("solve", ("k",), 1, "payload", check_fp=9)
        val, audit = p.serve("solve", ("k",), 1)
        assert val == "payload" and audit is False
        assert p.stats["serves"] == 1 and p.stats["misses"] == 1

    def test_changed_fingerprint_is_a_miss(self):
        p = DeltaPlane()
        p.store("spread", ("k",), 1, "old")
        assert p.serve("spread", ("k",), 2) is None
        # re-store under the new fingerprint: the world moved on
        p.store("spread", ("k",), 2, "new")
        assert p.serve("spread", ("k",), 2)[0] == "new"

    def test_audit_cadence_refuses_the_nth_serve(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_DELTA_AUDIT", "3")
        p = DeltaPlane()
        p.store("affinity", ("k",), 5, "desc", check_fp=7)
        for _ in range(3):
            val, audit = p.serve("affinity", ("k",), 5)
            assert audit is False
        val, audit = p.serve("affinity", ("k",), 5)
        assert audit is True and val == "desc"   # recompute, don't use
        # confirm resets the counter: serving resumes
        p.confirm("affinity", ("k",), 5, value="desc2", check_fp=7)
        val, audit = p.serve("affinity", ("k",), 5)
        assert audit is False and val == "desc2"
        assert p.stats["audits_due"] == 1 and p.stats["confirms"] == 1

    def test_audit_zero_never_serves(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_DELTA_AUDIT", "0")
        p = DeltaPlane()
        p.store("solve", ("k",), 1, "v")
        val, audit = p.serve("solve", ("k",), 1)
        assert audit is True                      # every pass recomputes
        assert p.stats["serves"] == 0

    def test_disarmed_plane_neither_serves_nor_stores(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "0")
        p = DeltaPlane()
        assert not p.store("solve", ("k",), 1, "v")
        assert p.serve("solve", ("k",), 1) is None
        assert p.entries() == 0

    def test_stale_reports_audit_due_entries(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_DELTA_AUDIT", "2")
        p = DeltaPlane()
        p.store("optimizer", ("pool-a",), 1, True)
        p.serve("optimizer", ("pool-a",), 1)
        assert p.stale() == []
        p.serve("optimizer", ("pool-a",), 1)
        assert p.stale() == [("optimizer", ("pool-a",), 2)]
        p.confirm("optimizer", ("pool-a",), 1)
        assert p.stale() == []

    def test_snapshot_and_debug_route(self):
        import json

        from karpenter_tpu.obs.exposition import render
        p = DeltaPlane()
        p.store("solve", ("k",), 1, "v")
        snap = p.snapshot()
        assert snap["entries"] == 1 and snap["per_stage"] == {"solve": 1}
        assert snap["domains"] == list(DOMAINS)
        assert snap["reasons"] == list(INVALIDATION_REASONS)
        status, ctype, body = render("/debug/delta")
        assert status == 200 and "json" in ctype
        doc = json.loads(body)
        assert doc["armed"] is True
        assert doc["domains"] == list(DOMAINS)


# --- the invalidation ladder ------------------------------------------------
# canonical coverage table: obs-audit asserts this file constructs every
# INVALIDATION_REASONS rung
INVALIDATION_CASES = [
    "divergence", "epoch", "quarantine", "capacity", "disarm",
]


class TestInvalidationLadder:
    def test_table_covers_ladder_exactly(self):
        assert INVALIDATION_CASES == list(INVALIDATION_REASONS)

    def test_divergence_drops_and_arms_never_wrong_twice(self):
        from karpenter_tpu.ops.delta import COOLDOWN
        p = DeltaPlane()
        p.store("solve", ("k",), 1, "wrong")
        p.diverge("solve", ("k",))
        assert p.serve("solve", ("k",), 1) is None
        assert p.snapshot()["invalidations"]["solve"]["divergence"] == 1
        # the cooldown declines the next COOLDOWN stores for this key
        for i in range(COOLDOWN):
            assert not p.store("solve", ("k",), 1, f"retry-{i}")
        assert p.stats["declined"] == COOLDOWN
        assert p.store("solve", ("k",), 1, "after-cooldown")
        assert p.serve("solve", ("k",), 1)[0] == "after-cooldown"

    def test_epoch_metered_on_restore_under_new_fingerprint(self):
        p = DeltaPlane()
        p.store("affinity", ("k",), 1, "old")
        p.store("affinity", ("k",), 2, "new")   # world moved: epoch
        assert p.snapshot()["invalidations"]["affinity"]["epoch"] == 1

    def test_quarantine_prefix_invalidation_is_scoped(self):
        p = DeltaPlane()
        p.store("solve", ("facade", 1, "np-a"), 1, "a")
        p.store("solve", ("facade", 2, "np-b"), 1, "b")
        n = p.invalidate(("solve", "facade", 1), reason="quarantine")
        assert n == 1
        assert p.serve("solve", ("facade", 1, "np-a"), 1) is None
        assert p.serve("solve", ("facade", 2, "np-b"), 1)[0] == "b"
        assert p.snapshot()["invalidations"]["solve"]["quarantine"] == 1

    def test_capacity_lru_eviction(self):
        p = DeltaPlane(max_entries=2)
        p.store("solve", ("a",), 1, "a")
        p.store("spread", ("b",), 1, "b")
        p.serve("solve", ("a",), 1)             # touch: a is now MRU
        p.store("optimizer", ("c",), 1, "c")    # evicts b (LRU)
        assert p.serve("spread", ("b",), 1) is None
        assert p.serve("solve", ("a",), 1)[0] == "a"
        assert p.snapshot()["invalidations"]["spread"]["capacity"] == 1

    def test_disarm_invalidates_the_whole_plane(self):
        from karpenter_tpu.metrics import DELTA_INVALIDATIONS
        p = DeltaPlane()
        for st in DOMAINS:
            p.store(st, ("k",), 1, st)
        v0 = DELTA_INVALIDATIONS.value(stage="solve", reason="disarm")
        assert p.invalidate((), reason="disarm") == len(DOMAINS)
        assert p.entries() == 0
        assert DELTA_INVALIDATIONS.value(stage="solve",
                                         reason="disarm") == v0 + 1

    def test_unknown_reason_is_rejected(self):
        p = DeltaPlane()
        with pytest.raises(AssertionError):
            p.invalidate((), reason="because")


# --- facade byte-parity fuzz ------------------------------------------------


_CPUS = ["100m", "250m", "500m", "1"]
_MEMS = ["128Mi", "512Mi", "1Gi"]


def _mk_pods(n, manifests, gen, spread, anti):
    """Content is a function of (n, manifests, spread, anti) only —
    `gen` moves pod NAMES, modeling same-shape churn."""
    pods = []
    for i in range(n):
        s = i % manifests
        kw = dict(requests=Resources.parse(
            {"cpu": _CPUS[s % len(_CPUS)], "memory": _MEMS[s % len(_MEMS)]}),
            labels={"app": f"m{s}"})
        if spread and s % 3 == 0:
            kw["topology_spread"] = [TopologySpreadConstraint(
                topology_key=L.ZONE, max_skew=1)]
        if anti and s % 4 == 1:
            kw["affinity_terms"] = [PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                label_selector={"app": f"m{s}"}, anti=True)]
        pods.append(Pod(name=f"dp-{gen}-{i}", **kw))
    return pods


def _digest(out):
    """Canonical, order-free content digest of a SolveOutput."""
    return (
        tuple(sorted(
            (l.instance_type, l.zone, l.capacity_type, round(l.price, 6),
             tuple(sorted(l.pod_keys)),
             tuple((o[0], o[1], o[2], round(o[3], 6))
                   for o in l.overrides))
            for l in out.launches)),
        tuple(sorted((k, tuple(sorted(v)))
                     for k, v in out.existing_placements.items())),
        tuple(sorted(out.unschedulable)),
    )


def _drive_rounds(seed):
    """One seeded mutation schedule: blocks of same-content rounds
    (churned names — the delta-served steady state) separated by
    content changes (the epoch boundaries). Returns the digest list."""
    rng = random.Random(seed)
    types = small_catalog()
    f = Solver(CatalogProvider(lambda: types), backend="auto")
    digests = []
    gen = 0
    for _block in range(3):
        n = rng.randint(6, 14)
        manifests = rng.randint(2, 4)
        spread = rng.random() < 0.7
        anti = rng.random() < 0.7
        for _rep in range(3):
            gen += 1
            out = f.solve(_mk_pods(n, manifests, gen, spread, anti), POOL)
            digests.append(_digest(out))
    return digests


class TestFacadeByteParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_served_equals_forced_cold(self, seed, monkeypatch):
        """The acceptance contract: with the memos armed, every solve's
        output is byte-identical to the forced-cold (disarmed) run of
        the SAME seeded schedule — and the armed run actually served
        (this test fails loudly if the serve path stops engaging)."""
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "0")
        DELTA.reset()
        cold = _drive_rounds(seed)
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "1")
        DELTA.reset()
        warm = _drive_rounds(seed)
        assert warm == cold
        assert DELTA.stats["serves"] >= 2, DELTA.snapshot()
        assert DELTA.stats["divergences"] == 0, DELTA.snapshot()

    @pytest.mark.parametrize("seed", [0, 3])
    def test_audit_every_pass_still_byte_identical(self, seed,
                                                   monkeypatch):
        """KARPENTER_TPU_DELTA_AUDIT=1 audits every other serve: the
        fresh recompute must CONFIRM the stored output every time (a
        divergence here means the memo replayed the world wrong)."""
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "0")
        DELTA.reset()
        cold = _drive_rounds(seed)
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "1")
        monkeypatch.setenv("KARPENTER_TPU_DELTA_AUDIT", "1")
        DELTA.reset()
        audited = _drive_rounds(seed)
        assert audited == cold
        assert DELTA.stats["confirms"] >= 1, DELTA.snapshot()
        assert DELTA.stats["divergences"] == 0, DELTA.snapshot()


# --- controller parity: optimizer + the full reconcile ----------------------


def _drive_sim(seed, rounds=3, quiet=3):
    """A miniature c16 regime: standing anti-affinity fleet + churnable
    residents, settled, then churned reconciles and quiet disruption
    passes. Returns the end-of-run cluster-state hash."""
    from karpenter_tpu.cloud.fake import FakeCloudConfig
    from karpenter_tpu.faults.runner import state_hash
    from karpenter_tpu.sim import make_sim
    rng = random.Random(seed)
    sim = make_sim(cloud_config=FakeCloudConfig(
        node_ready_delay=1.0, register_delay=0.5,
        create_fleet_rate=1e6, create_fleet_burst=10**6))
    for i in range(8):
        sim.store.add_pod(Pod(
            name=f"standing-{i}", labels={"app": "standing"},
            requests=Resources.parse({"cpu": "500m", "memory": "512Mi"}),
            affinity_terms=[PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                label_selector={"app": "standing"}, anti=True)]))
    n = 40
    live = _mk_pods(n, 4, 0, True, False)
    for p in live:
        sim.store.add_pod(p)
    assert sim.engine.run_until(
        lambda: all(p.node_name for p in sim.store.pods.values()),
        timeout=600.0, step=1.0)
    churn = max(2, n // 10)
    for rnd in range(1, rounds + 1):
        k = rng.randint(1, churn)
        for p in live[:k]:
            sim.store.delete_pod(p.namespace, p.name)
        fresh = _mk_pods(k, 4, rnd, True, False)
        for p in fresh:
            sim.store.add_pod(p)
        live = live[k:] + fresh
        sim.provisioner.reconcile(sim.clock.now())
        sim.disruption.reconcile(sim.clock.now())
    for _ in range(quiet):
        sim.disruption.reconcile(sim.clock.now())
    return state_hash(sim)


class TestControllerParity:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_full_reconcile_state_hash_parity(self, seed, monkeypatch):
        """Armed vs disarmed through the REAL controllers (provisioner,
        disruption incl. the optimizer's fruitless-search memo): the
        end-of-run cluster-state hash must match, and the armed run
        must have served."""
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "0")
        DELTA.reset()
        cold = _drive_sim(seed)
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "1")
        DELTA.reset()
        warm = _drive_sim(seed)
        assert warm == cold
        assert DELTA.stats["serves"] >= 1, DELTA.snapshot()
        assert DELTA.stats["divergences"] == 0, DELTA.snapshot()
        # the solve memo must engage on existing-node full reconciles —
        # the bulk of the measured c16 headroom
        assert DELTA.snapshot()["per_stage"].get("solve", 0) >= 1


# --- chaos digest parity (memo armed) ---------------------------------------


class TestChaosDigestParity:
    def test_smoke_repeat_digest_equality_with_memo_armed(self):
        """The chaos acceptance: `smoke` twice with the memos armed
        (the second run's plane still holds the first run's entries —
        facade-id key namespacing must keep them from cross-serving)
        plus once forced-cold, all three end-state digests identical."""
        from karpenter_tpu.faults import ScenarioRunner
        a = ScenarioRunner("smoke", seed=3).run()
        b = ScenarioRunner("smoke", seed=3).run()
        assert a.ok and b.ok
        assert a.end_hash == b.end_hash
        import os
        os.environ["KARPENTER_TPU_DELTA"] = "0"
        try:
            c = ScenarioRunner("smoke", seed=3).run()
        finally:
            os.environ.pop("KARPENTER_TPU_DELTA", None)
        assert c.ok
        assert c.end_hash == a.end_hash

    def test_fleet_smoke_repeat_digest_equality_with_memo_armed(self):
        """Same contract for the fleet pump (bucketed batched dispatch
        + the stable batch-composition residency): repeat runs and the
        forced-cold run share one fleet hash."""
        from karpenter_tpu.fleet import FleetRunner
        a = FleetRunner("fleet_smoke", tenants=4, seed=0).run()
        b = FleetRunner("fleet_smoke", tenants=4, seed=0).run()
        assert a.ok and b.ok
        assert a.fleet_hash == b.fleet_hash
        assert a.tenant_hashes == b.tenant_hashes
        import os
        os.environ["KARPENTER_TPU_DELTA"] = "0"
        try:
            c = FleetRunner("fleet_smoke", tenants=4, seed=0).run()
        finally:
            os.environ.pop("KARPENTER_TPU_DELTA", None)
        assert c.ok
        assert c.fleet_hash == a.fleet_hash


# --- the stable batch-composition contract ----------------------------------


class TestBucketResidency:
    def test_membership_must_repeat_before_residency(self):
        """fleet/service._bucket_resident_key: first sight of a bucket
        composition is donated (None), an IDENTICAL next-pump
        composition gets the resident key, any membership change drops
        back to donation for one pump."""
        import types as _t

        from karpenter_tpu.fleet.service import SolverService
        from karpenter_tpu.utils.clock import FakeClock
        svc = SolverService(FakeClock())

        def entry(tenant, mk):
            return {"ticket": _t.SimpleNamespace(tenant=tenant),
                    "batchable": _t.SimpleNamespace(
                        signature=("sig", 8), meter_key=mk,
                        shape_class="small")}

        e1 = [entry("a", 1), entry("b", 2)]
        assert svc._bucket_resident_key(e1) is None          # first sight
        key = svc._bucket_resident_key([entry("a", 1), entry("b", 2)])
        assert key is not None and key[0] == "fleet"
        again = svc._bucket_resident_key([entry("a", 1), entry("b", 2)])
        assert again == key                                  # stable
        # membership changed: donate this pump, resident next pump
        assert svc._bucket_resident_key([entry("a", 1)]) is None
        assert svc._bucket_resident_key([entry("a", 1)]) is not None
