"""The device telemetry plane (obs/devicemem.py): residency ledger,
transfer attribution, and the upload-redundancy meter.

Canonical coverage file for `make obs-audit`'s residency-taxonomy check:
every owner kind in `OWNER_KINDS` — catalog, solve_upload, batch_gbuf,
packed_result, mesh_shard — is exercised here, and the batched-pump
transfer contracts (one upload + one readback per BUCKET, byte-identical
totals batch on/off, fault-fallback re-runs metered under the degraded
tenant's scope) live here too.
"""

from __future__ import annotations

import gc
import json

import jax.numpy as jnp
import numpy as np
import pytest

from karpenter_tpu.catalog import CatalogProvider
from karpenter_tpu.catalog.generator import small_catalog
from karpenter_tpu.fleet.service import SolverService
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.obs import devicemem as dm
from karpenter_tpu.obs.devicemem import (OWNER_KINDS, TRANSFER_REASONS,
                                         ResidencyLedger, TransferLedger,
                                         UploadMeter)
from karpenter_tpu.ops.encode import encode_catalog, encode_pods
from karpenter_tpu.utils.clock import FakeClock

POOL = NodePool(name="default")


def mk_pods(n, prefix="p", cpu="500m", mem="1Gi"):
    return [Pod(name=f"{prefix}-{i}",
                requests=Resources.parse({"cpu": cpu, "memory": mem}))
            for i in range(n)]


class _Owner:
    pass


class TestResidencyLedger:
    def test_track_and_auto_release(self):
        led = ResidencyLedger()
        arr = jnp.zeros(1024, jnp.float32)
        led.track("solve_upload", [arr])
        assert led.live_bytes == 4096
        assert led.kind_bytes["solve_upload"] == 4096
        assert led.watermark_bytes == 4096
        del arr
        gc.collect()
        led._drain()
        assert led.live_bytes == 0
        assert led.kind_bytes["solve_upload"] == 0
        # the watermark remembers the peak after the release
        assert led.watermark_bytes == 4096

    def test_same_array_never_double_counted(self):
        led = ResidencyLedger()
        arr = jnp.zeros(16, jnp.float32)
        led.track("solve_upload", [arr])
        led.track("batch_gbuf", [arr])  # jnp.asarray identity reuse
        assert led.live_bytes == 64

    def test_orphans_require_dead_owner_and_live_bytes(self):
        led = ResidencyLedger()
        owner = _Owner()
        arr = jnp.zeros(32, jnp.float32)
        gid = led.track("catalog", [arr], owner=owner, token=("t", "x"))
        assert led.orphans() == []          # owner alive: healthy
        del owner
        orphans = led.orphans()
        assert len(orphans) == 1
        assert orphans[0]["group"] == gid
        assert orphans[0]["kind"] == "catalog"
        assert orphans[0]["bytes"] == 128
        assert orphans[0]["token"] == "t/x"
        del arr
        gc.collect()
        assert led.orphans() == []          # bytes freed: resolved

    def test_audit_meters_unaccounted_bytes(self):
        led = ResidencyLedger()
        tracked = jnp.zeros(64, jnp.float32)
        foreign = jnp.ones(64, jnp.float32)
        led.track("packed_result", [tracked])
        audit = led.audit(live_arrays=[tracked, foreign])
        assert audit["accounted_bytes"] == 256
        assert audit["unaccounted_bytes"] == 256
        assert audit["coverage"] == 0.5
        audit = led.audit(live_arrays=[tracked])
        assert audit["coverage"] == 1.0

    def test_audit_gap_flight_records(self):
        from karpenter_tpu.obs.tracer import TRACER, FlightRecorder
        saved = TRACER.recorder
        try:
            TRACER.recorder = FlightRecorder(8)
            led = ResidencyLedger()
            foreign = jnp.zeros(1024, jnp.float32)
            led.audit(live_arrays=[foreign])
            names = [t.root.name for t in TRACER.recorder.slowest()]
            assert "devicemem.unattributed" in names
        finally:
            TRACER.recorder = saved

    def test_mesh_shard_kind_tracks(self):
        # the mesh path's _put_sharded registers under "mesh_shard";
        # CPU rigs have no mesh, so the kind is exercised directly
        led = ResidencyLedger()
        arr = jnp.zeros(8, jnp.float32)
        led.track("mesh_shard", [arr])
        assert led.kind_bytes["mesh_shard"] == 32

    def test_owner_kinds_frozen(self):
        assert OWNER_KINDS == ("catalog", "solve_upload", "batch_gbuf",
                               "packed_result", "mesh_shard",
                               "resident_state")
        assert TRANSFER_REASONS == ("catalog_put", "request_upload",
                                    "batch_upload", "screen_upload",
                                    "readback", "resident_patch")


class TestTransferAttribution:
    def test_rows_key_on_reason_tenant_shape_class(self):
        from karpenter_tpu.metrics.tenant import tenant_scope
        led = TransferLedger()
        led.record("request_upload", 100, shape_class="g8/n64")
        with tenant_scope("t7"):
            led.record("readback", 40, shape_class="g8/n64")
        snap = led.snapshot()
        assert snap["h2d_bytes"] == 100 and snap["d2h_bytes"] == 40
        rows = {(r["reason"], r["tenant"], r["shape_class"]):
                (r["bytes"], r["calls"]) for r in snap["rows"]}
        assert rows[("request_upload", "default", "g8/n64")] == (100, 1)
        assert rows[("readback", "t7", "g8/n64")] == (40, 1)

    def test_solver_wrappers_thread_through_the_ledger(self):
        """A real solve attributes catalog_put/request_upload/readback
        rows, and transfer_bytes() equals the ledger totals (the global
        byte counters are REPLACED by, not parallel to, the plane)."""
        from karpenter_tpu.ops import solver as S
        cat = encode_catalog(small_catalog())
        enc = encode_pods(mk_pods(8), cat)
        rows0 = {(r["reason"],): r["bytes"]
                 for r in dm.TRANSFERS.snapshot()["rows"]}
        h0, d0 = S.transfer_bytes()
        S.solve_device(cat, enc)
        h1, d1 = S.transfer_bytes()
        assert h1 > h0 and d1 > d0
        assert (h1, d1) == dm.TRANSFERS.totals()
        snap = dm.TRANSFERS.snapshot()
        reasons = {r["reason"] for r in snap["rows"]}
        assert {"catalog_put", "request_upload", "readback"} <= reasons
        # the readback row carries the padded shape class
        assert any(r["reason"] == "readback"
                   and r["shape_class"].startswith("g")
                   for r in snap["rows"])
        del rows0

    def test_transfer_metric_family_observes(self):
        from karpenter_tpu.metrics import DEVICEMEM_TRANSFER
        before = DEVICEMEM_TRANSFER.value(reason="request_upload")
        dm.TRANSFERS.record("request_upload", 77)
        assert DEVICEMEM_TRANSFER.value(
            reason="request_upload") == before + 77


class TestUploadMeter:
    def test_identical_reupload_reads_fully_redundant(self):
        m = UploadMeter()
        mat = np.arange(64, dtype=np.float32).reshape(8, 8)
        assert m.observe(("k",), mat) == 0.0       # first sight
        assert m.observe(("k",), mat.copy()) == 1.0
        ident, total = m.totals()
        assert ident == mat.nbytes and total == 2 * mat.nbytes

    def test_changed_rows_reduce_the_fraction(self):
        m = UploadMeter()
        mat = np.zeros((8, 8), np.float32)
        m.observe(("k",), mat)
        mat2 = mat.copy()
        mat2[3] = 9.0   # one of eight rows changed
        assert m.observe(("k",), mat2) == pytest.approx(7 / 8)

    def test_keys_isolate_histories(self):
        m = UploadMeter()
        a = np.zeros((4, 4), np.float32)
        b = np.ones((4, 4), np.float32)
        m.observe(("a",), a)
        # b's first upload must not hash against a's history
        assert m.observe(("b",), b) == 0.0
        assert m.observe(("a",), a) == 1.0

    def test_key_lru_bounded(self):
        m = UploadMeter()
        mat = np.zeros((2, 2), np.float32)
        for i in range(dm._METER_MAX_KEYS + 10):
            m.observe((i,), mat)
        assert m.snapshot()["keys"] == dm._METER_MAX_KEYS

    def test_warm_resolve_meters_redundancy(self):
        """Re-solving the same encoded problem re-uploads a byte-
        identical request matrix — the measured ROADMAP-item-3 target."""
        from karpenter_tpu.ops import solver as S
        cat = encode_catalog(small_catalog())
        enc = encode_pods(mk_pods(12), cat)
        S.solve_device(cat, enc)  # seed this view's row hashes
        i0, t0 = dm.UPLOADS.totals()
        S.solve_device(cat, enc)
        i1, t1 = dm.UPLOADS.totals()
        assert t1 > t0
        assert (i1 - i0) == (t1 - t0)  # warm re-upload: 100% redundant


class TestDcatEvictions:
    def test_shared_view_eviction_releases_device_residency(self):
        """A SharedCatalogCache view rolling out of its LRU must drop
        its token-keyed device-catalog entries immediately — a dead
        view cannot pin device buffers until the FIFO bound trims it."""
        from karpenter_tpu.metrics import DCAT_EVICTIONS
        from karpenter_tpu.ops import solver as S
        from karpenter_tpu.ops.facade import SharedCatalogCache
        cache = SharedCatalogCache()
        types = small_catalog()
        cat = cache.get_or_encode("nc0", types)
        tok = tuple(cat.cache_token)
        S._auto_dcat(cat, cat.allocatable.shape[1])
        key = (tok, None)
        assert key in S._dcat_auto
        before = DCAT_EVICTIONS.value(reason="view_evicted")
        # push MAX_ENTRIES distinct views through: nc0 evicts
        for i in range(cache.MAX_ENTRIES):
            cache.get_or_encode(f"nc{i + 1}", types)
        assert key not in S._dcat_auto
        assert DCAT_EVICTIONS.value(reason="view_evicted") > before

    def test_fifo_bound_meters_evictions(self):
        from karpenter_tpu.metrics import DCAT_EVICTIONS
        from karpenter_tpu.ops import solver as S
        types = small_catalog()
        before = DCAT_EVICTIONS.value(reason="fifo")
        cats = []
        for i in range(S._DCAT_TOKEN_MAX + 4):
            cat = encode_catalog(types)
            cat.cache_token = ("shared", f"fifo-test-{i}", "fp")
            cats.append(cat)
            S._auto_dcat(cat, cat.allocatable.shape[1])
        assert DCAT_EVICTIONS.value(reason="fifo") >= before + 4
        tkeys = [k for k in S._dcat_auto if isinstance(k[0], tuple)]
        assert len(tkeys) <= S._DCAT_TOKEN_MAX

    def test_weakref_eviction_metered_on_next_lookup(self):
        from karpenter_tpu.metrics import DCAT_EVICTIONS
        from karpenter_tpu.ops import solver as S
        types = small_catalog()
        cat = encode_catalog(types)   # no token -> id-keyed + weakref
        S._auto_dcat(cat, cat.allocatable.shape[1])
        del cat
        gc.collect()
        assert "weakref" in S._dcat_evict_pending or not \
            S._dcat_evict_pending  # finalizer may already have flushed
        before = DCAT_EVICTIONS.value(reason="weakref")
        cat2 = encode_catalog(types)
        S._auto_dcat(cat2, cat2.allocatable.shape[1])  # flushes pending
        assert DCAT_EVICTIONS.value(reason="weakref") >= before

    def test_stale_shape_rebuild_metered(self):
        from karpenter_tpu.metrics import DCAT_EVICTIONS
        from karpenter_tpu.ops import solver as S
        types = small_catalog()
        cat = encode_catalog(types)
        cat.cache_token = ("shared", "stale-test", "fp")
        R = cat.allocatable.shape[1]
        S._auto_dcat(cat, R)
        before = DCAT_EVICTIONS.value(reason="stale")
        S._auto_dcat(cat, R + 3)   # resource axis grew: entry unusable
        assert DCAT_EVICTIONS.value(reason="stale") == before + 1


class TestBatchedPumpTransfers:
    """ISSUE 10 satellite: transfer accounting under the batched pump.

    The delta plane is disarmed here: a repeated same-content solve
    would be served at the facade and never reach the pump, hiding the
    per-bucket transfer accounting these tests assert."""

    @pytest.fixture(autouse=True)
    def _no_delta(self, monkeypatch):
        from karpenter_tpu.ops.delta import DELTA
        monkeypatch.setenv("KARPENTER_TPU_DELTA", "0")
        DELTA.reset()
        yield

    def _catalog_devices(self):
        from karpenter_tpu.ops import solver as S
        return S

    def test_one_upload_one_readback_per_bucket(self):
        """N co-batched tickets cross the boundary ONCE each way — per
        BUCKET, not per ticket (the whole point of batching the RTT)."""
        from karpenter_tpu.ops import solver as S
        svc = SolverService(FakeClock(), backend="device", batch=True)
        types = small_catalog()
        clients = [svc.register(f"t{i}",
                                CatalogProvider(lambda: types))
                   for i in range(4)]
        # warm round: catalog upload + executable compile happen here
        warm = [c.solve_async(mk_pods(6, f"w{i}"), POOL)
                for i, c in enumerate(clients)]
        svc.pump()
        for t in warm:
            assert t.result().launches
        u0, r0 = S.transfer_stats()
        batches0 = svc.stats["batches"]
        tickets = [c.solve_async(mk_pods(6, f"x{i}"), POOL)
                   for i, c in enumerate(clients)]
        svc.pump()
        for t in tickets:
            assert t.result().launches
        u1, r1 = S.transfer_stats()
        buckets = svc.stats["batches"] - batches0
        assert buckets == 1
        # one gstack upload + one packed readback per BUCKET
        assert u1 - u0 == buckets
        assert r1 - r0 == buckets
        assert all(t.batch_size == 4 for t in tickets)

    def test_bytes_identical_batch_on_off(self, monkeypatch):
        """The same solves move the same bytes whether dispatched
        serially or as one ladder-sized batch — batching amortizes
        ROUND-TRIPS, it must not inflate volume. Residency is disarmed
        here: the contract compares the two DISPATCH engines at equal
        upload policy (with residency armed, the serial path ships
        strictly fewer bytes — the delta win tests/test_resident.py
        measures on its own)."""
        monkeypatch.setenv("KARPENTER_TPU_RESIDENT", "0")
        from karpenter_tpu.ops import solver as S
        types = small_catalog()

        def run(batch):
            svc = SolverService(FakeClock(), backend="device", batch=batch)
            clients = [svc.register(f"t{i}",
                                    CatalogProvider(lambda: types))
                       for i in range(2)]
            warm = [c.solve_async(mk_pods(5, f"w{i}"), POOL)
                    for i, c in enumerate(clients)]
            svc.pump()
            [t.result() for t in warm]
            h0, d0 = S.transfer_bytes()
            tickets = [c.solve_async(mk_pods(5, f"x{i}"), POOL)
                       for i, c in enumerate(clients)]
            svc.pump()
            for t in tickets:
                assert t.result().launches
            h1, d1 = S.transfer_bytes()
            return h1 - h0, d1 - d0

        batched = run(True)    # B=2: in the padding ladder, no waste
        serial = run(False)
        assert batched == serial

    def test_fault_fallback_metered_under_degraded_tenant_scope(self):
        """A mid-batch device fault degrades exactly the faulted batch;
        the degraded tenant's re-run transfers (and fallback meters)
        land under ITS tenant scope, the co-batched neighbor keeps the
        device path and its own attribution."""
        from karpenter_tpu.faults.injector import fleet_device_fault_hook
        from karpenter_tpu.faults.plan import DeviceFault, FaultPlan
        from karpenter_tpu.metrics import SOLVER_FALLBACKS
        svc = SolverService(FakeClock(), backend="device", batch=True)
        types = small_catalog()
        a = svc.register("a", CatalogProvider(lambda: types))
        b = svc.register("b", CatalogProvider(lambda: types))
        warm = [a.solve_async(mk_pods(4, "wa"), POOL),
                b.solve_async(mk_pods(4, "wb"), POOL)]
        svc.pump()
        [t.result() for t in warm]
        fb0 = SOLVER_FALLBACKS.sum(from_backend="device", tenant="a")

        def rows_for(tenant, reason):
            return sum(r["bytes"] for r in dm.TRANSFERS.snapshot()["rows"]
                       if r["tenant"] == tenant and r["reason"] == reason)

        a_up0 = rows_for("a", "request_upload")
        b_up0 = rows_for("b", "request_upload")
        b_rd0 = rows_for("b", "readback")
        # dispatch 1 = the bucket probe under a's scope (aborts the
        # batch), dispatch 2 = a's serial re-run (degrades a to host)
        plan = FaultPlan(seed=0, rules=[DeviceFault(dispatch=1, count=2)])
        plan.clock = svc.clock
        with fleet_device_fault_hook({"a": plan}):
            ta = a.solve_async(mk_pods(4, "xa"), POOL)
            tb = b.solve_async(mk_pods(4, "xb"), POOL)
            svc.pump()
            assert ta.result().launches and tb.result().launches
        # a's degraded re-run metered under a's scope: its upload bytes
        # (shipped before the dispatch fault) attribute to tenant a,
        # and ITS facade recorded the fallback
        assert rows_for("a", "request_upload") > a_up0
        assert a.facade.stats["device_fallbacks"] == 1
        assert SOLVER_FALLBACKS.sum(from_backend="device",
                                    tenant="a") == fb0 + 1
        # the neighbor re-ran on the DEVICE under its own scope
        assert b.facade.stats["device_fallbacks"] == 0
        assert rows_for("b", "request_upload") > b_up0
        assert rows_for("b", "readback") > b_rd0

    def test_batch_residency_kinds_tracked(self):
        """A batched dispatch registers its stacked request matrix
        (batch_gbuf) and pending output (packed_result) in the
        residency ledger, owned by the in-flight batch."""
        from karpenter_tpu.ops import solver as S
        cat = encode_catalog(small_catalog())
        encs = [encode_pods(mk_pods(4, f"r{i}"), cat) for i in range(2)]
        reqs = [S.prepare_batchable(cat, e) for e in encs]
        assert all(r is not None for r in reqs)
        tracked0 = dm.DEVICEMEM.stats["tracked"]
        ifb = S.dispatch_batch(reqs)
        # the packed output is resident while the batch is in flight
        assert dm.DEVICEMEM.stats["tracked"] > tracked0
        with dm.DEVICEMEM._lock:
            kinds = {g["kind"] for g in dm.DEVICEMEM._groups.values()
                     if g["live"]}
        assert "packed_result" in kinds
        results = ifb.results()
        assert all(r.nodes for r in results)


class TestResidentStatePlane:
    """The device-resident state manager's face on the telemetry plane
    (ops/resident.py): the resident_state owner kind and the
    resident_patch transfer reason — obs-audit's taxonomy coverage."""

    def test_resident_state_kind_and_patch_reason(self):
        from karpenter_tpu.ops.resident import RESIDENT
        RESIDENT.reset()

        def patch_bytes():
            return sum(r["bytes"] for r in dm.TRANSFERS.snapshot()["rows"]
                       if r["reason"] == "resident_patch")

        try:
            mat = np.arange(32, dtype=np.float32).reshape(8, 4)
            RESIDENT.upload(("dm-kind",), mat, token=("t",))
            with dm.DEVICEMEM._lock:
                kinds = {g["kind"] for g in dm.DEVICEMEM._groups.values()
                         if g["live"]}
            # the resident buffer wears the resident_state owner kind
            assert "resident_state" in kinds
            # a delta patch attributes its traffic to resident_patch:
            # one changed row + the index vector, nothing else
            b0 = patch_bytes()
            mat2 = mat.copy()
            mat2[5] += 9.0
            RESIDENT.upload(("dm-kind",), mat2, token=("t",))
            assert patch_bytes() - b0 == 4 * 4 + 4
        finally:
            RESIDENT.reset()


class TestDebugRoute:
    def test_debug_device_serves_the_plane(self):
        from karpenter_tpu.obs.exposition import render
        status, ctype, body = render("/debug/device")
        assert status == 200 and "json" in ctype
        payload = json.loads(body)
        assert payload["owner_kinds"] == list(OWNER_KINDS)
        assert payload["reasons"] == list(TRANSFER_REASONS)
        assert "residency" in payload and "transfers" in payload
        assert "uploads" in payload and "orphans" in payload
        assert payload["residency"]["watermark_bytes"] >= 0


class TestDeviceReport:
    def test_device_report_runs_and_emits_json(self, capsys):
        import tools.device_report as dr
        rc = dr.main(["--pods", "64", "--rounds", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        line = [ln for ln in out.strip().splitlines()
                if ln.startswith("{")][-1]
        doc = json.loads(line)
        assert doc["rounds"] == 2
        # 64 pods at 1% churn rounds to zero churned pods: the single
        # warm round re-uploads a byte-identical matrix, and the cold
        # seeding round must NOT dilute the reported fraction
        assert doc["upload_redundant_frac"] >= 0.99
        assert doc["residency"]["watermark_bytes"] > 0
        assert doc["audit"]["coverage"] >= 0.0
