"""Disruption: emptiness, consolidation, drift, expiration, interruption, GC."""

import pytest

from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodeclaim import Phase
from karpenter_tpu.models.nodepool import DisruptionSpec, NodePool
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.sim import make_sim


def add_pods(sim, n, cpu="500m", mem="1Gi", prefix="p", **kw):
    pods = [Pod(name=f"{prefix}-{i}",
                requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)
            for i in range(n)]
    for p in pods:
        sim.store.add_pod(p)
    return pods


def all_bound(sim):
    return all(p.node_name is not None for p in sim.store.pods.values())


def settle(sim, timeout=120):
    ok = sim.engine.run_until(lambda: all_bound(sim), timeout=timeout)
    assert ok
    return ok


class TestEmptiness:
    def test_empty_node_deleted(self):
        sim = make_sim()
        pods = add_pods(sim, 20)
        settle(sim)
        n_claims = len(sim.store.nodeclaims)
        # all pods leave → nodes become empty → consolidated away
        for p in pods:
            sim.store.delete_pod(p.namespace, p.name)
        sim.engine.run_until(lambda: not sim.store.nodeclaims, timeout=300)
        assert not sim.store.nodeclaims
        assert sim.disruption.stats["empty"] >= 1
        # instances actually terminated
        assert not sim.cloud.describe()

    def test_when_empty_policy_never_consolidates_utilized(self):
        pool = NodePool(name="default",
                        disruption=DisruptionSpec(consolidation_policy="WhenEmpty"))
        sim = make_sim(nodepool=pool)
        add_pods(sim, 30)
        settle(sim)
        n = len(sim.store.nodeclaims)
        sim.engine.run_for(300, step=5)
        assert len(sim.store.nodeclaims) == n  # nothing disrupted
        assert sim.disruption.stats["consolidated"] == 0


class TestConsolidation:
    def test_single_node_consolidation_after_scale_down(self):
        sim = make_sim()
        pods = add_pods(sim, 60)
        settle(sim)
        n_before = len(sim.store.nodeclaims)
        cost_before = sum(c.price for c in sim.store.nodeclaims.values())
        # remove 70% of pods: cluster is now heavily under-utilized
        for p in pods[: int(len(pods) * 0.7)]:
            sim.store.delete_pod(p.namespace, p.name)
        sim.engine.run_for(600, step=5)
        assert all_bound(sim)  # survivors stayed scheduled
        cost_after = sum(c.price for c in sim.store.nodeclaims.values())
        assert len(sim.store.nodeclaims) < n_before
        assert cost_after < cost_before
        stats = sim.disruption.stats
        assert stats["empty"] + stats["consolidated"] + stats["multi_consolidated"] > 0

    def test_do_not_disrupt_blocks_consolidation(self):
        sim = make_sim()
        pods = add_pods(sim, 10, annotations={"karpenter.tpu/do-not-disrupt": "true"})
        settle(sim)
        claims = set(sim.store.nodeclaims)
        # even with massive headroom, protected pods pin their nodes
        sim.engine.run_for(400, step=5)
        assert claims <= set(sim.store.nodeclaims)

    def test_budget_limits_disruptions(self):
        from karpenter_tpu.models.nodepool import Budget
        pool = NodePool(name="default", disruption=DisruptionSpec(
            budgets=[Budget(nodes="0")]))  # no voluntary disruption at all
        sim = make_sim(nodepool=pool)
        pods = add_pods(sim, 20)
        settle(sim)
        n = len(sim.store.nodeclaims)
        for p in pods:
            sim.store.delete_pod(p.namespace, p.name)
        sim.engine.run_for(400, step=5)
        assert len(sim.store.nodeclaims) == n  # budget 0 blocks even empties


class TestDriftExpiration:
    def test_nodeclass_drift_replaces_nodes(self):
        sim = make_sim()
        add_pods(sim, 10)
        settle(sim)
        old = set(sim.store.nodeclaims)
        # mutate the NodeClass → hash changes → drift
        sim.store.nodeclasses["default"].user_data = "#!/bin/bash\necho new"
        sim.engine.run_for(600, step=5)
        assert all_bound(sim)
        assert sim.disruption.stats["drift"] >= 1
        new = set(sim.store.nodeclaims)
        assert not (old & new)  # every old claim replaced
        nc_hash = sim.store.nodeclasses["default"].hash()
        for c in sim.store.nodeclaims.values():
            assert c.annotations["karpenter.tpu/nodeclass-hash"] == nc_hash

    def test_expiration(self):
        pool = NodePool(name="default", expire_after=3600.0)
        sim = make_sim(nodepool=pool)
        add_pods(sim, 5)
        settle(sim)
        old = set(sim.store.nodeclaims)
        sim.engine.run_for(4000, step=20)
        assert all_bound(sim)
        assert not (old & set(sim.store.nodeclaims))
        assert sim.disruption.stats["expired"] >= 1


class TestInterruption:
    def test_spot_interruption_drains_and_marks(self):
        sim = make_sim()
        add_pods(sim, 10)
        settle(sim)
        victim = next(iter(sim.store.nodeclaims.values()))
        iid = victim.provider_id.rsplit("/", 1)[-1]
        inst = sim.cloud.instances[iid]
        sim.cloud.send_spot_interruption(iid)
        sim.engine.run_for(60)
        # claim drained + offering marked unavailable
        assert victim.name not in sim.store.nodeclaims
        assert sim.catalog.unavailable.is_unavailable(
            inst.instance_type, inst.zone, inst.capacity_type)
        # pods rescheduled elsewhere
        assert sim.engine.run_until(lambda: all_bound(sim), timeout=120)


class TestGC:
    def test_leaked_instance_reaped(self):
        sim = make_sim()
        from karpenter_tpu.cloud.provider import LaunchOverride, LaunchRequest
        t = next(iter(sim.cloud.types.values()))
        o = t.offerings[0]
        res = sim.cloud.create_fleet([LaunchRequest(
            nodeclaim_name="ghost",
            overrides=[LaunchOverride(t.name, o.zone, o.capacity_type, o.price)])])
        assert res[0].id in sim.cloud.instances
        sim.engine.run_for(200, step=10)
        assert sim.cloud.instances[res[0].id].state == "terminated"
        assert sim.gc.stats["instances_reaped"] == 1


class TestConsolidationScreen:
    def test_screen_identifies_absorbable_nodes(self):
        """Batched screen: a mostly-empty cluster screens nearly all nodes
        as absorbable; a packed cluster screens none."""
        import numpy as np
        from karpenter_tpu.ops.consolidate import consolidation_screen
        from karpenter_tpu.ops.encode import encode_pods

        sim = make_sim()
        pods = add_pods(sim, 40)
        settle(sim)
        cat = sim.solver.tensors(sim.store.nodeclasses["default"])
        from karpenter_tpu.state.cluster import build_node_views
        # drop most pods: lots of headroom
        for p in pods[:30]:
            sim.store.delete_pod(p.namespace, p.name)
        views = build_node_views(sim.store, cat, sim.clock.now())
        all_pods = [p for v in views for p in v.pods]
        enc = encode_pods(all_pods, cat)
        sig_to_g = {g.representative.constraint_signature(): i
                    for i, g in enumerate(enc.groups)}
        counts = np.zeros((len(views), max(enc.G, 1)), np.int32)
        for i, v in enumerate(views):
            for p in v.pods:
                counts[i, sig_to_g[p.constraint_signature()]] += 1
        screen, slack = consolidation_screen(cat, enc, views, counts)
        assert screen.any()  # at least one node's pods fit elsewhere

    def test_screen_speeds_up_large_consolidation(self):
        """5k-node-scale screen completes in one batched call (config #4
        shape, scaled down for CI but structurally identical)."""
        import numpy as np
        import time
        from karpenter_tpu.ops.consolidate import consolidation_screen
        from karpenter_tpu.ops.encode import encode_pods
        from karpenter_tpu.ops.binpack import VirtualNode
        from karpenter_tpu.state.cluster import NodeView
        from karpenter_tpu.models.nodeclaim import NodeClaim, Phase
        from karpenter_tpu.catalog import generate_catalog
        from karpenter_tpu.ops.encode import encode_catalog

        cat = encode_catalog(generate_catalog())
        N = 500
        rng = np.random.default_rng(0)
        pods = [Pod(name=f"p{i}", requests=Resources.parse(
            {"cpu": "500m", "memory": "1Gi"})) for i in range(N * 4)]
        enc = encode_pods(pods, cat)
        views = []
        t_idx = [i for i, n in enumerate(cat.names) if n.endswith(".2xlarge")][:10]
        for i in range(N):
            t = t_idx[i % len(t_idx)]
            vn = VirtualNode(
                type_idx=t, zone_mask=np.ones(cat.Z, bool),
                cap_mask=np.ones(cat.C, bool),
                cum=np.asarray(enc.requests[0] * 4, np.float32),
                existing_name=f"n{i}")
            claim = NodeClaim(name=f"n{i}", nodepool="default")
            claim.price = 0.1
            views.append(NodeView(claim=claim, node=None,
                                  pods=pods[i * 4:(i + 1) * 4], virtual=vn,
                                  price=0.1))
        counts = np.full((N, enc.G), 4, np.int32)
        consolidation_screen(cat, enc, views, counts)  # compile
        t0 = time.perf_counter()
        screen, slack = consolidation_screen(cat, enc, views, counts)
        dt = time.perf_counter() - t0
        assert dt < 2.0  # one batched call, not N simulations
        assert screen.shape == (N,)


class TestChaos:
    def test_cluster_survives_kill_thread(self):
        """kwok-style chaos: periodic instance kills; the state-change
        events drain dead claims, GC reaps orphans, pods reschedule."""
        sim = make_sim()
        pods = add_pods(sim, 30)
        settle(sim)
        sim.start_chaos(interval=120.0, seed=42)
        sim.engine.run_for(900, step=5)
        # chaos killed something
        killed = [i for i in sim.cloud.instances.values() if i.state == "terminated"]
        assert killed
        # and the cluster healed: every pod is bound to a live node
        assert sim.engine.run_until(lambda: all_bound(sim), timeout=300)
        for p in sim.store.pods.values():
            node = sim.store.nodes[p.node_name]
            iid = node.provider_id.rsplit("/", 1)[-1]
            assert sim.cloud.instances[iid].state == "running"
