"""Decision-time cordon + pre-delete re-validation of disruptions.

Reference step order (website concepts/disruption.md:14-27): taint victims
`disrupted:NoSchedule` FIRST, then pre-spin replacements, re-validate the
command against fresh state, and only then delete. Without the cordon a
victim can absorb pods during the replacement's boot; without the
re-validation a minutes-old decision executes against a cluster that no
longer supports it (designs/consolidation.md:5-43).
"""

from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodeclaim import Phase
from karpenter_tpu.models.pod import Pod, PodAffinityTerm
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.sim import make_sim


def add_pods(sim, n, cpu="500m", mem="1Gi", prefix="p", **kw):
    pods = [Pod(name=f"{prefix}-{i}",
                requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)
            for i in range(n)]
    for p in pods:
        sim.store.add_pod(p)
    return pods


def settle(sim, timeout=120):
    ok = sim.engine.run_until(
        lambda: all(p.node_name is not None for p in sim.store.pods.values()),
        timeout=timeout)
    assert ok


def make_pending_sim(n_anchors=3):
    """A sim holding a PendingDisruption: one-pod-per-node anchors (self
    hostname anti-affinity) so a drifted node's pod can never fold onto
    surviving nodes — the disruption must pre-spin a replacement and wait
    for it, which is exactly the window these tests probe."""
    sim = make_sim()
    pods = [Pod(name=f"a-{i}", labels={"role": "anchor"},
                requests=Resources.parse({"cpu": "1", "memory": "2Gi"}),
                affinity_terms=[PodAffinityTerm(
                    topology_key="kubernetes.io/hostname",
                    label_selector={"role": "anchor"}, anti=True)])
            for i in range(n_anchors)]
    for p in pods:
        sim.store.add_pod(p)
    settle(sim)
    sim.store.nodeclasses["default"].user_data = "v2"  # drift everything
    ok = sim.engine.run_until(lambda: bool(sim.disruption._pending),
                              timeout=900)
    assert ok, "no pre-spin disruption decision was made"
    return sim, sim.disruption._pending[0]


def test_victims_cordoned_at_decision_time():
    sim, pd = make_pending_sim()
    for vname in pd.victim_claims:
        claim = sim.store.nodeclaims[vname]
        node = sim.store.node_for_nodeclaim(claim)
        assert node is not None
        assert any(t.key == L.DISRUPTED_TAINT_KEY for t in node.taints), (
            f"victim {vname} not cordoned at decision time")
        assert not claim.is_deleting(), (
            "victim must not drain before its replacement is up")


def test_provisioner_skips_cordoned_victims():
    sim, pd = make_pending_sim()
    victims = set(pd.victim_claims)
    # new pending pods arrive while the replacement boots: none may be
    # nominated to or bound on a cordoned victim
    fresh = add_pods(sim, 6, prefix="late")
    sim.engine.run_for(60, step=1)
    from karpenter_tpu.controllers.provisioner import NOMINATED
    for p in fresh:
        live = sim.store.pods.get(f"{p.namespace}/{p.name}")
        if live is None:
            continue
        nominated = live.annotations.get(NOMINATED)
        assert nominated not in victims, (
            f"pod {p.name} nominated onto cordoned victim {nominated}")
        if live.node_name is not None:
            owner = next((c.name for c in sim.store.nodeclaims.values()
                          if c.node_name == live.node_name), None)
            assert owner not in victims, (
                f"pod {p.name} bound onto cordoned victim {owner}")


def test_validation_failure_aborts_disruption():
    """A pod force-bound onto the victim during replacement boot (tolerating
    the cordon, as a daemonset-like or direct-bind pod would) must abort
    the disruption: victims kept and uncordoned, abort event recorded."""
    sim, pd = make_pending_sim()
    victim = sim.store.nodeclaims[pd.victim_claims[0]]
    node = sim.store.node_for_nodeclaim(victim)
    # an unschedulable-elsewhere hog lands directly on the victim: big
    # enough that the surviving nodes cannot absorb it
    hog = Pod(name="hog", requests=Resources.parse({"cpu": "64",
                                                    "memory": "256Gi"}))
    sim.store.add_pod(hog)
    sim.store.bind_pod(hog, node.name)
    sim.engine.run_until(lambda: not sim.disruption._pending, timeout=900)
    # the decision was abandoned: victim survives, uncordoned, event logged
    live = sim.store.nodeclaims.get(victim.name)
    assert live is not None and not live.is_deleting(), (
        "victim was deleted despite failed re-validation")
    node = sim.store.node_for_nodeclaim(live)
    assert node is not None
    assert not any(t.key == L.DISRUPTED_TAINT_KEY for t in node.taints), (
        "aborted victim left cordoned")
    assert any(r == "DisruptionAborted" for _, _, r, _ in sim.store.events)


def test_validation_pass_deletes_victims():
    """The happy path still completes: with no interference the victims
    drain once replacements initialize."""
    sim, pd = make_pending_sim()
    victims = list(pd.victim_claims)
    sim.engine.run_until(
        lambda: all(sim.store.nodeclaims.get(v) is None
                    or sim.store.nodeclaims[v].is_deleting()
                    for v in victims),
        timeout=900)
    assert all(sim.store.nodeclaims.get(v) is None
               or sim.store.nodeclaims[v].is_deleting() for v in victims)


class TestNodeLevelControls:
    """Reference node-level controls (disruption.md:385-396): the
    do-not-disrupt annotation on the NODE blocks all voluntary
    disruption; a terminationGracePeriod on the claim overrides the
    block for drift/expiration (disruption.md:260-268)."""

    def _sim_with_annotated_node(self):
        from karpenter_tpu.models.pod import DO_NOT_DISRUPT
        sim = make_sim()
        pods = add_pods(sim, 2)
        settle(sim)
        claim = next(iter(sim.store.nodeclaims.values()))
        node = sim.store.node_for_nodeclaim(claim)
        node.annotations[DO_NOT_DISRUPT] = "true"
        return sim, claim, pods

    def test_node_annotation_blocks_emptiness(self):
        sim, claim, pods = self._sim_with_annotated_node()
        for p in pods:
            sim.store.delete_pod(p.namespace, p.name)
        sim.engine.run_for(600, step=5)
        live = sim.store.nodeclaims.get(claim.name)
        assert live is not None and not live.is_deleting(), (
            "empty pass reaped a node annotated do-not-disrupt")

    def test_node_annotation_blocks_drift(self):
        sim, claim, _ = self._sim_with_annotated_node()
        sim.store.nodeclasses["default"].user_data = "v2"
        sim.engine.run_for(600, step=5)
        live = sim.store.nodeclaims.get(claim.name)
        assert live is not None and not live.is_deleting(), (
            "drift rolled a node annotated do-not-disrupt")

    def test_grace_period_overrides_block_for_drift(self):
        sim, claim, _ = self._sim_with_annotated_node()
        claim.termination_grace_period = 300.0
        sim.store.nodeclasses["default"].user_data = "v2"
        sim.engine.run_until(
            lambda: (sim.store.nodeclaims.get(claim.name) is None
                     or sim.store.nodeclaims[claim.name].is_deleting()
                     or sim.disruption._pending),
            timeout=900)
        committed = (sim.store.nodeclaims.get(claim.name) is None
                     or sim.store.nodeclaims[claim.name].is_deleting()
                     or any(claim.name in pd.victim_claims
                            for pd in sim.disruption._pending))
        assert committed, (
            "terminationGracePeriod must let drift proceed past "
            "do-not-disrupt")


class TestForcedOverride:
    def test_grace_period_forces_drift_past_blocking_pdb(self):
        """terminationGracePeriod must carry the drift THROUGH the
        blocking-PDB re-check in _replace, not just the top-of-loop
        gate (disruption.md:260-268)."""
        from karpenter_tpu.models.pod import PodDisruptionBudget
        sim = make_sim()
        pods = add_pods(sim, 2, prefix="pdb", labels={"app": "web"})
        settle(sim)
        sim.store.add_pdb(PodDisruptionBudget(
            name="web", label_selector={"app": "web"},
            max_unavailable=0))  # fully blocking
        claim = next(iter(sim.store.nodeclaims.values()))
        claim.termination_grace_period = 300.0
        sim.store.nodeclasses["default"].user_data = "v2"  # drift
        sim.engine.run_until(
            lambda: (claim.is_deleting() or sim.disruption._pending),
            timeout=900)
        committed = claim.is_deleting() or any(
            claim.name in pd.victim_claims
            for pd in sim.disruption._pending)
        assert committed, (
            "blocking PDB silently dropped a terminationGracePeriod-"
            "forced drift")

    def test_annotation_during_replacement_boot_aborts(self):
        """Node-level do-not-disrupt applied while the replacement boots
        must abort the pending disruption at re-validation."""
        from karpenter_tpu.models.pod import DO_NOT_DISRUPT
        sim, pd = make_pending_sim()
        victim = sim.store.nodeclaims[pd.victim_claims[0]]
        node = sim.store.node_for_nodeclaim(victim)
        node.annotations[DO_NOT_DISRUPT] = "true"
        sim.engine.run_until(lambda: not sim.disruption._pending,
                             timeout=900)
        live = sim.store.nodeclaims.get(victim.name)
        assert live is not None and not live.is_deleting(), (
            "victim annotated do-not-disrupt mid-boot was still drained")
        assert any(r == "DisruptionAborted"
                   for _, _, r, _ in sim.store.events)


class TestDrainBlocking:
    """Drain semantics for do-not-disrupt pods (disruption.md:181-182 +
    :260-268): they block draining indefinitely; an explicit
    terminationGracePeriod forces them out after the window."""

    def _node_with_protected_pod(self):
        sim = make_sim()
        protected = sim.store.add_pod(Pod(
            name="keep",
            annotations={"karpenter.tpu/do-not-disrupt": "true"},
            requests=Resources.parse({"cpu": "250m", "memory": "512Mi"})))
        victim_pod = sim.store.add_pod(Pod(
            name="evictable",
            requests=Resources.parse({"cpu": "250m", "memory": "512Mi"})))
        settle(sim)
        claim = next(iter(sim.store.nodeclaims.values()))
        return sim, claim, protected, victim_pod

    def test_drain_waits_indefinitely_without_grace(self):
        sim, claim, protected, evictable = self._node_with_protected_pod()
        sim.termination.delete_nodeclaim(claim, sim.clock.now(), "Test")
        sim.engine.run_for(600, step=5)  # 20x the default 30s drain grace
        assert claim.name in sim.store.nodeclaims, (
            "node with a do-not-disrupt pod was torn down without a "
            "terminationGracePeriod")
        live = sim.store.pods[f"{protected.namespace}/{protected.name}"]
        assert live.node_name is not None, "protected pod was evicted"
        # the evictable pod left and rescheduled meanwhile
        other = sim.store.pods[f"{evictable.namespace}/{evictable.name}"]
        assert other.node_name is not None

    def test_grace_period_forces_protected_pods_out(self):
        sim, claim, protected, _ = self._node_with_protected_pod()
        claim.termination_grace_period = 60.0
        sim.termination.delete_nodeclaim(claim, sim.clock.now(), "Test")
        sim.engine.run_until(lambda: claim.name not in sim.store.nodeclaims,
                             timeout=600)
        assert claim.name not in sim.store.nodeclaims
        # protected pod rescheduled elsewhere, not stranded
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in sim.store.pods.values()),
            timeout=600)

    def test_annotation_removed_unblocks_drain(self):
        sim, claim, protected, _ = self._node_with_protected_pod()
        sim.termination.delete_nodeclaim(claim, sim.clock.now(), "Test")
        sim.engine.run_for(120, step=5)
        assert claim.name in sim.store.nodeclaims
        live = sim.store.pods[f"{protected.namespace}/{protected.name}"]
        del live.annotations["karpenter.tpu/do-not-disrupt"]
        sim.engine.run_until(lambda: claim.name not in sim.store.nodeclaims,
                             timeout=600)
        assert claim.name not in sim.store.nodeclaims


class TestNodePoolDrift:
    def test_template_taint_change_rolls_the_pool(self):
        sim = make_sim()
        add_pods(sim, 2, tolerations=[])
        settle(sim)
        old = set(sim.store.nodeclaims)
        from karpenter_tpu.models.pod import Taint, Toleration
        # every pod must tolerate the new taint or nothing reschedules
        for p in sim.store.pods.values():
            p.tolerations.append(Toleration(key="team", operator="Exists"))
            p.invalidate_group_key(); p.group_key()
        sim.store.nodepools["default"].taints.append(
            Taint(key="team", value="a", effect="NoSchedule"))
        sim.engine.run_for(900, step=10)
        assert not (set(sim.store.nodeclaims) & old), (
            "nodepool template taint change did not roll the fleet")
        assert all(p.node_name for p in sim.store.pods.values())

    def test_requirements_drift_rolls_mismatched_nodes(self):
        """Tightening the pool's requirements drifts nodes whose labels
        no longer satisfy them (dynamic drift, no hash involved)."""
        from karpenter_tpu.models import labels as L
        from karpenter_tpu.models.requirements import (Operator, Requirement)
        sim = make_sim()
        add_pods(sim, 2)
        settle(sim)
        claim = next(iter(sim.store.nodeclaims.values()))
        node = sim.store.node_for_nodeclaim(claim)
        zone = node.labels[L.ZONE]
        other = [z for z in ("zone-a", "zone-b", "zone-c") if z != zone][0]
        sim.store.nodepools["default"].requirements.add(
            Requirement(L.ZONE, Operator.IN, (other,)))
        sim.engine.run_until(
            lambda: claim.name not in sim.store.nodeclaims
            or claim.is_deleting() or sim.disruption._pending,
            timeout=900)
        rolled = (claim.name not in sim.store.nodeclaims
                  or claim.is_deleting()
                  or any(claim.name in pd.victim_claims
                         for pd in sim.disruption._pending))
        assert rolled, "requirements drift did not flag the node"


class TestNodePoolDriftPersistence:
    def test_nodepool_hash_survives_restart(self):
        """The nodepool-hash stamp round-trips through instance adoption
        tags: a template change AFTER an operator restart must still roll
        the adopted fleet."""
        sim = make_sim()
        add_pods(sim, 2)
        settle(sim)
        # operator restart: new stack adopts the fleet from cloud state
        sim2 = make_sim(cloud=sim.cloud)
        claim = next(iter(sim2.store.nodeclaims.values()))
        assert claim.annotations.get("karpenter.tpu/nodepool-hash"), (
            "adopted claim lost its nodepool-hash stamp")
        old = set(sim2.store.nodeclaims)
        sim2.store.nodepools["default"].labels["team"] = "ml"
        sim2.engine.run_for(900, step=10)
        assert not (set(sim2.store.nodeclaims) & old), (
            "template change after restart did not roll the adopted fleet")

    def test_absent_pinned_label_is_drift(self):
        """A single-valued requirement pin added to the pool drifts
        pre-existing nodes that never got the label (absence semantics,
        restricted to materializable pins so replacements converge)."""
        from karpenter_tpu.models.requirements import (Operator, Requirement)
        sim = make_sim()
        add_pods(sim, 1)
        settle(sim)
        claim = next(iter(sim.store.nodeclaims.values()))
        sim.store.nodepools["default"].requirements.add(
            Requirement("team.example/name", Operator.IN, ("ml",)))
        sim.engine.run_until(
            lambda: claim.name not in sim.store.nodeclaims
            or claim.is_deleting() or sim.disruption._pending,
            timeout=900)
        rolled = (claim.name not in sim.store.nodeclaims
                  or claim.is_deleting()
                  or any(claim.name in pd.victim_claims
                         for pd in sim.disruption._pending))
        assert rolled
        # the fleet CONVERGES: replacements carry the pin and stop rolling
        sim.engine.run_for(600, step=10)
        assert all(p.node_name for p in sim.store.pods.values())
        live = [c for c in sim.store.nodeclaims.values()
                if not c.is_deleting()]
        assert live
        for c in live:
            node = sim.store.node_for_nodeclaim(c)
            if node is not None:
                assert node.labels.get("team.example/name") == "ml"
