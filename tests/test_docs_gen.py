"""Docs generator (reference `make docgen`, hack/docs): the generated
pages must exist, stay in sync with the live registry/catalog, and the
per-instance-type page must cover the whole catalog."""

import importlib.util
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_gen():
    spec = importlib.util.spec_from_file_location(
        "gen_docs", os.path.join(ROOT, "tools", "gen_docs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metrics_page_covers_registry():
    gen = _load_gen()
    from karpenter_tpu import metrics as M
    page = gen.gen_metrics()
    for m in M.REGISTRY._metrics:
        assert f"`{m.name}`" in page


def test_settings_page_covers_options():
    gen = _load_gen()
    from dataclasses import fields

    from karpenter_tpu.utils.options import Options
    page = gen.gen_settings()
    for f in fields(Options):
        if f.name == "feature_gates":
            continue
        assert f.name.replace("_", "-") in page


def test_instance_types_page_covers_catalog():
    gen = _load_gen()
    from karpenter_tpu.catalog import GeneratorConfig, generate_catalog
    types = generate_catalog(GeneratorConfig(families=["m5", "c5"]))
    page = gen.gen_instance_types(types)
    for t in types:
        assert f"### `{t.name}`" in page
    # labels, resources, and offerings sections render per type
    assert page.count("#### Labels") == len(types)
    assert page.count("#### Resources") == len(types)
    assert page.count("#### Offerings") == len(types)
    # the scheduling surface is present
    assert "karpenter.tpu/instance-family" in page
    assert "topology.kubernetes.io/region" in page
    assert "on-demand" in page and "spot" in page


def test_checked_in_generated_pages_are_current():
    """docs/reference/* are generated output — a registry/options/catalog
    change without regenerating them is documentation drift (found live:
    settings.md shipped without the leader_elect_endpoint row)."""
    gen = _load_gen()
    for fname, generate in (("instance-types.md", gen.gen_instance_types),
                            ("metrics.md", gen.gen_metrics),
                            ("settings.md", gen.gen_settings)):
        path = os.path.join(ROOT, "docs", "reference", fname)
        assert os.path.exists(path), f"run tools/gen_docs.py ({fname})"
        with open(path) as f:
            on_disk = f.read()
        assert on_disk == generate(), (
            f"docs/reference/{fname} is stale — rerun tools/gen_docs.py")
