"""End-to-end slice: pending pods → Solve → NodeClaims → fake nodes → bound.

The reference's scale-suite floor (BASELINE config #1): 500 pods, one
NodePool, ~20 instance types on the kwok-style fake cloud.
"""

import pytest

from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodeclaim import Phase
from karpenter_tpu.models.pod import Pod, Toleration, Taint
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.requirements import Operator, Requirement, Requirements
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.sim import make_sim


def add_pods(sim, n, cpu="500m", mem="1Gi", prefix="p", **kw):
    pods = [Pod(name=f"{prefix}-{i}",
                requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)
            for i in range(n)]
    for p in pods:
        sim.store.add_pod(p)
    return pods


def all_bound(sim):
    return all(p.node_name is not None for p in sim.store.pods.values())


class TestE2ESlice:
    def test_500_pods_end_to_end(self):
        sim = make_sim()
        add_pods(sim, 500)
        ok = sim.engine.run_until(lambda: all_bound(sim), timeout=120)
        assert ok, f"unbound={len(sim.store.pending_pods())}"
        # all claims initialized, nodes ready
        claims = list(sim.store.nodeclaims.values())
        assert claims
        assert all(c.phase == Phase.INITIALIZED for c in claims)
        # dense packing: far fewer nodes than pods
        assert len(sim.store.nodes) < 100
        # single solve batch → single CreateFleet call (batching works)
        assert sim.cloud.api_calls["create_fleet"] <= 3
        # pods actually fit their nodes
        for node in sim.store.nodes.values():
            used = Resources()
            for p in sim.store.pods_on_node(node.name):
                used = used.add(p.requests)
            assert used.fits(node.allocatable)

    def test_in_flight_claims_absorb_followup_pods(self):
        sim = make_sim()
        add_pods(sim, 20)
        sim.engine.run_until(lambda: all_bound(sim), timeout=60)
        n_claims = len(sim.store.nodeclaims)
        # small follow-up batch fits in the headroom of existing nodes...
        # but v1 only packs onto in-flight claims; bound-node headroom reuse
        # arrives with cluster-state (consolidation) — so allow new claims,
        # just require everything binds again
        add_pods(sim, 5, prefix="follow")
        ok = sim.engine.run_until(lambda: all_bound(sim), timeout=60)
        assert ok

    def test_ice_failover(self):
        sim = make_sim()
        # exhaust every spot pool so launches fail over to on-demand
        for t in sim.cloud.types.values():
            for o in t.offerings:
                if o.capacity_type == "spot":
                    sim.cloud.set_capacity(t.name, o.zone, "spot", 0)
        add_pods(sim, 50)
        ok = sim.engine.run_until(lambda: all_bound(sim), timeout=120)
        assert ok
        for c in sim.store.nodeclaims.values():
            assert c.capacity_type == "on-demand"

    def test_ice_marks_unavailable_and_resolves(self):
        sim = make_sim()
        # kill capacity for everything except one family to force ICE retries
        seen = sim.catalog.unavailable
        for t in sim.cloud.types.values():
            for o in t.offerings:
                if not t.name.startswith("m5."):
                    sim.cloud.set_capacity(t.name, o.zone, o.capacity_type, 0)
        add_pods(sim, 30)
        ok = sim.engine.run_until(lambda: all_bound(sim), timeout=180)
        assert ok
        assert all(c.instance_type.startswith("m5.")
                   for c in sim.store.nodeclaims.values())

    def test_nodepool_taints_and_tolerations(self):
        taint = Taint(key="dedicated", value="ml", effect="NoSchedule")
        sim = make_sim(nodepool=NodePool(name="tainted", taints=[taint]))
        add_pods(sim, 5, prefix="plain")
        tolerant = add_pods(sim, 5, prefix="tol",
                            tolerations=[Toleration(key="dedicated", operator="Exists")])
        sim.engine.run_for(30)
        # tolerant pods bound; plain pods unschedulable (no other pool)
        assert all(p.node_name is not None for p in tolerant)
        plain = [p for p in sim.store.pods.values() if p.name.startswith("plain")]
        assert all(p.node_name is None for p in plain)
        assert any(e[2] == "FailedScheduling" for e in sim.store.events)

    def test_multi_nodepool_weight_and_fallthrough(self):
        from karpenter_tpu.catalog import small_catalog
        sim = make_sim(types=small_catalog(8))  # includes the g5 gpu family
        del sim.store.nodepools["default"]
        # heavy pool restricted to m5 family; light pool open
        heavy = NodePool(name="heavy", weight=10)
        heavy.requirements.add(Requirement(L.INSTANCE_FAMILY, Operator.IN, ("m5",)))
        light = NodePool(name="light", weight=1)
        sim.store.add_nodepool(heavy)
        sim.store.add_nodepool(light)
        add_pods(sim, 10)
        # gpu-needing pod can't go on m5 → falls through to light pool
        add_pods(sim, 1, prefix="gpu", cpu="1", mem="2Gi",
                 node_affinity=[{"key": L.INSTANCE_GPU_COUNT,
                                 "operator": "Gt", "values": ["0"]}])
        ok = sim.engine.run_until(lambda: all_bound(sim), timeout=120)
        assert ok
        by_pool = {}
        for c in sim.store.nodeclaims.values():
            by_pool.setdefault(c.nodepool, []).append(c)
        assert set(by_pool) == {"heavy", "light"}
        assert all(c.instance_type.startswith("m5.") for c in by_pool["heavy"])
        assert all(c.instance_type.startswith("g") for c in by_pool["light"])

    def test_nodepool_limits(self):
        pool = NodePool(name="limited",
                        limits=Resources.parse({"cpu": "8"}))
        sim = make_sim(nodepool=pool)
        add_pods(sim, 100, cpu="1", mem="1Gi")
        sim.engine.run_for(30)
        total_cpu = sum(c.capacity.get("cpu") for c in sim.store.nodeclaims.values())
        assert 0 < total_cpu <= 8
        assert any(e[2] == "LimitExceeded" for e in sim.store.events)

    def test_registration_timeout_reaps_claim(self):
        sim = make_sim()
        # instances never register (infinite delay)
        sim.cloud.config.register_delay = 10**9
        add_pods(sim, 3)
        sim.engine.run_for(20)
        first = set(sim.store.nodeclaims)
        assert first  # launched, waiting
        sim.engine.run_for(16 * 60, step=30)
        # original claims reaped by liveness; pods returned to pending and
        # the provisioner retried with fresh claims
        assert not (first & set(sim.store.nodeclaims))
        assert any(e[2] == "RegistrationTimeout" for e in sim.store.events)
        assert all(p.node_name is None for p in sim.store.pods.values())


def test_device_backend_e2e_smoke():
    """One full provisioning round through the ACTUAL TPU kernel path
    (device backend on the CPU-mesh jax) — everything else uses host."""
    sim = make_sim(backend="device")
    add_pods(sim, 40)
    ok = sim.engine.run_until(lambda: all_bound(sim), timeout=120)
    assert ok
    assert sim.store.nodeclaims
