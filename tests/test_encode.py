import numpy as np

from karpenter_tpu.catalog import generate_catalog, small_catalog
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.pod import (Pod, PodAffinityTerm, Toleration,
                                      TopologySpreadConstraint)
from karpenter_tpu.models.pod import Taint
from karpenter_tpu.models.requirements import (Operator, Requirement,
                                               Requirements)
from karpenter_tpu.models.resources import CPU, Resources, resource_index
from karpenter_tpu.ops.encode import (compat_mask, encode_catalog, encode_pods,
                                      group_pods)


def mk_pod(name, cpu="500m", mem="1Gi", **kw):
    return Pod(name=name, requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)


class TestEncodeCatalog:
    def setup_method(self):
        self.types = generate_catalog()
        self.cat = encode_catalog(self.types)

    def test_shapes(self):
        T, Z, C = self.cat.T, self.cat.Z, self.cat.C
        assert T == len(self.types) and Z == 3 and C == 3
        assert self.cat.allocatable.shape[0] == T
        assert self.cat.price.shape == (T, Z, C)
        assert self.cat.available.shape == (T, Z, C)

    def test_price_matches_offerings(self):
        t5 = self.types[5]
        i = self.cat.name_to_idx[t5.name]
        for o in t5.offerings:
            zi = self.cat.zones.index(o.zone)
            ci = self.cat.captypes.index(o.capacity_type)
            assert self.cat.price[i, zi, ci] == np.float32(o.price)
            assert self.cat.available[i, zi, ci] == o.available
        # non-offered combos are +inf / unavailable
        assert np.isinf(self.cat.price[i][~self.cat.available[i]]).all()

    def test_allocatable_matches_model(self):
        t0 = self.types[0]
        i = self.cat.name_to_idx[t0.name]
        cpu = self.cat.allocatable[i, resource_index(CPU)]
        assert abs(cpu - t0.allocatable()[CPU]) < 1e-3

    def test_compat_mask_oracle_agreement(self):
        """Vectorized compat must agree with the exact set-algebra on a
        spread of requirement shapes (this pins the encoder to the
        Requirements oracle)."""
        cases = [
            Requirements(Requirement(L.INSTANCE_FAMILY, Operator.IN, ("m5", "c5"))),
            Requirements(Requirement(L.ARCH, Operator.IN, ("arm64",))),
            Requirements(Requirement(L.INSTANCE_CPU, Operator.GT, ("8",))),
            Requirements(Requirement(L.INSTANCE_CPU, Operator.GT, ("4",)),
                         Requirement(L.INSTANCE_CPU, Operator.LT, ("64",))),
            Requirements(Requirement(L.INSTANCE_GPU_COUNT, Operator.EXISTS)),
            Requirements(Requirement(L.INSTANCE_GPU_COUNT, Operator.DOES_NOT_EXIST)),
            Requirements(Requirement(L.INSTANCE_LOCAL_NVME, Operator.NOT_IN, ("0",))),
            Requirements(Requirement(L.INSTANCE_SIZE, Operator.NOT_IN, ("metal",)),
                         Requirement(L.INSTANCE_CATEGORY, Operator.IN, ("c", "m", "r"))),
            Requirements(Requirement("nonexistent-key", Operator.IN, ("x",))),
            Requirements(Requirement("nonexistent-key", Operator.NOT_IN, ("x",))),
            Requirements(Requirement(L.INSTANCE_MEMORY, Operator.GT, ("100000",))),
        ]
        for reqs in cases:
            mask = compat_mask(reqs, self.cat)
            for i in range(0, self.cat.T, 37):  # sample types
                expected = reqs.compatible(self.types[i].requirements)
                assert mask[i] == expected, (
                    f"{reqs} vs {self.types[i].name}: mask={mask[i]} exact={expected}")


class TestEncodePods:
    def setup_method(self):
        self.types = small_catalog()
        self.cat = encode_catalog(self.types)

    def test_grouping_dedupes(self):
        pods = [mk_pod(f"a-{i}") for i in range(50)] + \
               [mk_pod(f"b-{i}", cpu="2") for i in range(30)]
        groups = group_pods(pods)
        assert len(groups) == 2
        # FFD order: bigger cpu first
        assert groups[0].count == 30 and groups[1].count == 50

    def test_grouping_survives_intern_rotation(self, monkeypatch):
        """Review finding: the gid intern table rotates at capacity, so
        equal signatures interned across a rotation get DIFFERENT gids.
        Grouping must still yield one group per distinct signature."""
        import karpenter_tpu.models.pod as pod_mod
        early = [mk_pod(f"e-{i}") for i in range(10)]
        for p in early:
            p.group_key()  # interned pre-rotation
        monkeypatch.setattr(pod_mod, "_SIG_INTERN_MAX", 1)
        # distinct signature forces the rotation (table hits "capacity")
        filler = mk_pod("filler", cpu="3")
        filler.group_key()
        late = [mk_pod(f"l-{i}") for i in range(10)]
        for p in late:
            p.group_key()  # same signature as `early`, post-rotation
        assert early[0].group_key() != late[0].group_key(), \
            "rotation did not split gids — test setup is stale"
        groups = group_pods(early + late + [filler])
        sizes = sorted(g.count for g in groups)
        assert len(groups) == 2 and sizes == [1, 20], (
            "equal-signature pods split across intern generations must "
            "re-merge into one group")

    def test_decorated_prelim_key_sound(self):
        """intern_pods' unsorted prelim key for decorated pods: equal
        content in a different insertion order must still land in ONE
        group (canonicalization on prelim miss), and distinct content
        must never merge."""
        from karpenter_tpu.models.pod import PodAffinityTerm, intern_pods
        a = [mk_pod(f"a-{i}") for i in range(4)]
        for p in a:
            p.labels = {"app": "web", "tier": "fe"}
            p.affinity_terms = [PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                label_selector={"app": "web"}, anti=True)]
        b = [mk_pod(f"b-{i}") for i in range(4)]
        for p in b:
            p.labels = {"tier": "fe", "app": "web"}  # reversed order
            p.affinity_terms = [PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                label_selector={"app": "web"}, anti=True)]
        c = [mk_pod(f"c-{i}") for i in range(3)]
        for p in c:
            p.labels = {"app": "db", "tier": "fe"}  # distinct content
        intern_pods(a + b + c)
        groups = group_pods(a + b + c)
        sizes = sorted(g.count for g in groups)
        assert len(groups) == 2 and sizes == [3, 8], (
            "insertion-order variants of equal content must merge; "
            "distinct content must not")

    def test_encoded_fields(self):
        pods = ([mk_pod(f"a-{i}") for i in range(10)] +
                [mk_pod(f"z-{i}", node_selector={L.ZONE: "zone-b"}) for i in range(5)] +
                [mk_pod(f"s-{i}", node_affinity=[
                    {"key": L.CAPACITY_TYPE, "operator": "In", "values": ["spot"]}])
                 for i in range(3)])
        enc = encode_pods(pods, self.cat)
        assert enc.G == 3
        assert enc.counts.sum() == 18
        for i, g in enumerate(enc.groups):
            rep = g.representative
            if rep.name.startswith("z"):
                assert enc.allow_zone[i].tolist() == [z == "zone-b" for z in self.cat.zones]
            if rep.name.startswith("s"):
                assert enc.allow_cap[i].tolist() == [c == "spot" for c in self.cat.captypes]

    def test_taints_filter(self):
        taints = [Taint(key="dedicated", value="ml", effect="NoSchedule")]
        pods = [mk_pod("plain"),
                mk_pod("tolerant", tolerations=[Toleration(key="dedicated", operator="Exists")])]
        enc = encode_pods(pods, self.cat, taints=taints)
        assert enc.G == 1
        assert enc.groups[0].representative.name == "tolerant"

    def test_nodepool_requirements_layered(self):
        extra = Requirements(Requirement(L.INSTANCE_FAMILY, Operator.IN, ("m5",)))
        enc = encode_pods([mk_pod("p")], self.cat, extra_requirements=extra)
        m5 = [i for i, n in enumerate(self.cat.names) if n.startswith("m5.")]
        not_m5 = [i for i, n in enumerate(self.cat.names) if not n.startswith("m5.")]
        assert enc.compat[0, m5].all()
        assert not enc.compat[0, not_m5].any()

    def test_anti_affinity_and_spread(self):
        anti = mk_pod("anti", labels={"app": "x"},
                      affinity_terms=[PodAffinityTerm(
                          topology_key="kubernetes.io/hostname",
                          label_selector={"app": "x"}, anti=True)])
        spread = mk_pod("spread", topology_spread=[TopologySpreadConstraint(
            topology_key=L.ZONE, max_skew=1)])
        enc = encode_pods([anti, spread], self.cat)
        by_name = {g.representative.name: i for i, g in enumerate(enc.groups)}
        assert enc.max_per_node[by_name["anti"]] == 1
        assert enc.spread_zone[by_name["spread"]]
        assert enc.max_per_node[by_name["spread"]] == 0


class TestExoticInstanceFilter:
    """Reference filter.go:279 ExoticInstanceFilter: metal and accelerator
    types serve only pods that ask for them."""

    def _cat(self):
        from karpenter_tpu.catalog import GeneratorConfig, generate_catalog
        return encode_catalog(generate_catalog(GeneratorConfig(
            families=["c5", "g5", "q6"])))

    def test_plain_pod_excluded_from_exotic(self):
        import numpy as np
        from karpenter_tpu.ops.encode import exotic_mask
        cat = self._cat()
        ex = exotic_mask(cat)
        assert ex.any()
        p = Pod(name="plain",
                requests=Resources.parse({"cpu": "1", "memory": "1Gi"}))
        enc = encode_pods([p], cat)
        assert not (enc.compat[0] & ex).any()
        # but non-exotic types remain
        assert enc.compat[0].any()

    def test_gpu_request_keeps_gpu_types(self):
        import numpy as np
        cat = self._cat()
        p = Pod(name="gpu", requests=Resources.parse(
            {"cpu": "1", "memory": "1Gi", "nvidia.com/gpu": "1"}))
        enc = encode_pods([p], cat)
        names = [cat.names[t] for t in np.flatnonzero(enc.compat[0])]
        assert any(n.startswith("g5") for n in names)

    def test_explicit_family_intent_keeps_exotic(self):
        import numpy as np
        from karpenter_tpu.models import labels as L
        cat = self._cat()
        p = Pod(name="pinned",
                requests=Resources.parse({"cpu": "1", "memory": "1Gi"}),
                node_selector={L.INSTANCE_FAMILY: "g5"})
        enc = encode_pods([p], cat)
        names = [cat.names[t] for t in np.flatnonzero(enc.compat[0])]
        assert names and all(n.startswith("g5") for n in names)

    def test_metal_excluded_without_intent(self):
        import numpy as np
        from karpenter_tpu.models import labels as L
        cat = self._cat()
        p = Pod(name="plain",
                requests=Resources.parse({"cpu": "1", "memory": "1Gi"}))
        enc = encode_pods([p], cat)
        names = [cat.names[t] for t in np.flatnonzero(enc.compat[0])]
        assert names and not any(n.endswith(".metal") for n in names)
        # explicit size intent brings metal back
        p2 = Pod(name="metal",
                 requests=Resources.parse({"cpu": "1", "memory": "1Gi"}),
                 node_selector={L.INSTANCE_SIZE: "metal"})
        enc2 = encode_pods([p2], cat)
        names2 = [cat.names[t] for t in np.flatnonzero(enc2.compat[0])]
        assert names2 and all(n.endswith(".metal") for n in names2)


class TestFloorRowsPerKey:
    def test_unreachable_floor_keeps_other_floors(self):
        """Review finding: one unreachable minValues floor must not discard
        the reservations other keys already secured."""
        import numpy as np
        from karpenter_tpu.models import labels as L
        from karpenter_tpu.ops.facade import Solver
        cat = encode_catalog(small_catalog())
        # all rows, price-sorted
        t_idx, z_idx, c_idx = np.nonzero(cat.available)
        prices = cat.price[t_idx, z_idx, c_idx]
        by_price = np.argsort(prices, kind="stable")
        order = Solver._floor_rows(
            cat, t_idx, z_idx, c_idx, by_price,
            [(L.ZONE, 3), (L.INSTANCE_TYPE, 10_000)])  # 2nd unreachable
        zones = {int(z_idx[j]) for j in order}
        assert len(zones) >= 3  # the reachable zone floor still ships
