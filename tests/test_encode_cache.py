"""Columnar encode pipeline: signature-keyed compat cache, staging
arena, and the catalog-tensor LRU.

The cache's one hard contract — cached and cold encodes are
byte-identical — is swept by tests/test_solver_fuzz.py's parity fuzz;
this file pins the machinery: keying/invalidation riding the catalog
epoch, the context LRU, taint-drop caching, row rotation, arena lease
semantics, and the tensors() LRU that replaced the single-slot
clear-on-new-key policy (two NodeClass views alternating per reconcile
must not rebuild — and re-upload — every flip).
"""

import numpy as np
import pytest

from karpenter_tpu.catalog import (CatalogProvider, GeneratorConfig,
                                   generate_catalog, small_catalog)
from karpenter_tpu.models import labels as L
from karpenter_tpu.models.nodepool import NodeClassSpec, NodePool
from karpenter_tpu.models.pod import Pod, Taint, Toleration
from karpenter_tpu.models.resources import Resources
from karpenter_tpu.ops.encode import encode_catalog, encode_pods
from karpenter_tpu.ops.encode_cache import (EncodeArena, EncodeCache,
                                            requirements_token)
from karpenter_tpu.ops.facade import Solver


def mk_pod(name, cpu="500m", mem="1Gi", **kw):
    return Pod(name=name,
               requests=Resources.parse({"cpu": cpu, "memory": mem}), **kw)


def _cat(token=("t",)):
    cat = encode_catalog(small_catalog())
    cat.cache_token = token
    return cat


class TestEncodeCache:
    def test_second_encode_is_all_hits(self):
        cat = _cat()
        cache = EncodeCache()
        ctx = cache.context_for(cat)
        pods = [mk_pod(f"a{i}") for i in range(20)] + \
               [mk_pod(f"b{i}", cpu="2") for i in range(10)]
        e1 = encode_pods(pods, cat, cache=ctx)
        assert (e1.cache_hits, e1.cache_misses) == (0, 2)
        e2 = encode_pods(pods, cat, cache=ctx)
        assert (e2.cache_hits, e2.cache_misses) == (2, 0)
        for f in ("requests", "compat", "allow_zone", "allow_cap",
                  "max_per_node", "counts"):
            assert getattr(e1, f).tobytes() == getattr(e2, f).tobytes(), f

    def test_cached_rows_never_alias_the_returned_arrays(self):
        cat = _cat()
        ctx = EncodeCache().context_for(cat)
        pods = [mk_pod(f"p{i}") for i in range(4)]
        e1 = encode_pods(pods, cat, cache=ctx)
        e1.compat[:] = False  # downstream narrowing (fits_cap, limits)
        e1.allow_zone[:] = False
        e2 = encode_pods(pods, cat, cache=ctx)
        assert e2.compat.any(), "in-place narrowing leaked into the cache"
        assert e2.allow_zone.any()

    def test_token_change_is_a_fresh_context(self):
        cache = EncodeCache()
        pods = [mk_pod("p")]
        e1 = encode_pods(pods, _cat(("epoch", 1)),
                         cache=cache.context_for(_cat(("epoch", 1))))
        e2 = encode_pods(pods, _cat(("epoch", 2)),
                         cache=cache.context_for(_cat(("epoch", 2))))
        assert e1.cache_misses == 1 and e2.cache_misses == 1
        # returning to epoch 1's context hits again (LRU keeps it warm)
        e3 = encode_pods(pods, _cat(("epoch", 1)),
                         cache=cache.context_for(_cat(("epoch", 1))))
        assert e3.cache_hits == 1

    def test_pool_context_partitions_rows(self):
        """Same signature under different pool requirements must not
        share rows — the NodePool requirements enter every compat row."""
        from karpenter_tpu.models.requirements import (Operator, Requirement,
                                                       Requirements)
        cat = _cat()
        cache = EncodeCache()
        pods = [mk_pod("p")]
        wide = encode_pods(pods, cat, cache=cache.context_for(cat))
        narrow_reqs = Requirements(
            Requirement(L.INSTANCE_FAMILY, Operator.IN, ("m5",)))
        narrow = encode_pods(pods, cat, extra_requirements=narrow_reqs,
                             cache=cache.context_for(
                                 cat, extra_requirements=narrow_reqs))
        assert narrow.compat.sum() < wide.compat.sum()

    def test_taint_drop_verdict_cached(self):
        cat = _cat()
        taints = [Taint(key="dedicated", value="ml", effect="NoSchedule")]
        cache = EncodeCache()
        ctx = cache.context_for(cat, taints=taints)
        pods = [mk_pod("plain"),
                mk_pod("tol", tolerations=[
                    Toleration(key="dedicated", operator="Exists")])]
        e1 = encode_pods(pods, cat, taints=taints, cache=ctx)
        assert e1.G == 1 and e1.dropped_keys == ["default/plain"]
        e2 = encode_pods(pods, cat, taints=taints, cache=ctx)
        assert e2.G == 1 and e2.dropped_keys == ["default/plain"]
        assert e2.cache_hits == 2 and e2.cache_misses == 0

    def test_row_rotation_recovers(self):
        cat = _cat()
        ctx = EncodeCache().context_for(cat)
        ctx.max_rows = 4
        for batch in range(3):
            # distinct requests per batch → distinct signatures → the
            # tiny row cap must rotate, and encoding must still succeed
            # (oddball millicpu values so no other test's signatures
            # interact with this one through the process-global intern)
            pods = [mk_pod(f"r{batch}-{i}",
                           cpu=f"{611 + 7 * (i + 3 * batch)}m")
                    for i in range(3)]
            enc = encode_pods(pods, cat, cache=ctx)
            assert enc.G == 3  # rotation never loses groups
        assert ctx.stats["rotations"] >= 1

    def test_context_lru_bounded(self):
        cache = EncodeCache(max_contexts=2)
        for e in range(5):
            cat = _cat(("epoch", e))
            encode_pods([mk_pod("p")], cat, cache=cache.context_for(cat))
        assert len(cache._ctxs) == 2
        assert cache.stats["evictions"] == 3

    def test_requirements_token_orders_keys(self):
        from karpenter_tpu.models.requirements import (Operator, Requirement,
                                                       Requirements)
        a = Requirements(Requirement("x", Operator.IN, ("1",)),
                         Requirement("y", Operator.IN, ("2",)))
        b = Requirements(Requirement("y", Operator.IN, ("2",)),
                         Requirement("x", Operator.IN, ("1",)))
        assert requirements_token(a) == requirements_token(b)
        assert requirements_token(None) is None


class TestTermMatcher:
    def test_agrees_with_term_selects_oracle(self):
        """The columnar TermMatcher is THE vectorized selector — it must
        agree with the scalar term_selects oracle on every (pod, term)
        pair across a randomized population (namespaces, partial labels,
        empty selectors, unknown keys/values)."""
        import random
        from karpenter_tpu.models.pod import (PodAffinityTerm, term_selects)
        from karpenter_tpu.ops.encode import TermMatcher
        rng = random.Random(0xE17C0DE)
        keys = ["app", "tier", "zone-group", "absent-key"]
        vals = ["a", "b", "c"]
        pods = []
        for i in range(200):
            labels = {k: rng.choice(vals) for k in keys[:3]
                      if rng.random() < 0.7}
            pods.append(Pod(name=f"tm-{i}",
                            namespace=rng.choice(["default", "team-a",
                                                  "team-b"]),
                            labels=labels))
        matcher = TermMatcher(pods)
        terms = [PodAffinityTerm(topology_key="kubernetes.io/hostname",
                                 label_selector=sel, anti=True)
                 for sel in ({}, {"app": "a"}, {"app": "a", "tier": "b"},
                             {"absent-key": "a"}, {"app": "zzz"},
                             {"tier": "c", "zone-group": "a"})]
        for ns in ("default", "team-a", "never-seen"):
            for t in terms:
                got = matcher.matches(ns, t.label_selector)
                for j, p in enumerate(pods):
                    want = term_selects(t, p.namespace == ns, p.labels)
                    assert bool(got[j]) == want, (
                        f"ns={ns} sel={t.label_selector} pod={p.labels}"
                        f"/{p.namespace}")


class TestEncodeArena:
    def test_buffers_reused_across_encodes(self):
        cat = _cat()
        arena = EncodeArena()
        pods = [mk_pod(f"p{i}") for i in range(10)]
        e1 = encode_pods(pods, cat, arena=arena)
        ptr1 = e1.compat.__array_interface__["data"][0]
        e2 = encode_pods(pods, cat, arena=arena)
        ptr2 = e2.compat.__array_interface__["data"][0]
        assert ptr1 == ptr2, "staging buffer was reallocated"
        assert not arena._leased

    def test_nested_lease_bypasses(self):
        arena = EncodeArena()
        assert arena.acquire()
        try:
            # a nested encode (reserved-capacity retry) must not share
            # the leased buffers
            cat = _cat()
            enc = encode_pods([mk_pod("p")], cat, arena=arena)
            assert enc.compat.any()
        finally:
            arena.release()
        assert arena.acquire()
        arena.release()

    def test_take_grows_and_zeroes(self):
        arena = EncodeArena()
        a = arena.take("x", (2, 3), np.float32, zero=True)
        assert a.shape == (2, 3) and not a.any()
        a.fill(7)
        b = arena.take("x", (4, 3), np.float32, zero=True)
        assert b.shape == (4, 3) and not b.any()


class TestTensorsLRU:
    """Satellite regression: Solver.tensors() kept ONE epoch view and
    cleared on every new key — two NodeClass views alternating each
    reconcile rebuilt (and re-uploaded) the catalog every flip."""

    def _solver(self):
        return Solver(CatalogProvider(
            lambda: generate_catalog(GeneratorConfig(families=["m5", "c5"]))),
            backend="host")

    def test_alternating_node_classes_dont_thrash(self):
        s = self._solver()
        nc_a = NodeClassSpec(name="a")
        nc_b = NodeClassSpec(name="b", zones=["zone-a", "zone-b"])
        s.tensors(nc_a)
        s.tensors(nc_b)
        built = s.stats["catalog_rebuilds"]
        assert built == 2
        for _ in range(8):
            assert s.tensors(nc_a) is not None
            assert s.tensors(nc_b) is not None
        assert s.stats["catalog_rebuilds"] == built, (
            "alternating NodeClass views rebuilt the catalog tensors")

    def test_lru_evicts_beyond_capacity(self):
        s = self._solver()
        # hash() covers spec fields, not the name — vary a hashed field
        ncs = [NodeClassSpec(name=f"nc{i}", block_device_gib=float(i + 1))
               for i in range(Solver.CAT_CACHE_SIZE + 2)]
        for nc in ncs:
            s.tensors(nc)
        assert len(s._cat_cache) == Solver.CAT_CACHE_SIZE
        # oldest view evicted → next access rebuilds exactly once
        before = s.stats["catalog_rebuilds"]
        s.tensors(ncs[0])
        assert s.stats["catalog_rebuilds"] == before + 1

    def test_epoch_bump_rekeys(self):
        s = self._solver()
        nc = NodeClassSpec(name="a")
        s.tensors(nc)
        before = s.stats["catalog_rebuilds"]
        s.catalog.unavailable.mark_unavailable("m5.large", "zone-a", "spot",
                                               reason="test")
        s.tensors(nc)
        assert s.stats["catalog_rebuilds"] == before + 1


class TestFacadeCacheWiring:
    def test_solve_twice_hits_and_meters(self):
        from karpenter_tpu.metrics import ENCODE_CACHE, ENCODE_CACHE_ROWS
        s = Solver(CatalogProvider(lambda: small_catalog()), backend="host")
        pool = NodePool(name="p")
        pods = [mk_pod(f"p{i}") for i in range(12)]
        h0 = ENCODE_CACHE.value(event="hit")
        s.solve(pods, pool)
        assert s._encode_cache.stats["misses"] >= 1
        s.solve(pods, pool)
        assert s._encode_cache.stats["hits"] >= 1
        assert ENCODE_CACHE.value(event="hit") > h0
        assert ENCODE_CACHE_ROWS.value() >= 1

    def test_encode_cache_disable(self):
        s = Solver(CatalogProvider(lambda: small_catalog()), backend="host",
                   encode_cache=False)
        pool = NodePool(name="p")
        out = s.solve([mk_pod("p0")], pool)
        assert out.launches and s._encode_cache is None

    def test_trace_spans_cover_cache_path(self):
        from karpenter_tpu.obs.tracer import TRACER
        s = Solver(CatalogProvider(lambda: small_catalog()), backend="host")
        pool = NodePool(name="p")
        pods = [mk_pod(f"p{i}") for i in range(4)]
        s.solve(pods, pool)  # prime
        TRACER.configure(enabled=True, ring_size=4)
        try:
            with TRACER.trace("test.solve"):
                s.solve(pods, pool)
            trace = next(t for t in TRACER.recorder.slowest()
                         if t.root.name == "test.solve")
            names = {sp.name for sp in trace.spans}
            assert "encode.lower" in names
            assert "encode.cache_hit" in names
            lower = next(sp for sp in trace.spans
                         if sp.name == "encode.lower")
            assert lower.attrs.get("cache_hits", 0) >= 1
        finally:
            TRACER.configure(enabled=False)
