"""Fault-injection subsystem: plan determinism, injection seams, the
solver's degraded-mode fallback, and the batcher's jittered backoff /
admission-gating / Retry-After satellites."""

import random

import pytest

from karpenter_tpu.catalog import small_catalog
from karpenter_tpu.catalog.unavailable import UnavailableOfferings
from karpenter_tpu.cloud.batcher import BatchingCloud
from karpenter_tpu.cloud.fake import FakeCloud, FakeCloudConfig
from karpenter_tpu.cloud.provider import (Instance, NotFoundError,
                                          RateLimitedError, ServerError)
from karpenter_tpu.faults import (ApiFault, ClockJump, DeviceFault,
                                  FaultPlan, IceWindow, InjectedFault)
from karpenter_tpu.faults.injector import FaultyCloud, device_fault_hook
from karpenter_tpu.utils.clock import FakeClock


def _mk_cloud(clock=None, **cfg):
    clock = clock or FakeClock()
    config = FakeCloudConfig(**cfg) if cfg else None
    return FakeCloud(small_catalog(), clock=clock, config=config), clock


class TestFaultPlan:
    def test_ice_window_selectors_and_timeline(self):
        plan = FaultPlan(seed=1, rules=[
            IceWindow(10.0, 20.0, zone="zone-a", capacity_type="spot")])
        assert not plan.ice_active("m5.large", "zone-a", "spot", 5.0)
        assert plan.ice_active("m5.large", "zone-a", "spot", 10.0)
        assert not plan.ice_active("m5.large", "zone-b", "spot", 10.0)
        assert not plan.ice_active("m5.large", "zone-a", "on-demand", 10.0)
        assert not plan.ice_active("m5.large", "zone-a", "spot", 20.0)
        assert plan.timeline == [(10.0, "ice", "m5.large/zone-a/spot")]

    def test_api_fault_taxonomy_and_probability_determinism(self):
        rules = [ApiFault(("create_fleet",), 0.0, 100.0, p=0.5,
                          error="rate_limited", retry_after=7.0),
                 ApiFault(("describe",), 0.0, 100.0, p=1.0, error="server")]
        a, b = FaultPlan(seed=3, rules=rules), FaultPlan(seed=3, rules=rules)
        seq_a = [type(a.api_fault("create_fleet", t)).__name__
                 for t in range(40)]
        seq_b = [type(b.api_fault("create_fleet", t)).__name__
                 for t in range(40)]
        assert seq_a == seq_b  # same seed, same draw sequence
        assert "RateLimitedError" in seq_a and "NoneType" in seq_a
        err = a.api_fault("create_fleet", 50.0)
        if err is None:  # p=0.5: draw until one fires
            while err is None:
                err = a.api_fault("create_fleet", 50.0)
        assert isinstance(err, RateLimitedError) and err.retry_after == 7.0
        assert isinstance(a.api_fault("describe", 0.0), ServerError)
        assert a.api_fault("describe", 100.0) is None  # window closed
        assert a.fingerprint()  # non-empty digest

    def test_device_fault_counts_dispatches(self):
        plan = FaultPlan(rules=[DeviceFault(dispatch=2, count=1)])
        plan.on_dispatch("device")          # dispatch 1: healthy
        with pytest.raises(InjectedFault):
            plan.on_dispatch("device")      # dispatch 2: fault
        plan.on_dispatch("device")          # dispatch 3: healthy again

    def test_origin_makes_rule_times_run_relative(self):
        plan = FaultPlan(rules=[IceWindow(10.0, 20.0)])
        plan.origin = 1_000_000.0
        assert plan.ice_active("t", "z", "c", 1_000_015.0)
        assert not plan.ice_active("t", "z", "c", 1_000_025.0)
        # ledger stores run-relative time
        assert plan.timeline[0][0] == 15.0


class TestInjectionSeams:
    def test_hooks_are_noop_by_default(self):
        """Zero overhead with injection disabled: every seam is a single
        None/empty check."""
        from karpenter_tpu.ops import solver as solver_mod
        cloud, clock = _mk_cloud()
        assert cloud.fault_plan is None
        assert solver_mod._dispatch_fault_hook is None
        assert clock._jumps == []

    def test_faulty_cloud_raises_and_passes_through(self):
        cloud, clock = _mk_cloud()
        plan = FaultPlan(rules=[
            ApiFault(("terminate",), 0.0, 100.0, p=1.0)])
        plan.origin = clock.now()  # rule times are run-relative
        fc = FaultyCloud(cloud, plan, clock)
        with pytest.raises(RateLimitedError):
            fc.terminate(["i-x"])
        assert fc.describe() == []            # uninjected method forwards
        assert fc.describe_types()            # passthrough via name
        assert fc.snapshot()["instances"] == {}  # __getattr__ passthrough

    def test_fake_cloud_ice_window_forces_failover(self):
        """During the window the launch must slide past the ICE'd rows to
        a surviving override, exactly like a real ICE."""
        from karpenter_tpu.cloud.provider import LaunchOverride, LaunchRequest
        cloud, clock = _mk_cloud()
        cloud.fault_plan = FaultPlan(rules=[
            IceWindow(0.0, 1e9, capacity_type="spot")])
        cloud.fault_plan.origin = clock.now()
        t = next(iter(cloud.types))
        req = LaunchRequest(nodeclaim_name="nc", overrides=[
            LaunchOverride(t, "zone-a", "spot", 1.0),
            LaunchOverride(t, "zone-a", "on-demand", 3.0)])
        (res,) = cloud.create_fleet([req])
        assert isinstance(res, Instance)
        assert res.capacity_type == "on-demand"
        assert cloud.fault_plan.timeline  # the skipped row was recorded

    def test_clock_jump_applies_once_with_callback(self):
        clock = FakeClock(start=0.0)
        seen = []
        clock.schedule_jump(10.0, 90.0, lambda now, d: seen.append((now, d)))
        clock.step(9.0)
        assert clock.now() == 9.0 and not seen
        clock.step(1.0)
        assert clock.now() == 100.0
        assert clock.now() == 100.0  # one-shot, not reapplied
        assert seen == [(100.0, 90.0)]

    def test_chained_clock_jumps_drain(self):
        clock = FakeClock(start=0.0)
        clock.schedule_jump(10.0, 20.0)
        clock.schedule_jump(25.0, 5.0)  # the first jump carries time past it
        clock.step(10.0)
        assert clock.now() == 35.0

    def test_unavailable_on_mark_hook_and_active_count(self):
        clock = FakeClock()
        u = UnavailableOfferings(clock=clock, ttl=60.0)
        marks = []
        u.on_mark.append(lambda kind, key, reason: marks.append((kind, key)))
        u.mark_unavailable("m5.large", "zone-a", "spot", reason="ICE")
        u.mark_zone_unavailable("zone-b")
        assert marks == [("offering", ("m5.large", "zone-a", "spot")),
                         ("zone", ("zone-b",))]
        assert u.active() == 2 and u.stats["marks"] == 2
        from karpenter_tpu.metrics import DEGRADED_MODE
        assert DEGRADED_MODE.value(component="capacity") == 2.0
        clock.step(61.0)
        u.seqnum  # prune on read
        assert u.active() == 0
        assert DEGRADED_MODE.value(component="capacity") == 0.0


class TestSolverDeviceFallback:
    def _solver(self):
        from karpenter_tpu.catalog import CatalogProvider
        from karpenter_tpu.ops.facade import Solver
        types = small_catalog()
        return Solver(CatalogProvider(lambda: types), backend="device")

    def _pods(self, n=4):
        from karpenter_tpu.models.pod import Pod
        from karpenter_tpu.models.resources import Resources
        return [Pod(name=f"p{i}",
                    requests=Resources.parse({"cpu": "1", "memory": "1Gi"}))
                for i in range(n)]

    def test_fault_mid_solve_falls_back_and_suspends(self):
        from karpenter_tpu.metrics import SOLVER_FALLBACKS
        from karpenter_tpu.models.nodepool import NodePool
        s = self._solver()
        plan = FaultPlan(rules=[DeviceFault(dispatch=1, count=1)])
        pods = self._pods()
        before = SOLVER_FALLBACKS.value(from_backend="device",
                                        to_backend="host") + \
            SOLVER_FALLBACKS.value(from_backend="device",
                                   to_backend="native")
        with device_fault_hook(plan):
            out = s.solve(pods, NodePool(name="np"))
            # the degraded solve still returned a full placement
            assert not out.unschedulable and out.launches
            assert s.stats["device_fallbacks"] == 1
            after = SOLVER_FALLBACKS.value(from_backend="device",
                                           to_backend="host") + \
                SOLVER_FALLBACKS.value(from_backend="device",
                                       to_backend="native")
            assert after == before + 1
            from karpenter_tpu.metrics import DEGRADED_MODE
            assert DEGRADED_MODE.value(component="solver") == 1.0
            # cooldown: the next solves are rerouted WITHOUT touching the
            # device (the hook would raise again on dispatch #2 only if
            # the device path ran — rule says count=1, so a dispatch
            # would succeed; assert no dispatch happens at all)
            d0 = plan._dispatches
            out2 = s.solve(self._pods(3), NodePool(name="np"))
            assert not out2.unschedulable
            assert plan._dispatches == d0  # no device dispatch: suspended
        assert s._device_suspended > 0

    def test_cooldown_expires_and_reprobes_device(self):
        from karpenter_tpu.models.nodepool import NodePool
        s = self._solver()
        plan = FaultPlan(rules=[DeviceFault(dispatch=1, count=1)])
        with device_fault_hook(plan):
            s.solve(self._pods(), NodePool(name="np"))  # fault + fallback
            for _ in range(s.FALLBACK_COOLDOWN):
                s.solve(self._pods(2), NodePool(name="np"))
            assert s._device_suspended == 0
            d0 = plan._dispatches
            out = s.solve(self._pods(2), NodePool(name="np"))
            assert plan._dispatches == d0 + 1  # device re-probed
            assert not out.unschedulable
        from karpenter_tpu.metrics import DEGRADED_MODE
        assert DEGRADED_MODE.value(component="solver") == 0.0


class TestFleetSolverServiceFallback:
    """Shared-solver degradation under a fleet (ISSUE 6 satellite): a
    device loss during ONE tenant's dispatch must degrade that tenant's
    solves to host fallback without suspending any neighbor's device
    path — per-tenant facades confine the cooldown, and the tenant-
    routed dispatch hook confines the fault itself."""

    def _fleet(self, backend="device"):
        from karpenter_tpu.catalog import CatalogProvider
        from karpenter_tpu.fleet import SolverService
        svc = SolverService(FakeClock(), backend=backend)
        a = svc.register("a", CatalogProvider(lambda: small_catalog()))
        b = svc.register("b", CatalogProvider(lambda: small_catalog()))
        return svc, a, b

    def _pods(self, n=4, prefix="p"):
        from karpenter_tpu.models.pod import Pod
        from karpenter_tpu.models.resources import Resources
        return [Pod(name=f"{prefix}{i}",
                    requests=Resources.parse({"cpu": "1",
                                              "memory": "1Gi"}))
                for i in range(n)]

    def test_device_loss_confined_to_faulted_tenant(self):
        from karpenter_tpu.faults.injector import fleet_device_fault_hook
        from karpenter_tpu.metrics import SOLVER_FALLBACKS
        from karpenter_tpu.metrics.tenant import tenant_scope
        from karpenter_tpu.models.nodepool import NodePool
        svc, a, b = self._fleet()
        pool = NodePool(name="default")
        plan_a = FaultPlan(seed=0, rules=[DeviceFault(dispatch=1, count=1)])
        # b's sentinel plan never fires (dispatch 999) — it exists to
        # COUNT b's device dispatches through the routed hook
        plan_b = FaultPlan(seed=0, rules=[DeviceFault(dispatch=999)])
        with fleet_device_fault_hook({"a": plan_a, "b": plan_b}):
            with tenant_scope("a"):
                out = a.solve(self._pods(), pool)
            # a's solve degraded to a per-shard host fallback but still
            # returned a full placement
            assert out.launches and not out.unschedulable
            assert a.facade.stats["device_fallbacks"] == 1
            assert a.facade._device_suspended > 0
            assert SOLVER_FALLBACKS.value(from_backend="device",
                                          to_backend="host",
                                          tenant="a") + \
                SOLVER_FALLBACKS.value(from_backend="device",
                                       to_backend="native",
                                       tenant="a") >= 1
            # b's next solve DISPATCHES on the device (its plan counts
            # it) — no cross-tenant suspension leak
            with tenant_scope("b"):
                out = b.solve(self._pods(prefix="q"), pool)
            assert out.launches
            assert plan_b._dispatches == 1
            assert b.facade._device_suspended == 0
            assert b.facade.stats["device_fallbacks"] == 0
            # and a's cooldown keeps rerouting a WITHOUT device dispatches
            d0 = plan_a._dispatches
            with tenant_scope("a"):
                a.solve(self._pods(3, prefix="r"), pool)
            assert plan_a._dispatches == d0
            assert a.facade.stats["device_fallbacks"] == 1

    def test_faulted_tenant_reprobes_after_cooldown(self):
        from karpenter_tpu.faults.injector import fleet_device_fault_hook
        from karpenter_tpu.metrics.tenant import tenant_scope
        from karpenter_tpu.models.nodepool import NodePool
        svc, a, b = self._fleet()
        pool = NodePool(name="default")
        plan_a = FaultPlan(seed=0, rules=[DeviceFault(dispatch=1, count=1)])
        with fleet_device_fault_hook({"a": plan_a}):
            with tenant_scope("a"):
                a.solve(self._pods(), pool)  # fault + fallback
                for _ in range(a.facade.FALLBACK_COOLDOWN):
                    a.solve(self._pods(2, prefix="c"), pool)
                assert a.facade._device_suspended == 0
                d0 = plan_a._dispatches
                out = a.solve(self._pods(2, prefix="d"), pool)
            assert plan_a._dispatches == d0 + 1  # device re-probed
            assert not out.unschedulable


class TestBatcherJitterAndGating:
    def _throttling(self, clock, fail_times):
        """A terminate backend failing with RateLimitedError while
        fail_times says so."""
        calls = []

        class Inner:
            def __init__(self):
                self.clock = clock

            def terminate(self, ids):
                calls.append((clock.now(), list(ids)))
                if fail_times(clock.now()):
                    raise RateLimitedError("throttle")

            def describe(self, ids=None):
                return []
        return Inner(), calls

    def test_full_jitter_is_seed_deterministic(self):
        clock = FakeClock(start=0.0)
        inner, _ = self._throttling(clock, lambda t: True)

        def gates(seed):
            b = BatchingCloud(inner, clock, idle=0.1,
                              rng=random.Random(seed))
            out = []
            for _ in range(6):
                b.terminate(["x"])
                clock.step(0.2)
                b._retry_after = 0.0  # force the attempt; capture the gate
                b.flush()
                out.append(round(b._retry_after - clock.now(), 6))
            return out
        g1, g2, g3 = gates(7), gates(7), gates(8)
        assert g1 == g2              # same seed → same jitter sequence
        assert g1 != g3              # different seed → desynchronized
        # full jitter: delays live in [0, ceiling], ceiling doubles to 30
        assert all(0.0 <= d <= 30.0 for d in g1)

    def test_backlog_during_backoff_flushes_chunked_not_starved(self):
        """Items enqueued while the gate is closed must all ship once it
        opens — in wire calls capped at max_items."""
        clock = FakeClock(start=0.0)
        state = {"fail": True}
        inner, calls = self._throttling(clock, lambda t: state["fail"])
        b = BatchingCloud(inner, clock, idle=0.1, max_items=5,
                          rng=random.Random(0))
        b.terminate([f"i-{k}" for k in range(5)])  # max_items: attempt 1
        assert len(calls) == 1
        # 12 more ids arrive during the backoff window
        for k in range(5, 17):
            b.terminate([f"i-{k}"])
        assert len(calls) == 1  # gate holds
        state["fail"] = False
        clock.step(35.0)  # past any jittered gate (ceiling 30)
        b.flush()
        sent = [ids for _, ids in calls[1:]]
        assert all(len(ids) <= 5 for ids in sent)  # cap is a wire invariant
        assert sorted(sum(sent, [])) == sorted(f"i-{k}" for k in range(17))
        assert not b._pending  # nothing starved
        assert b._retry_after == 0.0 and b._backoff == 0.0

    def test_partial_batch_success_keeps_backoff_for_failed_window(self):
        """Chunk 1 succeeds, chunk 2 throttles: the succeeded window must
        not re-send, the failed window stays queued, and the backoff grows
        instead of resetting on the partial success."""
        clock = FakeClock(start=0.0)
        state = {"poison": True}
        calls = []

        class Inner:
            def __init__(self):
                self.clock = clock

            def terminate(self, ids):
                calls.append(list(ids))
                if state["poison"] and "i-5" in ids:
                    raise RateLimitedError("throttle")

            def describe(self, ids=None):
                return []
        b = BatchingCloud(Inner(), clock, idle=0.1, max_items=3,
                          rng=random.Random(0))
        # first three hit max_items and throttle-free flush immediately?
        # no: i-5 isn't among them — they flush clean as their own call
        b.terminate(["i-0", "i-1", "i-2"])
        assert calls == [["i-0", "i-1", "i-2"]]
        # next three contain the poison id; they flush as one chunk and
        # throttle, raising the gate
        b.terminate(["i-3", "i-4", "i-5"])
        assert calls[-1] == ["i-3", "i-4", "i-5"]
        assert sorted(b._pending) == ["i-3", "i-4", "i-5"]  # failed window
        assert b._backoff > 0 and b._retry_after > clock.now()
        items_after_success = b.stats["terminate_items"]
        assert items_after_success == 3  # only the clean window counted
        # gate open + backend healthy: ONLY the failed window retries —
        # the earlier success didn't clear the backoff for it
        state["poison"] = False
        n_calls = len(calls)
        clock.step(35.0)
        b.flush()
        assert calls[n_calls:] == [["i-3", "i-4", "i-5"]]
        assert not b._pending
        assert b.stats["terminate_items"] == 6  # each id shipped once

    def test_retry_after_hint_floors_the_gate(self):
        clock = FakeClock(start=0.0)
        calls = []

        class Inner:
            def __init__(self):
                self.clock = clock

            def terminate(self, ids):
                calls.append(clock.now())
                raise RateLimitedError("throttle", retry_after=12.0)

            def describe(self, ids=None):
                return []
        b = BatchingCloud(Inner(), clock, idle=0.1, rng=random.Random(0))
        b.terminate(["i-a"])
        clock.step(0.2)
        b.flush()
        # local jitter would allow < 1s; the server hint floors it at 12
        assert b._retry_after >= clock.now() + 12.0
        for _ in range(300):
            clock.step(0.1)
            b.flush()
            if len(calls) > 1:
                break
        assert len(calls) > 1
        assert calls[1] - calls[0] >= 12.0

    def test_nonretryable_per_id_path_still_chunks_and_recovers(self):
        """Poisoned batch falls back per-id inside its chunk; later chunks
        still flush whole."""
        clock = FakeClock(start=0.0)
        cloud, _ = _mk_cloud(clock=clock)
        for i in range(6):
            cloud.instances[f"i-{i}"] = Instance(
                id=f"i-{i}", instance_type="m5.large", zone="zone-a",
                capacity_type="on-demand", image_id="img", state="running")
        real = cloud.terminate
        calls = []

        def poisoned(ids):
            calls.append(list(ids))
            if "i-poison" in ids and len(ids) > 1:
                raise NotFoundError("i-poison")
            if ids == ["i-poison"]:
                raise NotFoundError("i-poison")
            real(ids)
        cloud.terminate = poisoned
        b = BatchingCloud(cloud, clock, idle=0.1, max_items=4,
                          rng=random.Random(0))
        b.terminate(["i-0", "i-poison", "i-1", "i-2", "i-3", "i-4", "i-5"])
        clock.step(0.2)
        b.flush()
        assert all(cloud.instances[f"i-{k}"].state == "terminated"
                   for k in range(6))
        assert not b._pending


class TestRetryAfterOverTheWire:
    def test_429_carries_retry_after_header_and_envelope(self):
        """Server-side throttle hint survives HTTP into the client's
        RateLimitedError (the batcher gate consumes it from there)."""
        from karpenter_tpu.cloud.remote import RemoteCloud, serve_in_thread
        from karpenter_tpu.utils.clock import RealClock
        cloud = FakeCloud(small_catalog(), clock=RealClock(),
                          config=FakeCloudConfig(terminate_rate=0.25,
                                                 terminate_burst=1))
        srv, port = serve_in_thread(cloud)
        try:
            rc = RemoteCloud("127.0.0.1", port)
            rc.terminate([])  # drains the single-token bucket
            with pytest.raises(RateLimitedError) as ei:
                rc.terminate(["i-x"])
            assert ei.value.retry_after is not None
            assert ei.value.retry_after > 0
        finally:
            srv.shutdown()

    def test_error_envelope_roundtrip(self):
        from karpenter_tpu.cloud.remote import decode_error, encode_error
        e = RateLimitedError("slow down", retry_after=4.5)
        out = decode_error(encode_error(e))
        assert isinstance(out, RateLimitedError)
        assert out.retry_after == 4.5
        out2 = decode_error(encode_error(RateLimitedError("no hint")))
        assert out2.retry_after is None


class TestWireFaults:
    """The federation wire-weather family: per-kind semantics driven
    directly through the plan's on_wire/on_wire_reply seams, plus the
    same-seed ⇒ identical-fingerprint contract every fault family
    carries (docs/robustness.md)."""

    @staticmethod
    def _mk_plan(rules, seed=0):
        from karpenter_tpu.faults import WireFault  # noqa: F401 (export)
        plan = FaultPlan(seed=seed, rules=rules)
        clock = FakeClock()
        plan.clock = clock
        plan.origin = clock.now()
        return plan, clock

    @staticmethod
    def _drive(plan, clock, methods, step=1.0):
        """Fire a fixed method sequence through both seams, swallowing
        the injected raises; returns the per-probe outcome sequence."""
        outcomes = []
        for m in methods:
            try:
                plan.on_wire(m)
                outcomes.append("ok")
            except ServerError:
                outcomes.append("slow")
            except ConnectionResetError:
                outcomes.append("reset")
            except ConnectionError:
                outcomes.append("down")
            raw = plan.on_wire_reply(m, b'{"result": 1}')
            outcomes.append("garbled" if raw != b'{"result": 1}' else "clean")
            clock.step(step)
        return outcomes

    def test_blackhole_every_probe_in_window(self):
        from karpenter_tpu.faults import WireFault
        rule = WireFault(kind="blackhole", at=2.0, window=3.0)
        plan, clock = self._mk_plan([rule])
        seq = ["solve_bucket", "healthz"] * 4
        out = self._drive(plan, clock, seq)
        # t=0,1: pre-window clean; t=2,3,4: EVERY method down (probes
        # included — a partition has no nth); t=5+: window lifted
        assert out[0::2] == ["ok", "ok", "down", "down", "down",
                             "ok", "ok", "ok"]
        assert all(o == "clean" for o in out[1::2])
        assert all(d.startswith("blackhole:") for _, k, d in plan.timeline)

    def test_flap_alternates_runs_of_nth(self):
        from karpenter_tpu.faults import WireFault
        rule = WireFault(kind="flap", at=0.0, window=100.0, nth=2,
                         methods=("solve_bucket",))
        plan, clock = self._mk_plan([rule])
        out = self._drive(plan, clock, ["solve_bucket"] * 8)[0::2]
        # runs of nth=2: down,down,up,up,down,down,up,up
        assert out == ["down", "down", "ok", "ok",
                       "down", "down", "ok", "ok"]
        # ineligible methods never count against the flap cadence
        plan2, clock2 = self._mk_plan([rule])
        out2 = self._drive(plan2, clock2,
                           ["healthz", "solve_bucket"] * 4)[0::2]
        assert out2 == ["ok", "down", "ok", "down",
                        "ok", "ok", "ok", "ok"]

    def test_latency_fires_nth_through_count_as_retryable(self):
        from karpenter_tpu.faults import WireFault
        rule = WireFault(kind="latency", at=0.0, window=100.0, nth=2,
                         count=2)
        plan, clock = self._mk_plan([rule])
        out = self._drive(plan, clock, ["has_catalog"] * 5)[0::2]
        assert out == ["ok", "slow", "slow", "ok", "ok"]
        # the raise is the retry ladder's food: a retryable ServerError
        plan2, clock2 = self._mk_plan([rule])
        plan2.on_wire("has_catalog")
        with pytest.raises(ServerError) as ei:
            plan2.on_wire("has_catalog")
        assert getattr(ei.value, "retryable", False)
        assert "deadline exceeded" in str(ei.value)

    def test_slow_handshake_only_connect_paths_eligible(self):
        from karpenter_tpu.faults import WireFault
        rule = WireFault(kind="slow_handshake", at=0.0, window=100.0,
                         nth=1, count=1)
        plan, clock = self._mk_plan([rule])
        out = self._drive(plan, clock,
                          ["solve_bucket", "put_catalog", "handshake",
                           "healthz", "handshake"])[0::2]
        # solves never count; the FIRST connect-path probe eats the stall
        assert out == ["ok", "ok", "slow", "ok", "ok"]

    def test_reset_raises_connection_reset(self):
        from karpenter_tpu.faults import WireFault
        plan, clock = self._mk_plan(
            [WireFault(kind="reset", at=0.0, window=100.0, nth=1)])
        with pytest.raises(ConnectionResetError):
            plan.on_wire("report")
        plan.on_wire("report")  # count spent: clean again

    def test_corrupt_frame_garbled_reply_never_parses(self):
        import json

        from karpenter_tpu.faults import WireFault
        rule = WireFault(kind="corrupt_frame", at=0.0, window=100.0,
                         nth=2, count=1)
        plan, clock = self._mk_plan([rule])
        out = self._drive(plan, clock, ["solve_bucket"] * 3)
        # request seam never fires for a reply-only kind
        assert out[0::2] == ["ok", "ok", "ok"]
        assert out[1::2] == ["clean", "garbled", "clean"]
        garbled = FaultPlan(seed=0, rules=[WireFault(
            kind="corrupt_frame", at=0.0, window=100.0, nth=1)])
        garbled.clock = FakeClock()
        garbled.origin = garbled.clock.now()
        raw = garbled.on_wire_reply("solve_bucket", b'{"result": 1}')
        with pytest.raises(Exception):
            json.loads(raw.decode("utf-8", errors="strict"))

    def test_same_seed_identical_fingerprint_per_kind(self):
        from karpenter_tpu.faults import WireFault
        seq = ["handshake", "has_catalog", "put_catalog", "solve_bucket",
               "solve_bucket", "healthz", "solve_bucket", "report"] * 3
        for kind in ("blackhole", "latency", "reset", "flap",
                     "slow_handshake", "corrupt_frame"):
            rule = WireFault(kind=kind, at=3.0, window=9.0, nth=2,
                             count=2)
            runs = []
            for _ in range(2):
                plan, clock = self._mk_plan([rule], seed=7)
                out = self._drive(plan, clock, seq)
                runs.append((out, plan.timeline, plan.fingerprint()))
            assert runs[0] == runs[1], kind
            assert runs[0][1], kind  # every kind actually fired
            assert all(k == "wire" for _, k, _d in runs[0][1])

    def test_wire_plan_hook_arms_and_restores_the_seams(self):
        from karpenter_tpu.faults import WireFault
        from karpenter_tpu.faults.injector import wire_fault_plan_hook
        from karpenter_tpu.federation import transport as tmod
        plan, clock = self._mk_plan(
            [WireFault(kind="reset", at=0.0, window=100.0, nth=1)])
        assert tmod._wire_fault_hook is None
        assert tmod._wire_reply_hook is None
        with wire_fault_plan_hook(plan):
            assert tmod._wire_fault_hook is not None
            assert tmod._wire_reply_hook is not None
            with pytest.raises(ConnectionResetError):
                tmod._wire_fault_hook("solve_bucket")
        assert tmod._wire_fault_hook is None
        assert tmod._wire_reply_hook is None
        # a plan without wire rules never arms the seams (zero overhead)
        with wire_fault_plan_hook(FaultPlan(seed=0)):
            assert tmod._wire_fault_hook is None


class TestScreenFaultSeam:
    def test_screen_fault_degrades_to_cost_order_metered(self):
        """The consolidation screen shares the solver's dispatch fault
        seam; a device fault at screen dispatch degrades the disruption
        pass to plain cost order (best-effort contract) and meters it."""
        import numpy as np

        from karpenter_tpu.metrics import SOLVER_FALLBACKS
        from karpenter_tpu.models.pod import Pod
        from karpenter_tpu.models.resources import Resources
        from karpenter_tpu.ops.consolidate import consolidation_screen
        from karpenter_tpu.ops.encode import encode_pods
        from karpenter_tpu.sim import make_sim
        from karpenter_tpu.state.cluster import build_node_views

        sim = make_sim()
        for i in range(20):
            sim.store.add_pod(Pod(
                name=f"p{i}",
                requests=Resources.parse({"cpu": "500m", "memory": "1Gi"})))
        assert sim.engine.run_until(
            lambda: all(p.node_name for p in sim.store.pods.values()),
            timeout=60)
        cat = sim.solver.tensors(sim.store.nodeclasses["default"])
        views = build_node_views(sim.store, cat, sim.clock.now())
        all_pods = [p for v in views for p in v.pods]
        enc = encode_pods(all_pods, cat)
        sig_to_g = {g.representative.constraint_signature(): i
                    for i, g in enumerate(enc.groups)}
        counts = np.zeros((len(views), max(enc.G, 1)), np.int32)
        for i, v in enumerate(views):
            for p in v.pods:
                counts[i, sig_to_g[p.constraint_signature()]] += 1

        # the seam fires inside consolidation_screen itself…
        plan = FaultPlan(rules=[DeviceFault(dispatch=1, count=1)])
        with device_fault_hook(plan):
            with pytest.raises(InjectedFault):
                consolidation_screen(cat, enc, views, counts)
        assert plan.timeline and plan.timeline[0][1] == "device"

        # …and the controller's best-effort wrapper absorbs + meters it
        before = SOLVER_FALLBACKS.value(from_backend="screen",
                                        to_backend="cost-order")
        plan2 = FaultPlan(rules=[DeviceFault(dispatch=1, count=1)])
        pool = sim.store.nodepools["default"]
        with device_fault_hook(plan2):
            ordered = sim.disruption._screen_order(pool, list(views),
                                                   cat, views)
        assert len(ordered) == len(views)  # cost-order fallback, no crash
        assert SOLVER_FALLBACKS.value(
            from_backend="screen", to_backend="cost-order") == before + 1
        assert sim.disruption.stats.get("screen_errors") == 1
